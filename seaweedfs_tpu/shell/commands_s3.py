"""s3.* shell commands.

Equivalents of /root/reference/weed/shell/command_s3_configure.go,
command_s3_bucket_list.go, command_s3_bucket_create.go,
command_s3_bucket_delete.go, command_s3_circuitbreaker.go: manage the
S3 gateway's identities, buckets, and circuit-breaker limits. All of it
is filer state (/buckets/* entries + the s3/identities and
s3/circuit_breaker KV keys the gateways hot-reload), so these commands
talk to the filer, not to a gateway instance.
"""
from __future__ import annotations

import json

from .commands_fs import _filer, _is_dir, _list, _name
from .env import CommandEnv, ShellError
from ..rpc.httpclient import session

IDENTITIES_KEY = "s3/identities"
CIRCUIT_BREAKER_KEY = "s3/circuit_breaker"
BUCKETS_DIR = "/buckets"


def _kv_get(env: CommandEnv, key: str) -> dict:
    r = session().get(f"{_filer(env)}/kv/{key}", timeout=30)
    if r.status_code == 404:
        return {}
    if r.status_code >= 300:
        raise ShellError(f"read {key}: {r.text}")
    return json.loads(r.content)


def _kv_put(env: CommandEnv, key: str, value: dict) -> None:
    r = session().put(f"{_filer(env)}/kv/{key}",
                     data=json.dumps(value, indent=1).encode(),
                     timeout=30)
    if r.status_code >= 300:
        raise ShellError(f"write {key}: {r.text}")


def s3_configure(env: CommandEnv, user: str = "",
                 access_key: str = "", secret_key: str = "",
                 actions: str = "", delete: bool = False,
                 apply: bool = False) -> dict:
    """Show/edit S3 identities (command_s3_configure.go). Without
    -user just prints the config; edits are dry-run unless -apply."""
    conf = _kv_get(env, IDENTITIES_KEY)
    conf.setdefault("identities", [])
    if user:
        existing = next((i for i in conf["identities"]
                         if i.get("name") == user), None)
        conf["identities"] = [i for i in conf["identities"]
                              if i.get("name") != user]
        if not delete:
            # MERGE into the existing identity: an edit that only
            # broadens -actions must not wipe credentials the admin
            # didn't re-type (command_s3_configure.go:119-152)
            ident = existing or {"name": user, "credentials": [],
                                 "actions": ["Read", "Write", "List"]}
            if actions:
                ident["actions"] = [a.strip()
                                    for a in actions.split(",")
                                    if a.strip()]
            # note: an EXISTING identity with actions=[] stays deny-all
            # — key-only edits must not escalate privileges
            if access_key:
                ident["credentials"] = [
                    c for c in ident.get("credentials", [])
                    if c.get("accessKey") != access_key]
                ident["credentials"].append(
                    {"accessKey": access_key,
                     "secretKey": secret_key})
            conf["identities"].append(ident)
        if apply:
            _kv_put(env, IDENTITIES_KEY, conf)
    out = dict(conf)
    out["applied"] = apply or not user
    return out


def s3_bucket_list(env: CommandEnv) -> list[dict]:
    _filer(env)  # a missing -filer must error, not read as "no buckets"
    try:
        entries = _list(env, BUCKETS_DIR)  # paginates past 1024
    except ShellError as e:
        if "not found" in str(e):
            return []  # no /buckets dir yet: no buckets
        raise
    return [{"name": _name(e), "ctime": e.get("mtime", 0)}
            for e in entries if _is_dir(e)]


def s3_bucket_create(env: CommandEnv, name: str) -> dict:
    if not name:
        raise ShellError("s3.bucket.create needs -name")
    r = session().post(f"{_filer(env)}{BUCKETS_DIR}/{name}/",
                      params={"mkdir": "1"}, timeout=30)
    if r.status_code >= 300:
        raise ShellError(f"s3.bucket.create: {r.text}")
    return {"created": name}


def s3_bucket_delete(env: CommandEnv, name: str,
                     include_objects: bool = False) -> dict:
    if not name:
        raise ShellError("s3.bucket.delete needs -name")
    params = {"recursive": "true"} if include_objects else {}
    r = session().delete(f"{_filer(env)}{BUCKETS_DIR}/{name}",
                        params=params, timeout=60)
    if r.status_code == 409:
        raise ShellError(f"bucket {name} is not empty "
                         "(use -includeObjects)")
    if r.status_code >= 300 and r.status_code != 404:
        raise ShellError(f"s3.bucket.delete: {r.text}")
    return {"deleted": name}


def _bucket_usage_bytes(env: CommandEnv, name: str) -> int:
    from .commands_fs import _size, _walk

    total = 0
    for e in _walk(env, f"{BUCKETS_DIR}/{name}"):
        if not _is_dir(e):
            total += _size(e)
    return total


def s3_bucket_quota(env: CommandEnv, name: str,
                    quota_mb: int = -1) -> dict:
    """Show or set a bucket's size quota
    (command_s3_bucketquota.go): stored on the bucket entry; enforced
    by s3.bucket.quota.enforce. -quotaMB=0 removes the quota."""
    if not name:
        raise ShellError("s3.bucket.quota needs -name")
    from .commands_fs import _stat

    path = f"{BUCKETS_DIR}/{name}"
    meta = _stat(env, path)
    ext = dict(meta.get("extended", {}))
    if quota_mb < 0:
        return {"bucket": name,
                "quota_bytes": int(ext.get("s3_quota_bytes", 0)),
                "used_bytes": _bucket_usage_bytes(env, name)}
    env.confirm_locked()
    if quota_mb == 0:
        ext.pop("s3_quota_bytes", None)
    else:
        ext["s3_quota_bytes"] = str(quota_mb << 20)
    meta["extended"] = ext
    meta.pop("full_path", None)
    r = session().put(f"{_filer(env)}{path}?meta=1", json=meta,
                     timeout=30)
    if r.status_code >= 300:
        raise ShellError(f"s3.bucket.quota: {r.text}")
    return {"bucket": name,
            "quota_bytes": int(ext.get("s3_quota_bytes", 0))}


def s3_bucket_quota_enforce(env: CommandEnv) -> list[dict]:
    """Walk the buckets; mark a bucket's collection volumes read-only
    when over quota and writable again when back under — including
    buckets whose quota was since removed, tracked by an
    `s3_quota_enforced` latch on the bucket entry so clearing a quota
    releases the volumes instead of leaving them read-only forever
    (command_s3_bucketquota.go enforcement pass, run from the master
    maintenance cron in the reference)."""
    env.confirm_locked()
    from .commands_fs import _stat

    out = []
    for b in s3_bucket_list(env):
        name = b["name"]
        path = f"{BUCKETS_DIR}/{name}"
        meta = _stat(env, path)
        ext = dict(meta.get("extended", {}))
        quota = int(ext.get("s3_quota_bytes", 0) or 0)
        latched = ext.get("s3_quota_enforced") == "true"
        if quota <= 0 and not latched:
            continue
        used = _bucket_usage_bytes(env, name) if quota > 0 else 0
        over = quota > 0 and used > quota
        # bucket objects are written into collection=<bucket>.
        # Read-only marking is idempotent and re-runs WHILE over —
        # volumes auto-grown after the latch must be caught too; the
        # writable direction fires only on the latch TRANSITION so
        # volumes made read-only for other reasons (tiering, operator
        # volume.mark) are never blanket-flipped back
        touched = []
        if over or over != latched:
            for n in env.data_nodes():
                for vid in n["volumes"]:
                    if n.get("collections", {}).get(str(vid)) != name:
                        continue
                    vs_path = "/admin/mark_readonly" if over \
                        else "/admin/mark_writable"
                    env.vs_post(n["url"], vs_path, {"volume": vid})
                    touched.append(vid)
        if over != latched:
            if over:
                ext["s3_quota_enforced"] = "true"
            else:
                ext.pop("s3_quota_enforced", None)
            meta["extended"] = ext
            meta.pop("full_path", None)
            r = session().put(f"{_filer(env)}{path}?meta=1", json=meta,
                             timeout=30)
            if r.status_code >= 300:
                # a lost latch write would leave the volumes read-only
                # with nothing left to release them
                raise ShellError(
                    f"quota latch update for {name}: {r.text}")
        out.append({"bucket": name, "used": used, "quota": quota,
                    "over": over, "volumes": sorted(set(touched))})
    return out


def s3_clean_uploads(env: CommandEnv,
                     time_ago_seconds: int = 86400) -> list[str]:
    """Abort multipart uploads older than -timeAgo
    (command_s3_clean_uploads.go): removes stale .uploads/<id> dirs."""
    env.confirm_locked()
    import time as _time

    from .commands_fs import _list as _ls

    cutoff = _time.time() - time_ago_seconds
    removed = []
    for b in s3_bucket_list(env):
        updir = f"{BUCKETS_DIR}/{b['name']}/.uploads"
        try:
            uploads = _ls(env, updir)
        except ShellError:
            continue
        for u in uploads:
            if u.get("mtime", 0) < cutoff:
                full = u["full_path"]
                session().delete(f"{_filer(env)}{full}",
                                params={"recursive": "true"},
                                timeout=60)
                removed.append(full)
    return removed


def s3_circuit_breaker(env: CommandEnv, global_conf: str = "",
                       bucket: str = "", bucket_conf: str = "",
                       delete: bool = False,
                       apply: bool = False) -> dict:
    """Show/edit circuit-breaker limits (command_s3_circuitbreaker.go).
    -global/-bucketConf take JSON like '{"writeCount": 32}'."""
    conf = _kv_get(env, CIRCUIT_BREAKER_KEY)
    changed = False
    if delete and bucket:
        conf.get("buckets", {}).pop(bucket, None)
        changed = True
    elif delete:
        conf.pop("global", None)
        changed = True
    if global_conf:
        conf["global"] = json.loads(global_conf)
        changed = True
    if bucket and bucket_conf:
        conf.setdefault("buckets", {})[bucket] = json.loads(bucket_conf)
        changed = True
    if changed and apply:
        _kv_put(env, CIRCUIT_BREAKER_KEY, conf)
    out = dict(conf)
    out["applied"] = apply or not changed
    return out
