"""Shell command environment: master access + cluster-wide admin lock.

Equivalent of /root/reference/weed/shell/commands.go:41-78 (command
interface + CommandEnv.confirmIsLocked). The exclusive admin lock is held
in the master process here (single control plane) rather than a filer
DLM; the filer-side distributed lock manager lives in
filer/lock_manager.py.
"""
from __future__ import annotations

import time

from ..rpc.httpclient import session


class ShellError(Exception):
    pass


class CommandEnv:
    def __init__(self, master_url: str, filer_url: str = ""):
        self.master_url = master_url.rstrip("/")
        self.filer_url = filer_url.rstrip("/")
        self.locked = False
        self._dlm = None
        # fs.cd / fs.pwd working directory (commands.go option.directory)
        self.cwd = "/"

    def resolve(self, path: str) -> str:
        """Resolve a possibly-relative shell path against fs.cd's cwd."""
        import posixpath

        if not path.startswith("/"):
            path = posixpath.join(self.cwd, path)
        norm = posixpath.normpath(path)
        return norm if norm != "." else "/"

    ADMIN_LOCK = "admin"  # cluster-wide exclusive shell lock name

    # -- master helpers -------------------------------------------------
    def master_get(self, path: str, **params) -> dict:
        resp = session().get(f"{self.master_url}{path}", params=params,
                            timeout=60)
        # status first: a 502/500 from a proxy carries an HTML body
        # that would raise JSONDecodeError past ShellError-only callers
        if resp.status_code >= 300:
            try:
                detail = resp.json().get("error", resp.status_code)
            except ValueError:
                detail = resp.status_code
            raise ShellError(f"{path}: {detail}")
        try:
            return resp.json()
        except ValueError as e:
            raise ShellError(f"{path}: non-json response: {e}") from e

    def topology(self) -> dict:
        return self.master_get("/cluster/status")["Topology"]

    def data_nodes(self) -> list[dict]:
        out = []
        for dc in self.topology()["datacenters"]:
            for rack in dc["racks"]:
                for n in rack["nodes"]:
                    n = dict(n)
                    n["dc"] = dc["id"]
                    n["rack"] = rack["id"]
                    out.append(n)
        return out

    def ec_shard_locations(self, vid: int) -> dict[int, list[str]]:
        body = self.master_get("/cluster/ec_shards", volumeId=vid)
        return {int(sid): urls for sid, urls in body["shards"].items()}

    def ec_collection(self, vid: int) -> str:
        return self.master_get("/cluster/ec_shards",
                               volumeId=vid).get("collection", "")

    def ec_codec(self, vid: int) -> tuple[int, int]:
        """(k, m) of an EC volume from the master registry
        ('' -> RS(10,4) default)."""
        return self.ec_info(vid)[1]

    def ec_info(self, vid: int) -> tuple[str, tuple[int, int],
                                         "dict[int, list[str]]"]:
        """(collection, (k, m), {shard_id: [urls]}) in ONE master
        round trip — /cluster/ec_shards carries all three."""
        col, code, locs = self.ec_full_info(vid)
        return col, (code.k, code.m), locs

    def ec_full_info(self, vid: int):
        """(collection, CodeConfig, {shard_id: [urls]}) in ONE master
        round trip — the code config (not just its (k, m) geometry)
        drives rebuild planning for structured codes."""
        from ..ec import geometry as geo

        body = self.master_get("/cluster/ec_shards", volumeId=vid)
        return (body.get("collection", ""),
                geo.parse_code(body.get("codec", "")),
                {int(sid): urls
                 for sid, urls in body.get("shards", {}).items()})

    def volume_collection(self, vid: int) -> str:
        for n in self.data_nodes():
            col = n.get("collections", {}).get(str(vid))
            if col is not None:
                return col
        return ""

    def volume_locations(self, vid: int) -> list[str]:
        try:
            body = self.master_get("/dir/lookup", volumeId=str(vid))
        except ShellError:
            return []
        return [l["url"] for l in body["locations"]]

    # -- volume server admin -------------------------------------------
    def vs_post(self, server: str, path: str, body: dict,
                timeout: float = 600) -> dict:
        resp = session().post(f"http://{server}{path}", json=body,
                             timeout=timeout)
        try:
            out = resp.json()
        except Exception:
            out = {"error": resp.text}
        if resp.status_code >= 300:
            raise ShellError(
                f"{server}{path}: {out.get('error', resp.status_code)}")
        return out

    # -- admin lock (commands.go:78 confirmIsLocked) --------------------
    # Cluster-wide exclusive via the filer DLM when a filer is known;
    # process-local otherwise (single-operator mode).
    def confirm_locked(self) -> None:
        if not self.locked:
            raise ShellError(
                "lock is required: run `lock` before cluster-mutating "
                "commands")
        if self._dlm is not None and not self._dlm.is_held(self.ADMIN_LOCK):
            self.locked = False
            raise ShellError(
                "admin lock lost (renewal failed); run `lock` again")

    def acquire_lock(self) -> None:
        if self.filer_url:
            from ..cluster.lock_manager import DlmClient

            if self._dlm is None:
                self._dlm = DlmClient(self.filer_url, owner="shell")
            try:
                self._dlm.lock(self.ADMIN_LOCK)
            except RuntimeError as e:
                raise ShellError(f"cannot acquire admin lock: {e}")
        self.locked = True

    def release_lock(self) -> None:
        if self._dlm is not None:
            try:
                self._dlm.unlock(self.ADMIN_LOCK)
            except RuntimeError:
                pass
        self.locked = False

    def close(self) -> None:
        """Release the admin lock and stop the renewer on shell exit —
        otherwise the cluster-wide lock stays wedged until TTL."""
        if self.locked:
            self.release_lock()
        if self._dlm is not None:
            self._dlm.close()
            self._dlm = None

    def wait_for_ec_registration(self, vid: int, min_shards: int,
                                 timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            locs = self.ec_shard_locations(vid)
            if sum(len(v) for v in locs.values()) >= min_shards:
                return
            time.sleep(0.1)
        raise ShellError(f"ec shards of volume {vid} not registered in time")
