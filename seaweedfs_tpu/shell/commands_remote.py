"""Shell remote.* commands: cloud-mount configuration and data motion.

Equivalent of /root/reference/weed/shell/command_remote_configure.go,
command_remote_mount.go, command_remote_unmount.go,
command_remote_meta_sync.go, command_remote_cache.go and
command_remote_uncache.go — operating on the remote-storage
configuration stored in the filer (remote_storage/mount.py) and the
filer server's cacheRemote/uncacheRemote verbs.
"""
from __future__ import annotations

import json

import requests

from ..remote_storage import (RemoteMount, find_mount, load_conf,
                              make_client, remote_key_for, save_conf)
from .commands_fs import _filer, _walk
from .env import CommandEnv, ShellError
from ..rpc.httpclient import session


def remote_configure(env: CommandEnv, name: str = "",
                     delete: bool = False, **conf) -> dict:
    """No args: show configured storages (secrets redacted). With
    -name/-type...: create or update one; -delete removes it."""
    rc = load_conf(_filer(env))
    if not name:
        return {n: {k: ("***" if "secret" in k else v)
                    for k, v in s.items()}
                for n, s in rc.storages.items()}
    env.confirm_locked()
    if delete:
        used_by = [d for d, m in rc.mounts.items() if m.storage == name]
        if used_by:
            raise ShellError(
                f"storage {name!r} is mounted at {used_by}; unmount first")
        if rc.storages.pop(name, None) is None:
            raise ShellError(f"no storage named {name!r}")
        save_conf(_filer(env), rc)
        return {"deleted": name}
    if not conf.get("type"):
        raise ShellError("remote.configure needs -type=(s3|local)")
    make_client(conf)  # validate before persisting
    rc.storages[name] = conf
    save_conf(_filer(env), rc)
    return {name: conf.get("type")}


def remote_mount(env: CommandEnv, dir: str = "",
                 remote: str = "") -> dict:
    """remote.mount -dir=/path -remote=storage[/key/prefix]; no args
    lists current mounts (command_remote_mount.go listExistingRemote
    StorageMounts)."""
    rc = load_conf(_filer(env))
    if not dir:
        return {d: f"{m.storage}/{m.remote_path}".rstrip("/")
                for d, m in rc.mounts.items()}
    env.confirm_locked()
    if not remote:
        raise ShellError("remote.mount needs -remote=storage[/prefix]")
    storage, _, prefix = remote.partition("/")
    if storage not in rc.storages:
        raise ShellError(f"storage {storage!r} not configured "
                         f"(known: {sorted(rc.storages)})")
    dir = "/" + dir.strip("/")
    rc.mounts[dir] = RemoteMount(dir=dir, storage=storage,
                                 remote_path=prefix)
    save_conf(_filer(env), rc)
    # make sure the mount dir exists, then pull metadata
    session().post(f"{_filer(env)}{dir}", params={"mkdir": "1"},
                  timeout=30)
    synced = remote_meta_sync(env, dir)
    return {"mounted": dir, **synced}


def remote_mount_buckets(env: CommandEnv, remote: str,
                         bucket_pattern: str = "") -> dict:
    """Mount every top-level bucket/dir of a remote storage under
    /buckets (command_remote_mount_buckets.go). -remote=storagename,
    optional -bucketPattern=glob filter."""
    import fnmatch

    env.confirm_locked()
    rc = load_conf(_filer(env))
    storage, _, _ = remote.partition("/")
    if storage not in rc.storages:
        raise ShellError(f"storage {storage!r} not configured "
                         f"(known: {sorted(rc.storages)})")
    client = make_client(rc.storages[storage])
    mounted = []
    for name in client.list_buckets():
        if bucket_pattern and not fnmatch.fnmatch(name, bucket_pattern):
            continue
        remote_mount(env, dir=f"/buckets/{name}",
                     remote=f"{storage}/{name}")
        mounted.append(name)
    return {"mounted": mounted}


def remote_unmount(env: CommandEnv, dir: str) -> dict:
    """Detach a dir from its storage. Local entries stay; uncached
    remote placeholders under it become dead metadata, so the reference
    requires the dir be cleaned up by the operator — mirrored here."""
    env.confirm_locked()
    rc = load_conf(_filer(env))
    dir = "/" + dir.strip("/")
    if rc.mounts.pop(dir, None) is None:
        raise ShellError(f"{dir} is not mounted")
    save_conf(_filer(env), rc)
    return {"unmounted": dir}


def _mount_for(env: CommandEnv, dir: str):
    rc = load_conf(_filer(env))
    dir = "/" + dir.strip("/")
    mount = find_mount(rc, dir)
    if mount is None:
        raise ShellError(f"{dir} is not under a remote mount")
    storage_conf = rc.storages.get(mount.storage)
    if storage_conf is None:
        raise ShellError(f"storage {mount.storage!r} vanished from conf")
    return dir, mount, make_client(storage_conf)


def remote_meta_sync(env: CommandEnv, dir: str) -> dict:
    """Pull the remote listing into filer metadata-only entries
    (command_remote_meta_sync.go): new/changed objects become (or
    refresh) uncached placeholders; local placeholders whose object
    vanished are removed. Cached or locally-written files keep their
    chunks unless the remote object changed."""
    env.confirm_locked()
    dir, mount, client = _mount_for(env, dir)
    prefix = remote_key_for(mount, dir)
    # '/'-terminated so sibling keys sharing the prefix string (e.g.
    # "photos2/x" for mount prefix "photos") are not swept in
    list_prefix = prefix.rstrip("/") + "/" if prefix else ""
    created = updated = removed = 0
    seen: set[str] = set()
    # one tree walk up front (a directory listing per dir) instead of a
    # meta-GET round trip per remote object — a 100k-object bucket
    # would otherwise issue 100k serial requests
    local: dict[str, dict] = {e["full_path"]: e
                              for e in _walk(env, dir)}
    for re_ in client.traverse(list_prefix):
        if list_prefix and not re_.key.startswith(list_prefix):
            continue
        rel = re_.key[len(list_prefix):]
        if not rel or rel.endswith("/"):
            continue  # bucket directory-marker objects aren't files
        path = f"{dir}/{rel}"
        seen.add(path)
        meta = {"key": re_.key, "size": re_.size, "mtime": re_.mtime,
                "etag": re_.etag}
        ent = local.get(path)
        if ent is None:
            entry = {"full_path": path, "mtime": re_.mtime or None,
                     "extended": {"remote": json.dumps(meta)}}
            session().post(f"{_filer(env)}{path}",
                          params={"meta": "1"},
                          data=json.dumps(entry), timeout=60
                          ).raise_for_status()
            created += 1
            continue
        old = json.loads(ent.get("extended", {}).get("remote", "{}"))
        if old.get("etag") == re_.etag and old.get("size") == re_.size \
                and old.get("etag"):
            continue  # unchanged
        ent.setdefault("extended", {})["remote"] = json.dumps(meta)
        ent["chunks"] = []  # changed upstream: drop the stale copy
        session().post(f"{_filer(env)}{path}", params={"meta": "1"},
                      data=json.dumps(ent), timeout=60).raise_for_status()
        updated += 1
    # prune placeholders whose remote object is gone (uncached only —
    # never delete local bytes on a listing hiccup); the snapshot from
    # before the sync is exact for this: entries created above are in
    # `seen`, and anything else that appeared mid-sync is left alone
    # for the next run
    for path, e in local.items():
        if path in seen or e.get("chunks") or \
                not e.get("extended", {}).get("remote"):
            continue
        # the snapshot is minutes old for big buckets: re-check the
        # LIVE entry so a placeholder that gained chunks (remote.cache
        # or a local write) mid-sync is never deleted with its bytes
        live = session().get(f"{_filer(env)}{path}",
                            params={"meta": "1"}, timeout=30)
        if live.status_code != 200:
            continue
        le = live.json()
        if le.get("chunks") or \
                not le.get("extended", {}).get("remote"):
            continue
        session().delete(f"{_filer(env)}{path}", timeout=30)
        removed += 1
    return {"created": created, "updated": updated, "removed": removed}


def remote_cache(env: CommandEnv, dir: str) -> dict:
    """Materialise every uncached remote file under `dir` into cluster
    chunks (command_remote_cache.go)."""
    env.confirm_locked()
    dir, _, _ = _mount_for(env, dir)
    cached = 0
    for e in _walk(env, dir):
        if e.get("chunks") or not e.get("extended", {}).get("remote"):
            continue
        r = session().post(f"{_filer(env)}{e['full_path']}",
                          params={"cacheRemote": "1"}, timeout=3600)
        if r.status_code != 200:
            raise ShellError(f"cache {e['full_path']}: {r.text}")
        cached += 1
    return {"cached": cached}


def remote_uncache(env: CommandEnv, dir: str) -> dict:
    """Drop local chunk copies of cached remote files under `dir`
    (command_remote_uncache.go)."""
    env.confirm_locked()
    dir, _, _ = _mount_for(env, dir)
    uncached = 0
    for e in _walk(env, dir):
        if not e.get("chunks") or \
                not e.get("extended", {}).get("remote"):
            continue
        r = session().post(f"{_filer(env)}{e['full_path']}",
                          params={"uncacheRemote": "1"}, timeout=600)
        if r.status_code != 200:
            raise ShellError(f"uncache {e['full_path']}: {r.text}")
        uncached += 1
    return {"uncached": uncached}
