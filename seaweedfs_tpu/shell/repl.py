"""Interactive admin shell REPL.

Equivalent of /root/reference/weed/shell/shell_liner.go: a line-based
REPL over the command registry, with the cluster-wide admin lock
(commands.go:78). Commands mirror the reference's ~60-command registry
(weed/shell/commands.go) — the families implemented here are cluster.*,
collection.*, volume.*, ec.*, fs.*, remote.*, mq.*, s3.*.
"""
from __future__ import annotations

import json
import shlex

from . import (commands_cluster, commands_ec, commands_fs, commands_mq,
               commands_remote, commands_s3, commands_volume)
from .env import CommandEnv, ShellError

HELP = """commands:
  lock / unlock                     acquire/release the admin lock
  cluster.check                     cluster health summary
  cluster.ps                        list masters/filers/volume servers
  cluster.raft.ps                   raft peer status
  cluster.raft.add -peer=H:P        add a master to the raft quorum
  cluster.raft.remove -peer=H:P     remove a master from the quorum
  collection.list                   list collections
  collection.delete <name>          delete all volumes of a collection
  volume.list                       list volumes and ec shards
  volume.grow [-count=1] [-collection=] [-replication=]
  volume.vacuum [-threshold=0.3]    compact garbage-heavy volumes
  volume.vacuum.disable/.enable     toggle vacuum cluster-wide
  volume.configure.replication -volumeId=N -replication=xyz
  volume.deleteEmpty [-quietFor=86400] [-force]
  volume.server.leave -server=H     stop a server's heartbeats
  volume.tier.move -toDiskType=ssd [-fromDiskType=] [-collection=]
  volume.balance                    even out volume counts
  volume.fix.replication            re-replicate under-replicated volumes
  volume.copy -volumeId=N -source=H -target=H
  volume.move -volumeId=N -source=H -target=H
  volume.delete -volumeId=N [-server=H]
  volume.mark -volumeId=N -readonly|-writable
  volume.mount/-unmount -volumeId=N -server=H
  volume.evacuate -server=H         move everything off a server
  volume.check.disk -volumeId=N     compare + repair replica divergence
  volume.fsck                       filer chunks vs volume needles
  volume.tier.upload -volumeId=N [-dest=s3.default] [-keepLocalDatFile]
  volume.tier.download -volumeId=N  bring a tiered .dat back to disk
  volume.tier.offload -volumeId=N -remote='{"type":...}' [-maxBps=0]
                                    offload EC shard bytes to cold tier
  volume.tier.recall -volumeId=N [-maxBps=0] [-noDecode]
                                    recall cold shards + decode to volume
  volume.scrub [-volumeId=N] [-collection=C] [-limit=N]
                                    full-read CRC verification
  ec.encode -volumeId=N [-codec=k.m]  erasure-code a volume (wide tier)
  ec.verify -volumeId=N [-sampleMB=4] [-backend=numpy|native|jax]
                                    parity-check spread shards
  ec.rebuild -volumeId=N            rebuild missing shards
  ec.balance                        even out shard counts
  ec.decode -volumeId=N             decode shards back to a volume
  fs.cd <dir> / fs.pwd              shell working directory
  fs.ls [-l] <dir>                  list a filer directory
  fs.cat <file>                     print file contents
  fs.du <dir>                       recursive usage
  fs.tree <dir>                     recursive listing
  fs.mkdir <dir>                    create a directory
  fs.rm [-r] <path>                 delete
  fs.mv <src> <dst>                 rename/move
  fs.meta.save <dir> <out.jsonl>    snapshot metadata
  fs.meta.load <in.jsonl>           restore metadata
  fs.meta.cat <path>                print one entry's stored metadata
  fs.meta.notify <dir>              re-publish events to notifications
  fs.meta.changeVolumeId <dir> -mapping=old:new[,..] [-apply]
  mount.configure -dir=/d -quotaMB=N   per-mount quota (0 clears)
  fs.verify <dir>                   check chunks are readable
  fs.configure [-locationPrefix=/p -collection=C -ttl=1d -readOnly=true
                -replication=001 -maxFileNameLength=N -delete -apply]
  remote.configure [-name=X -type=s3|local ...] [-delete]
  remote.mount [-dir=/d -remote=storage/prefix]
  remote.mount.buckets -remote=storage [-bucketPattern=glob]
  remote.unmount -dir=/d
  remote.meta.sync -dir=/d          pull remote listing into metadata
  remote.cache -dir=/d              materialise remote files locally
  remote.uncache -dir=/d            drop local copies, keep metadata
  s3.configure [-user=U -access_key=AK -secret_key=SK
                -actions=Read,Write -delete -apply]
  s3.bucket.list / s3.bucket.create -name=B
  s3.bucket.delete -name=B [-includeObjects]
  s3.bucket.quota -name=B [-quotaMB=N]   show/set quota (0 clears)
  s3.bucket.quota.enforce           mark over-quota buckets read-only
  s3.clean.uploads [-timeAgo=86400] abort stale multipart uploads
  s3.circuit.breaker [-global='{"writeCount":32}'
                      -bucket=B -bucketConf='{...}' -delete -apply]
  mq.topic.list                     list message-queue topics
  mq.topic.create [-namespace=ns] -topic=T [-partitions=4]
  mq.topic.describe [-namespace=ns] -topic=T
  mq.topic.delete [-namespace=ns] -topic=T
  help / exit
"""


def run_command(env: CommandEnv, line: str) -> object:
    parts = shlex.split(line)
    if not parts:
        return None
    cmd, args = parts[0], parts[1:]
    opts: dict[str, str] = {}
    pos: list[str] = []
    for a in args:
        if a.startswith("-") and "=" in a:
            k, _, v = a[1:].partition("=")
            opts[k] = v
        elif a.startswith("-"):
            opts[a.lstrip("-")] = "true"
        else:
            pos.append(a)

    def arg(i: int, default: str | None = None) -> str:
        if i < len(pos):
            return pos[i]
        if default is not None:
            return default
        raise ShellError(f"{cmd}: missing argument {i + 1}")

    if cmd == "lock":
        env.acquire_lock()
        return "locked"
    if cmd == "unlock":
        env.release_lock()
        return "unlocked"
    # -- cluster / collection ------------------------------------------
    if cmd == "cluster.check":
        return commands_volume.cluster_check(env)
    if cmd == "cluster.ps":
        return commands_cluster.cluster_ps(env)
    if cmd == "cluster.raft.ps":
        return commands_cluster.cluster_raft_ps(env)
    if cmd == "cluster.raft.add":
        return commands_cluster.cluster_raft_change(
            env, opts.get("peer", ""), add=True)
    if cmd == "cluster.raft.remove":
        return commands_cluster.cluster_raft_change(
            env, opts.get("peer", ""), add=False)
    if cmd == "collection.list":
        return commands_volume.collection_list(env)
    if cmd == "collection.delete":
        name = opts.get("collection") or arg(0)
        return commands_volume.collection_delete(env, name)
    # -- volume ---------------------------------------------------------
    if cmd == "volume.list":
        return commands_volume.volume_list(env)
    if cmd == "volume.grow":
        return commands_volume.volume_grow(
            env, int(opts.get("count", "1")), opts.get("collection", ""),
            opts.get("replication", ""), opts.get("disk", ""))
    if cmd == "volume.vacuum":
        return commands_volume.volume_vacuum(
            env, float(opts.get("threshold", 0.3)))
    if cmd == "volume.vacuum.disable":
        return commands_volume.volume_vacuum_toggle(env, disable=True)
    if cmd == "volume.vacuum.enable":
        return commands_volume.volume_vacuum_toggle(env, disable=False)
    if cmd == "volume.configure.replication":
        return commands_volume.volume_configure_replication(
            env, int(opts["volumeId"]), opts.get("replication", ""))
    if cmd == "volume.deleteEmpty":
        return commands_volume.volume_delete_empty(
            env, quiet_for_seconds=int(opts.get("quietFor", "86400")),
            force="force" in opts)
    if cmd == "volume.server.leave":
        return commands_volume.volume_server_leave(env, opts["server"])
    if cmd == "volume.tier.move":
        return commands_volume.volume_tier_move(
            env, opts["toDiskType"], opts.get("collection", ""),
            opts.get("fromDiskType", ""))
    if cmd == "volume.balance":
        return commands_volume.volume_balance(env)
    if cmd == "volume.fix.replication":
        return commands_volume.volume_fix_replication(env)
    if cmd == "volume.copy":
        return commands_volume.volume_copy(
            env, int(opts["volumeId"]), opts["source"], opts["target"])
    if cmd == "volume.move":
        return commands_volume.volume_move(
            env, int(opts["volumeId"]), opts["source"], opts["target"])
    if cmd == "volume.delete":
        return commands_volume.volume_delete(
            env, int(opts["volumeId"]), opts.get("server", ""))
    if cmd == "volume.mark":
        return commands_volume.volume_mark(
            env, int(opts["volumeId"]), writable="writable" in opts)
    if cmd == "volume.mount":
        return commands_volume.volume_mount(
            env, int(opts["volumeId"]), opts["server"])
    if cmd == "volume.unmount":
        return commands_volume.volume_unmount(
            env, int(opts["volumeId"]), opts["server"])
    if cmd == "volume.evacuate":
        return commands_volume.volume_evacuate(env, opts["server"])
    if cmd == "volume.check.disk":
        return commands_volume.volume_check_disk(
            env, int(opts["volumeId"]))
    if cmd == "volume.fsck":
        return commands_volume.volume_fsck(env)
    if cmd == "volume.scrub":
        return commands_volume.volume_scrub(
            env, int(opts.get("volumeId", 0)),
            opts.get("collection", ""), int(opts.get("limit", 0)))
    if cmd == "volume.tier.upload":
        return commands_volume.volume_tier_upload(
            env, int(opts["volumeId"]), opts.get("dest", "s3.default"),
            keep_local="keepLocalDatFile" in opts)
    if cmd == "volume.tier.download":
        return commands_volume.volume_tier_download(
            env, int(opts["volumeId"]))
    if cmd == "volume.tier.offload":
        from ..remote_storage.client import parse_remote_spec

        return commands_volume.volume_tier_offload(
            env, int(opts["volumeId"]),
            parse_remote_spec(opts["remote"]),
            max_bps=float(opts.get("maxBps", 0) or 0))
    if cmd == "volume.tier.recall":
        return commands_volume.volume_tier_recall(
            env, int(opts["volumeId"]),
            max_bps=float(opts.get("maxBps", 0) or 0),
            decode="noDecode" not in opts)
    # -- erasure coding -------------------------------------------------
    if cmd == "ec.encode":
        return commands_ec.ec_encode(env, int(opts["volumeId"]),
                                     opts.get("collection", ""),
                                     codec=opts.get("codec", ""))
    if cmd == "ec.rebuild":
        return commands_ec.ec_rebuild(env, int(opts["volumeId"]),
                                      opts.get("collection", ""))
    if cmd == "ec.balance":
        return commands_ec.ec_balance(env, opts.get("collection", ""))
    if cmd == "ec.decode":
        return commands_ec.ec_decode(env, int(opts["volumeId"]),
                                     opts.get("collection", ""))
    if cmd == "ec.verify":
        return commands_ec.ec_verify(
            env, int(opts["volumeId"]),
            sample_mb=int(opts.get("sampleMB", 4)),
            backend=opts.get("backend", "numpy"))
    # -- filesystem -----------------------------------------------------
    def rarg(i: int, default: str | None = None) -> str:
        # fs paths resolve against the fs.cd working directory
        return env.resolve(arg(i, default))

    if cmd == "fs.cd":
        return commands_fs.fs_cd(env, arg(0, "/"))
    if cmd == "fs.pwd":
        return commands_fs.fs_pwd(env)
    if cmd == "fs.ls":
        return commands_fs.fs_ls(env, rarg(0, "."), long="l" in opts)
    if cmd == "fs.cat":
        return commands_fs.fs_cat(env, rarg(0)).decode(errors="replace")
    if cmd == "fs.du":
        return commands_fs.fs_du(env, rarg(0, "."))
    if cmd == "fs.tree":
        return "\n".join(commands_fs.fs_tree(env, rarg(0, ".")))
    if cmd == "fs.mkdir":
        return commands_fs.fs_mkdir(env, rarg(0))
    if cmd == "fs.rm":
        commands_fs.fs_rm(env, rarg(0), recursive="r" in opts)
        return "removed"
    if cmd == "fs.mv":
        commands_fs.fs_mv(env, rarg(0), rarg(1))
        return "moved"
    if cmd == "fs.meta.save":
        n = commands_fs.fs_meta_save(env, rarg(0, "."),
                                     arg(1, "meta.jsonl"))
        return f"saved {n} entries"
    if cmd == "fs.meta.load":
        n = commands_fs.fs_meta_load(env, arg(0))
        return f"loaded {n} entries"
    if cmd == "fs.meta.cat":
        return commands_fs.fs_meta_cat(env, rarg(0))
    if cmd == "fs.meta.notify":
        return commands_fs.fs_meta_notify(env, rarg(0, "."))
    if cmd == "fs.meta.changeVolumeId":
        return commands_fs.fs_meta_change_volume_id(
            env, rarg(0, "."), opts.get("mapping", ""),
            apply="apply" in opts or "force" in opts)
    if cmd == "fs.verify":
        return commands_fs.fs_verify(env, rarg(0, "."))
    if cmd == "mount.configure":
        return commands_fs.mount_configure(
            env, opts.get("dir", ""),
            int(opts.get("quotaMB", "-1")))
    if cmd == "fs.configure":
        return commands_fs.fs_configure(
            env, opts.pop("locationPrefix", ""),
            delete=opts.pop("delete", "") == "true",
            apply=opts.pop("apply", "") == "true", **opts)
    # -- remote storage -------------------------------------------------
    if cmd == "remote.configure":
        conf = {k: v for k, v in opts.items()
                if k not in ("name", "delete")}
        return commands_remote.remote_configure(
            env, opts.get("name", ""), delete="delete" in opts, **conf)
    if cmd == "remote.mount":
        return commands_remote.remote_mount(
            env, opts.get("dir", ""), opts.get("remote", ""))
    if cmd == "remote.mount.buckets":
        return commands_remote.remote_mount_buckets(
            env, opts.get("remote", ""),
            opts.get("bucketPattern", ""))
    if cmd == "remote.unmount":
        return commands_remote.remote_unmount(env, opts["dir"])
    if cmd == "remote.meta.sync":
        return commands_remote.remote_meta_sync(env, opts["dir"])
    if cmd == "remote.cache":
        return commands_remote.remote_cache(env, opts["dir"])
    if cmd == "remote.uncache":
        return commands_remote.remote_uncache(env, opts["dir"])
    # -- s3 gateway state -----------------------------------------------
    if cmd == "s3.configure":
        return commands_s3.s3_configure(
            env, user=opts.get("user", ""),
            access_key=opts.get("access_key", ""),
            secret_key=opts.get("secret_key", ""),
            actions=opts.get("actions", ""),
            delete=opts.get("delete", "") == "true",
            apply=opts.get("apply", "") == "true")
    if cmd == "s3.bucket.list":
        return commands_s3.s3_bucket_list(env)
    if cmd == "s3.bucket.create":
        return commands_s3.s3_bucket_create(
            env, opts.get("name") or arg(0, ""))
    if cmd == "s3.bucket.delete":
        return commands_s3.s3_bucket_delete(
            env, opts.get("name") or arg(0, ""),
            include_objects=opts.get("includeObjects", "") == "true")
    if cmd == "s3.bucket.quota":
        return commands_s3.s3_bucket_quota(
            env, opts.get("name") or arg(0, ""),
            quota_mb=int(opts.get("quotaMB", "-1")))
    if cmd == "s3.bucket.quota.enforce":
        return commands_s3.s3_bucket_quota_enforce(env)
    if cmd == "s3.clean.uploads":
        return commands_s3.s3_clean_uploads(
            env, time_ago_seconds=int(opts.get("timeAgo", "86400")))
    if cmd == "s3.circuit.breaker":
        return commands_s3.s3_circuit_breaker(
            env, global_conf=opts.get("global", ""),
            bucket=opts.get("bucket", ""),
            bucket_conf=opts.get("bucketConf", ""),
            delete=opts.get("delete", "") == "true",
            apply=opts.get("apply", "") == "true")
    # -- message queue --------------------------------------------------
    if cmd == "mq.topic.list":
        return commands_mq.mq_topic_list(env)
    if cmd == "mq.topic.create":
        ns = opts.get("namespace", "default")
        name = opts.get("topic", "")
        if not name:  # positional `ns/topic` or bare `topic`
            p = arg(0)
            if "/" in p:
                ns, _, name = p.partition("/")
            else:
                name = p
        return commands_mq.mq_topic_create(
            env, ns, name, int(opts.get("partitions", "4")))
    if cmd == "mq.topic.describe":
        return commands_mq.mq_topic_describe(
            env, opts.get("namespace", "default"), opts["topic"])
    if cmd == "mq.topic.delete":
        return commands_mq.mq_topic_delete(
            env, opts.get("namespace", "default"), opts["topic"])
    if cmd == "help":
        return HELP
    raise ShellError(f"unknown command {cmd!r} (try `help`)")


def run_shell(master_url: str, filer_url: str = "") -> int:
    env = CommandEnv(master_url, filer_url=filer_url)
    print(f"seaweedfs-tpu shell connected to {master_url}")
    print("type `help` for commands, `exit` to quit")
    try:
        while True:
            try:
                line = input("> ").strip()
            except (EOFError, KeyboardInterrupt):
                print()
                return 0
            if line in ("exit", "quit"):
                return 0
            if not line:
                continue
            try:
                out = run_command(env, line)
                if out is not None:
                    print(out if isinstance(out, str)
                          else json.dumps(out, indent=2, default=str))
            except ShellError as e:
                print(f"error: {e}")
            except Exception as e:
                print(f"error: {type(e).__name__}: {e}")
    finally:
        # exiting with the cluster-wide admin lock held would wedge
        # other operators until the lock TTL expires
        env.close()
