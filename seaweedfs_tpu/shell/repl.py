"""Interactive admin shell REPL.

Equivalent of /root/reference/weed/shell/shell_liner.go: a line-based
REPL over the command registry, with the cluster-wide admin lock
(commands.go:78).
"""
from __future__ import annotations

import json
import shlex

from . import commands_ec, commands_volume
from .env import CommandEnv, ShellError

HELP = """commands:
  lock / unlock                     acquire/release the admin lock
  cluster.check                     cluster health summary
  volume.list                       list volumes and ec shards
  volume.vacuum [-threshold=0.3]    compact garbage-heavy volumes
  volume.balance                    even out volume counts
  volume.fix.replication            re-replicate under-replicated volumes
  ec.encode -volumeId=N             erasure-code a volume
  ec.rebuild -volumeId=N            rebuild missing shards
  ec.balance                        even out shard counts
  ec.decode -volumeId=N             decode shards back to a volume
  help / exit
"""


def run_command(env: CommandEnv, line: str) -> object:
    parts = shlex.split(line)
    if not parts:
        return None
    cmd, args = parts[0], parts[1:]
    opts = {}
    for a in args:
        if a.startswith("-") and "=" in a:
            k, _, v = a[1:].partition("=")
            opts[k] = v

    if cmd == "lock":
        env.acquire_lock()
        return "locked"
    if cmd == "unlock":
        env.release_lock()
        return "unlocked"
    if cmd == "cluster.check":
        return commands_volume.cluster_check(env)
    if cmd == "volume.list":
        return commands_volume.volume_list(env)
    if cmd == "volume.vacuum":
        return commands_volume.volume_vacuum(
            env, float(opts.get("threshold", 0.3)))
    if cmd == "volume.balance":
        return commands_volume.volume_balance(env)
    if cmd == "volume.fix.replication":
        return commands_volume.volume_fix_replication(env)
    if cmd == "ec.encode":
        return commands_ec.ec_encode(env, int(opts["volumeId"]),
                                     opts.get("collection", ""))
    if cmd == "ec.rebuild":
        return commands_ec.ec_rebuild(env, int(opts["volumeId"]),
                                      opts.get("collection", ""))
    if cmd == "ec.balance":
        return commands_ec.ec_balance(env, opts.get("collection", ""))
    if cmd == "ec.decode":
        return commands_ec.ec_decode(env, int(opts["volumeId"]),
                                     opts.get("collection", ""))
    if cmd == "help":
        return HELP
    raise ShellError(f"unknown command {cmd!r} (try `help`)")


def run_shell(master_url: str, filer_url: str = "") -> int:
    env = CommandEnv(master_url, filer_url=filer_url)
    print(f"seaweedfs-tpu shell connected to {master_url}")
    print("type `help` for commands, `exit` to quit")
    try:
        while True:
            try:
                line = input("> ").strip()
            except (EOFError, KeyboardInterrupt):
                print()
                return 0
            if line in ("exit", "quit"):
                return 0
            if not line:
                continue
            try:
                out = run_command(env, line)
                if out is not None:
                    print(out if isinstance(out, str)
                          else json.dumps(out, indent=2, default=str))
            except ShellError as e:
                print(f"error: {e}")
            except Exception as e:
                print(f"error: {type(e).__name__}: {e}")
    finally:
        # exiting with the cluster-wide admin lock held would wedge
        # other operators until the lock TTL expires
        env.close()
