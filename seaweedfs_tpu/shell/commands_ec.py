"""EC orchestration shell commands.

Equivalents of /root/reference/weed/shell/command_ec_encode.go (freeze ->
generate -> spread -> delete original, :95-192), command_ec_rebuild.go
(:58-229), command_ec_balance.go + command_ec_common.go:111-170, and
command_ec_decode.go.
"""
from __future__ import annotations

from collections import defaultdict

from ..ec import geometry as geo
from .env import CommandEnv, ShellError


def ec_encode(env: CommandEnv, volume_id: int,
              collection: str = "", codec: str = "") -> dict:
    """Mark readonly, generate the shard set on the source server,
    spread shards across servers by free slots, then delete the
    original volume everywhere (command_ec_encode.go:95-192).
    `codec` selects the code family — "k.m" (e.g. "28.4") a wide RS
    tier, "lrc-k.l.g" (e.g. "lrc-12.3.2") a locally-repairable code;
    empty falls back to the process `-ec.code` default, then
    RS(10,4)."""
    env.confirm_locked()
    if not codec:
        from ..ec.backend import default_code_spec

        codec = default_code_spec()
    k, m = geo.parse_codec(codec)
    total = k + m
    sources = env.volume_locations(volume_id)
    if not sources:
        raise ShellError(f"volume {volume_id} not found")
    if not collection:
        collection = env.volume_collection(volume_id)
    for url in sources:
        env.vs_post(url, "/admin/mark_readonly", {"volume": volume_id})
    source = sources[0]
    env.vs_post(source, "/admin/ec/generate",
                {"volume": volume_id, "collection": collection,
                 "codec": codec})
    placement = spread_ec_shards(env, volume_id, collection, source,
                                 total=total)
    # delete original replicas now that shards are mounted
    for url in sources:
        env.vs_post(url, "/admin/delete_volume", {"volume": volume_id})
    env.wait_for_ec_registration(volume_id, total)
    return {sid: url for sid, url in placement.items()}


def spread_ec_shards(env: CommandEnv, vid: int, collection: str,
                     source: str,
                     total: int = geo.TOTAL_SHARDS) -> dict[int, str]:
    """Allocate shards to servers rack-aware (command_ec_encode.go:145
    spreadEcShards): round-robin across RACKS first, nodes inside a
    rack by free capacity, so a rack loss costs the fewest shards of
    any one volume — the same spreading contract repair preserves
    (master.placement)."""
    from ..master import placement as pl

    nodes = env.data_nodes()
    if not nodes:
        raise ShellError("no data nodes")
    order = pl.ec_spread_order(nodes, total)
    placement: dict[int, str] = {}
    per_node: dict[str, list[int]] = defaultdict(list)
    for sid in range(total):
        node = order[sid]
        placement[sid] = node["url"]
        per_node[node["url"]].append(sid)
    for url, sids in per_node.items():
        if url != source:
            env.vs_post(url, "/admin/ec/copy",
                        {"volume": vid, "collection": collection,
                         "shard_ids": sids, "source": source,
                         "copy_ecx": True, "copy_ecj": True})
        env.vs_post(url, "/admin/ec/mount",
                    {"volume": vid, "collection": collection,
                     "shard_ids": sids})
    # source keeps only its assigned shards
    source_keeps = set(per_node.get(source, []))
    drop = [sid for sid in range(total)
            if sid not in source_keeps]
    if drop:
        env.vs_post(source, "/admin/ec/delete",
                    {"volume": vid, "shard_ids": drop})
    return placement


def ec_rebuild(env: CommandEnv, volume_id: int,
               collection: str = "", max_bps: float = 0,
               partial: bool = True) -> dict:
    """Rebuild missing shards of an EC volume
    (command_ec_rebuild.go:58-229).

    The rebuilder is chosen by master.placement.select_ec_rebuilder —
    a node holding no shard of the volume, in the rack with the fewest
    of its shards — because the rebuilt shard lives where it is
    rebuilt.  When ``partial`` (default) and <= m shards are missing,
    the rebuilder's /admin/ec/rebuild_partial streams only the k shard
    ranges reconstruction needs (mode="partial" byte accounting)
    instead of borrowing every surviving shard file; the classic
    full-stripe path remains as fallback (mode="full").  ``max_bps``
    shapes all transfers against each node's repair bucket."""
    from ..master import placement as pl

    env.confirm_locked()
    reg_collection, code, locations = env.ec_full_info(volume_id)
    k, m = code.k, code.m
    if not collection:
        collection = reg_collection
    present = set(locations)
    missing = [sid for sid in range(k + m)
               if sid not in present]
    if not missing:
        return {"rebuilt": []}
    if not code.recoverable(sorted(present)):
        raise ShellError(
            f"volume {volume_id}: shards {sorted(present)} cannot "
            f"rebuild {code.spec}")
    nodes = env.data_nodes()
    node, violations = pl.select_ec_rebuilder(nodes, volume_id,
                                              locations)
    if node is None:  # every node full: fall back to emptiest
        node = max(nodes,
                   key=lambda n: n["max_volumes"] - len(n["volumes"]))
    rebuilder = node["url"]
    if partial and len(missing) <= m:
        try:
            out = env.vs_post(rebuilder, "/admin/ec/rebuild_partial",
                              {"volume": volume_id,
                               "collection": collection,
                               "shard_ids": missing,
                               "max_bps": max_bps})
            env.wait_for_ec_registration(volume_id, k + m)
            return {"rebuilt": out["rebuilt_shards"],
                    "rebuilder": rebuilder, "mode": "partial",
                    "rebuilt_bytes": out.get("rebuilt_bytes", 0),
                    "read_bytes": out.get("read_bytes", 0),
                    "placement_violations": violations}
        except ShellError:
            pass  # stale holder map / peer down: full path below
    local = set()
    for sid, urls in locations.items():
        if rebuilder in urls:
            local.add(sid)
    # copy ALL present-elsewhere shards to the rebuilder so the local
    # rebuild regenerates exactly the globally-missing ones
    # (prepareDataToRecover, command_ec_rebuild.go:193)
    borrowed = []
    for sid in sorted(present - local):
        src = locations[sid][0]
        env.vs_post(rebuilder, "/admin/ec/copy",
                    {"volume": volume_id, "collection": collection,
                     "shard_ids": [sid], "source": src,
                     "copy_ecx": not local and not borrowed,
                     "copy_ecj": False, "max_bps": max_bps,
                     "repair": True})
        borrowed.append(sid)
    out = env.vs_post(rebuilder, "/admin/ec/rebuild",
                      {"volume": volume_id})
    rebuilt = out["rebuilt_shards"]
    env.vs_post(rebuilder, "/admin/ec/mount",
                {"volume": volume_id, "collection": collection,
                 "shard_ids": rebuilt})
    if borrowed:
        env.vs_post(rebuilder, "/admin/ec/delete",
                    {"volume": volume_id, "shard_ids": borrowed})
    env.wait_for_ec_registration(volume_id, k + m)
    return {"rebuilt": rebuilt, "rebuilder": rebuilder, "mode": "full",
            "rebuilt_bytes": out.get("rebuilt_bytes", 0),
            "placement_violations": violations}


def ec_balance(env: CommandEnv, collection: str = "") -> list[dict]:
    """Even out shard counts across servers (command_ec_balance.go):
    move shards from overloaded to underloaded nodes."""
    env.confirm_locked()
    nodes = env.data_nodes()
    if not nodes:
        return []
    shard_count = {n["url"]: sum(bin(b).count("1")
                                 for b in n["ec_volumes"].values())
                   for n in nodes}
    holdings: dict[str, list[tuple[int, int]]] = defaultdict(list)
    for n in nodes:
        for vid_s, bits in n["ec_volumes"].items():
            for sid in range(geo.MAX_SHARD_COUNT):
                if bits >> sid & 1:
                    holdings[n["url"]].append((int(vid_s), sid))
    total = sum(shard_count.values())
    target = -(-total // len(nodes))  # ceil
    moves = []
    under = [u for u in shard_count if shard_count[u] < target]
    for src in sorted(shard_count, key=shard_count.get, reverse=True):
        while shard_count[src] > target and under:
            dst = under[0]
            vid, sid = holdings[src].pop()
            col = collection or env.ec_collection(vid)
            env.vs_post(dst, "/admin/ec/copy",
                        {"volume": vid, "collection": col,
                         "shard_ids": [sid], "source": src,
                         "copy_ecx": True, "copy_ecj": True})
            env.vs_post(dst, "/admin/ec/mount",
                        {"volume": vid, "collection": col,
                         "shard_ids": [sid]})
            env.vs_post(src, "/admin/ec/delete",
                        {"volume": vid, "shard_ids": [sid]})
            shard_count[src] -= 1
            shard_count[dst] += 1
            moves.append({"volume": vid, "shard": sid,
                          "from": src, "to": dst})
            if shard_count[dst] >= target:
                under.pop(0)
            if not under:
                break
    return moves


def ec_decode(env: CommandEnv, volume_id: int,
              collection: str = "") -> dict:
    """Collect all shards onto one server and decode back to a normal
    volume (command_ec_decode.go)."""
    env.confirm_locked()
    reg_collection, (k, m), locations = env.ec_info(volume_id)
    if not collection:
        collection = reg_collection
    if not locations:
        raise ShellError(f"ec volume {volume_id} not found")
    present = set(locations)
    if len(present) < k:
        raise ShellError(f"only {len(present)} shards survive")
    # choose the server with most shards as the collector
    count_by_server: dict[str, int] = defaultdict(int)
    for sid, urls in locations.items():
        for u in urls:
            count_by_server[u] += 1
    collector = max(count_by_server, key=count_by_server.get)
    have = {sid for sid, urls in locations.items() if collector in urls}
    need = sorted((present - have))[:k + m]
    for sid in need:
        src = locations[sid][0]
        env.vs_post(collector, "/admin/ec/copy",
                    {"volume": volume_id, "collection": collection,
                     "shard_ids": [sid], "source": src,
                     "copy_ecx": False, "copy_ecj": True})
    env.vs_post(collector, "/admin/ec/mount",
                {"volume": volume_id, "collection": collection,
                 "shard_ids": need})
    env.vs_post(collector, "/admin/ec/to_volume",
                {"volume": volume_id, "collection": collection})
    # drop shards elsewhere
    for sid, urls in locations.items():
        for u in urls:
            if u != collector:
                env.vs_post(u, "/admin/ec/delete",
                            {"volume": volume_id, "shard_ids": [sid]})
    return {"volume": volume_id, "server": collector}


def ec_verify(env: CommandEnv, volume_id: int, sample_mb: int = 4,
              backend: str = "numpy", quarantine: bool = True) -> dict:
    """Parity-check an EC volume's spread shards: fetch the same
    aligned prefix of every shard from its holder and run the codec
    backend's RS verify (batched GF(256) matmul — `-backend=jax` puts
    the check on the TPU). Any aligned prefix of all 14 shards is
    itself a valid codeword set, so `sample_mb` bounds IO while still
    exercising every shard end-to-end; 0 means full shards.

    With ``quarantine`` (default), a parity mismatch that pinpoints to
    exactly one corrupt shard deletes that shard on its holder and
    enqueues an ec rebuild on the master repair queue instead of only
    reporting the failure."""
    import numpy as np

    from ..ec.backend import ReedSolomon
    from ..rpc.httpclient import session

    _col, code, locs = env.ec_full_info(volume_id)
    k, m = code.k, code.m
    missing = [sid for sid in range(k + m) if sid not in locs]
    if missing:
        return {"volume": volume_id, "verified": False,
                "missing_shards": missing}
    sample = sample_mb << 20
    shards = []
    for sid in range(k + m):
        url = locs[sid][0]
        params = {"volume": str(volume_id), "shard": str(sid),
                  "offset": "0"}
        if sample:
            params["size"] = str(sample)
        resp = session().get(f"http://{url}/admin/ec/shard_read",
                            params=params, timeout=600)
        if resp.status_code != 200:
            return {"volume": volume_id, "verified": False,
                    "missing_shards": [sid],
                    "error": f"shard {sid} read from {url}: "
                             f"{resp.status_code}"}
        shards.append(np.frombuffer(resp.content, dtype=np.uint8))
    n = min(len(s) for s in shards)
    stack = np.stack([s[:n] for s in shards])
    rs = ReedSolomon(k, m, backend=backend, code=code)
    ok = bool(rs.verify(stack))
    out = {"volume": volume_id, "verified": ok,
           "bytes_checked_per_shard": int(n), "backend": backend}
    if not ok and quarantine:
        rows = {sid: stack[sid] for sid in range(k + m)}
        corrupt = _locate_corrupt_shard(rs, rows)
        out["corrupt_shard"] = corrupt
        if corrupt is not None:
            # the shard is regenerable from the other k+m-1: delete it
            # (a merely-unmounted file would poison a later local
            # rebuild on the same server) and let the repair queue
            # rebuild it through the codec router
            from .commands_volume import enqueue_repair

            env.vs_post(locs[corrupt][0], "/admin/ec/delete",
                        {"volume": volume_id, "shard_ids": [corrupt]})
            out["quarantined"] = True
            out["repair_enqueued"] = enqueue_repair(
                env, volume_id, "ec", "scrub", collection=_col)
    return out


def _locate_corrupt_shard(rs, rows: dict) -> int | None:
    """Pinpoint a single corrupt shard by reconstruction: decode the
    codeword from k clean shards and the one id whose fetched bytes
    disagree with the reconstruction is the corruption.  When the
    first basis (lowest k ids) contains the corrupt shard the decode
    disagrees in many places; retry excluding one basis member at a
    time.  None = not attributable to exactly one shard (multiple
    corruptions or systematic failure) — caller reports only."""
    import numpy as np

    total = rs.k + rs.m

    def mismatches(basis: list[int]) -> list[int] | None:
        try:
            recon = rs.reconstruct({sid: rows[sid] for sid in basis},
                                   missing=[i for i in range(total)
                                            if i not in basis])
        except ValueError:
            # dependent basis (possible for structured codes): this
            # basis can't decode — inconclusive, try the next
            return None
        return [i for i in range(total) if i not in basis and
                not np.array_equal(recon[i], rows[i])]

    basis = list(range(rs.k))
    bad = mismatches(basis)
    if bad is not None and len(bad) == 1:
        return bad[0]
    if not bad:
        return None
    for c in basis:
        alt = [i for i in range(total) if i != c][:rs.k]
        if mismatches(alt) == [c]:
            return c
    return None
