"""`scaffold` — print starter config templates.

Equivalent of /root/reference/weed/command/scaffold.go +
command/scaffold/*.toml: `weed scaffold -config=filer|master|security|
replication|notification|s3|shell` prints an annotated template the
operator copies into place. The reference's TOML templates carry
comments; these are JSON (what the servers and the filer KV actually
consume), so the annotations live in "//" keys — every template is
valid JSON that the consumers accept as-is (unknown keys are ignored).
"""
from __future__ import annotations

import json

TEMPLATES: dict[str, dict] = {
    "filer": {
        "//": "filer store selection: pass as `filer -store=... "
              "-store.path=...`. Stores: memory (ephemeral), sqlite "
              "(single file), leveldb (weedkv LSM directory). Per-path "
              "rules live IN the filer: `fs.configure` in the shell.",
        "store": "leveldb",
        "store.path": "/var/lib/seaweedfs/filerdb",
    },
    "master": {
        "//": "master flags, incl. periodic maintenance scripts the "
              "leader runs (master.toml [master.maintenance] "
              "equivalent)",
        "volumeSizeLimitMB": 30720,
        "defaultReplication": "000",
        "admin.scripts":
            "volume.vacuum; volume.fix.replication; ec.rebuild",
        "admin.scriptInterval": 1800,
    },
    "security": {
        "//": "shared JWT secret: volume servers verify write tokens "
              "minted by the master (security.toml jwt.signing "
              "equivalent). Empty disables auth. The https section "
              "(security.toml [https.*] equivalent) enables TLS on "
              "control/gateway listeners when passed via the global "
              "-security flag; ca + client_auth turns on mutual TLS.",
        "jwt.secret": "change-me",
        "https": {"cert": "", "key": "", "ca": "",
                  "client_auth": False},
    },
    "replication": {
        "//": "sink for `filer.replicate` (replication.toml "
              "equivalent)",
        "sink": "s3:https://s3.example.com,backup-bucket,prefix/",
        "alternatives": ["local:/mnt/backup",
                         "filer:http://other:8888,/"],
    },
    "notification": {
        "//": "metadata-event fanout targets (notification.toml "
              "equivalent)",
        "enabled": ["log"],
        "queues": {"log": {}, "memory": {}},
    },
    "s3": {
        "//": "identities: store at filer KV key s3/identities (or "
              "pass -config); circuit-breaker limits: filer KV key "
              "s3/circuit_breaker",
        "identities": [
            {"name": "admin",
             "credentials": [{"accessKey": "AK", "secretKey": "SK"}],
             "actions": ["Admin", "Read", "Write", "List", "Tagging"]},
        ],
        "circuit_breaker": {
            "global": {"readCount": 1024, "writeCount": 512,
                       "writeBytes": 1073741824},
            "buckets": {},
        },
    },
    "shell": {
        "//": "defaults for the admin shell (shell.toml equivalent)",
        "master": "http://127.0.0.1:9333",
        "filer": "http://127.0.0.1:8888",
    },
}


def scaffold(config: str) -> str:
    if config not in TEMPLATES:
        raise KeyError(
            f"unknown config {config!r}; have {sorted(TEMPLATES)}")
    return json.dumps(TEMPLATES[config], indent=2) + "\n"
