"""Native (C++) kernels for the host-side data plane.

The reference's performance-critical native code lives in vendored
dependencies (SURVEY.md section 2.1): klauspost/reedsolomon SIMD
GF(256) and hardware CRC32C. Here they are in-tree C++
(gf256_codec.cc), built by build.py and bound via ctypes — no
pybind11 needed for a flat C ABI.

`load()` builds on demand and returns the configured ctypes handle;
`available()` is a cheap probe. All consumers (ops.codec_native, the
storage scrub path) go through here.
"""
from __future__ import annotations

import ctypes
import os
import shutil
import threading

import numpy as np

_lib = None
_load_lock = threading.Lock()


def available() -> bool:
    from . import build as _b
    return os.path.exists(_b.LIB) or shutil.which("g++") is not None


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _load_lock:  # concurrent first loads must not race the build
        if _lib is not None:
            return _lib
        return _load_locked()


def _load_locked() -> ctypes.CDLL:
    global _lib
    from . import build as _b
    path = _b.build(verbose=False)
    lib = ctypes.CDLL(path)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.gf256_coded_matmul.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int64, u8p]
    lib.gf256_coded_matmul.restype = None
    lib.gf256_mul_xor.argtypes = [ctypes.c_uint8, u8p, u8p,
                                  ctypes.c_int64]
    lib.gf256_mul_xor.restype = None
    lib.crc32c_update.argtypes = [ctypes.c_uint32, u8p, ctypes.c_int64]
    lib.crc32c_update.restype = ctypes.c_uint32
    lib.crc32c_batch.argtypes = [u8p, ctypes.c_int, ctypes.c_int64, u32p]
    lib.crc32c_batch.restype = None
    lib.native_simd_level.argtypes = []
    lib.native_simd_level.restype = ctypes.c_int
    try:
        lib.gf256_scheduled_matmul.argtypes = [
            ctypes.POINTER(ctypes.c_int32), u8p, ctypes.c_int,
            ctypes.c_int64, u8p]
        lib.gf256_scheduled_matmul.restype = None
    except AttributeError:  # stale prebuilt .so without the kernel
        pass
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.dat_scan.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), i64p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, i64p]
    lib.dat_scan.restype = ctypes.c_int64
    lib.ec_encode_file.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int]
    lib.ec_encode_file.restype = ctypes.c_int64
    _lib = lib
    return lib


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def coded_matmul(coef: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """out[i] = XOR_j coef[i,j]*shards[j] over GF(256) — C++ kernel."""
    lib = load()
    coef = np.ascontiguousarray(coef, dtype=np.uint8)
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    m, k = coef.shape
    assert shards.shape[0] == k, (coef.shape, shards.shape)
    n = shards.shape[1]
    out = np.empty((m, n), dtype=np.uint8)
    lib.gf256_coded_matmul(_u8p(coef), m, k, _u8p(shards),
                           ctypes.c_int64(n), _u8p(out))
    return out


def has_scheduled() -> bool:
    """Whether the loaded library carries the scheduled XOR kernel
    (False only for a stale prebuilt .so with no compiler to refresh)."""
    return hasattr(load(), "gf256_scheduled_matmul")


def scheduled_matmul(prog: np.ndarray, shards: np.ndarray,
                     m: int) -> np.ndarray:
    """Run a flattened ops/schedule program (int32, schedule.flatten
    layout) over (k, n) uint8 shards -> (m, n) uint8. Bit-identical
    with coded_matmul for the program's coefficient matrix."""
    lib = load()
    prog = np.ascontiguousarray(prog, dtype=np.int32)
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    k, n = shards.shape
    out = np.empty((m, n), dtype=np.uint8)
    lib.gf256_scheduled_matmul(
        prog.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _u8p(shards), k, ctypes.c_int64(n), _u8p(out))
    return out


def crc32c(data: bytes | np.ndarray, initial: int = 0) -> int:
    lib = load()
    buf = np.frombuffer(data, dtype=np.uint8) \
        if isinstance(data, (bytes, bytearray, memoryview)) \
        else np.ascontiguousarray(data, dtype=np.uint8)
    return int(lib.crc32c_update(ctypes.c_uint32(initial), _u8p(buf),
                                 ctypes.c_int64(buf.size)))


def crc32c_batch(rows: np.ndarray) -> np.ndarray:
    """(m, n) rows -> (m,) uint32 CRCs, one C call."""
    lib = load()
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    m, n = rows.shape
    out = np.empty(m, dtype=np.uint32)
    lib.crc32c_batch(_u8p(rows), m, ctypes.c_int64(n),
                     out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


def simd_level() -> int:
    """0=scalar, 1=SSSE3, 2=SSSE3+SSE4.2, 3=AVX2."""
    return int(load().native_simd_level())


def ec_encode_file(dat_path: str, shard_paths: list[str],
                   coef: np.ndarray, k: int, m: int,
                   large_block: int, small_block: int,
                   chunk: int = 2 << 20, n_threads: int = 4) -> None:
    """Whole-file EC encode with no GIL anywhere: worker threads do
    pread -> GF(256) parity -> pwrite per stripe row (the
    ec_encoder.go:198-235 loop as one native call). Shard bytes are
    identical to every other backend (same ops/rs_matrix coefficients)."""
    lib = load()
    coef = np.ascontiguousarray(coef, dtype=np.uint8)
    assert coef.shape == (m, k), (coef.shape, k, m)
    arr = (ctypes.c_char_p * len(shard_paths))(
        *[p.encode() for p in shard_paths])
    rc = lib.ec_encode_file(
        dat_path.encode(), arr, len(shard_paths), _u8p(coef), k, m,
        ctypes.c_int64(large_block), ctypes.c_int64(small_block),
        ctypes.c_int64(chunk), n_threads)
    if rc != 0:
        raise IOError(f"native ec_encode_file: {os.strerror(-rc)}")


def dat_scan(dat: np.ndarray, start: int, version: int
             ) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Walk a .dat image natively -> (ids u64, byte offsets i64,
    signed sizes i32, end_offset). end_offset < len(dat) means the
    tail is torn after the last whole record."""
    lib = load()
    dat = np.ascontiguousarray(dat, dtype=np.uint8)
    # smallest record is an empty v2 tombstone: 16+4 padded -> 24
    cap = max(1, dat.size // 24)
    ids = np.empty(cap, dtype=np.uint64)
    offsets = np.empty(cap, dtype=np.int64)
    sizes = np.empty(cap, dtype=np.int32)
    end = ctypes.c_int64(0)
    n = lib.dat_scan(
        _u8p(dat), ctypes.c_int64(dat.size), ctypes.c_int64(start),
        ctypes.c_int(version),
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(cap), ctypes.byref(end))
    return ids[:n], offsets[:n], sizes[:n], int(end.value)
