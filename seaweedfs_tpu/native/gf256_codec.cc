// Native GF(256) Reed-Solomon kernels + CRC32C.
//
// The reference gets these from vendored native code:
// klauspost/reedsolomon's AVX2/SSSE3 assembly (used at
// /root/reference/weed/storage/erasure_coding/ec_encoder.go:202) and the
// hardware Castagnoli CRC in hash/crc32 (weed/storage/needle/crc.go:12).
// This file re-implements both for the host-side CPU path of the TPU
// framework: the same split-nibble PSHUFB trick for GF(256) multiply
// (16-entry low/high tables per coefficient, 16 bytes per instruction)
// with a portable table fallback, and CRC32C via SSE4.2 crc32
// instructions with a slicing-by-8 software fallback.
//
// Field: poly 0x11d, generator 2 — matches seaweedfs_tpu/ops/gf256.py
// and klauspost, so shard bytes interoperate.
//
// Build: seaweedfs_tpu/native/build.py -> libseaweed_native.so (ctypes).

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#define HAVE_AVX2 1
#endif
#if defined(__SSSE3__)
#include <tmmintrin.h>
#define HAVE_SSSE3 1
#endif
#if defined(__SSE4_2__)
#include <nmmintrin.h>
#define HAVE_SSE42 1
#endif

namespace {

constexpr unsigned kPoly = 0x11d;

uint8_t MUL[256][256];
// Per-coefficient split-nibble tables: product of c with (low nibble)
// and with (high nibble << 4). c*b = LOW[c][b&15] ^ HIGH[c][b>>4].
alignas(16) uint8_t LOW[256][16];
alignas(16) uint8_t HIGH[256][16];

uint8_t gf_mul_slow(unsigned a, unsigned b) {
  unsigned r = 0;
  while (b) {
    if (b & 1) r ^= a;
    a <<= 1;
    if (a & 0x100) a ^= kPoly;
    b >>= 1;
  }
  return static_cast<uint8_t>(r);
}

struct TableInit {
  TableInit() {
    for (unsigned a = 0; a < 256; ++a)
      for (unsigned b = 0; b < 256; ++b) MUL[a][b] = gf_mul_slow(a, b);
    for (unsigned c = 0; c < 256; ++c)
      for (unsigned n = 0; n < 16; ++n) {
        LOW[c][n] = MUL[c][n];
        HIGH[c][n] = MUL[c][n << 4];
      }
  }
} table_init;

// dst ^= c * src over n bytes.
void mul_xor_row(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n) {
  if (c == 0) return;
  size_t i = 0;
  if (c == 1) {
    for (; i + 8 <= n; i += 8) {
      uint64_t a, b;
      std::memcpy(&a, dst + i, 8);
      std::memcpy(&b, src + i, 8);
      a ^= b;
      std::memcpy(dst + i, &a, 8);
    }
    for (; i < n; ++i) dst[i] ^= src[i];
    return;
  }
#if HAVE_AVX2
  {
    const __m256i lo_tbl = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(LOW[c])));
    const __m256i hi_tbl = _mm256_broadcastsi128_si256(
        _mm_load_si128(reinterpret_cast<const __m128i*>(HIGH[c])));
    const __m256i nib = _mm256_set1_epi8(0x0f);
    for (; i + 32 <= n; i += 32) {
      __m256i s =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(src + i));
      __m256i d =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(dst + i));
      __m256i lo = _mm256_and_si256(s, nib);
      __m256i hi = _mm256_and_si256(_mm256_srli_epi64(s, 4), nib);
      __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(lo_tbl, lo),
                                      _mm256_shuffle_epi8(hi_tbl, hi));
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(dst + i),
                          _mm256_xor_si256(d, prod));
    }
  }
#endif
#if HAVE_SSSE3
  const __m128i lo_tbl =
      _mm_load_si128(reinterpret_cast<const __m128i*>(LOW[c]));
  const __m128i hi_tbl =
      _mm_load_si128(reinterpret_cast<const __m128i*>(HIGH[c]));
  const __m128i nib = _mm_set1_epi8(0x0f);
  for (; i + 16 <= n; i += 16) {
    __m128i s =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i));
    __m128i d =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(dst + i));
    __m128i lo = _mm_and_si128(s, nib);
    __m128i hi = _mm_and_si128(_mm_srli_epi64(s, 4), nib);
    __m128i prod = _mm_xor_si128(_mm_shuffle_epi8(lo_tbl, lo),
                                 _mm_shuffle_epi8(hi_tbl, hi));
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm_xor_si128(d, prod));
  }
#endif
  const uint8_t* row = MUL[c];
  for (; i < n; ++i) dst[i] ^= row[src[i]];
}

// 8x8 bit-matrix transpose (Hacker's Delight 7-3). With byte i of the
// little-endian word as matrix row i, byte s of the result packs bit s
// of every input byte — the bytes<->bit-planes pivot of the scheduled
// XOR kernel below.
uint64_t bit_transpose8(uint64_t x) {
  uint64_t t;
  t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAULL;
  x = x ^ t ^ (t << 7);
  t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCULL;
  x = x ^ t ^ (t << 14);
  t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ULL;
  x = x ^ t ^ (t << 28);
  return x;
}

// ---- CRC32C (Castagnoli, reflected poly 0x82f63b78) ------------------
uint32_t CRC_TBL[8][256];

struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0x82f63b78u ^ (c >> 1) : c >> 1;
      CRC_TBL[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int t = 1; t < 8; ++t)
        CRC_TBL[t][i] =
            CRC_TBL[t - 1][i] >> 8 ^ CRC_TBL[0][CRC_TBL[t - 1][i] & 0xff];
  }
} crc_init;

}  // namespace

extern "C" {

// out[i,:] = XOR_j coef[i,j] * shards[j,:]  over GF(256).
// coef: m*k row-major; shards: k*n row-major; out: m*n row-major
// (zeroed here).
void gf256_coded_matmul(const uint8_t* coef, int m, int k,
                        const uint8_t* shards, int64_t n, uint8_t* out) {
  std::memset(out, 0, static_cast<size_t>(m) * n);
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < k; ++j)
      mul_xor_row(coef[i * k + j], shards + static_cast<size_t>(j) * n,
                  out + static_cast<size_t>(i) * n, n);
}

// dst ^= c * src (exposed for incremental/streaming encode).
void gf256_mul_xor(uint8_t c, const uint8_t* src, uint8_t* dst,
                   int64_t n) {
  mul_xor_row(c, src, dst, static_cast<size_t>(n));
}

// Walk a .dat image record-by-record — the hot loop of offline .idx
// reconstruction (`weed fix`, storage/volume.py rebuild_index) and the
// torn-tail integrity check, natively. Header layout per
// storage/needle.py: cookie u32be, id u64be, size u32be (signed;
// <=0 marks a tombstone); record disk size = 16 + size + 4 checksum
// (+8 timestamp for v3), padded to the next multiple of 8 with at
// least one pad byte.
//
// Emits per-record (id, byte offset, signed size) into caller arrays
// of capacity `cap`; returns the record count and stores the byte
// offset after the last whole record in *end_off (a caller seeing
// *end_off < dat_size knows the tail is torn and truncates there).
int64_t dat_scan(const uint8_t* dat, int64_t dat_size, int64_t start,
                 int version, uint64_t* ids, int64_t* offsets,
                 int32_t* sizes, int64_t cap, int64_t* end_off) {
  int64_t off = start, count = 0;
  const int64_t extra = (version >= 3) ? 8 : 0;
  while (off + 16 <= dat_size && count < cap) {
    uint64_t nid = 0;
    for (int b = 0; b < 8; ++b) nid = (nid << 8) | dat[off + 4 + b];
    uint32_t szu = (static_cast<uint32_t>(dat[off + 12]) << 24) |
                   (static_cast<uint32_t>(dat[off + 13]) << 16) |
                   (static_cast<uint32_t>(dat[off + 14]) << 8) |
                   static_cast<uint32_t>(dat[off + 15]);
    int32_t nsize = static_cast<int32_t>(szu);
    int64_t body = (nsize < 0) ? 0 : nsize;
    int64_t total = 16 + body + 4 + extra;
    int64_t disk = total + (8 - (total % 8));  // pad is always 1..8
    if (off + disk > dat_size) break;
    ids[count] = nid;
    offsets[count] = off;
    sizes[count] = nsize;
    ++count;
    off += disk;
  }
  *end_off = off;
  return count;
}

uint32_t crc32c_update(uint32_t crc, const uint8_t* data, int64_t len) {
  crc = ~crc;
  size_t n = static_cast<size_t>(len);
  size_t i = 0;
#if HAVE_SSE42
  for (; i + 8 <= n; i += 8) {
    uint64_t v;
    std::memcpy(&v, data + i, 8);
    crc = static_cast<uint32_t>(_mm_crc32_u64(crc, v));
  }
  for (; i < n; ++i) crc = _mm_crc32_u8(crc, data[i]);
#else
  for (; i + 8 <= n; i += 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, data + i, 4);
    std::memcpy(&hi, data + i + 4, 4);
    lo ^= crc;
    crc = CRC_TBL[7][lo & 0xff] ^ CRC_TBL[6][(lo >> 8) & 0xff] ^
          CRC_TBL[5][(lo >> 16) & 0xff] ^ CRC_TBL[4][lo >> 24] ^
          CRC_TBL[3][hi & 0xff] ^ CRC_TBL[2][(hi >> 8) & 0xff] ^
          CRC_TBL[1][(hi >> 16) & 0xff] ^ CRC_TBL[0][hi >> 24];
  }
  for (; i < n; ++i)
    crc = crc >> 8 ^ CRC_TBL[0][(crc ^ data[i]) & 0xff];
#endif
  return ~crc;
}

// Batched CRC32C: m rows of n bytes each -> m crcs (the TPU scrub
// pipeline's host-side check, BASELINE.json batched-scrub config).
void crc32c_batch(const uint8_t* rows, int m, int64_t n, uint32_t* out) {
  for (int i = 0; i < m; ++i)
    out[i] = crc32c_update(0, rows + static_cast<size_t>(i) * n, n);
}

// Scheduled bit-plane XOR program (ops/schedule.py `flatten` layout):
// prog = [n_in, n_out, n_ops, (dst, a, b) * n_ops, out_var * n_out]
// with n_in = 8k input bit-planes (bit s of shard row j is var 8j+s)
// and n_out = 8m output planes. Columns are processed in cache-sized
// chunks: bytes pivot to packed bit-planes (bit_transpose8), the op
// list runs as word-wide XORs over plane rows, planes pivot back to
// bytes. Bit-identical with gf256_coded_matmul by construction — the
// schedule rewrites the XOR program, never the shard byte layout.
void gf256_scheduled_matmul(const int32_t* prog, const uint8_t* shards,
                            int k, int64_t n, uint8_t* out) {
  const int n_in = prog[0], n_out = prog[1], n_ops = prog[2];
  const int32_t* ops = prog + 3;
  const int32_t* outs = ops + 3 * static_cast<int64_t>(n_ops);
  const int m = n_out / 8;
  constexpr int64_t kChunk = 4096;       // column bytes per pass
  constexpr int64_t kPlane = kChunk / 8; // packed plane bytes
  constexpr int64_t kWords = kPlane / 8;
  std::vector<uint64_t> pool(
      static_cast<size_t>(n_in + n_ops) * kWords);
  uint8_t* cells = reinterpret_cast<uint8_t*>(pool.data());
  for (int64_t c0 = 0; c0 < n; c0 += kChunk) {
    const int64_t w = std::min(kChunk, n - c0);
    const int64_t wcells = (w + 7) / 8;
    for (int j = 0; j < k; ++j) {
      const uint8_t* src = shards + static_cast<size_t>(j) * n + c0;
      uint8_t* pl = cells + static_cast<size_t>(8 * j) * kPlane;
      for (int64_t i = 0; i < wcells; ++i) {
        uint64_t x = 0;
        const int64_t rem = w - i * 8;
        std::memcpy(&x, src + i * 8,
                    rem >= 8 ? 8 : static_cast<size_t>(rem));
        x = bit_transpose8(x);
        for (int s = 0; s < 8; ++s)
          pl[static_cast<size_t>(s) * kPlane + i] =
              static_cast<uint8_t>(x >> (8 * s));
      }
    }
    for (int o = 0; o < n_ops; ++o) {
      const int32_t* op = ops + 3 * o;
      uint64_t* d = pool.data() + static_cast<size_t>(op[0]) * kWords;
      const uint64_t* a =
          pool.data() + static_cast<size_t>(op[1]) * kWords;
      const uint64_t* b =
          pool.data() + static_cast<size_t>(op[2]) * kWords;
      for (int64_t i = 0; i < kWords; ++i) d[i] = a[i] ^ b[i];
    }
    for (int i = 0; i < m; ++i) {
      const int32_t* ov = outs + 8 * i;
      uint8_t* dst = out + static_cast<size_t>(i) * n + c0;
      for (int64_t j = 0; j < wcells; ++j) {
        uint64_t x = 0;
        for (int s = 0; s < 8; ++s) {
          const int32_t v = ov[s];
          const uint8_t byte =
              v < 0 ? 0 : cells[static_cast<size_t>(v) * kPlane + j];
          x |= static_cast<uint64_t>(byte) << (8 * s);
        }
        x = bit_transpose8(x);
        const int64_t rem = w - j * 8;
        std::memcpy(dst + j * 8, &x,
                    rem >= 8 ? 8 : static_cast<size_t>(rem));
      }
    }
  }
}

int native_simd_level() {
#if HAVE_AVX2
  return 3;
#elif HAVE_SSE42 && HAVE_SSSE3
  return 2;
#elif HAVE_SSSE3
  return 1;
#else
  return 0;
#endif
}

// ---------------------------------------------------------------------------
// Whole-file EC encode — the reference's encodeDatFile hot loop
// (ec_encoder.go:198-235) as one native call. The Python loop (read ->
// gather -> codec -> write) kept a third of the disk idle even with a
// writer thread pool: producer-side numpy copies and ctypes dispatch
// share the GIL with the writers. Here worker threads claim stripe
// rows off an atomic counter and do pread -> GF(256) parity -> pwrite
// at computed offsets with no interpreter anywhere — shard offsets are
// deterministic (row r of `block` bytes lands at r*block in every
// shard file), so workers need no ordering or shared buffers.
//
// Layout identical to ec/geometry.py row_layout: large rows of
// `large_block` while remaining > k*large_block, then small rows of
// `small_block`, the last zero-padded. coef is the m*k parity matrix
// from ops/rs_matrix (klauspost-compatible), so shard bytes are
// byte-identical with every other backend.
// Returns 0 or -errno.
int64_t ec_encode_file(const char* dat_path,
                       const char* const* shard_paths, int n_shards,
                       const uint8_t* coef, int k, int m,
                       int64_t large_block, int64_t small_block,
                       int64_t chunk, int n_threads) {
  if (n_shards != k + m || k <= 0 || m <= 0) return -EINVAL;
  int dat_fd = open(dat_path, O_RDONLY);
  if (dat_fd < 0) return -errno;
  struct stat st;
  if (fstat(dat_fd, &st) != 0) {
    int e = errno;
    close(dat_fd);
    return -e;
  }
  const int64_t dat_size = st.st_size;
  // row layout (must match geometry.row_layout exactly)
  int64_t remaining = dat_size, n_large = 0, n_small = 0;
  while (remaining > large_block * k) {
    n_large++;
    remaining -= large_block * k;
  }
  while (remaining > 0) {
    n_small++;
    remaining -= small_block * k;
  }
  const int64_t shard_size = n_large * large_block + n_small * small_block;
  std::vector<int> fds(n_shards, -1);
  int rc = 0;
  for (int i = 0; i < n_shards && rc == 0; i++) {
    fds[i] = open(shard_paths[i], O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fds[i] < 0 || ftruncate(fds[i], shard_size) != 0) rc = -errno;
  }
  struct Row {
    int64_t dat_start;   // byte offset of the row's first data block
    int64_t shard_off;   // byte offset of the row inside every shard
    int64_t block;
  };
  std::vector<Row> rows;
  rows.reserve((size_t)(n_large + n_small));
  for (int64_t r = 0; r < n_large; r++)
    rows.push_back({r * large_block * k, r * large_block, large_block});
  const int64_t small0 = n_large * large_block * k;
  for (int64_t r = 0; r < n_small; r++)
    rows.push_back({small0 + r * small_block * k,
                    n_large * large_block + r * small_block, small_block});

  if (chunk <= 0) chunk = 2 << 20;
  chunk = std::min<int64_t>(chunk, 4 << 20);  // bounds worker buffers
  std::atomic<size_t> next{0};
  std::atomic<int> err{0};

  auto worker = [&]() {
    const int64_t wmax =
        std::min<int64_t>(chunk, std::max(large_block, small_block));
    std::vector<uint8_t> data((size_t)k * wmax);
    std::vector<uint8_t> parity((size_t)m * wmax);
    while (!err.load(std::memory_order_relaxed)) {
      size_t ri = next.fetch_add(1);
      if (ri >= rows.size()) return;
      const Row& row = rows[ri];
      for (int64_t c0 = 0; c0 < row.block; c0 += wmax) {
        const int64_t w = std::min(wmax, row.block - c0);
        for (int i = 0; i < k; i++) {
          uint8_t* buf = data.data() + (size_t)i * w;
          const int64_t off = row.dat_start + i * row.block + c0;
          const int64_t avail =
              std::max<int64_t>(0, std::min(w, dat_size - off));
          int64_t got = 0;
          while (got < avail) {
            ssize_t r2 = pread(dat_fd, buf + got, avail - got, off + got);
            if (r2 <= 0) {
              err.store(errno ? errno : EIO);
              return;
            }
            got += r2;
          }
          if (avail < w) memset(buf + avail, 0, w - avail);
        }
        memset(parity.data(), 0, (size_t)m * w);
        for (int i = 0; i < m; i++)
          for (int j = 0; j < k; j++)
            mul_xor_row(coef[i * k + j], data.data() + (size_t)j * w,
                        parity.data() + (size_t)i * w, w);
        for (int i = 0; i < n_shards; i++) {
          const uint8_t* src = i < k
                                   ? data.data() + (size_t)i * w
                                   : parity.data() + (size_t)(i - k) * w;
          if (pwrite(fds[i], src, w, row.shard_off + c0) != w) {
            err.store(errno ? errno : EIO);
            return;
          }
        }
      }
    }
  };

  if (rc == 0) {
    if (n_threads < 1) n_threads = 4;
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; t++) threads.emplace_back(worker);
    for (auto& th : threads) th.join();
    if (err.load()) rc = -err.load();
  }
  close(dat_fd);
  for (int fd : fds)
    if (fd >= 0) close(fd);
  return rc;
}

}  // extern "C"
