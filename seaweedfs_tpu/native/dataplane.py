"""ctypes binding for the native HTTP data plane (dataplane.cc).

The C++ front owns the volume server's public port: GET/HEAD and plain
POST by fid are served natively (reference hot path
volume_server_handlers_read.go:31 / volume_write.go:144); everything
else is relayed to the Python aiohttp backend. While a volume is
attached, the native library is the single authority for its needle
map and append offsets — Python's Volume delegates mutations here and
reads counters through NativeNeedleMap.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..storage import idx as idxmod
from ..storage import types as t

_lib = None
_lib_mode: str | None = None  # sanitize mode the cached _lib was built in
_load_lock = threading.Lock()

# role ids, mirroring ROLE_* in dataplane.cc
ROLE_VOLUME = 0
ROLE_S3 = 1
ROLE_FILER = 2


def available() -> bool:
    from . import build as _b
    import shutil

    return os.path.exists(_b.dp_lib_path()) or \
        shutil.which("g++") is not None


def sanitizer_env(mode: str, log_dir: str) -> dict[str, str]:
    """Environment for a *new* python process that will dlopen the
    sanitized data plane. The interpreter itself is uninstrumented, so
    the sanitizer runtime must be LD_PRELOADed before python starts —
    setting these in an already-running process does nothing, which is
    why the sanitize suite spawns subprocesses.

    halt_on_error=1 turns any report into a nonzero exit (the test
    gate); detect_leaks=0 because CPython itself "leaks" arenas at
    exit and would drown real reports; log_path redirects reports to
    files the caller can assert empty.
    """
    from . import build as _b

    if mode not in _b.SANITIZE_FLAGS:
        raise ValueError(f"unknown sanitize mode {mode!r}")
    rt = {"asan": "libasan.so", "tsan": "libtsan.so"}[mode]
    preload = subprocess.run(
        ["gcc", f"-print-file-name={rt}"],
        capture_output=True, text=True, check=True).stdout.strip()
    log_path = os.path.join(log_dir, f"{mode}-report")
    common = f"halt_on_error=1:log_path={log_path}:exitcode=66"
    env = {
        _b.SANITIZE_ENV: mode,
        "LD_PRELOAD": preload,
    }
    if mode == "asan":
        env["ASAN_OPTIONS"] = common + ":detect_leaks=0"
    else:
        # ignore_noninstrumented_modules: uninstrumented CPython
        # extension modules (e.g. _socket) look racy to TSan because
        # their atomics read as plain accesses; races are still
        # reported whenever any frame lands in the instrumented
        # data plane, which is the surface under test
        env["TSAN_OPTIONS"] = (common + ":report_signal_unsafe=0"
                               ":ignore_noninstrumented_modules=1")
    return env


def _load() -> ctypes.CDLL:
    global _lib, _lib_mode
    from . import build as _b

    mode = _b.sanitize_mode()
    if _lib is not None and _lib_mode == mode:
        return _lib
    with _load_lock:
        if _lib is not None:
            if _lib_mode != mode:
                # a sanitized .so cannot be safely swapped into a
                # process that already holds the plain one (and the
                # sanitizer runtime must be preloaded at exec time)
                raise RuntimeError(
                    f"data plane already loaded in mode "
                    f"{_lib_mode or 'plain'!r}; start a new process "
                    f"for {_b.SANITIZE_ENV}={mode}")
            return _lib

        lib = ctypes.CDLL(_b.build_dataplane(verbose=False))
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.dp_start.argtypes = [ctypes.c_uint16, ctypes.c_uint16,
                                 ctypes.c_int, u16p, ctypes.c_char_p]
        lib.dp_start.restype = ctypes.c_int
        lib.dp_stop.argtypes = []
        lib.dp_stop.restype = None
        lib.dp_config.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.dp_config.restype = None
        lib.dp_faults.argtypes = [ctypes.c_double, ctypes.c_double,
                                  ctypes.c_double, ctypes.c_double,
                                  ctypes.c_uint64]
        lib.dp_faults.restype = None
        lib.dp_set_peers.argtypes = [ctypes.c_uint32, ctypes.c_char_p]
        lib.dp_set_peers.restype = ctypes.c_int
        lib.dp_peers_stale.argtypes = [ctypes.c_uint32]
        lib.dp_peers_stale.restype = ctypes.c_int
        lib.dp_hmac_sha256.argtypes = [u8p, ctypes.c_int64, u8p,
                                       ctypes.c_int64, u8p]
        lib.dp_hmac_sha256.restype = None
        lib.dp_attach.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int64,
            ctypes.c_uint64, u64p, i64p, i32p, ctypes.c_int64]
        lib.dp_attach.restype = ctypes.c_int
        lib.dp_detach.argtypes = [ctypes.c_uint32, i64p, u64p]
        lib.dp_detach.restype = ctypes.c_int
        lib.dp_set_readonly.argtypes = [ctypes.c_uint32, ctypes.c_int]
        lib.dp_set_readonly.restype = ctypes.c_int
        lib.dp_set_replicas.argtypes = [ctypes.c_uint32, ctypes.c_int]
        lib.dp_set_replicas.restype = ctypes.c_int
        lib.dp_append.argtypes = [ctypes.c_uint32, u8p, ctypes.c_int64,
                                  ctypes.c_uint64, ctypes.c_int32,
                                  ctypes.c_uint64]
        lib.dp_append.restype = ctypes.c_int64
        lib.dp_delete.argtypes = [ctypes.c_uint32, ctypes.c_uint64, u8p,
                                  ctypes.c_int64, ctypes.c_uint64]
        lib.dp_delete.restype = ctypes.c_int64
        lib.dp_lookup.argtypes = [ctypes.c_uint32, ctypes.c_uint64, i64p,
                                  i32p]
        lib.dp_lookup.restype = ctypes.c_int
        lib.dp_lookup_any.argtypes = [ctypes.c_uint32, ctypes.c_uint64,
                                      i64p, i32p]
        lib.dp_lookup_any.restype = ctypes.c_int
        lib.dp_stats.argtypes = [ctypes.c_uint32, i64p]
        lib.dp_stats.restype = ctypes.c_int
        lib.dp_export.argtypes = [ctypes.c_uint32, u64p, i64p, i32p,
                                  ctypes.c_int64]
        lib.dp_export.restype = ctypes.c_int64
        lib.dp_http_stats.argtypes = [i64p]
        lib.dp_http_stats.restype = None
        try:
            # missing from prebuilt .so files older than the front
            # counters — front_stats() then reports None
            lib.dp_front_stats.argtypes = [i64p]
            lib.dp_front_stats.restype = None
        except AttributeError:
            pass
        lib.dp_bench.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                 ctypes.c_int, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int64,
                                 ctypes.c_int64, ctypes.c_int, i64p, i64p]
        lib.dp_bench.restype = ctypes.c_int64
        lib.dp_bench_raw.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                     u8p, i64p, ctypes.c_int64,
                                     ctypes.c_int, i64p, i64p]
        lib.dp_bench_raw.restype = ctypes.c_int64
        # -- native S3 front ------------------------------------------
        lib.dp_s3_start.argtypes = [ctypes.c_uint16, ctypes.c_uint16,
                                    ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_uint16),
                                    ctypes.c_char_p, ctypes.c_int]
        lib.dp_s3_start.restype = ctypes.c_int
        lib.dp_s3_stop.argtypes = []
        lib.dp_s3_stop.restype = None
        lib.dp_s3_set_identities.argtypes = [ctypes.c_char_p]
        lib.dp_s3_set_identities.restype = None
        lib.dp_s3_set_buckets.argtypes = [ctypes.c_char_p]
        lib.dp_s3_set_buckets.restype = None
        lib.dp_s3_push_fids.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_int]
        lib.dp_s3_push_fids.restype = ctypes.c_int
        lib.dp_s3_pool_level.argtypes = [ctypes.c_char_p]
        lib.dp_s3_pool_level.restype = ctypes.c_int
        lib.dp_s3_cache_put.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_int64, ctypes.c_char_p,
                                        ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_int64]
        lib.dp_s3_cache_put.restype = ctypes.c_int
        lib.dp_s3_invalidate.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dp_s3_invalidate.restype = None
        lib.dp_s3_stats.argtypes = [i64p]
        lib.dp_s3_stats.restype = None
        lib.dp_md5_hex.argtypes = [u8p, ctypes.c_int64, ctypes.c_char_p]
        lib.dp_md5_hex.restype = None
        try:
            # group-commit pipeline — absent from prebuilt .so files
            # older than the write pipeline; callers degrade to the
            # buffered contract
            lib.dp_set_commit.argtypes = [ctypes.c_int, ctypes.c_double,
                                          ctypes.c_longlong]
            lib.dp_set_commit.restype = ctypes.c_int
            lib.dp_commit_stats.argtypes = [i64p]
            lib.dp_commit_stats.restype = None
        except AttributeError:
            pass
        try:
            # role-addressed fronts (filer front + per-role faults and
            # counters) — absent from prebuilt .so files older than the
            # filer front; the callers degrade gracefully
            lib.dp_role_faults.argtypes = [ctypes.c_int, ctypes.c_double,
                                           ctypes.c_double, ctypes.c_double,
                                           ctypes.c_double, ctypes.c_uint64]
            lib.dp_role_faults.restype = None
            lib.dp_role_front_stats.argtypes = [ctypes.c_int, i64p]
            lib.dp_role_front_stats.restype = None
            lib.dp_s3_upload_mark.argtypes = [ctypes.c_char_p,
                                              ctypes.c_char_p, ctypes.c_int]
            lib.dp_s3_upload_mark.restype = None
            lib.dp_filer_start.argtypes = [ctypes.c_uint16, ctypes.c_uint16,
                                           ctypes.c_int,
                                           ctypes.POINTER(ctypes.c_uint16),
                                           ctypes.c_char_p, ctypes.c_int]
            lib.dp_filer_start.restype = ctypes.c_int
            lib.dp_filer_stop.argtypes = []
            lib.dp_filer_stop.restype = None
            lib.dp_filer_cache_put.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_int64]
            lib.dp_filer_cache_put.restype = ctypes.c_int
            lib.dp_filer_invalidate.argtypes = [ctypes.c_char_p,
                                                ctypes.c_int]
            lib.dp_filer_invalidate.restype = None
            lib.dp_filer_push_fids.argtypes = [ctypes.c_char_p,
                                               ctypes.c_int]
            lib.dp_filer_push_fids.restype = ctypes.c_int
            lib.dp_filer_pool_level.argtypes = []
            lib.dp_filer_pool_level.restype = ctypes.c_int
            lib.dp_filer_set_writes.argtypes = [ctypes.c_int]
            lib.dp_filer_set_writes.restype = None
            lib.dp_filer_stats.argtypes = [i64p]
            lib.dp_filer_stats.restype = None
        except AttributeError:
            pass
        _lib = lib
        _lib_mode = mode
        return lib


def md5_hex(data: bytes) -> str:
    """Test hook for the in-tree C++ MD5 (the S3 front's ETag hash)."""
    lib = _load()
    out = ctypes.create_string_buffer(33)
    lib.dp_md5_hex(_u8p(data), len(data), out)
    return out.value.decode()


def bench(host: str, port: int, mode: str, fids: list[str],
          payload_size: int, concurrency: int,
          auths: list[str] | None = None
          ) -> tuple[float, np.ndarray, int]:
    """Native load generator (no server needed on this side): drives
    GETs/POSTs over keep-alive connections from C++ worker threads.
    `auths`: optional per-fid bearer tokens for jwt-guarded rows.
    -> (wall seconds, per-request latency seconds — negative entries
    are failures, error count)."""
    lib = _load()
    blob = "\n".join(fids).encode()
    ablob = "\n".join(auths).encode() if auths else None
    lats = np.empty(len(fids), np.int64)
    errs = ctypes.c_int64(0)
    wall = lib.dp_bench(
        host.encode(), port, 1 if mode == "post" else 0, blob, ablob,
        len(fids), payload_size, concurrency,
        lats.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(errs))
    if wall < 0:
        raise OSError(-wall, os.strerror(-wall))
    return wall / 1e9, lats.astype(np.float64) / 1e9, int(errs.value)


def bench_raw(host: str, port: int, requests: list[bytes],
              concurrency: int) -> tuple[float, np.ndarray, int]:
    """Replay prebuilt HTTP request bytes (already signed/framed by the
    caller) over native keep-alive connections — the S3/filer gateway
    benchmark client. -> (wall seconds, latency seconds with failures
    negative, error count)."""
    lib = _load()
    blob = b"".join(requests)
    offs = np.zeros(len(requests) + 1, np.int64)
    np.cumsum([len(r) for r in requests], out=offs[1:])
    lats = np.empty(len(requests), np.int64)
    errs = ctypes.c_int64(0)
    wall = lib.dp_bench_raw(
        host.encode(), port, _u8p(blob),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(requests), concurrency,
        lats.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(errs))
    if wall < 0:
        raise OSError(-wall, os.strerror(-wall))
    return wall / 1e9, lats.astype(np.float64) / 1e9, int(errs.value)


def _u8p(b: bytes):
    return ctypes.cast(ctypes.c_char_p(b), ctypes.POINTER(ctypes.c_uint8))


class DataPlane:
    """One native front server per process (the C library is a
    singleton); `attach` hands a volume's hot path to it."""

    def __init__(self) -> None:
        self._lib = _load()
        self.port = 0
        self.backend_port = 0

    # -- lifecycle ------------------------------------------------------
    def start(self, listen_port: int, backend_port: int,
              workers: int = 2, listen_ip: str = "") -> int:
        """listen_ip '' = all interfaces; otherwise the -ip bind
        address, honored exactly like the Python listener."""
        actual = ctypes.c_uint16(0)
        rc = self._lib.dp_start(listen_port, backend_port, workers,
                                ctypes.byref(actual), listen_ip.encode())
        if rc != 0:
            raise OSError(-rc, f"dp_start failed: {os.strerror(-rc)}")
        self.port = int(actual.value)
        self.backend_port = backend_port
        return self.port

    def stop(self) -> None:
        self._lib.dp_stop()

    def config(self, jwt_required: bool, secret: str = "") -> None:
        """jwt_required + the HS256 secret so the front verifies write
        tokens in-process instead of relaying every guarded write."""
        self._lib.dp_config(1 if jwt_required else 0, secret.encode())

    def set_commit(self, durability: str, max_delay: float,
                   max_bytes: int) -> None:
        """Push the group-commit ack contract (-commit.*) to every
        native front in this process: 'buffered' acks after pwrite
        (today's semantics), 'batch' acks from the fsync-completion
        callback, 'sync' fsyncs inline per write. No-op on libraries
        that predate the write pipeline (buffered contract holds)."""
        fn = getattr(self._lib, "dp_set_commit", None)
        if fn is None:
            return
        modes = {"buffered": 0, "batch": 1, "sync": 2}
        if durability not in modes:
            raise ValueError(f"unknown durability {durability!r}")
        fn(modes[durability], max_delay, max_bytes)

    def commit_stats(self) -> dict | None:
        """Group-commit counters (monotonic except queue_depth) for
        /debug/commit and the /metrics merge; None when the loaded
        library predates the write pipeline."""
        fn = getattr(self._lib, "dp_commit_stats", None)
        if fn is None:
            return None
        out = (ctypes.c_int64 * 6)()
        fn(out)
        return {"batches": int(out[0]), "fsyncs": int(out[1]),
                "writes": int(out[2]), "bytes": int(out[3]),
                "fsync_seconds": int(out[4]) / 1e9,
                "queue_depth": int(out[5])}

    def set_faults(self, read_err: float = 0.0, write_err: float = 0.0,
                   read_delay: float = 0.0, write_delay: float = 0.0,
                   seed: int = 0) -> None:
        """Mirror this service's share of the -fault.spec into the
        native front: error probability and fixed delay per op class
        (read = GET/HEAD, write = POST/PUT/DELETE), with a seeded RNG
        for deterministic chaos runs. All zeros disables the gate."""
        self._lib.dp_faults(read_err, write_err, read_delay, write_delay,
                            seed & 0xFFFFFFFFFFFFFFFF)

    # -- volumes --------------------------------------------------------
    def attach(self, vid: int, dat_path: str, idx_path: str, version: int,
               read_only: bool, has_replicas: bool, tail: int,
               last_append_ns: int) -> None:
        """Load the .idx log and hand the volume to the native plane.
        The index replay (same semantics as load_needle_map) happens in
        C from the raw entry arrays."""
        if os.path.exists(idx_path):
            arr = idxmod.read_index(idx_path)
            keys = np.ascontiguousarray(arr["key"], dtype=np.uint64)
            offs = np.ascontiguousarray(
                arr["offset"].astype(np.int64) * t.NEEDLE_PADDING)
            sizes = np.ascontiguousarray(
                arr["size"].astype(np.uint32).view(np.int32))
        else:
            keys = np.empty(0, np.uint64)
            offs = np.empty(0, np.int64)
            sizes = np.empty(0, np.int32)
        rc = self._lib.dp_attach(
            vid, dat_path.encode(), idx_path.encode(), version,
            t.OFFSET_SIZE, 1 if read_only else 0, 1 if has_replicas else 0,
            tail, last_append_ns,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(keys))
        if rc != 0:
            raise OSError(-rc, f"dp_attach({vid}): {os.strerror(-rc)}")

    def detach(self, vid: int) -> tuple[int, int]:
        """-> (dat tail offset, last_append_ns) at the detach point."""
        tail = ctypes.c_int64(0)
        ns = ctypes.c_uint64(0)
        rc = self._lib.dp_detach(vid, ctypes.byref(tail), ctypes.byref(ns))
        if rc != 0:
            raise OSError(-rc, f"dp_detach({vid}): {os.strerror(-rc)}")
        return int(tail.value), int(ns.value)

    def set_readonly(self, vid: int, ro: bool) -> None:
        self._lib.dp_set_readonly(vid, 1 if ro else 0)

    def set_replicas(self, vid: int, has: bool) -> None:
        self._lib.dp_set_replicas(vid, 1 if has else 0)

    def set_peers(self, vid: int, peers: list[str]) -> None:
        """Push the replica peer list ("host:port", self excluded) so
        the front fans primary writes out natively; clears the stale
        flag. Raises KeyError when the volume is not attached."""
        rc = self._lib.dp_set_peers(vid, ",".join(peers).encode())
        if rc != 0:
            raise KeyError(f"volume {vid} not attached")

    def peers_stale(self, vid: int) -> bool:
        """True when a fan-out failure invalidated the peer list (writes
        relay to Python until set_peers pushes a fresh one)."""
        rc = self._lib.dp_peers_stale(vid)
        if rc < 0:
            raise KeyError(f"volume {vid} not attached")
        return rc == 1

    def hmac_sha256(self, key: bytes, msg: bytes) -> bytes:
        """Test hook: the native HMAC-SHA256 (JWT verification core)."""
        out = (ctypes.c_uint8 * 32)()
        self._lib.dp_hmac_sha256(_u8p(key), len(key), _u8p(msg), len(msg),
                                 out)
        return bytes(out)

    # -- needle ops (Python-side delegation) ----------------------------
    def append(self, vid: int, rec: bytes, key: int, size: int,
               append_ns: int) -> int:
        off = self._lib.dp_append(vid, _u8p(rec), len(rec), key, size,
                                  append_ns)
        if off < 0:
            raise IOError(f"native append vid={vid}: {os.strerror(-off)}")
        return int(off)

    def delete(self, vid: int, key: int, tomb: bytes,
               append_ns: int) -> int:
        r = self._lib.dp_delete(vid, key, _u8p(tomb), len(tomb), append_ns)
        if r < 0:
            raise IOError(f"native delete vid={vid}: {os.strerror(-r)}")
        return int(r)

    def lookup(self, vid: int, key: int) -> tuple[int, int] | None:
        """-> (byte offset, size) of a live needle, else None."""
        off = ctypes.c_int64(0)
        size = ctypes.c_int32(0)
        rc = self._lib.dp_lookup(vid, key, ctypes.byref(off),
                                 ctypes.byref(size))
        if rc == 1:
            return int(off.value), int(size.value)
        return None

    def lookup_any(self, vid: int, key: int) -> tuple[int, int] | None:
        """Raw map entry incl. tombstones (size<0) — readDeleted."""
        off = ctypes.c_int64(0)
        size = ctypes.c_int32(0)
        rc = self._lib.dp_lookup_any(vid, key, ctypes.byref(off),
                                     ctypes.byref(size))
        if rc == 1:
            return int(off.value), int(size.value)
        return None

    def stats(self, vid: int) -> dict:
        out = (ctypes.c_int64 * 9)()
        rc = self._lib.dp_stats(vid, out)
        if rc != 0:
            raise KeyError(f"volume {vid} not attached")
        return {"file_count": out[0], "file_bytes": out[1],
                "deleted_count": out[2], "deleted_bytes": out[3],
                "tail": out[4], "last_append_ns": out[5],
                "max_key": out[6], "map_len": out[7],
                "read_only": bool(out[8])}

    def export(self, vid: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Full map dump incl. tombstones -> (keys u64, byte offsets
        i64, signed sizes i32)."""
        cap = max(16, self.stats(vid)["map_len"] + 1024)
        while True:
            keys = np.empty(cap, np.uint64)
            offs = np.empty(cap, np.int64)
            sizes = np.empty(cap, np.int32)
            n = self._lib.dp_export(
                vid, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
            if n == -28:  # ENOSPC: grew between stats and export
                cap *= 2
                continue
            if n < 0:
                raise KeyError(f"volume {vid} not attached")
            return keys[:n], offs[:n], sizes[:n]

    def http_stats(self) -> dict:
        out = (ctypes.c_int64 * 8)()
        self._lib.dp_http_stats(out)
        return {"fast_get": out[0], "fast_post": out[1],
                "proxied": out[2], "errors": out[3],
                "fast_delete": out[4], "repl_post": out[5],
                "jwt_reject": out[6], "fanout_fail": out[7]}

    def front_stats(self) -> dict | None:
        """Native-front response/byte counters (monotonic snapshot for
        the host's /metrics merge); None when the loaded library
        predates dp_front_stats."""
        fn = getattr(self._lib, "dp_front_stats", None)
        if fn is None:
            return None
        out = (ctypes.c_int64 * 6)()
        fn(out)
        return {"2xx": int(out[0]), "3xx": int(out[1]),
                "4xx": int(out[2]), "5xx": int(out[3]),
                "bytes_in": int(out[4]), "bytes_out": int(out[5])}

    def role_front_stats(self, role: int) -> dict | None:
        """Per-role front counters (ROLE_VOLUME/ROLE_S3/ROLE_FILER) for
        the per-front /metrics families; None when the loaded library
        predates the role-addressed fronts."""
        fn = getattr(self._lib, "dp_role_front_stats", None)
        if fn is None:
            return None
        out = (ctypes.c_int64 * 6)()
        fn(role, out)
        return {"2xx": int(out[0]), "3xx": int(out[1]),
                "4xx": int(out[2]), "5xx": int(out[3]),
                "bytes_in": int(out[4]), "bytes_out": int(out[5])}


class NativeNeedleMap:
    """needle_map interface over an attached volume's native map —
    get/counters/iteration for the Python control plane; mutations go
    through Volume's delegated append/delete, never through here."""

    def __init__(self, dp: DataPlane, vid: int):
        self._dp = dp
        self._vid = vid

    def get(self, key: int) -> tuple[int, int] | None:
        hit = self._dp.lookup(self._vid, key)
        if hit is None:
            return None
        byte_off, size = hit
        return byte_off // t.NEEDLE_PADDING, size

    def get_any(self, key: int) -> tuple[int, int] | None:
        """Raw entry incl. tombstones (readDeleted path)."""
        hit = self._dp.lookup_any(self._vid, key)
        if hit is None:
            return None
        byte_off, size = hit
        return byte_off // t.NEEDLE_PADDING, size

    def __len__(self) -> int:
        return self._dp.stats(self._vid)["map_len"]

    @property
    def file_count(self) -> int:
        return self._dp.stats(self._vid)["file_count"]

    @property
    def file_bytes(self) -> int:
        return self._dp.stats(self._vid)["file_bytes"]

    @property
    def deleted_count(self) -> int:
        return self._dp.stats(self._vid)["deleted_count"]

    @property
    def deleted_bytes(self) -> int:
        return self._dp.stats(self._vid)["deleted_bytes"]

    @property
    def max_key(self) -> int:
        return self._dp.stats(self._vid)["max_key"]

    def items(self):
        keys, offs, sizes = self._dp.export(self._vid)
        for k, o, s in zip(keys, offs, sizes):
            yield int(k), int(o) // t.NEEDLE_PADDING, int(s)

    def live_items(self):
        for k, o, s in self.items():
            if t.size_is_valid(s):
                yield k, o, s

    def deleted_keys(self):
        for k, _o, s in self.items():
            if t.size_is_deleted(s):
                yield k

    def put(self, key: int, offset: int, size: int) -> None:
        raise RuntimeError(
            "volume is natively attached; mutations must go through "
            "Volume.append_needle/delete_needle (delegated)")

    delete = put

    def close(self) -> None:
        pass  # lifetime is the attach window; detach owns cleanup


class S3Front:
    """The native S3 gateway front (one per process, combined-server
    mode): owns the public S3 port, serves SigV4 small-object PUT/GET
    natively against the LOCAL volume store, and relays everything
    else to the python S3 app on `backend_port`. Entry metadata flows
    to the in-process filer over `chan_sock` (a socketpair created by
    the caller); identities/buckets/fid-pools/cache are pushed through
    the setters. See the S3-front block in dataplane.cc."""

    def __init__(self) -> None:
        self._lib = _load()
        self.port = 0

    def start(self, listen_port: int, backend_port: int, chan_fd: int,
              workers: int = 2, listen_ip: str = "") -> int:
        actual = ctypes.c_uint16(0)
        rc = self._lib.dp_s3_start(listen_port, backend_port, workers,
                                   ctypes.byref(actual),
                                   listen_ip.encode(), chan_fd)
        if rc != 0:
            raise OSError(-rc, f"dp_s3_start failed: {os.strerror(-rc)}")
        self.port = int(actual.value)
        return self.port

    def stop(self) -> None:
        self._lib.dp_s3_stop()

    def set_identities(self, rows: list[tuple[str, str, str, str, str]]
                       ) -> None:
        """rows: (access_key, secret, flags 'AWR', wr_csv, rd_csv)."""
        tsv = "\n".join("\t".join(r) for r in rows)
        self._lib.dp_s3_set_identities(tsv.encode())

    def set_buckets(self, buckets: list[str]) -> None:
        self._lib.dp_s3_set_buckets(",".join(buckets).encode())

    def push_fids(self, bucket: str, fid: str, count: int) -> None:
        rc = self._lib.dp_s3_push_fids(bucket.encode(), fid.encode(),
                                       count)
        if rc != 0:
            raise ValueError(f"bad fid {fid!r}")

    def pool_level(self, bucket: str) -> int:
        return int(self._lib.dp_s3_pool_level(bucket.encode()))

    def cache_put(self, path: str, fid: str, size: int, etag: str,
                  mime: str, meta_block: str, mtime: int) -> None:
        self._lib.dp_s3_cache_put(path.encode(), fid.encode(), size,
                                  etag.encode(), mime.encode(),
                                  meta_block.encode(), mtime)

    def invalidate(self, path: str, prefix: bool = False) -> None:
        self._lib.dp_s3_invalidate(path.encode(), 1 if prefix else 0)

    def upload_mark(self, bucket: str, upload_id: str,
                    present: bool) -> None:
        """Mark a multipart upload id as in flight (initiate) or gone
        (complete/abort); only marked uploads take the native
        part-upload path."""
        fn = getattr(self._lib, "dp_s3_upload_mark", None)
        if fn is not None:
            fn(bucket.encode(), upload_id.encode(), 1 if present else 0)

    def set_faults(self, read_err: float = 0.0, write_err: float = 0.0,
                   read_delay: float = 0.0, write_delay: float = 0.0,
                   seed: int = 0) -> None:
        """This front's share of a -fault.spec (service 's3')."""
        fn = getattr(self._lib, "dp_role_faults", None)
        if fn is not None:
            fn(ROLE_S3, read_err, write_err, read_delay, write_delay,
               seed & 0xFFFFFFFFFFFFFFFF)

    def stats(self) -> dict:
        out = np.zeros(6, np.int64)
        self._lib.dp_s3_stats(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return {"fast_put": int(out[0]), "fast_get": int(out[1]),
                "rejected": int(out[2]), "chan_fail": int(out[3]),
                "fast_del": int(out[4]), "fast_part": int(out[5])}


class FilerFront:
    """The native filer gateway front (one per process, combined-server
    mode): owns the public filer port, serves GET/PUT/HEAD/DELETE of
    plain files natively against the LOCAL volume store, and relays
    every other verb/path class to the python filer app on
    `backend_port`. Entry mutations ride the same TSV applier channel
    shape as the S3 front (`chan_sock` socketpair created by the
    caller), so the zero-staleness cache contract holds across both
    fronts. See the filer-front block in dataplane.cc."""

    def __init__(self) -> None:
        self._lib = _load()
        self.port = 0
        if not hasattr(self._lib, "dp_filer_start"):
            raise OSError("loaded dataplane library predates the filer "
                          "front; rebuild it")

    def start(self, listen_port: int, backend_port: int, chan_fd: int,
              workers: int = 2, listen_ip: str = "") -> int:
        actual = ctypes.c_uint16(0)
        rc = self._lib.dp_filer_start(listen_port, backend_port, workers,
                                      ctypes.byref(actual),
                                      listen_ip.encode(), chan_fd)
        if rc != 0:
            raise OSError(-rc, f"dp_filer_start failed: {os.strerror(-rc)}")
        self.port = int(actual.value)
        return self.port

    def stop(self) -> None:
        self._lib.dp_filer_stop()

    def push_fids(self, fid: str, count: int) -> None:
        rc = self._lib.dp_filer_push_fids(fid.encode(), count)
        if rc != 0:
            raise ValueError(f"bad fid {fid!r}")

    def pool_level(self) -> int:
        return int(self._lib.dp_filer_pool_level())

    def set_writes(self, on: bool) -> None:
        """Enable the native PUT/DELETE fast path — only sound while
        the python filer would apply its defaults verbatim (no
        filer.conf path rules, no cipher, no save-inside inlining)."""
        self._lib.dp_filer_set_writes(1 if on else 0)

    def cache_put(self, path: str, fid: str, size: int, etag: str,
                  mime: str, ext_block: str, mtime: int) -> None:
        self._lib.dp_filer_cache_put(path.encode(), fid.encode(), size,
                                     etag.encode(), mime.encode(),
                                     ext_block.encode(), mtime)

    def invalidate(self, path: str, prefix: bool = False) -> None:
        self._lib.dp_filer_invalidate(path.encode(), 1 if prefix else 0)

    def set_faults(self, read_err: float = 0.0, write_err: float = 0.0,
                   read_delay: float = 0.0, write_delay: float = 0.0,
                   seed: int = 0) -> None:
        """This front's share of a -fault.spec (service 'filer')."""
        self._lib.dp_role_faults(ROLE_FILER, read_err, write_err,
                                 read_delay, write_delay,
                                 seed & 0xFFFFFFFFFFFFFFFF)

    def stats(self) -> dict:
        out = np.zeros(4, np.int64)
        self._lib.dp_filer_stats(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return {"fast_put": int(out[0]), "fast_get": int(out[1]),
                "fast_del": int(out[2]), "chan_fail": int(out[3])}
