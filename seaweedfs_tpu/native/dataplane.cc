// Native volume-server data plane: epoll HTTP front for GET/POST by fid.
//
// The reference serves its object hot path from compiled Go
// (/root/reference/weed/server/volume_server_handlers_read.go:31
// GetOrHeadHandler, volume_server_handlers_write.go:18 PostHandler,
// hot loop volume_write.go:144 doWriteRequest); the Python asyncio
// server tops out ~1k req/s/core on the same path. This library owns
// the volume server's public port and serves the two hot verbs —
// GET/HEAD and POST of a plain needle — entirely in C++: pre-parsed
// fid routing, native needle-map lookup, pread/pwrite on the .dat,
// CRC32C, zero Python in the loop. Everything else (admin RPCs, EC
// reads, deletes, range/image requests, replicated or guarded
// writes) is transparently relayed to the Python aiohttp backend on
// a loopback port, which stays the control plane.
//
// Concurrency model: one epoll IO thread runs the parser and the
// fast paths; a small pool of proxy workers does blocking relays so
// a slow admin call (vacuum, EC generate) can never stall the data
// plane. Python threads call into the same per-volume mutexes via
// the dp_* C ABI (ctypes), so the needle map has ONE authority —
// this library — while a volume is attached; detach hands the
// files back to Python for maintenance (vacuum, EC encode, copy).
//
// ABI consumers: seaweedfs_tpu/native/dataplane.py.
#include <arpa/inet.h>
#include <array>
#include <cmath>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <strings.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <ctype.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) — hardware when available, slicing table otherwise.
// Mirrors needle.py crc32c + legacy_crc_value (needle/crc.go:26-28).
// ---------------------------------------------------------------------------
uint32_t crc32c_table[8][256];
std::once_flag crc_once;

void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    crc32c_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      crc32c_table[t][i] =
          (crc32c_table[t - 1][i] >> 8) ^ crc32c_table[0][crc32c_table[t - 1][i] & 0xFF];
}

uint32_t crc32c(uint32_t crc, const uint8_t* p, size_t n) {
  std::call_once(crc_once, crc_init);
  crc = ~crc;
#if defined(__SSE4_2__)
  while (n >= 8) {
    crc = (uint32_t)_mm_crc32_u64(crc, *(const uint64_t*)p);
    p += 8;
    n -= 8;
  }
  while (n--) crc = _mm_crc32_u8(crc, *p++);
#else
  while (n >= 8) {
    crc ^= *(const uint32_t*)p;
    uint32_t hi = *(const uint32_t*)(p + 4);
    crc = crc32c_table[7][crc & 0xFF] ^ crc32c_table[6][(crc >> 8) & 0xFF] ^
          crc32c_table[5][(crc >> 16) & 0xFF] ^ crc32c_table[4][crc >> 24] ^
          crc32c_table[3][hi & 0xFF] ^ crc32c_table[2][(hi >> 8) & 0xFF] ^
          crc32c_table[1][(hi >> 16) & 0xFF] ^ crc32c_table[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = crc32c_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
#endif
  return ~crc;
}

uint32_t legacy_crc_value(uint32_t c) {
  return (((c >> 15) | (c << 17)) + 0xA282EAD8u);
}

uint32_t be32(const uint8_t* p) {
  return (uint32_t)p[0] << 24 | (uint32_t)p[1] << 16 | (uint32_t)p[2] << 8 | p[3];
}
uint64_t be64(const uint8_t* p) {
  return (uint64_t)be32(p) << 32 | be32(p + 4);
}
void put_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
void put_be64(uint8_t* p, uint64_t v) {
  put_be32(p, v >> 32);
  put_be32(p + 4, (uint32_t)v);
}

// ---------------------------------------------------------------------------
// SHA-256 + HMAC-SHA256 — for HS256 JWT verification in the front
// (security/guard.go:41 checks write tokens from compiled code; relaying
// every guarded write to Python would forfeit the fast path under the
// production config). Standard FIPS 180-4 compression, no dependencies.
// ---------------------------------------------------------------------------
constexpr uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

struct Sha256 {
  uint32_t h[8];
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof h);
  }

  static uint32_t rotr(uint32_t x, int n) { return x >> n | x << (32 - n); }

  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++) w[i] = be32(p + 4 * i);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + SHA_K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    total += n;
    if (buflen) {
      size_t take = std::min(n, sizeof buf - buflen);
      memcpy(buf + buflen, p, take);
      buflen += take;
      p += take;
      n -= take;
      if (buflen == 64) {
        block(buf);
        buflen = 0;
      }
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    if (n) {
      memcpy(buf, p, n);
      buflen = n;
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;  // captured before padding joins the stream
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t lenb[8];
    put_be64(lenb, bits);
    update(lenb, 8);
    for (int i = 0; i < 8; i++) put_be32(out + 4 * i, h[i]);
  }
};

// MD5 (RFC 1321 structure) — S3 object ETags are hex md5; computing
// them here keeps the gateway hot path off the GIL. The sine-derived
// round constants are generated at startup rather than transcribed.
struct Md5 {
  uint32_t h[4] = {0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476};
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  static const uint32_t* table() {
    static uint32_t t[64];
    static bool init = [] {
      for (int i = 0; i < 64; i++)
        t[i] = (uint32_t)(4294967296.0 * std::fabs(std::sin(i + 1.0)));
      return true;
    }();
    (void)init;
    return t;
  }

  static uint32_t rotl(uint32_t x, int n) { return x << n | x >> (32 - n); }

  void block(const uint8_t* p) {
    static const int S[4][4] = {
        {7, 12, 17, 22}, {5, 9, 14, 20}, {4, 11, 16, 23}, {6, 10, 15, 21}};
    const uint32_t* T = table();
    uint32_t m[16];
    for (int i = 0; i < 16; i++)
      m[i] = (uint32_t)p[4 * i] | (uint32_t)p[4 * i + 1] << 8 |
             (uint32_t)p[4 * i + 2] << 16 | (uint32_t)p[4 * i + 3] << 24;
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    for (int i = 0; i < 64; i++) {
      uint32_t f;
      int g;
      if (i < 16) {
        f = (b & c) | (~b & d);
        g = i;
      } else if (i < 32) {
        f = (d & b) | (~d & c);
        g = (5 * i + 1) & 15;
      } else if (i < 48) {
        f = b ^ c ^ d;
        g = (3 * i + 5) & 15;
      } else {
        f = c ^ (b | ~d);
        g = (7 * i) & 15;
      }
      uint32_t tmp = d;
      d = c;
      c = b;
      b += rotl(a + f + T[i] + m[g], S[i >> 4][i & 3]);
      a = tmp;
    }
    h[0] += a;
    h[1] += b;
    h[2] += c;
    h[3] += d;
  }

  void update(const uint8_t* p, size_t n) {
    total += n;
    if (buflen) {
      size_t take = std::min(n, sizeof buf - buflen);
      memcpy(buf + buflen, p, take);
      buflen += take;
      p += take;
      n -= take;
      if (buflen == 64) {
        block(buf);
        buflen = 0;
      }
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    if (n) {
      memcpy(buf, p, n);
      buflen = n;
    }
  }

  void final(uint8_t out[16]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = (uint8_t)(bits >> (8 * i));
    update(lenb, 8);
    for (int i = 0; i < 4; i++) {
      out[4 * i] = (uint8_t)h[i];
      out[4 * i + 1] = (uint8_t)(h[i] >> 8);
      out[4 * i + 2] = (uint8_t)(h[i] >> 16);
      out[4 * i + 3] = (uint8_t)(h[i] >> 24);
    }
  }
};

void hex_encode(const uint8_t* d, size_t n, char* out) {
  static const char* H = "0123456789abcdef";
  for (size_t i = 0; i < n; i++) {
    out[2 * i] = H[d[i] >> 4];
    out[2 * i + 1] = H[d[i] & 15];
  }
}

std::string md5_hex(const uint8_t* d, size_t n) {
  Md5 m;
  m.update(d, n);
  uint8_t dig[16];
  m.final(dig);
  char hx[32];
  hex_encode(dig, 16, hx);
  return std::string(hx, 32);
}

std::string sha256_hex(const uint8_t* d, size_t n) {
  Sha256 s;
  s.update(d, n);
  uint8_t dig[32];
  s.final(dig);
  char hx[64];
  hex_encode(dig, 32, hx);
  return std::string(hx, 64);
}

void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* msg,
                 size_t msglen, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (keylen > 64) {
    Sha256 kh;
    kh.update(key, keylen);
    kh.final(k);
  } else {
    memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 ih;
  ih.update(ipad, 64);
  ih.update(msg, msglen);
  ih.final(inner);
  Sha256 oh;
  oh.update(opad, 64);
  oh.update(inner, 32);
  oh.final(out);
}

// base64url decode (padding optional). Returns false on any bad symbol.
bool b64url_decode(const char* s, size_t n, std::string* out) {
  while (n && s[n - 1] == '=') n--;
  out->clear();
  out->reserve(n * 3 / 4 + 3);
  uint32_t acc = 0;
  int bits = 0;
  for (size_t i = 0; i < n; i++) {
    char c = s[i];
    int v = c >= 'A' && c <= 'Z'   ? c - 'A'
            : c >= 'a' && c <= 'z' ? c - 'a' + 26
            : c >= '0' && c <= '9' ? c - '0' + 52
            : c == '-'             ? 62
            : c == '_'             ? 63
            : c == '+'             ? 62  // tolerate standard alphabet
            : c == '/'             ? 63
                                   : -1;
    if (v < 0) return false;
    acc = acc << 6 | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back((char)(acc >> bits & 0xFF));
    }
  }
  return true;
}

bool const_time_eq(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t d = 0;
  for (size_t i = 0; i < n; i++) d |= a[i] ^ b[i];
  return d == 0;
}

// base64url encode, unpadded (the JWT segment alphabet).
void b64url_encode(const uint8_t* d, size_t n, std::string* out) {
  static const char T[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";
  out->clear();
  out->reserve((n + 2) / 3 * 4);
  size_t i = 0;
  for (; i + 3 <= n; i += 3) {
    uint32_t v = (uint32_t)d[i] << 16 | (uint32_t)d[i + 1] << 8 | d[i + 2];
    out->push_back(T[v >> 18]);
    out->push_back(T[v >> 12 & 63]);
    out->push_back(T[v >> 6 & 63]);
    out->push_back(T[v & 63]);
  }
  if (n - i == 1) {
    uint32_t v = (uint32_t)d[i] << 16;
    out->push_back(T[v >> 18]);
    out->push_back(T[v >> 12 & 63]);
  } else if (n - i == 2) {
    uint32_t v = (uint32_t)d[i] << 16 | (uint32_t)d[i + 1] << 8;
    out->push_back(T[v >> 18]);
    out->push_back(T[v >> 12 & 63]);
    out->push_back(T[v >> 6 & 63]);
  }
}

// ---------------------------------------------------------------------------
// Needle record constants (needle.py / needle_write.go:20-110 layout)
// ---------------------------------------------------------------------------
constexpr int HEADER = 16;  // cookie(4) id(8) size(4), all big-endian
constexpr int PADDING = 8;
constexpr int CHECKSUM = 4;
constexpr int TS = 8;  // append_at_ns, version 3 only
constexpr uint8_t FLAG_IS_COMPRESSED = 0x01;
constexpr uint8_t FLAG_IS_CHUNK_MANIFEST = 0x80;
constexpr uint8_t FLAG_HAS_NAME = 0x02;
constexpr uint8_t FLAG_HAS_MIME = 0x04;
constexpr uint8_t FLAG_HAS_LAST_MODIFIED = 0x08;
constexpr uint8_t FLAG_HAS_TTL = 0x10;
constexpr uint8_t FLAG_HAS_PAIRS = 0x20;

int64_t disk_size(int64_t body, int version) {
  int64_t total = HEADER + body + CHECKSUM + (version == 3 ? TS : 0);
  return total + (PADDING - total % PADDING);  // full 8 pad when aligned
}

// ---------------------------------------------------------------------------
// Volume registry
// ---------------------------------------------------------------------------
struct MapVal {
  int64_t offset;  // byte offset in .dat
  int32_t size;    // body size; <0 = tombstone
};

struct Vol {
  std::mutex mu;
  int dat_fd = -1;
  int idx_fd = -1;
  int version = 3;
  int offset_size = 4;  // index offset width: 4 or 5 bytes
  bool read_only = false;
  bool has_replicas = false;
  int64_t tail = 0;      // .dat append offset
  int64_t idx_tail = 0;  // .idx append offset
  uint64_t last_append_ns = 0;
  // counters mirror needle_map.py NeedleMap accounting exactly
  int64_t file_count = 0, file_bytes = 0;
  int64_t deleted_count = 0, deleted_bytes = 0;
  uint64_t max_key = 0;
  // set under mu by dp_detach: an op that resolved this Vol just before
  // the detach must notice and bail instead of appending to files that
  // Python is about to vacuum/replace
  bool detached = false;
  // replica peer "host:port" list, pushed by the Python control plane
  // from master lookups (store_replicate.go:191 resolves the same way
  // from the masterClient vidMap). peers_stale is set on any fan-out
  // failure: writes then relay to Python (which re-resolves) until the
  // next peer refresh clears it.
  std::vector<std::string> peers;
  bool peers_stale = false;
  std::unordered_map<uint64_t, MapVal> map;

  ~Vol() {
    if (dat_fd >= 0) close(dat_fd);
    if (idx_fd >= 0) close(idx_fd);
  }

  // put/delete replicate NeedleMap.put/.delete counter semantics
  void put(uint64_t key, int64_t off, int32_t size) {
    auto it = map.find(key);
    if (it != map.end() && it->second.size > 0) {
      deleted_count++;
      deleted_bytes += it->second.size;
      file_count--;
      file_bytes -= it->second.size;
    }
    map[key] = {off, size};
    if (size > 0) {
      file_count++;
      file_bytes += size;
    }
    if (key > max_key) max_key = key;
  }

  int64_t del(uint64_t key) {
    auto it = map.find(key);
    if (it == map.end() || it->second.size <= 0) return 0;
    int64_t reclaimed = it->second.size;
    it->second.size = -1;
    deleted_count++;
    deleted_bytes += reclaimed;
    file_count--;
    file_bytes -= reclaimed;
    return reclaimed;
  }

  // append one .idx log entry: key(8 BE) offset-units(4|5) size-u32(4 BE)
  int write_idx(uint64_t key, int64_t byte_off, uint32_t size_u32) {
    uint8_t e[17];
    put_be64(e, key);
    uint64_t units = (uint64_t)(byte_off / PADDING);
    int n;
    if (offset_size == 4) {
      put_be32(e + 8, (uint32_t)units);
      put_be32(e + 12, size_u32);
      n = 16;
    } else {  // 5-byte: 4 BE low bytes then one high byte (offset_5bytes.go)
      put_be32(e + 8, (uint32_t)(units & 0xFFFFFFFF));
      e[12] = (uint8_t)(units >> 32);
      put_be32(e + 13, size_u32);
      n = 17;
    }
    if (pwrite(idx_fd, e, n, idx_tail) != n) return -1;
    idx_tail += n;
    return 0;
  }
};

std::shared_mutex vols_mu;
// shared_ptr: a fast-path request may still hold the Vol while a
// concurrent dp_detach removes it from the registry
std::unordered_map<uint32_t, std::shared_ptr<Vol>> vols;
std::atomic<bool> jwt_required{false};
std::shared_mutex jwt_mu;
std::string jwt_secret;  // under jwt_mu; non-empty iff jwt_required

// server roles; N_ROLES sizes the per-role fault/counter tables below
constexpr int ROLE_VOLUME = 0;
constexpr int ROLE_S3 = 1;
constexpr int ROLE_FILER = 2;
constexpr int N_ROLES = 3;

// Which role's server the current thread serves. Every native response
// is written on the owning server's IO/worker thread (channel acks
// included: chan_read runs on that server's IO thread), so a
// thread_local set once at thread start routes gate_request and
// count_resp to the right per-role slot without threading a Server*
// through every call site. Threads that never serve requests (bench
// clients) keep the volume default and never call either function.
thread_local int t_role = ROLE_VOLUME;

// fault injection (utils/faults.py subset): error probability + fixed
// delay per op class and role, set at spawn via dp_faults /
// dp_role_faults before traffic. Rates/delays are written before
// faults_on flips, so relaxed reads from the IO threads are safe; the
// seeded RNG sits under its own mutex so a fixed seed gives one
// deterministic decision sequence.
std::atomic<bool> faults_on[N_ROLES] = {{false}, {false}, {false}};
std::mutex faults_mu;
double fault_read_err[N_ROLES] = {0}, fault_write_err[N_ROLES] = {0};
double fault_read_delay[N_ROLES] = {0}, fault_write_delay[N_ROLES] = {0};
uint64_t fault_rng = 0x9E3779B97F4A7C15ull;

// splitmix64 step -> uniform double in [0, 1)
double fault_roll() {
  std::lock_guard<std::mutex> lk(faults_mu);
  uint64_t z = (fault_rng += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  z ^= z >> 31;
  return (double)(z >> 11) * 0x1.0p-53;
}

void set_role_faults(int role, double read_err, double write_err,
                     double read_delay, double write_delay,
                     uint64_t seed) {
  auto clamp01 = [](double v) { return v < 0 ? 0 : (v > 1 ? 1 : v); };
  std::lock_guard<std::mutex> lk(faults_mu);
  fault_read_err[role] = clamp01(read_err);
  fault_write_err[role] = clamp01(write_err);
  fault_read_delay[role] = read_delay < 0 ? 0 : read_delay;
  fault_write_delay[role] = write_delay < 0 ? 0 : write_delay;
  fault_rng = seed ? seed : 0x9E3779B97F4A7C15ull;
  faults_on[role].store(fault_read_err[role] > 0 ||
                        fault_write_err[role] > 0 ||
                        fault_read_delay[role] > 0 ||
                        fault_write_delay[role] > 0);
}

double wall_now() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (double)ts.tv_sec + ts.tv_nsec * 1e-9;
}

std::shared_ptr<Vol> find_vol(uint32_t vid) {
  std::shared_lock<std::shared_mutex> lk(vols_mu);
  auto it = vols.find(vid);
  return it == vols.end() ? nullptr : it->second;
}

// request counters, surfaced through dp_http_stats
std::atomic<int64_t> n_fast_get{0}, n_fast_post{0}, n_proxied{0}, n_errors{0};
std::atomic<int64_t> n_fast_delete{0}, n_repl_post{0}, n_jwt_reject{0},
    n_fanout_fail{0};

// front visibility counters, surfaced through dp_front_stats (summed
// across roles) and dp_role_front_stats (per role): responses the
// native front wrote itself, bucketed by status class, plus payload
// bytes in (uploaded bodies) / out (served bodies). The host process
// merges them into /metrics as native_front_requests_total{code} /
// native_front_bytes_total, so -dataplane native traffic shows up in
// the cluster metrics federation like any Python-served request.
struct FrontStats {
  std::atomic<int64_t> n_2xx{0}, n_3xx{0}, n_4xx{0}, n_5xx{0};
  std::atomic<int64_t> bytes_in{0}, bytes_out{0};
};
FrontStats front_stats[N_ROLES];

void count_resp(int code, int64_t bytes_out) {
  FrontStats& fs = front_stats[t_role];
  if (code < 300)
    fs.n_2xx++;
  else if (code < 400)
    fs.n_3xx++;
  else if (code < 500)
    fs.n_4xx++;
  else
    fs.n_5xx++;
  if (bytes_out > 0) fs.bytes_out += bytes_out;
}

// ---------------------------------------------------------------------------
// JWT (HS256) verification — mirrors utils/security.py verify_jwt +
// Guard.check and the reference's maybeCheckJwtAuthorization
// (volume_server_handlers.go:145-187): signature, exp, and fid claim
// with the `_N` batch-slot suffix stripped before comparison (:181).
// ---------------------------------------------------------------------------
enum class JwtRes {
  OK,      // verified (or not required)
  REJECT,  // definitively bad: missing/expired/bad signature/fid mismatch
  UNSURE,  // structurally odd token: relay to Python for the verdict
};

// Scan a flat JSON object for an integer field. Handles only the shape
// our own signers emit ({"exp": 123, "fid": "..."}); anything fancier
// returns false and the caller downgrades to UNSURE.
bool json_int_field(const std::string& js, const char* name, int64_t* out) {
  std::string pat = std::string("\"") + name + "\"";
  size_t p = js.find(pat);
  if (p == std::string::npos) return false;
  p += pat.size();
  while (p < js.size() && (js[p] == ' ' || js[p] == ':')) p++;
  if (p >= js.size() || !isdigit((unsigned char)js[p])) return false;
  int64_t v = 0;
  while (p < js.size() && isdigit((unsigned char)js[p]))
    v = v * 10 + (js[p++] - '0');
  *out = v;
  return true;
}

bool json_str_field(const std::string& js, const char* name,
                    std::string* out, bool* malformed) {
  std::string pat = std::string("\"") + name + "\"";
  size_t p = js.find(pat);
  if (p == std::string::npos) return false;
  p += pat.size();
  while (p < js.size() && (js[p] == ' ' || js[p] == ':')) p++;
  if (p >= js.size() || js[p] != '"') return false;
  p++;
  size_t e = p;
  while (e < js.size() && js[e] != '"') {
    if (js[e] == '\\') {  // escapes never appear in fid strings we mint
      *malformed = true;
      return false;
    }
    e++;
  }
  if (e >= js.size()) {
    *malformed = true;
    return false;
  }
  out->assign(js, p, e - p);
  return true;
}

// `fid`/`fid_len`: the request fid as it appears in the path (no
// leading slash, extension already excluded), INCLUDING any _N suffix —
// stripped here exactly like the reference.
JwtRes jwt_check(const char* auth, size_t auth_len, const char* fid,
                 size_t fid_len) {
  if (!jwt_required.load(std::memory_order_relaxed)) return JwtRes::OK;
  if (!auth || auth_len < 8 || strncasecmp(auth, "Bearer ", 7) != 0)
    return JwtRes::REJECT;  // Guard.check: missing jwt
  const char* tok = auth + 7;
  size_t toklen = auth_len - 7;
  const char* d1 = (const char*)memchr(tok, '.', toklen);
  if (!d1) return JwtRes::REJECT;
  const char* d2 =
      (const char*)memchr(d1 + 1, '.', tok + toklen - d1 - 1);
  if (!d2) return JwtRes::REJECT;
  std::string sig;
  if (!b64url_decode(d2 + 1, tok + toklen - d2 - 1, &sig) || sig.size() != 32)
    return JwtRes::REJECT;
  uint8_t expect[32];
  {
    std::shared_lock<std::shared_mutex> lk(jwt_mu);
    hmac_sha256((const uint8_t*)jwt_secret.data(), jwt_secret.size(),
                (const uint8_t*)tok, d2 - tok, expect);
  }
  if (!const_time_eq(expect, (const uint8_t*)sig.data(), 32))
    return JwtRes::REJECT;
  std::string payload;
  if (!b64url_decode(d1 + 1, d2 - d1 - 1, &payload)) return JwtRes::UNSURE;
  int64_t exp = 0;
  if (!json_int_field(payload, "exp", &exp)) {
    // Python treats a missing exp as 0 => expired; a non-integer exp
    // (float/exotic) is a token we didn't mint: let Python decide
    if (payload.find("\"exp\"") != std::string::npos) return JwtRes::UNSURE;
  }
  if (exp < (int64_t)time(nullptr)) return JwtRes::REJECT;
  bool malformed = false;
  std::string claim_fid;
  if (json_str_field(payload, "fid", &claim_fid, &malformed) &&
      !claim_fid.empty()) {
    const char* us = (const char*)memrchr(fid, '_', fid_len);
    size_t base_len = us ? (size_t)(us - fid) : fid_len;
    if (claim_fid.size() != base_len ||
        memcmp(claim_fid.data(), fid, base_len) != 0)
      return JwtRes::REJECT;
  } else if (malformed) {
    return JwtRes::UNSURE;
  } else {
    // missing/empty fid claim: the reference requires an exact claim match
    // (volume_server_handlers.go:183) — a fid-less signed token is not a
    // universal write token
    return JwtRes::REJECT;
  }
  return JwtRes::OK;
}

// Mint the replication-channel handshake token: HS256 over the shared
// cluster secret with the reserved claim fid ".swrp" (a name no data
// fid can take — parse_fid_path rejects it). Only secret holders can
// mint it, and it is NOT a data-write token: jwt_check never matches
// ".swrp" against a real fid. Channel auth replaces the reference's
// per-replicate JWT forwarding (security/guard.go:41) — same trust
// root, one verification per connection instead of per write.
std::string mint_swrp_token() {
  std::shared_lock<std::shared_mutex> lk(jwt_mu);
  if (jwt_secret.empty()) return "";
  // {"alg":"HS256","typ":"JWT"} pre-encoded
  std::string signing = "eyJhbGciOiJIUzI1NiIsInR5cCI6IkpXVCJ9.";
  char pl[96];
  int n = snprintf(pl, sizeof pl, "{\"exp\": %lld, \"fid\": \".swrp\"}",
                   (long long)time(nullptr) + 300);
  std::string seg;
  b64url_encode((const uint8_t*)pl, (size_t)n, &seg);
  signing += seg;
  uint8_t mac[32];
  hmac_sha256((const uint8_t*)jwt_secret.data(), jwt_secret.size(),
              (const uint8_t*)signing.data(), signing.size(), mac);
  b64url_encode(mac, 32, &seg);
  return signing + "." + seg;
}

// ---------------------------------------------------------------------------
// HTTP front
// ---------------------------------------------------------------------------
struct Request {
  // views into Conn::in — valid only until the buffer is consumed
  const char* method = nullptr;
  size_t method_len = 0;
  const char* path = nullptr;  // path only, query excluded
  size_t path_len = 0;
  const char* query = nullptr;  // bytes after '?' (before any fragment)
  size_t query_len = 0;
  bool has_query = false;
  bool is_replicate = false;  // query is exactly "type=replicate"
  size_t head_len = 0;   // request line + headers + CRLFCRLF
  int64_t content_len = 0;
  bool chunked = false;
  bool keep_alive = true;
  bool accept_gzip = false;
  bool expect_100 = false;
  bool plain_upload = true;  // content-type empty or octet-stream
  bool proxy_only = false;   // seaweed-* metadata headers present
  const char* auth = nullptr;  // Authorization header value
  size_t auth_len = 0;
  const char* range = nullptr;  // Range header value
  size_t range_len = 0;
  const char* traceparent = nullptr;  // W3C trace context, relayed as-is
  size_t traceparent_len = 0;
  double deadline = 0;  // X-Sw-Deadline: absolute epoch seconds, 0 = none
};

// epoll data.ptr discrimination: Conn and PeerConn both lead with an
// int kind so the IO loop can tell them apart (both standard-layout,
// first-member address == struct address)
constexpr int KIND_CLIENT = 1;
constexpr int KIND_PEER = 2;
constexpr int KIND_CHAN = 3;  // S3 front <-> python filer channel

struct Conn {
  int kind = KIND_CLIENT;
  int fd = -1;
  std::string in;        // buffered request bytes
  size_t in_off = 0;     // consumed prefix
  std::string out;       // pending response bytes
  size_t out_off = 0;
  bool want_close = false;
  bool in_epoll = false;
  bool sent_100 = false;  // 100-continue sent for the current request
  // async replica fan-out state: while an op is in flight the conn's
  // pump is gated (response ordering) and a client disconnect turns
  // the conn into a zombie freed when the op concludes
  bool repl_pending = false;
  bool zombie = false;
  // conn upgraded to the binary replication protocol (SWRP): the
  // buffer carries frames, not HTTP, from the upgrade on
  bool swrp = false;
  time_t last_active = 0;
  int backend_fd = -1;  // persistent backend conn for this client conn
};

struct PeerConn;
struct S3Op;

// epoll tag for the S3 entry channel (leads with kind, like Conn)
struct ChanTag {
  int kind = KIND_CHAN;
};

// Group-commit handoff (storage/commit.py's native twin): one
// enqueued append waiting for the covering batch fsync. rop != null
// gates a volume-front op (the waiter IS the fsync token counted in
// ReplOp.waiting); otherwise s3_id names a chan-gated S3/filer op in
// the owning server's s3_pending. Completions are delivered back to
// the owning server's IO thread through commit_done + eventfd, the
// same handoff worker_loop uses for returned conns.
struct Server;

// One gated client op on the volume front (defined here, ahead of the
// fan-out machinery that owns it, because both the replica fan-out and
// the group-commit fsync token count into `waiting`): the client's
// response is sent from finalize_repl when the last outstanding
// peer ack / fsync completion lands. See the fan-out block below.
struct ReplOp {
  Conn* client;  // zombie-aware: finalize checks before responding
  std::shared_ptr<Vol> v;
  bool is_delete = false;
  bool keep_alive = true;
  int64_t size = 0;  // body_len (post) / reclaimed (delete)
  uint32_t crc = 0;
  int waiting = 0;  // peer acks + fsync tokens outstanding
  bool failed = false;
  bool plain = false;  // no peer wires: group-commit-gated fast post
  std::string failed_peer;
};

struct CommitWaiter {
  Server* s = nullptr;
  ReplOp* rop = nullptr;
  uint64_t s3_id = 0;
  int64_t nbytes = 0;
};

struct Server {
  int role = ROLE_VOLUME;
  uint16_t backend_port = 0;
  int listen_fd = -1;
  int epoll_fd = -1;
  int event_fd = -1;  // wakes the IO thread for returned conns / stop
  std::atomic<bool> stop{false};
  std::thread io_thread;
  std::vector<std::thread> workers;
  // proxy handoff
  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<Conn*> proxy_q;
  std::mutex ret_mu;
  std::deque<Conn*> returned;
  // fsync completions for this server's gated writes (guarded by
  // ret_mu, drained in io_loop's eventfd branch with `returned`)
  std::deque<CommitWaiter> commit_done;
  std::unordered_map<int, Conn*> conns;
  // replica-peer keep-alive conns, IO-thread-only (async fan-out)
  std::unordered_map<std::string, PeerConn*> peer_conns;
  // peers with freshly queued wires: flushed once per epoll batch so a
  // burst of client writes rides ONE writev per peer (syscall collapse
  // on this side; one recv + one coalesced ack burst on the replica)
  std::vector<PeerConn*> dirty_peers;
  time_t last_peer_sweep = 0;
  // conn currently inside pump(): a synchronous fan-out failure must
  // not re-enter that conn's pump from finalize_repl
  Conn* pumping = nullptr;
  // S3/filer roles only: the entry channel to the in-process python
  // filer. Records out (TSV lines, see s3_handle_put), acks in
  // ("id status\n"); both batched per epoll pass like the peer wires.
  int chan_fd = -1;
  ChanTag chan_tag;
  bool chan_in_epoll = false;
  std::string chan_out;
  size_t chan_out_off = 0;
  std::string chan_in;
  size_t chan_in_off = 0;
  std::unordered_map<uint64_t, S3Op*> s3_pending;
  uint64_t next_op_id = 1;
};

Server* g_srv = nullptr;      // volume front (one per process)
Server* g_s3srv = nullptr;    // S3 front (combined-server processes)
Server* g_filersrv = nullptr; // filer front (combined-server processes)

void set_nonblock(int fd, bool nb) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, nb ? (fl | O_NONBLOCK) : (fl & ~O_NONBLOCK));
}

bool ieq(const char* a, size_t alen, const char* b) {
  size_t blen = strlen(b);
  if (alen != blen) return false;
  for (size_t i = 0; i < alen; i++)
    if (tolower((unsigned char)a[i]) != tolower((unsigned char)b[i])) return false;
  return true;
}

bool icontains(const char* s, size_t n, const char* needle) {
  size_t m = strlen(needle);
  if (m > n) return false;
  for (size_t i = 0; i + m <= n; i++) {
    size_t j = 0;
    while (j < m && tolower((unsigned char)s[i + j]) == needle[j]) j++;
    if (j == m) return true;
  }
  return false;
}

// Parse request head out of buf[off..len). Returns head length (>0), 0 if
// incomplete, -1 on malformed input.
ssize_t parse_head(const char* buf, size_t len, Request* r) {
  const char* end = (const char*)memmem(buf, len, "\r\n\r\n", 4);
  if (!end) return len > (64 << 10) ? -1 : 0;
  size_t head_len = end - buf + 4;
  const char* line_end = (const char*)memmem(buf, head_len, "\r\n", 2);
  if (!line_end) return -1;
  const char* sp1 = (const char*)memchr(buf, ' ', line_end - buf);
  if (!sp1) return -1;
  const char* sp2 = (const char*)memchr(sp1 + 1, ' ', line_end - sp1 - 1);
  if (!sp2) return -1;
  r->method = buf;
  r->method_len = sp1 - buf;
  const char* target = sp1 + 1;
  size_t target_len = sp2 - target;
  const char* q = (const char*)memchr(target, '?', target_len);
  r->path = target;
  r->path_len = q ? (size_t)(q - target) : target_len;
  r->has_query = q != nullptr;
  if (q) {
    r->query = q + 1;
    r->query_len = target + target_len - (q + 1);
    r->is_replicate =
        r->query_len == 14 && memcmp(r->query, "type=replicate", 14) == 0;
  }
  r->keep_alive = memmem(line_end - 3, 3, "1.1", 3) != nullptr;
  r->head_len = head_len;
  r->content_len = 0;
  // header scan
  const char* p = line_end + 2;
  while (p < buf + head_len - 2) {
    const char* le = (const char*)memmem(p, buf + head_len - p, "\r\n", 2);
    if (!le) break;
    const char* colon = (const char*)memchr(p, ':', le - p);
    if (colon) {
      size_t klen = colon - p;
      const char* v = colon + 1;
      while (v < le && *v == ' ') v++;
      size_t vlen = le - v;
      if (ieq(p, klen, "content-length")) {
        r->content_len = strtoll(std::string(v, vlen).c_str(), nullptr, 10);
      } else if (ieq(p, klen, "transfer-encoding")) {
        if (icontains(v, vlen, "chunked")) r->chunked = true;
      } else if (ieq(p, klen, "connection")) {
        if (icontains(v, vlen, "close")) r->keep_alive = false;
        if (icontains(v, vlen, "keep-alive")) r->keep_alive = true;
      } else if (ieq(p, klen, "accept-encoding")) {
        if (icontains(v, vlen, "gzip")) r->accept_gzip = true;
      } else if (ieq(p, klen, "expect")) {
        if (icontains(v, vlen, "100-continue")) r->expect_100 = true;
      } else if (ieq(p, klen, "content-type")) {
        r->plain_upload =
            vlen == 0 || icontains(v, vlen, "application/octet-stream");
      } else if (ieq(p, klen, "authorization")) {
        r->auth = v;
        r->auth_len = vlen;
      } else if (ieq(p, klen, "range")) {
        r->range = v;
        r->range_len = vlen;
      } else if (ieq(p, klen, "traceparent")) {
        r->traceparent = v;
        r->traceparent_len = vlen;
      } else if (ieq(p, klen, "x-sw-deadline")) {
        double d = strtod(std::string(v, vlen).c_str(), nullptr);
        if (d > 0) r->deadline = d;
      } else if (ieq(p, klen, "content-encoding")) {
        r->proxy_only = true;  // pre-compressed body: python sets the needle flag
      } else if (klen >= 8 && ieq(p, 8, "seaweed-")) {
        r->proxy_only = true;  // metadata pairs: python builds the needle
      }
    }
    p = le + 2;
  }
  return (ssize_t)head_len;
}

// How many body bytes (after the head) does this request carry, given what
// is buffered? For chunked, returns -1 until the terminating chunk is
// buffered, then the framed length. `avail` excludes the head.
int64_t body_len_buffered(const Request& r, const char* body, size_t avail,
                          bool* complete) {
  if (!r.chunked) {
    *complete = (int64_t)avail >= r.content_len;
    return r.content_len;
  }
  // walk chunk frames
  size_t pos = 0;
  while (true) {
    const char* le = (const char*)memmem(body + pos, avail - pos, "\r\n", 2);
    if (!le) {
      *complete = false;
      return -1;
    }
    long sz = strtol(std::string(body + pos, le - (body + pos)).c_str(), nullptr, 16);
    size_t next = (le - body) + 2 + sz + 2;  // chunk data + CRLF
    if (sz == 0) {
      // optional trailers until CRLFCRLF; we sent none and accept none
      *complete = next <= avail;
      return *complete ? (int64_t)next : -1;
    }
    if (next > avail) {
      *complete = false;
      return -1;
    }
    pos = next;
  }
}

// fid path: "/<vid>,<keyhex><cookie8hex>[_delta][.ext]"
// (types.py parse_file_id / needle.go ParsePath:121-141)
bool parse_fid_path(const char* p, size_t n, uint32_t* vid, uint64_t* key,
                    uint32_t* cookie) {
  if (n < 2 || p[0] != '/') return false;
  p++;
  n--;
  // strip extension
  const char* dot = (const char*)memchr(p, '.', n);
  if (dot) n = dot - p;
  const char* comma = (const char*)memchr(p, ',', n);
  if (!comma) return false;
  uint64_t v = 0;
  for (const char* c = p; c < comma; c++) {
    if (*c < '0' || *c > '9') return false;
    v = v * 10 + (*c - '0');
    if (v > 0xFFFFFFFFull) return false;
  }
  const char* rest = comma + 1;
  size_t rlen = n - (comma + 1 - p);
  uint64_t delta = 0;
  const char* us = (const char*)memrchr(rest, '_', rlen);
  if (us) {
    for (const char* c = us + 1; c < rest + rlen; c++) {
      if (*c < '0' || *c > '9') return false;
      delta = delta * 10 + (*c - '0');
    }
    rlen = us - rest;
  }
  if (rlen <= 8 || rlen > 24) return false;
  uint64_t k = 0;
  for (size_t i = 0; i < rlen - 8; i++) {
    char c = rest[i];
    int d = c >= '0' && c <= '9'   ? c - '0'
            : c >= 'a' && c <= 'f' ? c - 'a' + 10
            : c >= 'A' && c <= 'F' ? c - 'A' + 10
                                   : -1;
    if (d < 0) return false;
    k = k << 4 | d;
  }
  uint32_t ck = 0;
  for (size_t i = rlen - 8; i < rlen; i++) {
    char c = rest[i];
    int d = c >= '0' && c <= '9'   ? c - '0'
            : c >= 'a' && c <= 'f' ? c - 'a' + 10
            : c >= 'A' && c <= 'F' ? c - 'A' + 10
                                   : -1;
    if (d < 0) return false;
    ck = ck << 4 | d;
  }
  *vid = (uint32_t)v;
  *key = k + delta;
  *cookie = ck;
  return true;
}

// `extra` is a pre-formatted header block ("K: v\r\n..." or "")
void simple_response_x(Conn* c, int code, const char* text, bool keep_alive,
                       const char* extra) {
  const char* reason = code == 200   ? "OK"
                       : code == 201 ? "Created"
                       : code == 202 ? "Accepted"
                       : code == 400 ? "Bad Request"
                       : code == 401 ? "Unauthorized"
                       : code == 403 ? "Forbidden"
                       : code == 404 ? "Not Found"
                       : code == 409 ? "Conflict"
                       : code == 416 ? "Requested Range Not Satisfiable"
                       : code == 500 ? "Internal Server Error"
                       : code == 502 ? "Bad Gateway"
                       : code == 503 ? "Service Unavailable"
                       : code == 504 ? "Gateway Timeout"
                                     : "Error";
  char head[384];
  int body_len = (int)strlen(text);
  int n = snprintf(head, sizeof head,
                   "HTTP/1.1 %d %s\r\nContent-Length: %d\r\n"
                   "Content-Type: text/plain\r\n%s%s\r\n",
                   code, reason, body_len, extra,
                   keep_alive ? "" : "Connection: close\r\n");
  c->out.append(head, n);
  c->out.append(text, body_len);
  if (!keep_alive) c->want_close = true;
  count_resp(code, body_len);
}

void simple_response(Conn* c, int code, const char* text, bool keep_alive) {
  simple_response_x(c, code, text, keep_alive, "");
}

// Deadline + fault gate, run on every parsed client request before
// dispatch. Replication hops are exempt: the primary already charged
// the client-facing deadline/fault budget for this write once.
// Returns false = pass, true = answered here (caller moves to the
// next pipelined request; when the body was not fully buffered the
// conn is close-marked so the unread stream cannot desync framing).
// Injected delays run on the IO thread on purpose: a slow front stalls
// every conn it owns, which is the failure mode being modelled.
bool gate_request(Conn* c, const Request& r, size_t avail) {
  if (r.is_replicate) return false;
  int deny = 0;
  const char* extra = "";
  if (r.deadline > 0 && wall_now() >= r.deadline) {
    deny = 504;
  } else if (faults_on[t_role].load(std::memory_order_relaxed)) {
    // same carve-outs as faults.aiohttp_middleware's _SKIP_PATHS
    static const char* kSkip[] = {"/metrics", "/debug/traces",
                                  "/debug/breakers", "/status", "/healthz"};
    for (const char* sp : kSkip)
      if (r.path_len == strlen(sp) && memcmp(r.path, sp, r.path_len) == 0)
        return false;
    bool is_read = ieq(r.method, r.method_len, "GET") ||
                   ieq(r.method, r.method_len, "HEAD") ||
                   ieq(r.method, r.method_len, "OPTIONS");
    double delay, prob;
    {
      std::lock_guard<std::mutex> lk(faults_mu);
      delay = is_read ? fault_read_delay[t_role] : fault_write_delay[t_role];
      prob = is_read ? fault_read_err[t_role] : fault_write_err[t_role];
    }
    if (delay > 0) usleep((useconds_t)(delay * 1e6));
    if (prob > 0 && fault_roll() < prob) {
      deny = 503;
      // same contract as faults.aiohttp_middleware: the handler never
      // ran, so the retry layer may replay blindly
      extra = "X-Sw-Retryable: 1\r\nRetry-After: 0\r\n";
    }
  }
  if (!deny) return false;
  n_errors++;
  const char* text = deny == 504 ? "deadline exceeded" : "fault injected";
  bool complete = false;
  int64_t blen = body_len_buffered(r, c->in.data() + c->in_off + r.head_len,
                                   avail - r.head_len, &complete);
  if (complete) {
    simple_response_x(c, deny, text, r.keep_alive, extra);
    c->in_off += r.head_len + (size_t)blen;
    c->sent_100 = false;
    return true;
  }
  // body still in flight: answer-and-close, discard whatever arrives
  simple_response_x(c, deny, text, false, extra);
  c->in.clear();
  c->in_off = 0;
  return true;
}

uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

// ---------------------------------------------------------------------------
// Group commit (dp_set_commit): one committer thread shared by every
// front in the process coalesces appended-but-unacked writes and
// issues ONE fsync per dirty volume per batch window — the Haystack
// amortization: concurrent needles share a contiguous .dat extent.
// Modes mirror storage/commit.py: 0=buffered (ack after pwrite,
// today's semantics, no commit machinery at all — native appends are
// unbuffered pwrites), 1=batch (ack from the fsync-completion
// callback), 2=sync (inline per-write fsync oracle).
//
// Lock discipline (commit-fsync contract, lock_discipline.py): the
// committer snapshots the queue under commit_mu, RELEASES it, and
// only then fsyncs — never under commit_mu and never under v->mu.
// fd lifetime is safe lock-free: dat_fd/idx_fd close only in ~Vol
// and the dirty map holds the shared_ptr until delivery.
// ---------------------------------------------------------------------------
std::atomic<int> commit_mode{0};  // 0 buffered / 1 batch / 2 sync
std::atomic<int64_t> commit_max_delay_ns{2000000};  // -commit.maxDelay
std::atomic<int64_t> commit_max_bytes_cfg{4 << 20};  // -commit.maxBytes
// monotonic stats, surfaced via dp_commit_stats
std::atomic<int64_t> n_commit_batches{0};
std::atomic<int64_t> n_commit_fsyncs{0};  // fsync() syscalls issued
std::atomic<int64_t> n_commit_writes{0};  // writes that paid a commit
std::atomic<int64_t> n_commit_bytes{0};
std::atomic<int64_t> n_commit_fsync_ns{0};

std::mutex commit_mu;
std::condition_variable commit_cv;
std::condition_variable commit_drain_cv;
std::thread commit_thread;
bool commit_thread_started = false;
bool commit_stop_flag = false;
bool commit_busy = false;  // fsync+delivery in flight (drain barrier)
std::deque<CommitWaiter> commit_q;
std::unordered_map<Vol*, std::shared_ptr<Vol>> commit_dirty;
int64_t commit_q_bytes = 0;
std::chrono::steady_clock::time_point commit_window_open;
std::atomic<int> n_active_servers{0};

const char* durability_name() {
  int m = commit_mode.load(std::memory_order_relaxed);
  return m == 1 ? "batch" : m == 2 ? "sync" : "buffered";
}

// sync-mode oracle: per-write fsync inline on the calling thread,
// covering both the .dat append and its idx entry (Volume.sync parity)
void commit_sync_inline(const std::shared_ptr<Vol>& v) {
  uint64_t t0 = now_ns();
  fsync(v->dat_fd);
  fsync(v->idx_fd);
  n_commit_fsync_ns += (int64_t)(now_ns() - t0);
  n_commit_fsyncs += 2;
  n_commit_writes += 1;
}

void committer_loop() {
  std::unique_lock<std::mutex> lk(commit_mu);
  while (true) {
    commit_cv.wait(lk, [] { return commit_stop_flag || !commit_q.empty(); });
    if (commit_stop_flag) return;
    // adaptive window: close at maxDelay after the first enqueue, once
    // maxBytes piled up, or — checked in ~250us slices — when the
    // queue has stopped growing. Quiescence means every in-flight
    // write of the wave is already queued; sleeping out the rest of
    // the window can't grow the batch, it only delays the acks (and
    // with request-response clients, the next wave's appends).
    // maxDelay stays the contract's MAXIMUM added latency; closing
    // early is always within it.
    auto deadline = commit_window_open + std::chrono::nanoseconds(
        commit_max_delay_ns.load(std::memory_order_relaxed));
    int64_t seen_bytes = commit_q_bytes;
    while (!commit_stop_flag && !commit_q.empty() &&
           commit_q_bytes <
               commit_max_bytes_cfg.load(std::memory_order_relaxed)) {
      auto slice = std::chrono::steady_clock::now() +
                   std::chrono::nanoseconds(250000);
      bool final_slice = slice >= deadline;
      if (commit_cv.wait_until(lk, final_slice ? deadline : slice) ==
          std::cv_status::timeout) {
        if (final_slice || commit_q_bytes == seen_bytes) break;
        seen_bytes = commit_q_bytes;
      }
    }
    if (commit_stop_flag) return;
    if (commit_q.empty()) continue;  // drained while we waited
    std::deque<CommitWaiter> batch;
    batch.swap(commit_q);
    std::unordered_map<Vol*, std::shared_ptr<Vol>> dirty;
    dirty.swap(commit_dirty);
    int64_t bytes = commit_q_bytes;
    commit_q_bytes = 0;
    commit_busy = true;
    lk.unlock();
    // lock released: the fsyncs happen out here (commit-fsync contract).
    // .dat only — one journal commit per dirty volume per batch. The
    // idx appends in .dat order, so a crash loses at most an idx
    // suffix that Volume.check_integrity's tail replay regains from
    // the fsynced .dat records.
    // fdatasync, not fsync: the size change is forced (needed to
    // retrieve the appended records) but the mtime journal ordering
    // is skipped — ~3x cheaper per batch on ext4
    uint64_t t0 = now_ns();
    for (auto& it : dirty) fdatasync(it.second->dat_fd);
    n_commit_fsync_ns += (int64_t)(now_ns() - t0);
    n_commit_fsyncs += (int64_t)dirty.size();
    n_commit_batches += 1;
    n_commit_bytes += bytes;
    // deliver per owning server so completions run on that server's
    // IO thread (same eventfd handoff as worker_loop's returned conns)
    std::unordered_map<Server*, std::vector<CommitWaiter>> per;
    for (auto& w : batch) per[w.s].push_back(w);
    for (auto& it : per) {
      Server* srv = it.first;
      {
        std::lock_guard<std::mutex> g(srv->ret_mu);
        for (auto& w : it.second) srv->commit_done.push_back(w);
      }
      uint64_t one = 1;
      (void)!write(srv->event_fd, &one, 8);
    }
    lk.lock();
    commit_busy = false;
    commit_drain_cv.notify_all();
  }
}

// IO-thread side: queue one appended write behind the open window.
void commit_enqueue(Server* s, const std::shared_ptr<Vol>& v,
                    int64_t nbytes, ReplOp* rop, uint64_t s3_id) {
  std::lock_guard<std::mutex> lk(commit_mu);
  if (!commit_thread_started) {
    commit_thread_started = true;
    commit_thread = std::thread(committer_loop);
  }
  bool was_empty = commit_q.empty();
  if (was_empty)
    commit_window_open = std::chrono::steady_clock::now();
  CommitWaiter w;
  w.s = s;
  w.rop = rop;
  w.s3_id = s3_id;
  w.nbytes = nbytes;
  commit_q.push_back(w);
  commit_dirty.emplace(v.get(), v);
  int64_t before = commit_q_bytes;
  commit_q_bytes += nbytes;
  n_commit_writes += 1;
  // wake the committer only at the two edges it acts on: window open
  // (it sits in the outer wait) and the maxBytes crossing (early
  // close). A notify per enqueue is a futex wake per write — on a
  // single core each one can preempt the IO loop mid-batch, and the
  // committer would just re-check its predicate and sleep again.
  int64_t cap = commit_max_bytes_cfg.load(std::memory_order_relaxed);
  if (was_empty || (before < cap && commit_q_bytes >= cap))
    commit_cv.notify_one();
}

// stop_server teardown: pull this server's queued waiters out of the
// committer (their acks will never be sent — the sweeps free the ops)
// and wait out any in-flight fsync/delivery so no Server* escapes the
// teardown. The removed waiters are parked in s->commit_done so the
// op sweep below frees exactly once, delivered or not.
void commit_drain_server(Server* s) {
  std::deque<CommitWaiter> mine;
  {
    std::unique_lock<std::mutex> lk(commit_mu);
    for (auto it = commit_q.begin(); it != commit_q.end();) {
      if (it->s == s) {
        commit_q_bytes -= it->nbytes;
        mine.push_back(*it);
        it = commit_q.erase(it);
      } else {
        ++it;
      }
    }
    commit_drain_cv.wait(lk, [] { return !commit_busy; });
  }
  std::lock_guard<std::mutex> g(s->ret_mu);
  for (auto& w : mine) s->commit_done.push_back(w);
}

// last front in the process stopped: join the committer so no thread
// outlives the library's users (clean under TSan / repeated restarts)
void commit_shutdown() {
  std::thread t;
  {
    std::lock_guard<std::mutex> lk(commit_mu);
    if (!commit_thread_started) return;
    commit_stop_flag = true;
    commit_cv.notify_all();
    t = std::move(commit_thread);
  }
  t.join();
  std::lock_guard<std::mutex> lk(commit_mu);
  commit_thread_started = false;
  commit_stop_flag = false;
  commit_dirty.clear();
}

// Flat {"Seaweed-K": "v", ...} JSON -> "Seaweed-K: v\r\n" header
// lines, Seaweed-prefixed keys only (python _read_fid:445-451).
// Returns false on anything beyond simple unescaped string:string
// members (the caller relays those to python) or on control chars
// (header-injection guard — python's header validation rejects them
// there too).
bool pairs_to_headers(const char* js, size_t n, std::string* out) {
  size_t i = 0;
  auto skip_ws = [&] {
    while (i < n && (js[i] == ' ' || js[i] == '\t' || js[i] == '\n' ||
                     js[i] == '\r'))
      i++;
  };
  auto parse_str = [&](std::string* s) -> bool {
    if (i >= n || js[i] != '"') return false;
    i++;
    s->clear();
    while (i < n && js[i] != '"') {
      unsigned char ch = js[i];
      if (ch == '\\' || ch < 0x20) return false;  // escapes/control: python
      s->push_back(js[i++]);
    }
    if (i >= n) return false;
    i++;  // closing quote
    return true;
  };
  skip_ws();
  if (i >= n || js[i] != '{') return false;
  i++;
  skip_ws();
  if (i < n && js[i] == '}') {  // empty object (+ nothing after)
    i++;
    skip_ws();
    return i == n;
  }
  while (true) {
    std::string k, v;
    skip_ws();
    if (!parse_str(&k)) return false;
    skip_ws();
    if (i >= n || js[i] != ':') return false;
    i++;
    skip_ws();
    if (!parse_str(&v)) return false;  // non-string values: python
    if (k.size() >= 8 && strncasecmp(k.c_str(), "seaweed-", 8) == 0) {
      out->append(k);
      out->append(": ");
      out->append(v);
      out->append("\r\n");
    }
    skip_ws();
    if (i < n && js[i] == ',') {
      i++;
      continue;
    }
    if (i < n && js[i] == '}') {
      i++;
      skip_ws();
      return i == n;  // trailing garbage = not valid JSON: python
    }
    return false;
  }
}

// Parse a single "bytes=..." Range spec against `size` bytes — the
// ONE range parser for the volume and S3 fast paths (python
// _read_fid:494-512 semantics: unknown units are ignored, huge
// numbers SATURATE like python's unbounded ints and then the bounds
// rules decide, a missing dash means an open end, multi-range and
// non-numeric specs are malformed). Returns 0 = serve full (no/
// ignored range), 1 = partial (start/end set), -1 = malformed,
// -2 = unsatisfiable.
int parse_byte_range(const char* range, size_t range_len, int64_t size,
                     int64_t* start, int64_t* end) {
  if (!range) return 0;
  if (range_len <= 6 || memcmp(range, "bytes=", 6) != 0)
    return 0;  // unknown unit: ignored per RFC 7233
  const char* spec = range + 6;
  size_t spec_len = range_len - 6;
  const char* dash = (const char*)memchr(spec, '-', spec_len);
  const char* s_end = dash ? dash : spec + spec_len;
  const char* e_begin = dash ? dash + 1 : spec + spec_len;
  auto parse_num = [](const char* p, const char* e, int64_t* out) {
    if (p == e) return false;
    int64_t v = 0;
    for (; p < e; p++) {
      if (*p < '0' || *p > '9') return false;  // incl. ',' multi-range
      // saturate instead of overflowing: python ints are unbounded,
      // and a wrapped-negative start once slipped past the bounds
      // checks into an out-of-bounds buffer read
      if (v > (INT64_MAX - 9) / 10)
        v = INT64_MAX;
      else
        v = v * 10 + (*p - '0');
    }
    *out = v;
    return true;
  };
  *start = 0;
  *end = size - 1;
  bool ok;
  if (s_end == spec) {  // suffix form bytes=-N: the LAST N bytes
    int64_t n_last = 0;
    ok = parse_num(e_begin, spec + spec_len, &n_last);
    if (ok) *start = std::max<int64_t>(0, size - n_last);
  } else {
    ok = parse_num(spec, s_end, start);
    if (ok && e_begin < spec + spec_len)
      ok = parse_num(e_begin, spec + spec_len, end);
  }
  if (!ok) return -1;
  *end = std::min<int64_t>(*end, size - 1);
  if (*start > *end || *start >= size) return -2;
  return 1;
}

// GET/HEAD fast path. Returns false when the request must be proxied.
bool handle_get(Conn* c, const Request& r, uint32_t vid, uint64_t key,
                uint32_t cookie, bool is_head) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return false;  // not attached (EC, remote, elsewhere): proxy
  int64_t off;
  int32_t size;
  int version;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached) return false;
    auto it = v->map.find(key);
    if (it == v->map.end() || it->second.size <= 0) {
      simple_response(c, 404, "", r.keep_alive);
      return true;
    }
    off = it->second.offset;
    size = it->second.size;
    version = v->version;
  }
  int64_t rec_len = disk_size(size, version);
  std::string rec;
  rec.resize(rec_len);
  ssize_t got = pread(v->dat_fd, &rec[0], rec_len, off);
  if (got != rec_len) {
    n_errors++;
    simple_response(c, 500, "short read", r.keep_alive);
    return true;
  }
  const uint8_t* p = (const uint8_t*)rec.data();
  uint32_t disk_cookie = be32(p);
  uint64_t disk_id = be64(p + 4);
  int32_t disk_size_field = (int32_t)be32(p + 12);
  if (disk_id != key || disk_size_field != size) {
    n_errors++;
    simple_response(c, 500, "needle mismatch", r.keep_alive);
    return true;
  }
  if (disk_cookie != cookie) {
    simple_response(c, 403, "cookie mismatch", r.keep_alive);
    return true;
  }
  // body: data_size(4) data flags(1) [name] [mime] [lm] [ttl] [pairs]
  uint32_t data_size = be32(p + HEADER);
  if ((int64_t)data_size + 5 > size) {
    n_errors++;
    simple_response(c, 500, "corrupt needle", r.keep_alive);
    return true;
  }
  const uint8_t* data = p + HEADER + 4;
  const uint8_t* cur = data + data_size;
  uint8_t flags = *cur++;
  bool compressed = flags & FLAG_IS_COMPRESSED;
  // python inflates; ranges address ORIGINAL bytes, so a compressed
  // needle with a Range header must inflate there too
  if (compressed && (!r.accept_gzip || r.range)) return false;
  // chunk-manifest needles reassemble server-side from sub-fids
  // (tryHandleChunkedFile) — python owns that path
  if (flags & FLAG_IS_CHUNK_MANIFEST) return false;
  const uint8_t* mime = nullptr;
  size_t mime_len = 0;
  const uint8_t* body_end = p + HEADER + size;
  if (flags & FLAG_HAS_NAME && cur < body_end) cur += 1 + *cur;
  if (flags & FLAG_HAS_MIME && cur < body_end) {
    mime_len = *cur++;
    mime = cur;
    cur += mime_len;
  }
  uint64_t last_modified = 0;
  if (flags & FLAG_HAS_LAST_MODIFIED && cur + 5 <= body_end) {
    for (int i = 0; i < 5; i++) last_modified = last_modified << 8 | cur[i];
    cur += 5;
  }
  if (flags & FLAG_HAS_TTL && cur + 2 <= body_end) cur += 2;
  // Seaweed-* metadata pairs ride the needle as flat JSON
  // (needle_parse_upload.go parsePairs); emit them as response
  // headers like the python read path. Anything beyond simple
  // string:string JSON (escapes, nesting, non-string values) relays
  // to python, which renders it exactly.
  std::string pair_headers;
  if (flags & FLAG_HAS_PAIRS) {
    if (cur + 2 > body_end) return false;
    size_t plen = (size_t)cur[0] << 8 | cur[1];
    cur += 2;
    if (cur + plen > body_end) return false;
    if (!pairs_to_headers((const char*)cur, plen, &pair_headers))
      return false;
    cur += plen;
  }
  if (cur > body_end) {
    n_errors++;
    simple_response(c, 500, "corrupt needle body", r.keep_alive);
    return true;
  }
  uint32_t stored_crc = be32(p + HEADER + size);
  uint32_t actual = data_size ? crc32c(0, data, data_size) : 0;
  if (data_size && stored_crc != actual &&
      stored_crc != legacy_crc_value(actual)) {
    n_errors++;
    simple_response(c, 500, "CRC error: data on disk corrupted", r.keep_alive);
    return true;
  }
  // single-range GET (handlers_read.go writeResponseContent): one
  // shared parser (parse_byte_range above); malformed specs RELAY so
  // the python path decides — multi-range answers as
  // multipart/byteranges there (common.go:348), and a garbage spec
  // gets python's 416 with its Content-Range: bytes */N header.
  int64_t start_i = 0, end_i = (int64_t)data_size - 1;
  bool partial = false;
  if (r.range && !is_head) {
    int rc = parse_byte_range(r.range, r.range_len, (int64_t)data_size,
                              &start_i, &end_i);
    if (rc == -1) return false;  // multi-range/junk: python path
    if (rc == -2) {
      // RFC 7233: a 416 SHOULD say the actual size — clients read
      // the total from "bytes */N" to retry with a valid range, and
      // the python paths send the same header
      char h416[160];
      int hn = snprintf(h416, sizeof h416,
                        "HTTP/1.1 416 Requested Range Not Satisfiable"
                        "\r\nContent-Length: 0\r\n"
                        "Content-Range: bytes */%lld\r\n%s\r\n",
                        (long long)data_size,
                        r.keep_alive ? "" : "Connection: close\r\n");
      c->out.append(h416, hn);
      if (!r.keep_alive) c->want_close = true;
      count_resp(416, 0);
      return true;
    }
    partial = rc == 1;
  }
  char head[512];
  int n = snprintf(head, sizeof head,
                   "HTTP/1.1 %s\r\nContent-Length: %lld\r\n"
                   "Content-Type: %.*s\r\nEtag: \"%08x\"\r\n",
                   partial ? "206 Partial Content" : "200 OK",
                   partial ? (long long)(end_i - start_i + 1)
                           : (long long)data_size,
                   mime ? (int)mime_len : 24,
                   mime ? (const char*)mime : "application/octet-stream",
                   actual);
  c->out.append(head, n);
  if (partial) {
    char crng[96];
    int cn = snprintf(crng, sizeof crng,
                      "Content-Range: bytes %lld-%lld/%u\r\n",
                      (long long)start_i, (long long)end_i, data_size);
    c->out.append(crng, cn);
  }
  if (compressed) c->out.append("Content-Encoding: gzip\r\n");
  if (last_modified) {
    char datebuf[64];
    time_t lm = (time_t)last_modified;
    struct tm tmv;
    gmtime_r(&lm, &tmv);
    strftime(datebuf, sizeof datebuf,
             "Last-Modified: %a, %d %b %Y %H:%M:%S GMT\r\n", &tmv);
    c->out.append(datebuf);
  }
  c->out.append(pair_headers);
  if (!r.keep_alive) {
    c->out.append("Connection: close\r\n");
    c->want_close = true;
  }
  c->out.append("\r\n");
  if (!is_head)
    c->out.append((const char*)data + start_i, (size_t)(end_i - start_i + 1));
  n_fast_get++;
  count_resp(partial ? 206 : 200,
             is_head ? 0 : (int64_t)(end_i - start_i + 1));
  return true;
}

// Append a plain needle record (header, data_size, data, flags=0, crc,
// ts, pad — the minimal branch of Volume.append_needle /
// volume_write.go:144 doWriteRequest). Returns an HTTP status: 201 ok,
// 409 read-only, 500 IO error, or 0 = caller must fall back to the
// python path (detached / non-v3 volume).
int append_plain(const std::shared_ptr<Vol>& v, uint64_t key, uint32_t cookie,
                 const uint8_t* body, int64_t body_len, uint32_t* out_crc) {
  int32_t size = (int32_t)(4 + body_len + 1);
  int64_t rec_len = disk_size(size, 3);
  std::string rec;
  rec.resize(rec_len, '\0');
  uint8_t* p = (uint8_t*)&rec[0];
  put_be32(p, cookie);
  put_be64(p + 4, key);
  put_be32(p + 12, (uint32_t)size);
  put_be32(p + 16, (uint32_t)body_len);
  memcpy(p + 20, body, body_len);
  p[20 + body_len] = 0;  // flags
  uint32_t crc = crc32c(0, body, body_len);
  put_be32(p + 21 + body_len, crc);
  *out_crc = crc;
  std::lock_guard<std::mutex> lk(v->mu);
  if (v->detached) return 0;
  if (v->read_only) return 409;
  if (v->version != 3) return 0;  // v2 volumes: rare, python path
  uint64_t ns = now_ns();
  if (ns <= v->last_append_ns) ns = v->last_append_ns + 1;
  v->last_append_ns = ns;
  put_be64(p + 25 + body_len, ns);
  if (pwrite(v->dat_fd, rec.data(), rec_len, v->tail) != rec_len) return 500;
  int64_t off = v->tail;
  v->tail += rec_len;
  v->put(key, off, size);
  if (v->write_idx(key, off, (uint32_t)size) != 0) return 500;
  return 201;
}

// Tombstone append (Volume.delete_needle / volume_write.go
// deleteNeedle2): empty v3 needle + 0xFFFFFFFF .idx entry. Absent
// needles write NOTHING and reclaim 0 — dp_delete semantics. Same
// status convention as append_plain (202 ok).
int delete_tomb(const std::shared_ptr<Vol>& v, uint64_t key,
                int64_t* out_reclaimed) {
  uint8_t rec[32] = {0};  // disk_size(0, v3) = 28 -> padded to 32
  put_be64(rec + 4, key);
  std::lock_guard<std::mutex> lk(v->mu);
  if (v->detached) return 0;
  if (v->read_only) return 409;
  if (v->version != 3) return 0;
  auto it = v->map.find(key);
  if (it == v->map.end() || it->second.size <= 0) {
    *out_reclaimed = 0;
    return 202;
  }
  uint64_t ns = now_ns();
  if (ns <= v->last_append_ns) ns = v->last_append_ns + 1;
  v->last_append_ns = ns;
  put_be64(rec + 20, ns);
  if (pwrite(v->dat_fd, rec, sizeof rec, v->tail) != (ssize_t)sizeof rec)
    return 500;
  v->tail += sizeof rec;
  *out_reclaimed = v->del(key);
  if (v->write_idx(key, 0, 0xFFFFFFFFu) != 0) return 500;
  return 202;
}

void respond_post_ok(Conn* c, bool keep_alive, int64_t body_len,
                     uint32_t crc) {
  char resp[256];
  char jbody[128];
  int bl = snprintf(jbody, sizeof jbody,
                    "{\"name\": \"\", \"size\": %lld, \"eTag\": \"%08x\"}",
                    (long long)body_len, crc);
  int n = snprintf(resp, sizeof resp,
                   "HTTP/1.1 201 Created\r\nContent-Length: %d\r\n"
                   "Content-Type: application/json\r\n"
                   "X-Sw-Durability: %s\r\n%s\r\n",
                   bl, durability_name(),
                   keep_alive ? "" : "Connection: close\r\n");
  c->out.append(resp, n);
  c->out.append(jbody, bl);
  if (!keep_alive) c->want_close = true;
  count_resp(201, bl);
  front_stats[t_role].bytes_in += body_len;
}

void respond_delete_ok(Conn* c, bool keep_alive, int64_t reclaimed) {
  char resp[256];
  char jbody[64];
  int bl = snprintf(jbody, sizeof jbody, "{\"size\": %lld}",
                    (long long)reclaimed);
  int n = snprintf(resp, sizeof resp,
                   "HTTP/1.1 202 Accepted\r\nContent-Length: %d\r\n"
                   "Content-Type: application/json\r\n%s\r\n",
                   bl, keep_alive ? "" : "Connection: close\r\n");
  c->out.append(resp, n);
  c->out.append(jbody, bl);
  if (!keep_alive) c->want_close = true;
  count_resp(202, bl);
}

// POST fast path: plain body, no metadata, writable local volume.
// Guarded writes verify the HS256 token right here; replicated
// PRIMARY writes decline (the worker pool owns the peer fan-out) while
// incoming ?type=replicate secondary writes append inline.
bool handle_post(Server* s, Conn* c, const Request& r, uint32_t vid,
                 uint64_t key, uint32_t cookie, const uint8_t* body,
                 int64_t body_len, const char* fid, size_t fid_len) {
  if (r.has_query && !r.is_replicate) return false;
  if (r.proxy_only || !r.plain_upload || r.chunked) return false;
  if (body_len <= 0 || body_len > (8 << 20)) return false;
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return false;
  if (v->has_replicas && !r.is_replicate) return false;  // async fan-out
  JwtRes jr = jwt_check(r.auth, r.auth_len, fid, fid_len);
  if (jr == JwtRes::UNSURE) return false;  // python gives the verdict
  if (jr == JwtRes::REJECT) {
    n_jwt_reject++;
    simple_response(c, 401, "jwt rejected", r.keep_alive);
    return true;
  }
  uint32_t crc = 0;
  int st = append_plain(v, key, cookie, body, body_len, &crc);
  if (st == 0) return false;
  if (st == 409) {
    simple_response(c, 409, "volume is read only", r.keep_alive);
    return true;
  }
  if (st == 500) {
    n_errors++;
    simple_response(c, 500, "write failed", r.keep_alive);
    return true;
  }
  int mode = commit_mode.load(std::memory_order_relaxed);
  if (mode == 2) commit_sync_inline(v);
  if (mode == 1 && !r.is_replicate) {
    // batch durability: the ack releases from the fsync-completion
    // callback, not after pwrite. Gate the conn behind a one-token
    // ReplOp (no peer wires — the commit waiter IS the token);
    // incoming ?type=replicate secondary appends keep the immediate
    // ack, the primary's client ack carries the durability contract.
    ReplOp* op = new ReplOp();
    op->client = c;
    op->v = v;
    op->keep_alive = r.keep_alive;
    op->size = body_len;
    op->crc = crc;
    op->waiting = 1;  // the fsync token
    op->plain = true;
    c->repl_pending = true;
    commit_enqueue(s, v, body_len, op, 0);
    return true;
  }
  respond_post_ok(c, r.keep_alive, body_len, crc);
  n_fast_post++;
  return true;
}

// DELETE fast path (volume_server_handlers_write.go DeleteHandler →
// python _delete_fid): tombstone + 202 {"size": reclaimed}. Replicated
// primaries decline to the worker pool like POST.
bool handle_delete(Conn* c, const Request& r, uint32_t vid, uint64_t key,
                   const char* fid, size_t fid_len) {
  if (r.has_query && !r.is_replicate) return false;
  if (r.proxy_only || r.chunked || r.content_len != 0) return false;
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return false;
  if (v->has_replicas && !r.is_replicate) return false;  // async fan-out
  JwtRes jr = jwt_check(r.auth, r.auth_len, fid, fid_len);
  if (jr == JwtRes::UNSURE) return false;
  if (jr == JwtRes::REJECT) {
    n_jwt_reject++;
    simple_response(c, 401, "jwt rejected", r.keep_alive);
    return true;
  }
  if (!r.is_replicate) {
    // chunk-manifest needles cascade their chunk deletes in python
    // (_delete_fid -> delete_chunks); tombstoning one natively would
    // orphan every chunk forever. Probe the stored flag byte — two
    // preads, and only on the client-facing delete path.
    int64_t probe_off = -1;
    int32_t probe_sz = 0;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      auto it = v->map.find(key);
      if (it != v->map.end() && it->second.size > 0) {
        probe_off = it->second.offset;
        probe_sz = it->second.size;
      }
    }
    if (probe_off >= 0) {
      uint8_t hdr[20];
      if (pread(v->dat_fd, hdr, sizeof hdr, probe_off) ==
          (ssize_t)sizeof hdr) {
        uint32_t data_size = be32(hdr + 16);
        if ((int64_t)data_size + 5 <= probe_sz) {
          uint8_t flag = 0;
          if (pread(v->dat_fd, &flag, 1,
                    probe_off + 20 + (int64_t)data_size) == 1 &&
              (flag & FLAG_IS_CHUNK_MANIFEST))
            return false;  // relay: python cascades
        }
      }
    }
  }
  int64_t reclaimed = 0;
  int st = delete_tomb(v, key, &reclaimed);
  if (st == 0) return false;
  if (st == 409) {
    simple_response(c, 409, "volume is read only", r.keep_alive);
    return true;
  }
  if (st == 500) {
    n_errors++;
    simple_response(c, 500, "delete failed", r.keep_alive);
    return true;
  }
  respond_delete_ok(c, r.keep_alive, reclaimed);
  n_fast_delete++;
  return true;
}

// ---------------------------------------------------------------------------
// Proxy relay (blocking, runs on worker threads)
// ---------------------------------------------------------------------------
int connect_backend(uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_in a = {};
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (connect(fd, (struct sockaddr*)&a, sizeof a) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  struct timeval tv = {300, 0};  // vacuum/EC admin calls can run minutes
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  return fd;
}

bool send_all(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= w;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Replica fan-out plumbing shared with the benchmark clients. The
// fan-out itself is the ASYNC state machine further down (submit_repl
// and friends, on the IO thread).
// ---------------------------------------------------------------------------
int connect_hostport(const std::string& hostport) {
  size_t colon = hostport.rfind(':');
  if (colon == std::string::npos) return -1;
  std::string host = hostport.substr(0, colon);
  std::string port = hostport.substr(colon + 1);
  struct addrinfo hints = {}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
    return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd >= 0 && connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    struct timeval tv = {30, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  }
  return fd;
}

// Non-blocking variant for the IO thread: a SYN that goes unanswered
// (peer power loss / partition) must never stall epoll_wait — the
// connect completes (or fails) as an EPOLLOUT/ERR event instead.
// *in_progress reports EINPROGRESS. Numeric peer addresses resolve
// without blocking (AI_NUMERICHOST); hostname peers fall back to a
// regular lookup — same trade the reference's dialer makes.
int connect_hostport_nb(const std::string& hostport, bool* in_progress) {
  *in_progress = false;
  size_t colon = hostport.rfind(':');
  if (colon == std::string::npos) return -1;
  std::string host = hostport.substr(0, colon);
  std::string port = hostport.substr(colon + 1);
  struct addrinfo hints = {}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_NUMERICHOST;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) {
    hints.ai_flags = 0;  // hostname peer: blocking DNS, rare
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0)
      return -1;
  }
  if (!res) return -1;
  int fd = socket(res->ai_family,
                  res->ai_socktype | SOCK_NONBLOCK, res->ai_protocol);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      if (errno == EINPROGRESS) {
        *in_progress = true;
      } else {
        close(fd);
        fd = -1;
      }
    }
  }
  freeaddrinfo(res);
  return fd;
}

// Read one HTTP response off `fd` (head + Content-Length body, or —
// when allow_chunked — a chunked body to its terminator). Returns the
// status code, or -1 on socket error / unframed / oversized response.
// Shared by the peer fan-out and both benchmark clients.
int read_framed_response(int fd, std::string* resp, size_t limit,
                         bool allow_chunked) {
  resp->clear();
  char buf[16 << 10];
  ssize_t head_end = -1;
  int64_t cl = -1;
  bool chunked = false;
  while (true) {
    if (head_end < 0) {
      const char* e =
          (const char*)memmem(resp->data(), resp->size(), "\r\n\r\n", 4);
      if (e) {
        head_end = e - resp->data() + 4;
        const char* clh = (const char*)memmem(resp->data(), head_end,
                                              "Content-Length:", 15);
        if (!clh)
          clh = (const char*)memmem(resp->data(), head_end,
                                    "content-length:", 15);
        if (clh) cl = strtoll(clh + 15, nullptr, 10);
        if (allow_chunked && memmem(resp->data(), head_end, "chunked", 7))
          chunked = true;
      }
    }
    if (head_end >= 0) {
      // 204/304 are body-less by status (RFC 7230 §3.3.3) and carry
      // no Content-Length — headers complete the response
      int code0 = head_end >= 12 ? atoi(resp->c_str() + 9) : 0;
      if (code0 == 204 || code0 == 304) break;
      if (chunked) {
        if (memmem(resp->data() + head_end, resp->size() - head_end,
                   "0\r\n\r\n", 5))
          break;
      } else if (cl >= 0) {
        if ((int64_t)resp->size() >= head_end + cl) break;
      } else {
        return -1;  // unframed: the conn can't be reused safely
      }
    }
    ssize_t got = recv(fd, buf, sizeof buf, 0);
    if (got <= 0) return -1;
    resp->append(buf, got);
    if (resp->size() > limit) return -1;
  }
  if (resp->size() < 12) return -1;
  return atoi(resp->c_str() + 9);
}

// Incremental chunked-transfer scanner: feed() consumes any byte
// slice and remembers mid-chunk state, so relays never re-parse a
// trimmed buffer (re-parsing from an arbitrary offset misreads chunk
// payload bytes as size lines).
struct ChunkScan {
  enum { SIZE_LINE, DATA, DATA_CRLF, TRAILER } state = SIZE_LINE;
  std::string line;      // current size/trailer line accumulator
  int64_t remaining = 0; // payload bytes left in the current chunk
  bool last = false;     // saw the 0-size chunk
  bool done = false;

  // Consumes up to n bytes; returns how many were consumed (< n only
  // when the terminator was reached mid-slice).
  size_t feed(const char* p, size_t n) {
    size_t i = 0;
    while (i < n && !done) {
      switch (state) {
        case SIZE_LINE:
          line.push_back(p[i++]);
          if (line.size() >= 2 && line[line.size() - 2] == '\r' &&
              line.back() == '\n') {
            remaining = strtoll(line.c_str(), nullptr, 16);
            last = remaining == 0;
            state = last ? TRAILER : DATA;
            line.clear();
          } else if (line.size() > 4096) {
            done = true;  // malformed; stop consuming
          }
          break;
        case DATA: {
          int64_t take = std::min<int64_t>(remaining, n - i);
          remaining -= take;
          i += take;
          if (remaining == 0) state = DATA_CRLF;
          break;
        }
        case DATA_CRLF:
          line.push_back(p[i++]);
          if (line.size() == 2) {
            line.clear();
            state = SIZE_LINE;
          }
          break;
        case TRAILER:
          line.push_back(p[i++]);
          if (line.size() >= 2 && line[line.size() - 2] == '\r' &&
              line.back() == '\n') {
            if (line.size() == 2) {
              done = true;  // empty line terminates the trailer block
            } else {
              line.clear();  // a trailer header line; keep scanning
            }
          }
          break;
      }
    }
    return i;
  }
};

// Relay one already-head-parsed request from client conn to the backend and
// its response back. Client fd is in BLOCKING mode here. Returns false if
// either connection must be dropped.
bool proxy_one(Server* s, Conn* c, const Request& r) {
  n_proxied++;
  if (c->backend_fd < 0) c->backend_fd = connect_backend(s->backend_port);
  if (c->backend_fd < 0) {
    simple_response(c, 502, "backend unavailable", false);
    return send_all(c->fd, c->out.data(), c->out.size()), false;
  }
  int bfd = c->backend_fd;
  // clip the backend read timeout to the request's remaining deadline
  // budget (the default 300s accommodates vacuum/EC admin calls); the
  // keep-alive backend conn gets the default restored for the next
  // request by the unconditional set here
  {
    double rem = 300.0;
    if (r.deadline > 0) {
      rem = r.deadline - wall_now();
      if (rem < 0.05) rem = 0.05;  // expired mid-queue: fail fast
      if (rem > 300.0) rem = 300.0;
    }
    struct timeval tv = {(time_t)rem,
                         (suseconds_t)((rem - (double)(time_t)rem) * 1e6)};
    setsockopt(bfd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
  }
  // 1+2. forward head + body (buffered part first, then streamed) —
  // chunked framing is tracked by the incremental ChunkScan so a
  // body of any size relays without re-parsing from buffer offsets
  const char* req0 = c->in.data() + c->in_off;
  size_t avail = c->in.size() - c->in_off;
  char buf[64 << 10];
  if (r.chunked) {
    ChunkScan scan;
    size_t used0 = scan.feed(req0 + r.head_len, avail - r.head_len);
    if (!send_all(bfd, req0, r.head_len + used0)) return false;
    std::string leftover;
    while (!scan.done) {
      ssize_t got = recv(c->fd, buf, sizeof buf, 0);
      if (got <= 0) return false;
      size_t used = scan.feed(buf, got);
      if (!send_all(bfd, buf, used)) return false;
      if (scan.done && used < (size_t)got)
        leftover.assign(buf + used, got - used);  // pipelined bytes
    }
    c->in_off += r.head_len + used0;
    if (!leftover.empty()) {
      c->in.erase(0, c->in_off);
      c->in_off = 0;
      c->in += leftover;
    }
  } else {
    bool body_complete =
        (int64_t)(avail - r.head_len) >= r.content_len;
    size_t fwd =
        body_complete ? r.head_len + (size_t)r.content_len : avail;
    if (!send_all(bfd, req0, fwd)) return false;
    int64_t remaining = body_complete
                            ? 0
                            : r.content_len - (int64_t)(avail - r.head_len);
    while (remaining > 0) {
      // never read past the request: the next pipelined request's
      // bytes must not leak into this relay
      size_t want = (size_t)std::min<int64_t>(remaining, sizeof buf);
      ssize_t got = recv(c->fd, buf, want, 0);
      if (got <= 0) return false;
      if (!send_all(bfd, buf, got)) return false;
      remaining -= got;
    }
    c->in_off += fwd;  // streamed body came straight off the wire
  }
  // 3. read backend response head
  std::string resp;
  size_t resp_head = 0;
  int64_t resp_cl = -1;
  bool resp_chunked = false;
  bool resp_close = false;
  while (true) {
    const char* e = (const char*)memmem(resp.data(), resp.size(), "\r\n\r\n", 4);
    if (e) {
      resp_head = e - resp.data() + 4;
      break;
    }
    ssize_t got = recv(bfd, buf, sizeof buf, 0);
    if (got <= 0) return false;
    resp.append(buf, got);
    if (resp.size() > (1 << 20)) return false;
  }
  // parse response framing headers
  {
    const char* p = resp.data();
    const char* hend = p + resp_head;
    const char* le = (const char*)memmem(p, resp_head, "\r\n", 2);
    while (le && le + 2 < hend) {
      const char* ls = le + 2;
      const char* ne = (const char*)memmem(ls, hend - ls, "\r\n", 2);
      if (!ne) break;
      const char* colon = (const char*)memchr(ls, ':', ne - ls);
      if (colon) {
        size_t klen = colon - ls;
        const char* v = colon + 1;
        while (v < ne && *v == ' ') v++;
        size_t vlen = ne - v;
        if (ieq(ls, klen, "content-length"))
          resp_cl = strtoll(std::string(v, vlen).c_str(), nullptr, 10);
        else if (ieq(ls, klen, "transfer-encoding") &&
                 icontains(v, vlen, "chunked"))
          resp_chunked = true;
        else if (ieq(ls, klen, "connection") && icontains(v, vlen, "close"))
          resp_close = true;
      }
      le = ne;
    }
  }
  // 204/304 are body-less BY STATUS (RFC 7230 §3.3.3) and typically
  // carry no Content-Length — without this check the relay would wait
  // on the keep-alive backend conn for a body that never comes (the
  // S3 app answers every DELETE with 204)
  int resp_code = resp_head >= 12 ? atoi(resp.data() + 9) : 0;
  bool head_only = ieq(r.method, r.method_len, "HEAD") ||
                   resp_code == 204 || resp_code == 304;
  // 4. relay response to client
  if (!send_all(c->fd, resp.data(), resp.size())) return false;
  int64_t body_have = resp.size() - resp_head;
  if (!head_only) {
    if (resp_chunked) {
      ChunkScan scan;
      scan.feed(resp.data() + resp_head, resp.size() - resp_head);
      while (!scan.done) {
        ssize_t got = recv(bfd, buf, sizeof buf, 0);
        if (got <= 0) return false;
        size_t used = scan.feed(buf, got);
        if (!send_all(c->fd, buf, used)) return false;
        // one request in flight per backend conn: bytes past the
        // terminator would mean a broken backend — drop the conn
        if (scan.done && used < (size_t)got) resp_close = true;
      }
    } else if (resp_cl >= 0) {
      int64_t remaining2 = resp_cl - body_have;
      while (remaining2 > 0) {
        ssize_t got = recv(bfd, buf,
                           (size_t)std::min<int64_t>(remaining2, sizeof buf), 0);
        if (got <= 0) return false;
        if (!send_all(c->fd, buf, got)) return false;
        remaining2 -= got;
      }
    } else {
      // no framing: relay until backend closes, then drop the client conn
      while (true) {
        ssize_t got = recv(bfd, buf, sizeof buf, 0);
        if (got < 0) return false;
        if (got == 0) break;
        if (!send_all(c->fd, buf, got)) return false;
      }
      resp_close = true;
    }
  }
  if (resp_close) {
    close(c->backend_fd);
    c->backend_fd = -1;
  }
  return r.keep_alive;
}

// ---------------------------------------------------------------------------
// IO loop
// ---------------------------------------------------------------------------
void close_conn(Server* s, Conn* c) {
  if (c->in_epoll) epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  s->conns.erase(c->fd);
  if (c->backend_fd >= 0) close(c->backend_fd);
  close(c->fd);
  if (c->repl_pending) {
    // a replica fan-out still references this conn: defer the free
    // until the op concludes (finalize_repl deletes zombies)
    c->fd = -1;
    c->backend_fd = -1;
    c->in_epoll = false;
    c->zombie = true;
    return;
  }
  delete c;
}

void arm(Server* s, Conn* c, uint32_t events) {
  struct epoll_event ev = {};
  ev.events = events;
  ev.data.ptr = c;
  if (c->in_epoll) {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, c->fd, &ev);
  } else {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, c->fd, &ev);
    c->in_epoll = true;
  }
}

// Async replica fan-out entry (defined after flush_out): primary
// append + pipelined peer ship on the IO thread. Returns true when the
// request was taken (response arrives when every peer acks).
bool submit_repl(Server* s, Conn* c, const Request& r, uint32_t vid,
                 uint64_t key, uint32_t cookie, const uint8_t* body,
                 int64_t body_len, const char* fid, size_t fid_len,
                 bool is_delete);

// ---------------------------------------------------------------------------
// SWRP — the binary replication wire (native peer -> native peer).
// The reference replicates via full HTTP POSTs with a per-write JWT
// re-verified by the peer (topology/store_replicate.go:24 + guard).
// Between two native fronts that costs an HTTP parse + HMAC per write
// on the replica; SWRP replaces it with a one-time authenticated
// upgrade (POST /.swrp carrying a ".swrp"-claim token minted from the
// same shared secret) followed by fixed 21-byte frames:
//   u8 op (1=append, 2=delete) | u32 vid | u64 key | u32 cookie |
//   u32 body_len | body          (little-endian, x86 fleet)
// each answered in order by a fixed 14-byte ack:
//   u16 http-ish code | u32 crc | u64 size
// Primaries fall back to HTTP replicate when the peer answers the
// upgrade with anything but 101 (python-only peer, old build, or a
// jwt verdict the native side can't give).
// ---------------------------------------------------------------------------
constexpr size_t SWRP_HDR = 21;
constexpr size_t SWRP_ACK = 14;

int swrp_pump(Conn* c) {
  while (true) {
    size_t avail = c->in.size() - c->in_off;
    if (avail < SWRP_HDR) break;
    const uint8_t* p = (const uint8_t*)c->in.data() + c->in_off;
    uint8_t op = p[0];
    uint32_t vid, cookie, blen;
    uint64_t key;
    memcpy(&vid, p + 1, 4);
    memcpy(&key, p + 5, 8);
    memcpy(&cookie, p + 13, 4);
    memcpy(&blen, p + 17, 4);
    if ((op != 1 && op != 2) || blen > (8u << 20) || (op == 2 && blen != 0))
      return -1;  // poisoned channel: close, primary retries over HTTP
    if (avail < SWRP_HDR + blen) break;
    uint16_t code;
    uint32_t crc = 0;
    int64_t size = 0;
    std::shared_ptr<Vol> v = find_vol(vid);
    if (!v) {
      code = 404;
    } else if (op == 1) {
      int st = append_plain(v, key, cookie, p + SWRP_HDR, blen, &crc);
      code = st == 0 ? 503 : (uint16_t)st;  // 0 = python-only volume
      size = blen;
      if (st == 201) n_fast_post++;
    } else {
      int64_t reclaimed = 0;
      int st = delete_tomb(v, key, &reclaimed);
      code = st == 0 ? 503 : (uint16_t)st;
      size = reclaimed;
      if (st == 202) n_fast_delete++;
    }
    uint8_t ack[SWRP_ACK];
    memcpy(ack, &code, 2);
    memcpy(ack + 2, &crc, 4);
    memcpy(ack + 6, &size, 8);
    c->out.append((const char*)ack, SWRP_ACK);
    c->in_off += SWRP_HDR + blen;
  }
  if (c->in_off == c->in.size()) {
    c->in.clear();
    c->in_off = 0;
  }
  return 0;
}

// Relay the conn to a proxy worker: flush queued fast responses, send
// any owed 100-continue, remove from the IO thread's tables and queue
// it. Shared by the volume and S3 fronts. Always returns 1.
int proxy_handoff(Server* s, Conn* c, const Request& r, size_t avail) {
  // a proxied request with Expect: 100-continue must get the interim
  // response from US before the relay blocks waiting for its body —
  // the backend's own 100 (if any) is relayed too, which clients
  // tolerate (1xx may repeat)
  if (r.expect_100 && !c->sent_100) {
    bool body_done = false;
    body_len_buffered(r, c->in.data() + c->in_off + r.head_len,
                      avail - r.head_len, &body_done);
    if (!body_done) {
      c->out.append("HTTP/1.1 100 Continue\r\n\r\n");
      c->sent_100 = true;
    }
  }
  // proxy: hand the whole connection to a worker thread (it is
  // removed from the conns table too — the worker owns and may
  // delete it; re-registration happens via the returned queue)
  if (c->in_epoll) {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
    c->in_epoll = false;
  }
  s->conns.erase(c->fd);
  // flush anything already queued (fast responses for pipelined reqs)
  if (c->out.size() > c->out_off) {
    set_nonblock(c->fd, false);
    send_all(c->fd, c->out.data() + c->out_off, c->out.size() - c->out_off);
    c->out.clear();
    c->out_off = 0;
  }
  {
    std::lock_guard<std::mutex> lk(s->q_mu);
    s->proxy_q.push_back(c);
  }
  s->q_cv.notify_one();
  return 1;
}

// Try to serve buffered requests. Returns: 0 keep reading, 1 handed to
// proxy workers, -1 close.
int pump_inner(Server* s, Conn* c) {
  // a replicated op is in flight: hold further pipelined requests
  // until its response is written (HTTP responses must stay ordered)
  if (c->repl_pending) return 0;
  if (c->swrp) return swrp_pump(c);
  if (c->want_close) {  // close-marked response still flushing:
    c->in.clear();      // discard whatever else the client streams
    c->in_off = 0;
    return 0;
  }
  while (true) {
    if (c->in_off > 0 && c->in_off == c->in.size()) {
      c->in.clear();
      c->in_off = 0;
    }
    size_t avail = c->in.size() - c->in_off;
    if (avail == 0) break;
    Request r;
    ssize_t hl = parse_head(c->in.data() + c->in_off, avail, &r);
    if (hl < 0) return -1;
    if (hl == 0) break;  // need more bytes
    bool is_get = ieq(r.method, r.method_len, "GET");
    bool is_head = ieq(r.method, r.method_len, "HEAD");
    bool is_post =
        ieq(r.method, r.method_len, "POST") || ieq(r.method, r.method_len, "PUT");
    bool is_del = ieq(r.method, r.method_len, "DELETE");
    // SWRP upgrade: authenticate the replication channel once, then
    // switch this conn to binary frames (see the block above swrp_pump)
    if (is_post && r.path_len == 6 && memcmp(r.path, "/.swrp", 6) == 0 &&
        !r.chunked && r.content_len == 0) {
      JwtRes jr = jwt_check(r.auth, r.auth_len, ".swrp", 5);
      c->in_off += r.head_len;
      c->sent_100 = false;
      if (jr == JwtRes::OK) {
        c->out.append(
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: swrp\r\nConnection: Upgrade\r\n\r\n");
        c->swrp = true;
        return swrp_pump(c);
      }
      // REJECT and UNSURE both refuse the upgrade — the primary falls
      // back to HTTP replicate, where per-write tokens get the full
      // (python-assisted) verdict
      simple_response(c, jr == JwtRes::REJECT ? 401 : 400,
                      "swrp upgrade refused", r.keep_alive);
      continue;
    }
    uint32_t vid;
    uint64_t key;
    uint32_t cookie;
    bool fid_ok = parse_fid_path(r.path, r.path_len, &vid, &key, &cookie);
    // deadline/fault gate (SWRP above stays exempt). Deferred while a
    // fast-path write is still buffering its body — the pump re-parses
    // that request on every read, and the gate must fire exactly once
    // per request (seeded RNG) — but run before any dispatch otherwise
    // (proxied bodies stream without ever being fully buffered here).
    bool fast_body_waiting =
        is_post && fid_ok && (!r.has_query || r.is_replicate) &&
        !r.proxy_only && !r.chunked && r.content_len > 0 &&
        r.content_len <= (8 << 20) &&
        avail - r.head_len < (size_t)r.content_len;
    if (!fast_body_waiting && gate_request(c, r, avail)) continue;
    // fid as the JWT claim sees it: no leading slash, extension excluded
    const char* fid = r.path + 1;
    size_t fid_len = r.path_len ? r.path_len - 1 : 0;
    if (const char* dot = (const char*)memchr(fid, '.', fid_len))
      fid_len = dot - fid;
    // GET/HEAD fast path needs no body
    if ((is_get || is_head) && fid_ok && !r.has_query && !r.proxy_only &&
        !r.chunked && r.content_len == 0) {
      if (handle_get(c, r, vid, key, cookie, is_head)) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        continue;
      }
      // fall through to proxy
    } else if (is_post && fid_ok && (!r.has_query || r.is_replicate) &&
               !r.proxy_only &&
               !r.chunked && r.content_len > 0 && r.content_len <= (8 << 20)) {
      if (r.expect_100 && !c->sent_100 &&
          avail - r.head_len < (size_t)r.content_len) {
        // client waits for the go-ahead before sending the body;
        // send the interim response exactly once per request
        c->out.append("HTTP/1.1 100 Continue\r\n\r\n");
        c->sent_100 = true;
      }
      if (avail - r.head_len < (size_t)r.content_len) break;  // need body
      const uint8_t* body =
          (const uint8_t*)c->in.data() + c->in_off + r.head_len;
      if (handle_post(s, c, r, vid, key, cookie, body, r.content_len, fid,
                      fid_len)) {
        c->in_off += r.head_len + r.content_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;  // batch mode: ack on fsync
        continue;
      }
      if (submit_repl(s, c, r, vid, key, cookie, body, r.content_len,
                      fid, fid_len, false)) {
        c->in_off += r.head_len + r.content_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;  // response arrives on peer ack
        continue;
      }
      // fall through to proxy
    } else if (is_del && fid_ok && (!r.has_query || r.is_replicate) &&
               !r.proxy_only && !r.chunked && r.content_len == 0) {
      if (handle_delete(c, r, vid, key, fid, fid_len)) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        continue;
      }
      if (submit_repl(s, c, r, vid, key, cookie, nullptr, 0, fid,
                      fid_len, true)) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;
        continue;
      }
      // fall through to proxy
    }
    return proxy_handoff(s, c, r, avail);
  }
  return 0;
}

int s3_pump_inner(Server* s, Conn* c);     // S3-role twin, defined below
int filer_pump_inner(Server* s, Conn* c);  // filer-role twin, below

int pump(Server* s, Conn* c) {
  Conn* prev = s->pumping;
  s->pumping = c;
  int st = s->role == ROLE_S3     ? s3_pump_inner(s, c)
           : s->role == ROLE_FILER ? filer_pump_inner(s, c)
                                   : pump_inner(s, c);
  s->pumping = prev;
  return st;
}

// Returns false when the Conn was closed AND FREED — the caller must
// not touch `c` again after a false return.
bool flush_out(Server* s, Conn* c) {
  while (c->out_off < c->out.size()) {
    ssize_t w = send(c->fd, c->out.data() + c->out_off,
                     c->out.size() - c->out_off, MSG_NOSIGNAL);
    if (w > 0) {
      c->out_off += w;
      continue;
    }
    if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      arm(s, c, EPOLLIN | EPOLLOUT);
      return true;
    }
    close_conn(s, c);
    return false;
  }
  c->out.clear();
  c->out_off = 0;
  if (c->want_close) {
    close_conn(s, c);
    return false;
  }
  arm(s, c, EPOLLIN);
  return true;
}

// ---------------------------------------------------------------------------
// Async replica fan-out (store_replicate.go:24 ReplicatedWrite +
// :171 DistributedOperation, redesigned as an IO-thread state
// machine): the primary appends locally, then ships the body to every
// peer as POST/DELETE /<fid>?type=replicate with the client's JWT
// forwarded — WITHOUT blocking a thread per round trip. Requests to
// one peer ride ONE pipelined keep-alive connection; the client's 200
// waits for every peer ack (both copies must exist, like the
// reference), but many client writes keep their replicates in flight
// concurrently, so the peer RTT amortizes instead of serializing.
// Any peer failure fails that write (500) and marks the volume's peer
// list stale so writes relay to Python (which re-resolves placement)
// until the control plane pushes a fresh list.
// ---------------------------------------------------------------------------
struct ReplWire {
  // raw op params — encoded for the peer conn's negotiated wire
  // (SWRP frame or HTTP request) at flush time, and re-encoded when a
  // reconnect renegotiates the protocol
  uint32_t vid = 0;
  uint32_t cookie = 0;
  uint64_t key = 0;
  std::string body;  // copied out of the client buffer (it advances)
  std::string auth;  // client token, forwarded on the HTTP wire
  std::string traceparent;  // trace context, forwarded on the HTTP wire
  std::string fid;   // path fid (no slash, no extension)
  std::string head;  // encoded header bytes (frame or HTTP head)
  int enc_mode = -1;  // PeerConn mode `head` was built for
  size_t sent = 0;    // bytes of head+body already on the socket
  time_t enq = 0;     // hang-sweep clock
  ReplOp* op = nullptr;
  bool is_delete = false;
};

// Peer wire protocol states: the first use of a conn sends the SWRP
// upgrade; 101 switches to binary frames, anything else falls back to
// per-request HTTP replicate on the same conn.
constexpr int PEER_HS = -1;
constexpr int PEER_HTTP = 0;
constexpr int PEER_BIN = 1;

struct PeerConn {
  int kind = KIND_PEER;
  std::string hostport;
  int fd = -1;
  bool in_epoll = false;
  int mode = PEER_HS;
  std::string hs_buf;  // upgrade request bytes
  size_t hs_off = 0;
  std::string in;  // response bytes
  size_t in_off = 0;
  std::deque<ReplWire*> sendq;  // not yet fully written
  std::deque<ReplWire*> await;  // written, awaiting response (FIFO)
  bool retried = false;     // one reconnect per failure burst
  bool dirty = false;       // queued in Server::dirty_peers this batch
  bool connecting = false;  // non-blocking connect still in flight
};

size_t wire_total(const ReplWire* w) {
  return w->head.size() + (w->is_delete ? 0 : w->body.size());
}

void encode_wire(ReplWire* w, int mode) {
  w->head.clear();
  w->sent = 0;
  w->enc_mode = mode;
  if (mode == PEER_BIN) {
    uint8_t h[21];
    h[0] = w->is_delete ? 2 : 1;
    memcpy(h + 1, &w->vid, 4);
    memcpy(h + 5, &w->key, 8);
    memcpy(h + 13, &w->cookie, 4);
    uint32_t blen = w->is_delete ? 0 : (uint32_t)w->body.size();
    memcpy(h + 17, &blen, 4);
    w->head.append((const char*)h, sizeof h);
    return;
  }
  w->head.append(w->is_delete ? "DELETE /" : "POST /");
  w->head.append(w->fid);
  w->head.append("?type=replicate HTTP/1.1\r\nHost: x\r\n"
                 "Content-Type: application/octet-stream\r\n"
                 "Content-Length: ");
  w->head.append(
      std::to_string(w->is_delete ? 0 : (long long)w->body.size()));
  w->head.append("\r\n");
  if (!w->auth.empty()) {
    // forward the client's token: same fid claim, still inside its
    // validity window (the reference forwards the jwt the same way)
    w->head.append("Authorization: ");
    w->head.append(w->auth);
    w->head.append("\r\n");
  }
  if (!w->traceparent.empty()) {
    // pass-through only: the dataplane never records spans, it just
    // keeps the python-side trace stitched across the replicate hop
    w->head.append("traceparent: ");
    w->head.append(w->traceparent);
    w->head.append("\r\n");
  }
  w->head.append("\r\n");
}

void arm_peer(Server* s, PeerConn* pc, uint32_t events) {
  struct epoll_event ev = {};
  ev.events = events;
  ev.data.ptr = pc;
  if (pc->in_epoll) {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, pc->fd, &ev);
  } else {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, pc->fd, &ev);
    pc->in_epoll = true;
  }
}

// Fresh conn: queue the SWRP upgrade. Op wires wait for the verdict —
// pipelining frames behind the upgrade would garble an HTTP-only peer.
void start_handshake(PeerConn* pc) {
  pc->mode = PEER_HS;
  pc->hs_off = 0;
  pc->hs_buf = "POST /.swrp HTTP/1.1\r\nHost: ";
  pc->hs_buf += pc->hostport;
  pc->hs_buf += "\r\nUpgrade: swrp\r\nConnection: Upgrade\r\n"
                "Content-Length: 0\r\n";
  std::string tok = mint_swrp_token();
  if (!tok.empty()) {
    pc->hs_buf += "Authorization: Bearer ";
    pc->hs_buf += tok;
    pc->hs_buf += "\r\n";
  }
  pc->hs_buf += "\r\n";
}

PeerConn* get_peer(Server* s, const std::string& hostport) {
  PeerConn*& pc = s->peer_conns[hostport];
  if (!pc) {
    pc = new PeerConn();
    pc->hostport = hostport;
  }
  if (pc->fd < 0) {
    bool in_progress = false;
    int fd = connect_hostport_nb(hostport, &in_progress);
    if (fd < 0) return nullptr;
    pc->fd = fd;
    pc->connecting = in_progress;
    pc->in_epoll = false;
    pc->in.clear();
    pc->in_off = 0;
    start_handshake(pc);
  }
  return pc;
}

void finalize_repl(Server* s, ReplOp* op);
void peer_fail(Server* s, PeerConn* pc);

void peer_flush(Server* s, PeerConn* pc) {
  if (pc->fd < 0) return;
  if (pc->connecting) {
    // wait for the connect verdict (EPOLLOUT / EPOLLERR)
    arm_peer(s, pc, EPOLLIN | EPOLLOUT);
    return;
  }
  if (pc->mode == PEER_HS) {
    // only the upgrade goes out until the peer picks the protocol
    while (pc->hs_off < pc->hs_buf.size()) {
      ssize_t n = send(pc->fd, pc->hs_buf.data() + pc->hs_off,
                       pc->hs_buf.size() - pc->hs_off, MSG_NOSIGNAL);
      if (n > 0) {
        pc->hs_off += n;
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
      peer_fail(s, pc);
      return;
    }
    arm_peer(s, pc,
             EPOLLIN | (pc->hs_off < pc->hs_buf.size() ? EPOLLOUT : 0));
    return;
  }
  for (ReplWire* w : pc->sendq)
    if (w->enc_mode != pc->mode) encode_wire(w, pc->mode);
  while (!pc->sendq.empty()) {
    // one writev per burst: every queued wire's remaining head+body
    struct iovec iov[64];
    int nv = 0;
    for (ReplWire* w : pc->sendq) {
      if (nv >= 62) break;
      size_t hs = w->head.size();
      if (w->sent < hs)
        iov[nv++] = {(void*)(w->head.data() + w->sent), hs - w->sent};
      if (!w->is_delete) {
        size_t boff = w->sent > hs ? w->sent - hs : 0;
        if (boff < w->body.size())
          iov[nv++] = {(void*)(w->body.data() + boff),
                       w->body.size() - boff};
      }
    }
    ssize_t n = writev(pc->fd, iov, nv);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      peer_fail(s, pc);
      return;
    }
    if (n == 0) break;
    size_t left = (size_t)n;
    while (left > 0) {
      ReplWire* w = pc->sendq.front();
      size_t total = wire_total(w);
      size_t take = std::min(left, total - w->sent);
      w->sent += take;
      left -= take;
      if (w->sent == total) {
        pc->sendq.pop_front();
        pc->await.push_back(w);
      }
    }
  }
  // EPOLLIN always: responses, or the peer closing an idle conn
  arm_peer(s, pc, EPOLLIN | (pc->sendq.empty() ? 0 : EPOLLOUT));
}

// Resume a conn whose gated async op just concluded: flush the queued
// response and pump any requests buffered while the op was in flight.
// No-op when called synchronously from inside this conn's own pump
// (the pump loop continues and its caller flushes).
void resume_gated(Server* s, Conn* c) {
  if (s->pumping == c) return;
  if (!flush_out(s, c)) return;  // conn freed on write error / close
  int st = pump(s, c);
  if (st == -1)
    close_conn(s, c);
  else if (st == 0)
    flush_out(s, c);
  // st == 1: handed to proxy workers
}

// Conclude one op: stats, stale marking, client response, resume the
// client's (gated) pipeline.
void finalize_repl(Server* s, ReplOp* op) {
  if (op->failed) {
    n_fanout_fail++;
    std::lock_guard<std::mutex> lk(op->v->mu);
    op->v->peers_stale = true;  // relay until the next peer refresh
  } else if (op->plain) {
    n_fast_post++;  // group-commit-gated fast post, no fan-out
  } else if (op->is_delete) {
    n_fast_delete++;
  } else {
    n_repl_post++;
  }
  Conn* c = op->client;
  c->repl_pending = false;
  if (c->zombie) {
    delete c;
    delete op;
    return;
  }
  if (op->failed) {
    std::string msg = (op->is_delete ? "replicate delete to "
                                     : "replicate to ") +
                      op->failed_peer + " failed";
    simple_response(c, 500, msg.c_str(), op->keep_alive);
  } else if (op->is_delete) {
    respond_delete_ok(c, op->keep_alive, op->size);
  } else {
    respond_post_ok(c, op->keep_alive, op->size, op->crc);
  }
  c->sent_100 = false;
  delete op;
  resume_gated(s, c);
}

// Peer conn died (or responded unframed): retry the unacked tail once
// on a fresh connection — a dead keep-alive conn looks identical to a
// peer error (same contract as the old blocking fan-out; the replicate
// append is same-key-same-bytes idempotent, so a duplicate delivery on
// the retry is harmless). A second death without an intervening
// response fails every queued op.
void peer_fail(Server* s, PeerConn* pc) {
  if (pc->fd >= 0) {
    if (pc->in_epoll)
      epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, pc->fd, nullptr);
    close(pc->fd);
    pc->fd = -1;
    pc->in_epoll = false;
    pc->connecting = false;
  }
  pc->in.clear();
  pc->in_off = 0;
  std::deque<ReplWire*> items;
  items.swap(pc->await);
  for (ReplWire* w : pc->sendq) items.push_back(w);
  pc->sendq.clear();
  if (items.empty()) {
    pc->retried = false;  // idle server-side close: nothing lost
    return;
  }
  if (!pc->retried) {
    pc->retried = true;
    bool in_progress = false;
    int fd = connect_hostport_nb(pc->hostport, &in_progress);
    if (fd >= 0) {
      pc->fd = fd;
      pc->connecting = in_progress;
      start_handshake(pc);  // the fresh conn renegotiates the protocol
      time_t now = time(nullptr);
      for (ReplWire* w : items) {
        w->sent = 0;
        w->enq = now;  // the retry earns a fresh hang window
        pc->sendq.push_back(w);
      }
      peer_flush(s, pc);
      return;
    }
  }
  pc->retried = false;
  for (ReplWire* w : items) {
    ReplOp* op = w->op;
    op->waiting--;
    if (!op->failed) {
      op->failed = true;
      op->failed_peer = pc->hostport;
    }
    delete w;
    if (op->waiting == 0) finalize_repl(s, op);
  }
}

void peer_read(Server* s, PeerConn* pc) {
  char buf[16 << 10];
  while (true) {
    ssize_t got = recv(pc->fd, buf, sizeof buf, 0);
    if (got > 0) {
      pc->in.append(buf, got);
      if (pc->in.size() - pc->in_off > (size_t)(16 << 20)) {
        peer_fail(s, pc);  // runaway response
        return;
      }
      continue;
    }
    if (got == 0 || (errno != EAGAIN && errno != EWOULDBLOCK)) {
      peer_fail(s, pc);
      return;
    }
    break;  // EAGAIN: parsed what we have
  }
  if (pc->mode == PEER_HS) {
    // upgrade verdict: 101 = binary frames; any other framed response
    // = HTTP fallback (python-only peer / jwt verdict refused)
    const char* base = pc->in.data() + pc->in_off;
    size_t avail = pc->in.size() - pc->in_off;
    const char* he = (const char*)memmem(base, avail, "\r\n\r\n", 4);
    if (!he) return;  // need more bytes
    size_t head_len = he - base + 4;
    int code = avail >= 12 ? atoi(base + 9) : 0;
    if (code == 101) {
      pc->in_off += head_len;
      pc->mode = PEER_BIN;
    } else {
      const char* clh =
          (const char*)memmem(base, head_len, "Content-Length:", 15);
      if (!clh)
        clh = (const char*)memmem(base, head_len, "content-length:", 15);
      if (!clh) {
        peer_fail(s, pc);  // unframed refusal: the conn can't be reused
        return;
      }
      int64_t cl = strtoll(clh + 15, nullptr, 10);
      if ((int64_t)avail < (int64_t)head_len + cl) return;  // need body
      pc->in_off += head_len + cl;
      pc->mode = PEER_HTTP;
    }
    // NOTE: the handshake verdict does NOT reset the retry budget —
    // only a completed op response does. A peer that refuses the
    // upgrade and then closes would otherwise reconnect forever
    // (refuse -> close -> retry -> refuse ...) instead of failing the
    // queued ops over to the Python relay after one retry.
    peer_flush(s, pc);  // encode + ship everything queued
    if (pc->fd < 0) return;
  }
  if (pc->mode == PEER_BIN) {
    while (!pc->await.empty() &&
           pc->in.size() - pc->in_off >= SWRP_ACK) {
      const uint8_t* a = (const uint8_t*)pc->in.data() + pc->in_off;
      uint16_t code;
      uint32_t crc;
      int64_t size;
      memcpy(&code, a, 2);
      memcpy(&crc, a + 2, 4);
      memcpy(&size, a + 6, 8);
      pc->in_off += SWRP_ACK;
      ReplWire* w = pc->await.front();
      pc->await.pop_front();
      pc->retried = false;
      ReplOp* op = w->op;
      bool ok = (code >= 200 && code < 300) ||
                (w->is_delete && code == 404);  // peer never had the copy
      delete w;
      op->waiting--;
      if (!ok && !op->failed) {
        op->failed = true;
        op->failed_peer = pc->hostport;
      }
      if (op->waiting == 0) finalize_repl(s, op);
    }
    if (pc->in_off == pc->in.size()) {
      pc->in.clear();
      pc->in_off = 0;
    }
    return;
  }
  while (!pc->await.empty()) {
    const char* base = pc->in.data() + pc->in_off;
    size_t avail = pc->in.size() - pc->in_off;
    const char* he = (const char*)memmem(base, avail, "\r\n\r\n", 4);
    if (!he) break;
    size_t head_len = he - base + 4;
    const char* clh =
        (const char*)memmem(base, head_len, "Content-Length:", 15);
    if (!clh)
      clh = (const char*)memmem(base, head_len, "content-length:", 15);
    if (!clh) {
      peer_fail(s, pc);  // unframed: the conn can't be trusted
      return;
    }
    int64_t cl = strtoll(clh + 15, nullptr, 10);
    if ((int64_t)avail < (int64_t)head_len + cl) break;
    int code = avail >= 12 ? atoi(base + 9) : 0;
    bool close_hint =
        memmem(base, head_len, "Connection: close", 17) ||
        memmem(base, head_len, "connection: close", 17);
    pc->in_off += head_len + cl;
    ReplWire* w = pc->await.front();
    pc->await.pop_front();
    pc->retried = false;  // a live response resets the retry budget
    ReplOp* op = w->op;
    bool ok = (code >= 200 && code < 300) ||
              (w->is_delete && code == 404);  // peer never had the copy
    delete w;
    op->waiting--;
    if (!ok && !op->failed) {
      op->failed = true;
      op->failed_peer = pc->hostport;
    }
    if (op->waiting == 0) finalize_repl(s, op);
    if (close_hint) {
      peer_fail(s, pc);
      return;
    }
  }
  if (pc->in_off == pc->in.size()) {
    pc->in.clear();
    pc->in_off = 0;
  }
}

void peer_event(Server* s, PeerConn* pc, uint32_t events) {
  if (events & (EPOLLHUP | EPOLLERR)) {
    peer_fail(s, pc);
    return;
  }
  if (pc->connecting && (events & EPOLLOUT)) {
    int err = 0;
    socklen_t len = sizeof err;
    getsockopt(pc->fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) {
      peer_fail(s, pc);
      return;
    }
    pc->connecting = false;  // connected: fall through to the flush
  }
  if (events & EPOLLOUT) {
    peer_flush(s, pc);
    if (pc->fd < 0) return;  // flush hit a dead conn
  }
  if (events & EPOLLIN) peer_read(s, pc);
}

// Ops stuck past the window (peer accepted the conn but never
// responds) get the same treatment as a dead conn: one retry burst,
// then failure. 30s matches the old blocking path's SO_RCVTIMEO.
void peer_sweep(Server* s) {
  time_t now = time(nullptr);
  if (now == s->last_peer_sweep) return;
  s->last_peer_sweep = now;
  // snapshot first: peer_fail -> finalize -> pump can submit new ops
  // whose get_peer inserts into peer_conns, invalidating a live
  // iterator (PeerConn objects themselves live until dp_stop)
  std::vector<PeerConn*> snap;
  snap.reserve(s->peer_conns.size());
  for (auto& [hp, pc] : s->peer_conns) snap.push_back(pc);
  for (PeerConn* pc : snap) {
    ReplWire* oldest = !pc->await.empty() ? pc->await.front()
                       : !pc->sendq.empty() ? pc->sendq.front()
                                            : nullptr;
    if (oldest && now - oldest->enq > 30) peer_fail(s, pc);
  }
}

bool submit_repl(Server* s, Conn* c, const Request& r, uint32_t vid,
                 uint64_t key, uint32_t cookie, const uint8_t* body,
                 int64_t body_len, const char* fid, size_t fid_len,
                 bool is_delete) {
  // multipart/form and metadata uploads need Python's form decoding —
  // appending the raw envelope would corrupt the needle on every
  // replica (same guard as handle_post)
  if (!is_delete && !r.plain_upload) return false;
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return false;
  std::vector<std::string> peers;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached || !v->has_replicas || v->peers_stale ||
        v->peers.empty())
      return false;  // python resolves placement and fans out
    peers = v->peers;
  }
  JwtRes jr = jwt_check(r.auth, r.auth_len, fid, fid_len);
  if (jr == JwtRes::UNSURE) return false;  // python gives the verdict
  if (jr == JwtRes::REJECT) {
    n_jwt_reject++;
    simple_response(c, 401, "jwt rejected", r.keep_alive);
    return true;
  }
  uint32_t crc = 0;
  int64_t reclaimed = 0;
  int st = is_delete ? delete_tomb(v, key, &reclaimed)
                     : append_plain(v, key, cookie, body, body_len, &crc);
  if (st == 0) return false;
  if (st == 409) {
    simple_response(c, 409, "volume is read only", r.keep_alive);
    return true;
  }
  if (st == 500) {
    n_errors++;
    simple_response(c, 500, is_delete ? "delete failed" : "write failed",
                    r.keep_alive);
    return true;
  }
  ReplOp* op = new ReplOp();
  op->client = c;
  op->v = v;
  op->is_delete = is_delete;
  op->keep_alive = r.keep_alive;
  op->size = is_delete ? reclaimed : body_len;
  op->crc = crc;
  c->repl_pending = true;
  time_t now = time(nullptr);
  for (const auto& peer : peers) {
    PeerConn* pc = get_peer(s, peer);
    if (!pc) {
      if (!op->failed) {
        op->failed = true;
        op->failed_peer = peer;
      }
      continue;  // still await peers already queued
    }
    ReplWire* w = new ReplWire();
    w->op = op;
    w->is_delete = is_delete;
    w->enq = now;
    w->vid = vid;
    w->key = key;
    w->cookie = cookie;
    w->fid.assign(fid, fid_len);
    if (r.auth && r.auth_len) w->auth.assign(r.auth, r.auth_len);
    if (r.traceparent && r.traceparent_len)
      w->traceparent.assign(r.traceparent, r.traceparent_len);
    if (!is_delete && body_len > 0)
      w->body.assign((const char*)body, body_len);
    pc->sendq.push_back(w);
    op->waiting++;
    if (!pc->dirty) {  // flushed once per epoll batch (writev burst)
      pc->dirty = true;
      s->dirty_peers.push_back(pc);
    }
  }
  int mode = commit_mode.load(std::memory_order_relaxed);
  if (!is_delete) {
    if (mode == 2) commit_sync_inline(v);
    if (mode == 1) {
      // replica sends are already queued (they start from the page
      // cache); only the client ack additionally waits on the local
      // fsync — network and disk overlap instead of serializing
      op->waiting++;  // the fsync token
      commit_enqueue(s, v, body_len, op, 0);
    }
  }
  if (op->waiting == 0) finalize_repl(s, op);
  return true;
}

// End-of-batch peer flush: every client write handled in this epoll
// round queued its replicates; ship each peer's burst with one writev.
void flush_dirty_peers(Server* s) {
  for (size_t i = 0; i < s->dirty_peers.size(); i++) {
    PeerConn* pc = s->dirty_peers[i];
    pc->dirty = false;
    if (pc->fd >= 0)
      peer_flush(s, pc);
    else if (!pc->sendq.empty())
      peer_fail(s, pc);  // conn died between queue and flush: retry path
  }
  s->dirty_peers.clear();
}

// ---------------------------------------------------------------------------
// Native S3 front (role ROLE_S3) — the gateway hot path in C++.
//
// The reference serves S3 entirely in compiled Go
// (s3api_object_handlers_put.go -> filer autochunk); this build's
// python gateway measured ~1k rps against the same box's 40-60k
// native volume path. The front owns the public S3 port in the
// combined `server -s3` process: small-object PUT/GET/HEAD with
// header SigV4 are verified (auth_signature_v4.go semantics),
// appended to the LOCAL volume store from a pre-assigned fid pool,
// and the metadata insert is handed to the in-process python filer
// over a socketpair channel (the create_entry — parent dirs, old-
// chunk GC, event log — keeps its one python implementation).
// Everything else (multipart, presigned, V2, streaming-signed,
// listings, bucket ops, unknown identities) relays to the python S3
// app unchanged. The GET cache is maintained ONLY by the filer's
// serialized meta-event stream (cache_put/invalidate pushed under the
// filer mutation lock), so any mutation path — native or python —
// keeps it coherent; a miss relays and stays strongly consistent.
// ---------------------------------------------------------------------------
struct S3Ident {
  std::string secret;
  bool admin = false;
  bool write_all = false;
  bool read_all = false;
  std::unordered_set<std::string> wr, rd;  // bucket-scoped actions
};

std::shared_mutex s3_mu;  // identities + buckets + signing-key cache
std::unordered_map<std::string, S3Ident> s3_idents;
bool s3_open_mode = true;  // no identities configured = open access
std::unordered_set<std::string> s3_buckets;
std::unordered_map<std::string, std::array<uint8_t, 32>> s3_keycache;

struct S3Slot {
  uint32_t vid;
  uint64_t key;
  uint32_t cookie;
};
std::mutex s3_pool_mu;
std::unordered_map<std::string, std::deque<S3Slot>> s3_pools;

struct S3Ent {
  uint32_t vid;
  uint64_t key;
  uint32_t cookie;
  int64_t size;
  int64_t mtime;  // unix seconds
  std::string etag, mime;
  std::string meta;  // response-ready "x-amz-meta-k: v\r\n" block
};
std::shared_mutex s3_cache_mu;
std::unordered_map<std::string, S3Ent> s3_cache;  // "/bucket/key"
constexpr size_t S3_CACHE_CAP = 200000;

std::atomic<int64_t> n_s3_put{0}, n_s3_get{0}, n_s3_reject{0},
    n_s3_chan_fail{0}, n_s3_del{0}, n_s3_part{0};

// live multipart upload ids ("bucket\tupload_id"), synced by the python
// glue's meta listener from /buckets/<b>/.uploads/<id>/ marker dirs; a
// part-upload PUT whose id is absent relays to python (which answers
// NoSuchUpload itself — no XML parity burden here)
std::shared_mutex s3_upload_mu;
std::unordered_set<std::string> s3_uploads;

// ---- native filer front (role ROLE_FILER) state ----
// Entry cache keyed by the normalized full path ("/dir/file"). Like the
// S3 cache it is positive-only and maintained exclusively by the python
// glue's meta-event listener, so it inherits the zero-staleness
// contract: any mutation (either channel) emits a meta event before the
// mutating call returns, and the listener runs synchronously on it.
struct FilerEnt {
  uint32_t vid;
  uint64_t key;
  uint32_t cookie;
  int64_t size;
  int64_t mtime;  // unix seconds
  std::string etag, mime;
  std::string ext;  // response-ready "x-seaweed-ext-k: v\r\n" block
};
std::shared_mutex filer_cache_mu;
std::unordered_map<std::string, FilerEnt> filer_cache;
constexpr size_t FILER_CACHE_CAP = 200000;

// pre-assigned fid slots for native filer PUTs (default collection /
// replication — anything else relays)
std::mutex filer_pool_mu;
std::deque<S3Slot> filer_pool;

// Native writes are only sound while the python filer would apply no
// per-path policy the front can't see: the glue clears this whenever
// filer.conf rules, cipher, or save-inline limits are active.
std::atomic<bool> filer_writes_on{false};

std::atomic<int64_t> n_filer_put{0}, n_filer_get{0}, n_filer_del{0},
    n_filer_chan_fail{0};

// scan the raw request head for one header (case-insensitive name)
bool find_header(const char* head, size_t head_len, const char* name,
                 const char** val, size_t* vlen) {
  size_t nlen = strlen(name);
  const char* p = (const char*)memchr(head, '\n', head_len);
  if (!p) return false;
  p++;  // past the request line
  const char* end = head + head_len;
  while (p < end) {
    const char* le = (const char*)memchr(p, '\n', end - p);
    if (!le) break;
    const char* colon = (const char*)memchr(p, ':', le - p);
    if (colon && (size_t)(colon - p) == nlen &&
        strncasecmp(p, name, nlen) == 0) {
      const char* v = colon + 1;
      const char* ve = le > p && le[-1] == '\r' ? le - 1 : le;
      while (v < ve && (*v == ' ' || *v == '\t')) v++;
      while (ve > v && (ve[-1] == ' ' || ve[-1] == '\t')) ve--;
      *val = v;
      *vlen = ve - v;
      return true;
    }
    p = le + 1;
  }
  return false;
}

// AWS canonical form: trim + collapse inner whitespace runs to one
// space (python: " ".join(v.split()))
void collapse_ws(const char* v, size_t n, std::string* out) {
  size_t i = 0;
  while (i < n) {
    while (i < n && (v[i] == ' ' || v[i] == '\t')) i++;
    size_t j = i;
    while (j < n && v[j] != ' ' && v[j] != '\t') j++;
    if (j > i) {
      if (!out->empty()) out->push_back(' ');
      out->append(v + i, j - i);
    }
    i = j;
  }
}

bool s3_canonical_path(const char* p, size_t n) {
  for (size_t i = 0; i < n; i++) {
    char c = p[i];
    if (!(isalnum((unsigned char)c) || c == '/' || c == '-' ||
          c == '.' || c == '_' || c == '~'))
      return false;  // would need percent-encoding: relay
  }
  return true;
}

void s3_error(Conn* c, int status, const char* code, const char* msg,
              const char* path, size_t path_len, bool keep_alive) {
  char body[512];
  int bl = snprintf(body, sizeof body,
                    "<?xml version=\"1.0\" encoding=\"UTF-8\"?>\n"
                    "<Error><Code>%s</Code><Message>%s</Message>"
                    "<Resource>%.*s</Resource></Error>",
                    code, msg, (int)path_len, path);
  char head[256];
  const char* st = status == 403   ? "403 Forbidden"
                   : status == 400 ? "400 Bad Request"
                   : status == 404 ? "404 Not Found"
                                   : "500 Internal Server Error";
  int hl = snprintf(head, sizeof head,
                    "HTTP/1.1 %s\r\nContent-Type: application/xml\r\n"
                    "Content-Length: %d\r\n%s\r\n",
                    st, bl, keep_alive ? "" : "Connection: close\r\n");
  c->out.append(head, hl);
  c->out.append(body, bl);
  if (!keep_alive) c->want_close = true;
  n_s3_reject++;
  count_resp(status, bl);
}

constexpr const char* EMPTY_SHA256 =
    "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855";

// SigV4 header-auth verdict for the fast path.
enum class S3Auth { OK, REJECTED, RELAY };

// Verifies Authorization: AWS4-HMAC-SHA256 against the pushed
// identity table (auth_signature_v4.go semantics: canonical request,
// credential-scope signing key [cached per access-key+date], ±15min
// clock skew, payload-hash check). Writes the rejection response
// itself. Anything it can't judge definitively relays to python.
S3Auth s3_auth(Conn* c, const Request& r, const char* head,
               const char* method, bool need_write,
               const std::string& bucket, const uint8_t* body,
               int64_t body_len, const std::string& canon_query = "") {
  {
    std::shared_lock<std::shared_mutex> lk(s3_mu);
    if (s3_open_mode) return S3Auth::OK;
  }
  if (!r.auth || r.auth_len < 17 ||
      strncmp(r.auth, "AWS4-HMAC-SHA256 ", 17) != 0)
    return S3Auth::RELAY;  // presigned / V2 / anonymous: python's call
  // parse Credential=AK/date/region/service/aws4_request,
  // SignedHeaders=a;b;c, Signature=hex
  std::string ak, datestamp, region, service, signed_hdrs, sig;
  {
    const char* p = r.auth + 17;
    const char* end = r.auth + r.auth_len;
    while (p < end) {
      while (p < end && (*p == ' ' || *p == ',')) p++;
      const char* comma = (const char*)memchr(p, ',', end - p);
      if (!comma) comma = end;
      const char* eq = (const char*)memchr(p, '=', comma - p);
      if (eq) {
        std::string k(p, eq - p);
        std::string v(eq + 1, comma - eq - 1);
        if (k == "Credential") {
          size_t a = v.find('/'), b = v.find('/', a + 1),
                 d = v.find('/', b + 1), e = v.find('/', d + 1);
          if (e == std::string::npos) return S3Auth::RELAY;
          ak = v.substr(0, a);
          datestamp = v.substr(a + 1, b - a - 1);
          region = v.substr(b + 1, d - b - 1);
          service = v.substr(d + 1, e - d - 1);
        } else if (k == "SignedHeaders") {
          signed_hdrs = v;
        } else if (k == "Signature") {
          sig = v;
        }
      }
      p = comma + 1;
    }
  }
  if (ak.empty() || sig.empty() || signed_hdrs.empty())
    return S3Auth::RELAY;
  S3Ident ident;
  {
    std::shared_lock<std::shared_mutex> lk(s3_mu);
    auto it = s3_idents.find(ak);
    if (it == s3_idents.end())
      return S3Auth::RELAY;  // table may lag a hot reload: python decides
    ident = it->second;
  }
  // clock skew (auth_signature_v4.go:MAX_CLOCK_SKEW equivalent)
  const char* dv;
  size_t dvl;
  if (!find_header(head, r.head_len, "x-amz-date", &dv, &dvl) || dvl != 16)
    return S3Auth::RELAY;
  std::string amz_date(dv, dvl);
  struct tm tmv = {};
  if (sscanf(amz_date.c_str(), "%4d%2d%2dT%2d%2d%2dZ", &tmv.tm_year,
             &tmv.tm_mon, &tmv.tm_mday, &tmv.tm_hour, &tmv.tm_min,
             &tmv.tm_sec) != 6)
    return S3Auth::RELAY;
  tmv.tm_year -= 1900;
  tmv.tm_mon -= 1;
  time_t t = timegm(&tmv);
  time_t now = time(nullptr);
  if (t < now - 900 || t > now + 900) {
    s3_error(c, 403, "RequestTimeTooSkewed", "request time skewed",
             r.path, r.path_len, r.keep_alive);
    return S3Auth::REJECTED;
  }
  // payload hash: header must match the actual body (or be UNSIGNED)
  const char* hv;
  size_t hvl;
  if (!find_header(head, r.head_len, "x-amz-content-sha256", &hv, &hvl))
    return S3Auth::RELAY;
  std::string declared(hv, hvl);
  if (declared.compare(0, 10, "STREAMING-", 0, 10) == 0)
    return S3Auth::RELAY;  // aws-chunked framing: python decodes
  if (declared != "UNSIGNED-PAYLOAD") {
    if (declared.size() != 64) return S3Auth::RELAY;
    std::string actual =
        body_len > 0 ? sha256_hex(body, body_len) : EMPTY_SHA256;
    if (declared != actual) {
      s3_error(c, 400, "XAmzContentSHA256Mismatch",
               "payload hash does not match body", r.path, r.path_len,
               r.keep_alive);
      return S3Auth::REJECTED;
    }
  }
  // canonical request (python _canonical_request; fast path has no
  // query and a pre-canonical URI)
  std::vector<std::string> names;
  {
    size_t i = 0;
    while (i <= signed_hdrs.size()) {
      size_t j = signed_hdrs.find(';', i);
      if (j == std::string::npos) j = signed_hdrs.size();
      std::string nm = signed_hdrs.substr(i, j - i);
      for (auto& ch : nm) ch = (char)tolower((unsigned char)ch);
      if (!nm.empty()) names.push_back(nm);
      i = j + 1;
    }
  }
  std::sort(names.begin(), names.end());
  std::string creq;
  creq.reserve(256);
  creq += method;
  creq += '\n';
  creq.append(r.path, r.path_len);
  creq += '\n';
  creq += canon_query;  // "" for the query-less fast paths
  creq += '\n';
  for (const auto& nm : names) {
    creq += nm;
    creq += ':';
    const char* vv;
    size_t vvl;
    if (find_header(head, r.head_len, nm.c_str(), &vv, &vvl)) {
      std::string collapsed;
      collapse_ws(vv, vvl, &collapsed);
      creq += collapsed;
    }
    creq += '\n';
  }
  creq += '\n';
  for (size_t i = 0; i < names.size(); i++) {
    if (i) creq += ';';
    creq += names[i];
  }
  creq += '\n';
  creq += declared;
  // string to sign + cached signing key
  std::string sts = "AWS4-HMAC-SHA256\n" + amz_date + "\n" + datestamp +
                    "/" + region + "/" + service + "/aws4_request\n" +
                    sha256_hex((const uint8_t*)creq.data(), creq.size());
  std::array<uint8_t, 32> key;
  std::string ck = ak + "/" + datestamp + "/" + region + "/" + service;
  bool have = false;
  {
    std::shared_lock<std::shared_mutex> lk(s3_mu);
    auto it = s3_keycache.find(ck);
    if (it != s3_keycache.end()) {
      key = it->second;
      have = true;
    }
  }
  if (!have) {
    // kDate = HMAC("AWS4"+secret, date); kRegion = HMAC(kDate, region);
    // kService = HMAC(kRegion, service); key = HMAC(kService, terminal)
    std::string k0 = "AWS4" + ident.secret;
    uint8_t d1[32], d2[32], d3[32];
    hmac_sha256((const uint8_t*)k0.data(), k0.size(),
                (const uint8_t*)datestamp.data(), datestamp.size(), d1);
    hmac_sha256(d1, 32, (const uint8_t*)region.data(), region.size(), d2);
    hmac_sha256(d2, 32, (const uint8_t*)service.data(), service.size(),
                d3);
    hmac_sha256(d3, 32, (const uint8_t*)"aws4_request", 12, key.data());
    std::unique_lock<std::shared_mutex> lk(s3_mu);
    if (s3_keycache.size() > 4096) s3_keycache.clear();
    s3_keycache[ck] = key;
  }
  uint8_t mac[32];
  hmac_sha256(key.data(), 32, (const uint8_t*)sts.data(), sts.size(),
              mac);
  char hex[64];
  hex_encode(mac, 32, hex);
  if (sig.size() != 64 ||
      !const_time_eq((const uint8_t*)hex, (const uint8_t*)sig.data(), 64)) {
    s3_error(c, 403, "SignatureDoesNotMatch", "signature mismatch",
             r.path, r.path_len, r.keep_alive);
    return S3Auth::REJECTED;
  }
  // permission (Identity.allows: exact action or action:bucket)
  bool allowed = ident.admin ||
                 (need_write
                      ? (ident.write_all || ident.wr.count(bucket))
                      : (ident.read_all || ident.rd.count(bucket)));
  if (!allowed) {
    s3_error(c, 403, "AccessDenied", "permission denied", r.path,
             r.path_len, r.keep_alive);
    return S3Auth::REJECTED;
  }
  return S3Auth::OK;
}

// One gated channel mutation awaiting the python applier's ack. The
// response shape on success is per-kind (S3 and filer fronts share the
// channel machinery; is_delete kept as a kind alias for readability).
constexpr int OP_S3_PUT = 0;    // 200 + ETag + Content-Length: 0
constexpr int OP_S3_DEL = 1;    // 204 No Content
constexpr int OP_S3_PART = 2;   // part upload: same wire shape as PUT
constexpr int OP_FILER_PUT = 3; // 201 + {"name","size","etag"} json
constexpr int OP_FILER_DEL = 4; // 204 No Content

struct S3Op {
  Conn* client;
  bool keep_alive = true;
  bool is_delete = false;  // OP_S3_DEL / OP_FILER_DEL
  int kind = OP_S3_PUT;
  std::string etag;
  std::string name;      // OP_FILER_PUT: final path segment
  int64_t size = 0;      // OP_FILER_PUT: body size for the json reply
  // batch durability: the op finalizes only once BOTH the applier
  // verdict (chan_status) and the covering fsync have landed
  bool fsync_pending = false;
  int chan_status = -1;  // applier verdict parked while fsync pends
};

void arm_chan(Server* s, uint32_t events) {
  struct epoll_event ev = {};
  ev.events = events;
  ev.data.ptr = &s->chan_tag;
  if (s->chan_in_epoll) {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, s->chan_fd, &ev);
  } else {
    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->chan_fd, &ev);
    s->chan_in_epoll = true;
  }
}

void chan_flush(Server* s) {
  while (s->chan_out_off < s->chan_out.size()) {
    ssize_t n = send(s->chan_fd, s->chan_out.data() + s->chan_out_off,
                     s->chan_out.size() - s->chan_out_off, MSG_NOSIGNAL);
    if (n > 0) {
      s->chan_out_off += n;
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    // applier died: pending ops fail via chan_read EOF
    (s->role == ROLE_FILER ? n_filer_chan_fail : n_s3_chan_fail)++;
    break;
  }
  if (s->chan_out_off == s->chan_out.size()) {
    s->chan_out.clear();
    s->chan_out_off = 0;
  }
  arm_chan(s, EPOLLIN | (s->chan_out.empty() ? 0 : EPOLLOUT));
}

// Conclude one gated S3 PUT with the applier's verdict.
void s3_finalize(Server* s, S3Op* op, int status) {
  Conn* c = op->client;
  c->repl_pending = false;
  if (c->zombie) {
    delete c;
    delete op;
    return;
  }
  if (status >= 200 && status < 300) {
    char head[512];
    int hl;
    if (op->is_delete) {
      // S3 DeleteObject / filer DELETE: 204 whether or not the key
      // existed (the python filer answers 204 the same way)
      hl = snprintf(head, sizeof head,
                    "HTTP/1.1 204 No Content\r\n%s\r\n",
                    op->keep_alive ? "" : "Connection: close\r\n");
      if (op->kind == OP_FILER_DEL)
        n_filer_del++;
      else
        n_s3_del++;
      count_resp(204, 0);
    } else if (op->kind == OP_FILER_PUT) {
      // byte-match the python filer's 201 json
      // (web.json_response({"name","size","etag"}))
      char jbody[384];
      int bl = snprintf(jbody, sizeof jbody,
                        "{\"name\": \"%s\", \"size\": %lld, "
                        "\"etag\": \"%s\"}",
                        op->name.c_str(), (long long)op->size,
                        op->etag.c_str());
      hl = snprintf(head, sizeof head,
                    "HTTP/1.1 201 Created\r\n"
                    "Content-Type: application/json; charset=utf-8\r\n"
                    "Content-Length: %d\r\n%s\r\n",
                    bl, op->keep_alive ? "" : "Connection: close\r\n");
      c->out.append(head, hl);
      c->out.append(jbody, bl);
      hl = 0;
      n_filer_put++;
      count_resp(201, bl);
      front_stats[t_role].bytes_in += op->size;
    } else {
      hl = snprintf(head, sizeof head,
                    "HTTP/1.1 200 OK\r\nETag: \"%s\"\r\n"
                    "Content-Length: 0\r\n%s\r\n",
                    op->etag.c_str(),
                    op->keep_alive ? "" : "Connection: close\r\n");
      if (op->kind == OP_S3_PART)
        n_s3_part++;
      else
        n_s3_put++;
      count_resp(200, 0);
      front_stats[t_role].bytes_in += op->size;
    }
    if (hl) c->out.append(head, hl);
    if (!op->keep_alive) c->want_close = true;
  } else {
    if (op->kind == OP_FILER_PUT || op->kind == OP_FILER_DEL) {
      simple_response(c, 500, "metadata mutation failed", op->keep_alive);
    } else {
      s3_error(c, 500, "InternalError", "metadata mutation failed", "", 0,
               op->keep_alive);
    }
  }
  c->sent_100 = false;
  delete op;
  resume_gated(s, c);
}

void chan_read(Server* s) {
  char buf[16 << 10];
  bool dead = false;
  while (true) {
    ssize_t got = recv(s->chan_fd, buf, sizeof buf, 0);
    if (got > 0) {
      s->chan_in.append(buf, got);
      continue;
    }
    if (got == 0 || (errno != EAGAIN && errno != EWOULDBLOCK)) dead = true;
    break;
  }
  while (true) {
    size_t off = s->chan_in_off;
    const char* base = s->chan_in.data() + off;
    size_t avail = s->chan_in.size() - off;
    const char* nl = (const char*)memchr(base, '\n', avail);
    if (!nl) break;
    uint64_t id = strtoull(base, nullptr, 10);
    const char* sp = (const char*)memchr(base, ' ', nl - base);
    int status = sp ? atoi(sp + 1) : 500;
    s->chan_in_off = nl - s->chan_in.data() + 1;
    auto it = s->s3_pending.find(id);
    if (it != s->s3_pending.end()) {
      S3Op* op = it->second;
      if (op->fsync_pending) {
        op->chan_status = status;  // finalize when the fsync lands
      } else {
        s->s3_pending.erase(it);
        s3_finalize(s, op, status);
      }
    }
  }
  if (s->chan_in_off == s->chan_in.size()) {
    s->chan_in.clear();
    s->chan_in_off = 0;
  }
  if (dead) {
    // the python applier is gone: fail every gated PUT loudly
    (s->role == ROLE_FILER ? n_filer_chan_fail : n_s3_chan_fail)++;
    std::unordered_map<uint64_t, S3Op*> pending;
    pending.swap(s->s3_pending);
    for (auto& [id, op] : pending) s3_finalize(s, op, 500);
  }
}

// One fsync completion, delivered on the owning server's IO thread
// (io_loop eventfd branch). Volume front: drop the ReplOp's fsync
// token. S3/filer fronts: the op finalizes only when the applier
// verdict has also landed (a chan-death sweep may have freed it
// already — the id missing from s3_pending is the tombstone).
void commit_complete(Server* s, const CommitWaiter& w) {
  if (w.rop) {
    ReplOp* op = w.rop;
    op->waiting--;
    if (op->waiting == 0) finalize_repl(s, op);
    return;
  }
  auto it = s->s3_pending.find(w.s3_id);
  if (it == s->s3_pending.end()) return;
  S3Op* op = it->second;
  op->fsync_pending = false;
  if (op->chan_status >= 0) {
    int st = op->chan_status;
    s->s3_pending.erase(it);
    s3_finalize(s, op, st);
  }
}

// DELETE fast path: the metadata delete rides the channel (the python
// applier's filer.delete_entry carries chunk reclamation and the meta
// event that invalidates our cache); the front only skips the HTTP
// relay. Returns 0 to relay (query/multipart abort, unknown bucket).
int s3_handle_delete(Server* s, Conn* c, const Request& r,
                     const char* head, const std::string& bucket,
                     const char* key, size_t key_len) {
  S3Auth a = s3_auth(c, r, head, "DELETE", true, bucket, nullptr, 0);
  if (a == S3Auth::RELAY) return 0;
  if (a == S3Auth::REJECTED) return 1;
  uint64_t id = s->next_op_id++;
  std::string rec;
  rec.reserve(64 + key_len);
  char nbuf[48];
  snprintf(nbuf, sizeof nbuf, "%llu\tdel\t", (unsigned long long)id);
  rec += nbuf;
  rec += bucket;
  rec += '\t';
  rec.append(key, key_len);
  rec += '\n';
  S3Op* op = new S3Op();
  op->client = c;
  op->keep_alive = r.keep_alive;
  op->is_delete = true;
  s->s3_pending[id] = op;
  c->repl_pending = true;
  s->chan_out += rec;  // flushed once per epoll batch
  return 1;
}

// Serve a GET/HEAD from the cache entry's local needle. false = relay
// (volume gone/detached, compressed needle, unusual Range forms, or
// on-disk surprises).
bool s3_serve_cached(Conn* c, const Request& r, const S3Ent& ent,
                     bool is_head) {
  std::shared_ptr<Vol> v = find_vol(ent.vid);
  if (!v) return false;
  int64_t off;
  int32_t size;
  int version;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached) return false;
    auto it = v->map.find(ent.key);
    if (it == v->map.end() || it->second.size <= 0)
      return false;  // cache newer than the needle map? let python look
    off = it->second.offset;
    size = it->second.size;
    version = v->version;
  }
  int64_t rec_len = disk_size(size, version);
  std::string rec;
  rec.resize(rec_len);
  if (pread(v->dat_fd, &rec[0], rec_len, off) != rec_len) return false;
  const uint8_t* p = (const uint8_t*)rec.data();
  if (be64(p + 4) != ent.key || be32(p) != ent.cookie) return false;
  uint32_t data_size = be32(p + HEADER);
  if ((int64_t)data_size + 5 > size) return false;
  const uint8_t* data = p + HEADER + 4;
  uint8_t flags = data[data_size];
  if (flags & FLAG_IS_COMPRESSED) return false;  // python inflates
  uint32_t stored_crc = be32(p + HEADER + size);
  uint32_t actual = data_size ? crc32c(0, data, data_size) : 0;
  if (data_size && stored_crc != actual &&
      stored_crc != legacy_crc_value(actual))
    return false;  // corrupt: python's read path reports it properly
  // single-range GET (S3 GetObject with Range): the shared parser
  // serves well-formed satisfiable slices; malformed or unsatisfiable
  // specs RELAY so the python path's 416 XML / ignore semantics apply
  // verbatim (HEAD with a Range never reaches here — the pump gate
  // relays it, since AWS honors Range on HeadObject)
  int64_t start = 0, end = (int64_t)data_size - 1;
  bool partial = false;
  if (r.range && !is_head) {
    int rc = parse_byte_range(r.range, r.range_len, (int64_t)data_size,
                              &start, &end);
    if (rc < 0) return false;
    partial = rc == 1;
  }
  int64_t body_len = end - start + 1;
  char lm[40] = "";
  struct tm tmv;
  time_t mt = (time_t)ent.mtime;
  gmtime_r(&mt, &tmv);
  strftime(lm, sizeof lm, "%a, %d %b %Y %H:%M:%S GMT", &tmv);
  char head[576];
  int hl;
  if (partial) {
    hl = snprintf(
        head, sizeof head,
        "HTTP/1.1 206 Partial Content\r\nContent-Type: %s\r\n"
        "Content-Length: %lld\r\n"
        "Content-Range: bytes %lld-%lld/%u\r\n"
        "ETag: \"%s\"\r\nLast-Modified: %s\r\nAccept-Ranges: bytes\r\n",
        ent.mime.empty() ? "application/octet-stream" : ent.mime.c_str(),
        (long long)body_len, (long long)start, (long long)end,
        data_size, ent.etag.c_str(), lm);
  } else {
    hl = snprintf(
        head, sizeof head,
        "HTTP/1.1 200 OK\r\nContent-Type: %s\r\nContent-Length: %u\r\n"
        "ETag: \"%s\"\r\nLast-Modified: %s\r\nAccept-Ranges: bytes\r\n",
        ent.mime.empty() ? "application/octet-stream" : ent.mime.c_str(),
        data_size, ent.etag.c_str(), lm);
  }
  if (hl >= (int)sizeof head) return false;
  c->out.append(head, hl);
  c->out.append(ent.meta);
  if (!r.keep_alive) c->out.append("Connection: close\r\n");
  c->out.append("\r\n");
  if (!is_head) c->out.append((const char*)data + start, body_len);
  if (!r.keep_alive) c->want_close = true;
  count_resp(partial ? 206 : 200, is_head ? 0 : body_len);
  return true;
}

// PUT fast path: local append + gated metadata insert through the
// channel. Returns 0 when the request must relay instead.
int s3_handle_put(Server* s, Conn* c, const Request& r, const char* head,
                  const std::string& bucket, const char* key,
                  size_t key_len, const uint8_t* body, int64_t body_len) {
  S3Auth a = s3_auth(c, r, head, "PUT", true, bucket, body, body_len);
  if (a == S3Auth::RELAY) return 0;
  if (a == S3Auth::REJECTED) return 1;
  // headers: content-type + x-amz-meta-* (printable ASCII only, like
  // the python gateway's US-ASCII gate — odd bytes relay for python's
  // verdict; control chars would also break the TSV channel framing)
  auto ascii_clean = [](const char* q, const char* qe) {
    for (; q < qe; q++) {
      unsigned char ch = (unsigned char)*q;
      if (ch < 0x20 || ch >= 0x7f) return false;
    }
    return true;
  };
  const char* ct = nullptr;
  size_t ct_len = 0;
  if (find_header(head, r.head_len, "content-type", &ct, &ct_len) &&
      !ascii_clean(ct, ct + ct_len))
    return 0;
  std::vector<std::pair<std::string, std::string>> meta;
  {
    const char* p = (const char*)memchr(head, '\n', r.head_len);
    const char* end = head + r.head_len;
    p = p ? p + 1 : end;
    while (p < end) {
      const char* le = (const char*)memchr(p, '\n', end - p);
      if (!le) break;
      const char* colon = (const char*)memchr(p, ':', le - p);
      if (colon && colon - p > 11 &&
          strncasecmp(p, "x-amz-meta-", 11) == 0) {
        std::string name(p + 11, colon - p - 11);
        for (auto& ch : name) {
          // control bytes (tab!) would break the TSV channel framing;
          // '=' is the pair separator
          if ((unsigned char)ch < 0x20 || (unsigned char)ch >= 0x7f ||
              ch == '=')
            return 0;
          ch = (char)tolower((unsigned char)ch);
        }
        const char* vv = colon + 1;
        const char* ve = le > p && le[-1] == '\r' ? le - 1 : le;
        while (vv < ve && (*vv == ' ' || *vv == '\t')) vv++;
        while (ve > vv && (ve[-1] == ' ' || ve[-1] == '\t')) ve--;
        if (!ascii_clean(vv, ve)) return 0;
        meta.emplace_back(name, std::string(vv, ve - vv));
      }
      p = le + 1;
    }
  }
  // pre-assigned fid slot for this bucket's collection. PEEK first:
  // popping before the volume checks would burn one slot per relayed
  // PUT on ineligible volumes (replicated/remote buckets), churning
  // the master with refill assigns for nothing. Single consumer (this
  // IO thread) — the front slot is stable between peek and pop.
  S3Slot slot;
  {
    std::lock_guard<std::mutex> lk(s3_pool_mu);
    auto it = s3_pools.find(bucket);
    if (it == s3_pools.end() || it->second.empty())
      return 0;  // pool dry: relay (the refill thread replenishes)
    slot = it->second.front();
  }
  std::shared_ptr<Vol> v = find_vol(slot.vid);
  if (!v) return 0;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached || v->read_only || v->has_replicas) return 0;
  }
  {
    std::lock_guard<std::mutex> lk(s3_pool_mu);
    s3_pools[bucket].pop_front();
  }
  uint32_t crc = 0;
  int st = append_plain(v, slot.key, slot.cookie, body, body_len, &crc);
  if (st == 0 || st == 409) return 0;  // python re-resolves placement
  if (st != 201) {
    n_errors++;
    s3_error(c, 500, "InternalError", "volume write failed", r.path,
             r.path_len, r.keep_alive);
    return 1;
  }
  std::string etag = md5_hex(body, (size_t)body_len);
  char fid[48];
  int fl = snprintf(fid, sizeof fid, "%u,%llx%08x", slot.vid,
                    (unsigned long long)slot.key, slot.cookie);
  // TSV channel record (cheap to build here, cheap to split there —
  // a json round trip measured ~5us/op of applier GIL time):
  //   id \t put \t bucket \t key \t fid \t size \t etag \t mime
  //   [\t k=v]...\n          (deletes: id \t del \t bucket \t key\n)
  // every field is gated printable-ASCII-no-tab above; keys passed
  // s3_canonical_path (unreserved bytes only)
  uint64_t id = s->next_op_id++;
  std::string rec;
  rec.reserve(160 + key_len);
  char nbuf[48];
  snprintf(nbuf, sizeof nbuf, "%llu\tput\t", (unsigned long long)id);
  rec += nbuf;
  rec += bucket;
  rec += '\t';
  rec.append(key, key_len);
  rec += '\t';
  rec.append(fid, fl);
  snprintf(nbuf, sizeof nbuf, "\t%lld\t", (long long)body_len);
  rec += nbuf;
  rec += etag;
  rec += '\t';
  if (ct) rec.append(ct, ct_len);
  for (auto& kv : meta) {
    rec += '\t';
    rec += kv.first;
    rec += '=';
    rec += kv.second;
  }
  rec += '\n';
  S3Op* op = new S3Op();
  op->client = c;
  op->keep_alive = r.keep_alive;
  op->etag = etag;
  op->size = body_len;
  s->s3_pending[id] = op;
  c->repl_pending = true;
  int mode = commit_mode.load(std::memory_order_relaxed);
  if (mode == 1) {
    // the metadata record ships to the applier now (page-cache
    // append done); only the 200 waits on the covering fsync
    op->fsync_pending = true;
    commit_enqueue(s, v, body_len, nullptr, id);
  } else if (mode == 2) {
    commit_sync_inline(v);
  }
  s->chan_out += rec;  // flushed once per epoll batch
  return 1;
}

// Recognize exactly "partNumber=N&uploadId=H" (either order, nothing
// else, unreserved bytes only — so the canonical-query form used for
// SigV4 is the literal sorted pair). Returns false = not a plain part
// upload: relay.
bool parse_part_query(const char* q, size_t qlen, std::string* upload_id,
                      long* part_num) {
  std::string pn, uid;
  size_t i = 0;
  while (i < qlen) {
    size_t amp = i;
    while (amp < qlen && q[amp] != '&') amp++;
    const char* eq = (const char*)memchr(q + i, '=', amp - i);
    if (!eq) return false;
    std::string k(q + i, eq - q - i);
    std::string v(eq + 1, q + amp - eq - 1);
    if (k == "partNumber" && pn.empty())
      pn = v;
    else if (k == "uploadId" && uid.empty())
      uid = v;
    else
      return false;  // extra/duplicate params: python's call
    i = amp + 1;
  }
  if (pn.empty() || pn.size() > 5 || uid.empty()) return false;
  for (char ch : pn)
    if (!isdigit((unsigned char)ch)) return false;
  for (char ch : uid)
    if (!(isalnum((unsigned char)ch) || ch == '-' || ch == '.' ||
          ch == '_' || ch == '~'))
      return false;  // would need percent-encoding in the canonical form
  long n = strtol(pn.c_str(), nullptr, 10);
  if (n < 1 || n > 10000) return false;  // python answers InvalidArgument
  *upload_id = uid;
  *part_num = n;
  return true;
}

// Multipart part-upload fast path (UploadPart is the highest-volume
// verb the S3 front still relayed): append the part bytes locally and
// gate the part-entry insert (/buckets/<b>/.uploads/<id>/NNNNN.part)
// through the channel. Returns 0 to relay — notably when the upload id
// is not in the live set, so python's NoSuchUpload XML applies.
int s3_handle_part(Server* s, Conn* c, const Request& r, const char* head,
                   const std::string& bucket, const std::string& upload_id,
                   long part_num, const uint8_t* body, int64_t body_len) {
  {
    std::shared_lock<std::shared_mutex> lk(s3_upload_mu);
    if (!s3_uploads.count(bucket + "\t" + upload_id)) return 0;
  }
  char cq[128];
  snprintf(cq, sizeof cq, "partNumber=%ld&uploadId=%s", part_num,
           upload_id.c_str());
  S3Auth a = s3_auth(c, r, head, "PUT", true, bucket, body, body_len, cq);
  if (a == S3Auth::RELAY) return 0;
  if (a == S3Auth::REJECTED) return 1;
  S3Slot slot;
  {
    std::lock_guard<std::mutex> lk(s3_pool_mu);
    auto it = s3_pools.find(bucket);
    if (it == s3_pools.end() || it->second.empty()) return 0;
    slot = it->second.front();
  }
  std::shared_ptr<Vol> v = find_vol(slot.vid);
  if (!v) return 0;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached || v->read_only || v->has_replicas) return 0;
  }
  {
    std::lock_guard<std::mutex> lk(s3_pool_mu);
    s3_pools[bucket].pop_front();
  }
  uint32_t crc = 0;
  int st = append_plain(v, slot.key, slot.cookie, body, body_len, &crc);
  if (st == 0 || st == 409) return 0;
  if (st != 201) {
    n_errors++;
    s3_error(c, 500, "InternalError", "volume write failed", r.path,
             r.path_len, r.keep_alive);
    return 1;
  }
  // etag of the PART bytes: CompleteMultipartUpload composes the final
  // "-N" etag from the parts' md5s, exactly like the python path's
  // fullmd5 POST
  std::string etag = md5_hex(body, (size_t)body_len);
  char fid[48];
  int fl = snprintf(fid, sizeof fid, "%u,%llx%08x", slot.vid,
                    (unsigned long long)slot.key, slot.cookie);
  // id \t part \t bucket \t upload_id \t part_number \t fid \t size
  //    \t etag\n
  uint64_t id = s->next_op_id++;
  std::string rec;
  rec.reserve(160);
  char nbuf[64];
  snprintf(nbuf, sizeof nbuf, "%llu\tpart\t", (unsigned long long)id);
  rec += nbuf;
  rec += bucket;
  rec += '\t';
  rec += upload_id;
  snprintf(nbuf, sizeof nbuf, "\t%ld\t", part_num);
  rec += nbuf;
  rec.append(fid, fl);
  snprintf(nbuf, sizeof nbuf, "\t%lld\t", (long long)body_len);
  rec += nbuf;
  rec += etag;
  rec += '\n';
  S3Op* op = new S3Op();
  op->client = c;
  op->keep_alive = r.keep_alive;
  op->kind = OP_S3_PART;
  op->etag = etag;
  op->size = body_len;
  s->s3_pending[id] = op;
  c->repl_pending = true;
  int mode = commit_mode.load(std::memory_order_relaxed);
  if (mode == 1) {
    op->fsync_pending = true;
    commit_enqueue(s, v, body_len, nullptr, id);
  } else if (mode == 2) {
    commit_sync_inline(v);
  }
  s->chan_out += rec;  // flushed once per epoll batch
  return 1;
}

// S3-role pump: the fast paths, with relay for everything else.
int s3_pump_inner(Server* s, Conn* c) {
  if (c->repl_pending) return 0;  // gated PUT in flight
  if (c->want_close) {  // close-marked response still flushing
    c->in.clear();
    c->in_off = 0;
    return 0;
  }
  while (true) {
    if (c->in_off > 0 && c->in_off == c->in.size()) {
      c->in.clear();
      c->in_off = 0;
    }
    size_t avail = c->in.size() - c->in_off;
    if (avail == 0) break;
    Request r;
    const char* head = c->in.data() + c->in_off;
    ssize_t hl = parse_head(head, avail, &r);
    if (hl < 0) return -1;
    if (hl == 0) break;
    bool is_get = ieq(r.method, r.method_len, "GET");
    bool is_head = ieq(r.method, r.method_len, "HEAD");
    bool is_put = ieq(r.method, r.method_len, "PUT");
    // bucket/key split: fast path needs a non-empty key and a
    // pre-canonical path (no percent-encoding required)
    std::string bucket;
    const char* key = nullptr;
    size_t key_len = 0;
    if (r.path_len > 1 && r.path[0] == '/' &&
        s3_canonical_path(r.path, r.path_len)) {
      const char* slash =
          (const char*)memchr(r.path + 1, '/', r.path_len - 1);
      if (slash && (size_t)(slash - r.path) + 1 < r.path_len) {
        bucket.assign(r.path + 1, slash - r.path - 1);
        key = slash + 1;
        key_len = r.path + r.path_len - key;
      }
    }
    bool bucket_known = false;
    if (!bucket.empty()) {
      std::shared_lock<std::shared_mutex> lk(s3_mu);
      bucket_known = s3_buckets.count(bucket) > 0;
    }
    // part-upload query recognized once per parse (PUT only)
    std::string upload_id;
    long part_num = 0;
    bool is_part =
        is_put && r.has_query && key_len &&
        parse_part_query(r.query, r.query_len, &upload_id, &part_num);
    // deadline/fault gate — deferred while a fast-path PUT is still
    // buffering its body so it fires exactly once per request
    // parts get a wider body gate: S3's own floor makes every
    // non-final part >= 5MB, so a 1MB cap would relay all of them
    int64_t put_max = is_part ? (16 << 20) : (1 << 20);
    bool fast_put_waiting =
        is_put && bucket_known && key_len && (!r.has_query || is_part) &&
        !r.proxy_only && !r.chunked && r.content_len > 0 &&
        r.content_len <= put_max &&
        avail - r.head_len < (size_t)r.content_len;
    if (!fast_put_waiting && gate_request(c, r, avail)) continue;
    if ((is_get || is_head) && bucket_known && !r.has_query &&
        !r.proxy_only && r.content_len == 0 && !r.chunked &&
        !(is_head && r.range)) {  // AWS honors Range on HEAD: relay
      S3Auth a = s3_auth(c, r, head, is_head ? "HEAD" : "GET", false,
                         bucket, nullptr, 0);
      if (a == S3Auth::REJECTED) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        continue;
      }
      if (a == S3Auth::OK) {
        S3Ent ent;
        bool hit = false;
        {
          std::shared_lock<std::shared_mutex> lk(s3_cache_mu);
          auto it = s3_cache.find(std::string(r.path, r.path_len));
          if (it != s3_cache.end()) {
            ent = it->second;
            hit = true;
          }
        }
        if (hit && s3_serve_cached(c, r, ent, is_head)) {
          c->in_off += r.head_len;
          c->sent_100 = false;
          n_s3_get++;
          continue;
        }
      }
      // miss / unsure: relay below
    } else if (is_put && bucket_known && key_len &&
               (!r.has_query || is_part) && !r.proxy_only && !r.chunked &&
               r.content_len > 0 && r.content_len <= put_max) {
      if (r.expect_100 && !c->sent_100 &&
          avail - r.head_len < (size_t)r.content_len) {
        c->out.append("HTTP/1.1 100 Continue\r\n\r\n");
        c->sent_100 = true;
      }
      if (avail - r.head_len < (size_t)r.content_len) break;
      const uint8_t* body = (const uint8_t*)head + r.head_len;
      int took =
          is_part ? s3_handle_part(s, c, r, head, bucket, upload_id,
                                   part_num, body, r.content_len)
                  : s3_handle_put(s, c, r, head, bucket, key, key_len,
                                  body, r.content_len);
      if (took) {
        c->in_off += r.head_len + r.content_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;  // awaiting the applier's ack
        continue;
      }
      // fall through to relay
    } else if (ieq(r.method, r.method_len, "DELETE") && bucket_known &&
               key_len && !r.has_query && !r.proxy_only && !r.chunked &&
               r.content_len == 0) {
      int took = s3_handle_delete(s, c, r, head, bucket, key, key_len);
      if (took) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;  // awaiting the applier's ack
        continue;
      }
      // fall through to relay
    }
    return proxy_handoff(s, c, r, avail);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Native filer front (role ROLE_FILER) — the filer HTTP gateway's hot
// verbs (GET/PUT/HEAD/DELETE of plain files) in C++, byte-matching the
// python handlers; everything else (listings, renames, WebDAV, tagging,
// query verbs, conditional policy) relays to the python filer app.
// Mutations ride the same TSV applier channel shape as the S3 front, so
// the zero-staleness cache contract holds across both fronts.
// ---------------------------------------------------------------------------

// Reserved route prefixes the python app handles itself (the catch-all
// file routes sit behind these in its route table).
bool filer_reserved_path(const char* p, size_t n) {
  static const char* kFirst[] = {"status", "metrics", "debug",
                                 "ws", "dlm", "kv", "healthz"};
  const char* seg = p + 1;
  const char* slash = (const char*)memchr(seg, '/', n - 1);
  size_t seg_len = slash ? (size_t)(slash - seg) : n - 1;
  for (const char* f : kFirst)
    if (seg_len == strlen(f) && memcmp(seg, f, seg_len) == 0) return true;
  return false;
}

// A path the fast paths may serve: already in norm_path() form (no
// empty or "." segments, no trailing slash) and restricted to bytes
// that need no percent-decoding, no json escaping in the 201 body, and
// no TSV escaping on the channel. Anything else relays so the python
// normalization/unicode semantics apply verbatim.
bool filer_path_ok(const char* p, size_t n) {
  if (n < 2 || p[0] != '/' || p[n - 1] == '/') return false;
  for (size_t i = 0; i < n; i++) {
    char c = p[i];
    if (!(isalnum((unsigned char)c) || c == '/' || c == '-' ||
          c == '.' || c == '_' || c == '~'))
      return false;
  }
  for (size_t i = 0; i + 1 < n; i++) {
    if (p[i] != '/') continue;
    if (p[i + 1] == '/') return false;                        // "//"
    if (p[i + 1] == '.' && (i + 2 == n || p[i + 2] == '/'))
      return false;                                           // "/./"
  }
  return !filer_reserved_path(p, n);
}

// Serve a filer GET/HEAD from the cache entry's local needle,
// byte-matching handle_get's plain-file path: ETag/If-None-Match,
// Last-Modified, Accept-Ranges, X-Seaweed-Entry and armored s3_* ext
// headers, single-range 206 (on HEAD too — the python filer honors
// Range on HEAD), and the bare 416. false = relay.
bool filer_serve_cached(Conn* c, const Request& r, const char* head,
                        const FilerEnt& ent, bool is_head) {
  std::shared_ptr<Vol> v = find_vol(ent.vid);
  if (!v) return false;
  int64_t off;
  int32_t size;
  int version;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached) return false;
    auto it = v->map.find(ent.key);
    if (it == v->map.end() || it->second.size <= 0) return false;
    off = it->second.offset;
    size = it->second.size;
    version = v->version;
  }
  int64_t rec_len = disk_size(size, version);
  std::string rec;
  rec.resize(rec_len);
  if (pread(v->dat_fd, &rec[0], rec_len, off) != rec_len) return false;
  const uint8_t* p = (const uint8_t*)rec.data();
  if (be64(p + 4) != ent.key || be32(p) != ent.cookie) return false;
  uint32_t data_size = be32(p + HEADER);
  if ((int64_t)data_size + 5 > size) return false;
  const uint8_t* data = p + HEADER + 4;
  uint8_t flags = data[data_size];
  if (flags & FLAG_IS_COMPRESSED) return false;  // python inflates
  uint32_t stored_crc = be32(p + HEADER + size);
  uint32_t actual = data_size ? crc32c(0, data, data_size) : 0;
  if (data_size && stored_crc != actual &&
      stored_crc != legacy_crc_value(actual))
    return false;  // corrupt: python's read path reports it properly
  const char* mime = ent.mime.empty() ? "application/octet-stream"
                                      : ent.mime.c_str();
  char lm[40] = "";
  struct tm tmv;
  time_t mt = (time_t)ent.mtime;
  gmtime_r(&mt, &tmv);
  strftime(lm, sizeof lm, "%a, %d %b %Y %H:%M:%S GMT", &tmv);
  // shared trailer: ETag .. ext block, same fields handle_get builds
  char common[256];
  int cl = snprintf(common, sizeof common,
                    "ETag: \"%s\"\r\nLast-Modified: %s\r\n"
                    "Accept-Ranges: bytes\r\nX-Seaweed-Entry: file\r\n",
                    ent.etag.c_str(), lm);
  if (cl >= (int)sizeof common) return false;
  // If-None-Match precedes the Range logic, exactly like handle_get
  const char* inm;
  size_t inm_len;
  if (find_header(head, r.head_len, "if-none-match", &inm, &inm_len) &&
      inm_len == ent.etag.size() + 2 && inm[0] == '"' &&
      inm[inm_len - 1] == '"' &&
      memcmp(inm + 1, ent.etag.data(), ent.etag.size()) == 0) {
    // no Content-Length: a 304 never carries a body (RFC 7232) and the
    // python stack (aiohttp) omits it — parity is byte-level
    c->out.append("HTTP/1.1 304 Not Modified\r\n");
    c->out.append(common, cl);
    c->out.append(ent.ext);
    if (!r.keep_alive) c->out.append("Connection: close\r\n");
    c->out.append("\r\n");
    if (!r.keep_alive) c->want_close = true;
    count_resp(304, 0);
    return true;
  }
  int64_t start = 0, end = (int64_t)data_size - 1;
  bool partial = false;
  if (r.range) {
    int rc = parse_byte_range(r.range, r.range_len, (int64_t)data_size,
                              &start, &end);
    if (rc == -2) {
      // handle_get's bare 416: only Content-Range advertised (the
      // python stack omits Content-Length on HEAD — parity is
      // byte-level)
      char h416[160];
      int hn = snprintf(h416, sizeof h416,
                        "HTTP/1.1 416 Range Not Satisfiable\r\n"
                        "%s"
                        "Content-Range: bytes */%u\r\n%s\r\n",
                        is_head ? "" : "Content-Length: 0\r\n",
                        data_size,
                        r.keep_alive ? "" : "Connection: close\r\n");
      c->out.append(h416, hn);
      if (!r.keep_alive) c->want_close = true;
      count_resp(416, 0);
      return true;
    }
    if (rc < 0) return false;  // malformed/multi-range: python decides
    partial = rc == 1;
  }
  int64_t body_len = end - start + 1;
  // HEAD advertises the would-be body length (range-aware, like the
  // python handler) and sends no body
  char h[640];
  int hl = snprintf(h, sizeof h,
                    "HTTP/1.1 %s\r\nContent-Type: %s\r\n"
                    "Content-Length: %lld\r\n",
                    partial ? "206 Partial Content" : "200 OK", mime,
                    (long long)body_len);
  if (hl >= (int)sizeof h) return false;
  c->out.append(h, hl);
  if (partial) {
    char crng[96];
    int cn = snprintf(crng, sizeof crng,
                      "Content-Range: bytes %lld-%lld/%u\r\n",
                      (long long)start, (long long)end, data_size);
    c->out.append(crng, cn);
  }
  c->out.append(common, cl);
  c->out.append(ent.ext);
  if (!r.keep_alive) c->out.append("Connection: close\r\n");
  c->out.append("\r\n");
  if (!is_head) c->out.append((const char*)data + start, body_len);
  if (!r.keep_alive) c->want_close = true;
  count_resp(partial ? 206 : 200, is_head ? 0 : body_len);
  return true;
}

// Filer PUT/POST fast path: local append + gated entry insert through
// the channel (the applier runs Filer.create_entry with the server's
// default collection/replication — the writes gate guarantees no
// filer.conf rule would have said otherwise). Returns 0 to relay.
int filer_handle_put(Server* s, Conn* c, const Request& r,
                     const char* head, const uint8_t* body,
                     int64_t body_len) {
  auto ascii_clean = [](const char* q, const char* qe) {
    for (; q < qe; q++) {
      unsigned char ch = (unsigned char)*q;
      if (ch < 0x20 || ch >= 0x7f) return false;
    }
    return true;
  };
  // headers that change python's write semantics relay: Content-MD5
  // (pre-validated + whole-stream md5), x-seaweed-ext-* (extended
  // attrs), multipart/form-data (form decode)
  const char* ct = nullptr;
  size_t ct_len = 0;
  if (find_header(head, r.head_len, "content-type", &ct, &ct_len)) {
    if (!ascii_clean(ct, ct + ct_len)) return 0;
    if (ct_len >= 19 && strncasecmp(ct, "multipart/form-data", 19) == 0)
      return 0;
  }
  {
    const char* q;
    size_t ql;
    if (find_header(head, r.head_len, "content-md5", &q, &ql)) return 0;
    const char* hp = (const char*)memchr(head, '\n', r.head_len);
    const char* end = head + r.head_len;
    hp = hp ? hp + 1 : end;
    while (hp < end) {
      const char* le = (const char*)memchr(hp, '\n', end - hp);
      if (!le) break;
      const char* colon = (const char*)memchr(hp, ':', le - hp);
      if (colon && colon - hp > 14 &&
          strncasecmp(hp, "x-seaweed-ext-", 14) == 0)
        return 0;
      hp = le + 1;
    }
  }
  S3Slot slot;
  {
    std::lock_guard<std::mutex> lk(filer_pool_mu);
    if (filer_pool.empty()) return 0;  // dry: relay, refill replenishes
    slot = filer_pool.front();
  }
  std::shared_ptr<Vol> v = find_vol(slot.vid);
  if (!v) return 0;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    if (v->detached || v->read_only || v->has_replicas) return 0;
  }
  {
    std::lock_guard<std::mutex> lk(filer_pool_mu);
    filer_pool.pop_front();
  }
  uint32_t crc = 0;
  int st = append_plain(v, slot.key, slot.cookie, body, body_len, &crc);
  if (st == 0 || st == 409) return 0;  // python re-resolves placement
  if (st != 201) {
    n_errors++;
    simple_response(c, 500, "volume write failed", r.keep_alive);
    return 1;
  }
  // the chunk md5 IS the file md5 for a single-chunk entry, so this is
  // both the 201 body's etag and the entry's md5 (handle_put parity)
  std::string etag = md5_hex(body, (size_t)body_len);
  char fid[48];
  int fl = snprintf(fid, sizeof fid, "%u,%llx%08x", slot.vid,
                    (unsigned long long)slot.key, slot.cookie);
  // id \t put \t path \t fid \t size \t etag \t mime\n
  // (deletes: id \t del \t path\n) — path passed filer_path_ok
  // (unreserved bytes), mime gated printable-ASCII above
  uint64_t id = s->next_op_id++;
  std::string rec;
  rec.reserve(160 + r.path_len);
  char nbuf[48];
  snprintf(nbuf, sizeof nbuf, "%llu\tput\t", (unsigned long long)id);
  rec += nbuf;
  rec.append(r.path, r.path_len);
  rec += '\t';
  rec.append(fid, fl);
  snprintf(nbuf, sizeof nbuf, "\t%lld\t", (long long)body_len);
  rec += nbuf;
  rec += etag;
  rec += '\t';
  if (ct) rec.append(ct, ct_len);
  rec += '\n';
  const char* base = (const char*)memrchr(r.path, '/', r.path_len);
  S3Op* op = new S3Op();
  op->client = c;
  op->keep_alive = r.keep_alive;
  op->kind = OP_FILER_PUT;
  op->etag = etag;
  op->size = body_len;
  op->name.assign(base + 1, r.path + r.path_len - base - 1);
  s->s3_pending[id] = op;
  c->repl_pending = true;
  int mode = commit_mode.load(std::memory_order_relaxed);
  if (mode == 1) {
    op->fsync_pending = true;
    commit_enqueue(s, v, body_len, nullptr, id);
  } else if (mode == 2) {
    commit_sync_inline(v);
  }
  s->chan_out += rec;  // flushed once per epoll batch
  return 1;
}

// Filer DELETE fast path — only for paths the cache proves are plain
// files (directories keep python's recursive/conflict semantics). The
// metadata delete rides the channel so chunk reclamation and the
// invalidating meta event happen exactly as in the python path.
int filer_handle_delete(Server* s, Conn* c, const Request& r) {
  uint64_t id = s->next_op_id++;
  std::string rec;
  rec.reserve(32 + r.path_len);
  char nbuf[48];
  snprintf(nbuf, sizeof nbuf, "%llu\tdel\t", (unsigned long long)id);
  rec += nbuf;
  rec.append(r.path, r.path_len);
  rec += '\n';
  S3Op* op = new S3Op();
  op->client = c;
  op->keep_alive = r.keep_alive;
  op->is_delete = true;
  op->kind = OP_FILER_DEL;
  s->s3_pending[id] = op;
  c->repl_pending = true;
  s->chan_out += rec;
  return 1;
}

// Filer-role pump: hot plain-file verbs, relay for everything else.
int filer_pump_inner(Server* s, Conn* c) {
  if (c->repl_pending) return 0;  // gated mutation in flight
  if (c->want_close) {
    c->in.clear();
    c->in_off = 0;
    return 0;
  }
  while (true) {
    if (c->in_off > 0 && c->in_off == c->in.size()) {
      c->in.clear();
      c->in_off = 0;
    }
    size_t avail = c->in.size() - c->in_off;
    if (avail == 0) break;
    Request r;
    const char* head = c->in.data() + c->in_off;
    ssize_t hl = parse_head(head, avail, &r);
    if (hl < 0) return -1;
    if (hl == 0) break;
    bool is_get = ieq(r.method, r.method_len, "GET");
    bool is_head = ieq(r.method, r.method_len, "HEAD");
    // the python filer routes POST and PUT to the same handler
    bool is_put = ieq(r.method, r.method_len, "PUT") ||
                  ieq(r.method, r.method_len, "POST");
    bool path_ok = filer_path_ok(r.path, r.path_len);
    bool writes_on = filer_writes_on.load(std::memory_order_relaxed);
    // deadline/fault gate — deferred while a fast-path PUT body is
    // still buffering so it fires exactly once per request
    bool fast_put_waiting =
        is_put && writes_on && path_ok && !r.has_query && !r.proxy_only &&
        !r.chunked && r.content_len > 0 && r.content_len <= (1 << 20) &&
        avail - r.head_len < (size_t)r.content_len;
    if (!fast_put_waiting && gate_request(c, r, avail)) continue;
    if ((is_get || is_head) && path_ok && !r.has_query && !r.proxy_only &&
        r.content_len == 0 && !r.chunked) {
      FilerEnt ent;
      bool hit = false;
      {
        std::shared_lock<std::shared_mutex> lk(filer_cache_mu);
        auto it = filer_cache.find(std::string(r.path, r.path_len));
        if (it != filer_cache.end()) {
          ent = it->second;
          hit = true;
        }
      }
      if (hit && filer_serve_cached(c, r, head, ent, is_head)) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        n_filer_get++;
        continue;
      }
      // miss (maybe a 404, a directory, an inline/multi-chunk entry):
      // relay below — the cache is positive-plain-files-only
    } else if (is_put && writes_on && path_ok && !r.has_query &&
               !r.proxy_only && !r.chunked && r.content_len > 0 &&
               r.content_len <= (1 << 20)) {
      if (r.expect_100 && !c->sent_100 &&
          avail - r.head_len < (size_t)r.content_len) {
        c->out.append("HTTP/1.1 100 Continue\r\n\r\n");
        c->sent_100 = true;
      }
      if (avail - r.head_len < (size_t)r.content_len) break;
      const uint8_t* body = (const uint8_t*)head + r.head_len;
      int took = filer_handle_put(s, c, r, head, body, r.content_len);
      if (took) {
        c->in_off += r.head_len + r.content_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;  // awaiting the applier's ack
        continue;
      }
      // fall through to relay
    } else if (ieq(r.method, r.method_len, "DELETE") && path_ok &&
               !r.has_query && !r.proxy_only && !r.chunked &&
               r.content_len == 0) {
      bool hit = false;
      {
        std::shared_lock<std::shared_mutex> lk(filer_cache_mu);
        hit = filer_cache.count(std::string(r.path, r.path_len)) > 0;
      }
      if (hit && filer_handle_delete(s, c, r)) {
        c->in_off += r.head_len;
        c->sent_100 = false;
        if (c->repl_pending) return 0;
        continue;
      }
      // unknown path: relay (python's 404/recursive semantics)
    }
    return proxy_handoff(s, c, r, avail);
  }
  return 0;
}

void io_loop(Server* s) {
  t_role = s->role;
  struct epoll_event evs[128];
  while (!s->stop.load()) {
    int n = epoll_wait(s->epoll_fd, evs, 128, 1000);
    peer_sweep(s);  // hung-replicate watchdog, 1Hz
    for (int i = 0; i < n; i++) {
      if (evs[i].data.ptr == nullptr) {  // listen fd
        while (true) {
          int fd = accept4(s->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (fd < 0) break;
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Conn* c = new Conn();
          c->fd = fd;
          c->last_active = time(nullptr);
          s->conns[fd] = c;
          arm(s, c, EPOLLIN);
        }
        continue;
      }
      if (evs[i].data.ptr == (void*)s) {  // eventfd: returned conns
        uint64_t junk;
        (void)!read(s->event_fd, &junk, 8);
        std::deque<Conn*> back;
        std::deque<CommitWaiter> cdone;
        {
          std::lock_guard<std::mutex> lk(s->ret_mu);
          back.swap(s->returned);
          cdone.swap(s->commit_done);
        }
        // fsync completions first: they release gated acks, and the
        // resumed pumps below may queue replicates for this batch's
        // flush_dirty_peers pass
        for (auto& w : cdone) commit_complete(s, w);
        for (Conn* c : back) {
          s->conns[c->fd] = c;
          set_nonblock(c->fd, true);
          int st = pump(s, c);
          if (st == -1)
            close_conn(s, c);
          else if (st == 0)
            flush_out(s, c);
          // st == 1: handed off again
        }
        continue;
      }
      if (*(int*)evs[i].data.ptr == KIND_PEER) {  // replica peer conn
        peer_event(s, (PeerConn*)evs[i].data.ptr, evs[i].events);
        continue;
      }
      if (*(int*)evs[i].data.ptr == KIND_CHAN) {  // S3 entry channel
        if (evs[i].events & EPOLLOUT) chan_flush(s);
        if (evs[i].events & (EPOLLIN | EPOLLHUP | EPOLLERR)) chan_read(s);
        continue;
      }
      Conn* c = (Conn*)evs[i].data.ptr;
      c->last_active = time(nullptr);
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        if (!flush_out(s, c)) continue;  // conn freed
      }
      if (evs[i].events & EPOLLIN) {
        char buf[64 << 10];
        bool closed = false;
        while (true) {
          ssize_t got = recv(c->fd, buf, sizeof buf, 0);
          if (got > 0) {
            c->in.append(buf, got);
            if (c->in.size() - c->in_off > (size_t)(280 << 20)) {
              closed = true;  // runaway request
              break;
            }
            continue;
          }
          if (got == 0) closed = true;
          break;  // EAGAIN or EOF
        }
        int st = pump(s, c);
        if (st == 1) continue;  // handed to proxy worker
        if (st == -1 || (closed && c->out.size() == c->out_off)) {
          close_conn(s, c);
          continue;
        }
        flush_out(s, c);
      }
    }
    flush_dirty_peers(s);  // one writev per peer for this whole batch
    if (s->chan_fd >= 0 && !s->chan_out.empty())
      chan_flush(s);  // ship the batch's entry records in one write
  }
}

void worker_loop(Server* s) {
  t_role = s->role;
  while (true) {
    Conn* c;
    {
      std::unique_lock<std::mutex> lk(s->q_mu);
      s->q_cv.wait(lk, [&] { return s->stop.load() || !s->proxy_q.empty(); });
      if (s->stop.load() && s->proxy_q.empty()) return;
      c = s->proxy_q.front();
      s->proxy_q.pop_front();
    }
    set_nonblock(c->fd, false);
    // pure relay to the python backend (replicated-volume writes are
    // the IO thread's async fan-out now). The head is re-parsed here:
    // Request views must point into this thread's view of the buffer.
    Request r;
    ssize_t hl =
        parse_head(c->in.data() + c->in_off, c->in.size() - c->in_off, &r);
    bool ok = hl > 0 && proxy_one(s, c, r);
    if (!ok) {
      if (c->backend_fd >= 0) close(c->backend_fd);
      close(c->fd);
      delete c;
      continue;
    }
    c->sent_100 = false;
    {
      std::lock_guard<std::mutex> lk(s->ret_mu);
      s->returned.push_back(c);
    }
    uint64_t one = 1;
    (void)!write(s->event_fd, &one, 8);
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------
extern "C" {

// Start a front server (volume or S3 role). Returns 0, or -errno.
// `actual_port` reports the bound port (differs from listen_port when
// that was 0). `listen_ip` honors the operator's bind address (-ip)
// exactly like the Python listener; NULL/"" = all interfaces.
// `chan_fd` (S3 role): the C++ end of the entry-channel socketpair.
static int start_server(Server** slot, int role, uint16_t listen_port,
                        uint16_t backend_port, int n_proxy_workers,
                        uint16_t* actual_port, const char* listen_ip,
                        int chan_fd) {
  if (*slot) return -EALREADY;
  int lfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (lfd < 0) return -errno;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a = {};
  a.sin_family = AF_INET;
  a.sin_port = htons(listen_port);
  a.sin_addr.s_addr = htonl(INADDR_ANY);
  if (listen_ip && *listen_ip &&
      inet_pton(AF_INET, listen_ip, &a.sin_addr) != 1) {
    close(lfd);
    return -EINVAL;
  }
  if (bind(lfd, (struct sockaddr*)&a, sizeof a) != 0 || listen(lfd, 1024) != 0) {
    int e = errno;
    close(lfd);
    return -e;
  }
  if (actual_port) {
    struct sockaddr_in bound = {};
    socklen_t blen = sizeof bound;
    getsockname(lfd, (struct sockaddr*)&bound, &blen);
    *actual_port = ntohs(bound.sin_port);
  }
  Server* s = new Server();
  s->role = role;
  s->backend_port = backend_port;
  s->listen_fd = lfd;
  s->epoll_fd = epoll_create1(0);
  s->event_fd = eventfd(0, EFD_NONBLOCK);
  struct epoll_event ev = {};
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, lfd, &ev);
  struct epoll_event ev2 = {};
  ev2.events = EPOLLIN;
  ev2.data.ptr = (void*)s;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->event_fd, &ev2);
  if (chan_fd >= 0) {
    s->chan_fd = chan_fd;
    set_nonblock(chan_fd, true);
    struct epoll_event ev3 = {};
    ev3.events = EPOLLIN;
    ev3.data.ptr = &s->chan_tag;
    epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, chan_fd, &ev3);
    s->chan_in_epoll = true;
  }
  *slot = s;
  n_active_servers++;
  s->io_thread = std::thread(io_loop, s);
  if (n_proxy_workers < 1) n_proxy_workers = 2;
  for (int i = 0; i < n_proxy_workers; i++)
    s->workers.emplace_back(worker_loop, s);
  return 0;
}

static void stop_server(Server** slot) {
  Server* s = *slot;
  if (!s) return;
  s->stop.store(true);
  s->q_cv.notify_all();
  uint64_t one = 1;
  (void)!write(s->event_fd, &one, 8);
  s->io_thread.join();
  for (auto& w : s->workers) w.join();
  // pull this server's queued commit waiters back (and wait out any
  // in-flight fsync delivery) BEFORE freeing conns/ops: parked in
  // s->commit_done, their ops join the sweeps below
  commit_drain_server(s);
  for (auto& [fd, c] : s->conns) {
    if (c->backend_fd >= 0) close(c->backend_fd);
    close(fd);
    if (c->repl_pending) {
      // an in-flight gated op still references this conn: freed via
      // its op in the sweeps below, not here (double-free otherwise)
      c->zombie = true;
      continue;
    }
    delete c;
  }
  for (Conn* c : s->returned) {
    if (c->backend_fd >= 0) close(c->backend_fd);
    close(c->fd);
    delete c;
  }
  // in-flight fan-out state: free wires once, ops once, and the client
  // conns the ops still reference (marked zombie above / by disconnect)
  {
    std::unordered_set<ReplOp*> ops;
    for (auto& [hp, pc] : s->peer_conns) {
      for (ReplWire* w : pc->sendq) {
        ops.insert(w->op);
        delete w;
      }
      for (ReplWire* w : pc->await) {
        ops.insert(w->op);
        delete w;
      }
      if (pc->fd >= 0) close(pc->fd);
      delete pc;
    }
    // undelivered fsync tokens reference ops too (a plain gated post
    // has no wires at all — this is its only reference)
    for (auto& w : s->commit_done)
      if (w.rop) ops.insert(w.rop);
    for (ReplOp* op : ops) {
      if (op->client && op->client->zombie) delete op->client;
      delete op;
    }
  }
  for (auto& [id, op] : s->s3_pending) {
    if (op->client && op->client->zombie) delete op->client;
    delete op;
  }
  if (s->chan_fd >= 0) close(s->chan_fd);
  close(s->listen_fd);
  close(s->epoll_fd);
  close(s->event_fd);
  delete s;
  *slot = nullptr;
  if (--n_active_servers == 0) commit_shutdown();
}

int dp_start(uint16_t listen_port, uint16_t backend_port, int n_proxy_workers,
             uint16_t* actual_port, const char* listen_ip) {
  return start_server(&g_srv, ROLE_VOLUME, listen_port, backend_port,
                      n_proxy_workers, actual_port, listen_ip, -1);
}

void dp_stop(void) {
  if (!g_srv) return;
  stop_server(&g_srv);
  std::unique_lock<std::shared_mutex> lk(vols_mu);
  vols.clear();
}

// jwt_req + the HS256 secret the master signs fid tokens with
// (security/guard.go:41; the front verifies write tokens in-process).
void dp_config(int jwt_req, const char* secret) {
  {
    std::unique_lock<std::shared_mutex> lk(jwt_mu);
    jwt_secret = secret ? secret : "";
  }
  jwt_required.store(jwt_req != 0 && secret && *secret);
}

// Group-commit ack contract for every front in this process
// (-commit.durability / -commit.maxDelay / -commit.maxBytes):
// mode 0=buffered, 1=batch, 2=sync. Set at spawn, before traffic.
int dp_set_commit(int mode, double max_delay_s, long long max_bytes) {
  if (mode < 0 || mode > 2) return -EINVAL;
  commit_mode.store(mode);
  if (max_delay_s > 0)
    commit_max_delay_ns.store((int64_t)(max_delay_s * 1e9));
  if (max_bytes > 0) commit_max_bytes_cfg.store((int64_t)max_bytes);
  return 0;
}

// out[6]: batches, fsyncs (syscalls), writes (committed), bytes,
// fsync-ns total, current queue depth. Monotonic except the depth.
void dp_commit_stats(int64_t* out) {
  out[0] = n_commit_batches.load();
  out[1] = n_commit_fsyncs.load();
  out[2] = n_commit_writes.load();
  out[3] = n_commit_bytes.load();
  out[4] = n_commit_fsync_ns.load();
  std::lock_guard<std::mutex> lk(commit_mu);
  out[5] = (int64_t)commit_q.size();
}

// Fault-injection knobs (the native front's share of a -fault.spec):
// error probability and fixed delay per op class (read = GET/HEAD,
// write = POST/PUT/DELETE), plus the RNG seed for deterministic chaos
// runs. Meant to be set once at spawn, before traffic; all zeros turn
// the gate off. dp_faults keeps the historical contract (volume role);
// dp_role_faults addresses any role so each native front gets its own
// -fault.spec gate (faults.native_params("volume"/"s3"/"filer")).
void dp_faults(double read_err, double write_err, double read_delay,
               double write_delay, uint64_t seed) {
  set_role_faults(ROLE_VOLUME, read_err, write_err, read_delay,
                  write_delay, seed);
}

void dp_role_faults(int role, double read_err, double write_err,
                    double read_delay, double write_delay, uint64_t seed) {
  if (role < 0 || role >= N_ROLES) return;
  set_role_faults(role, read_err, write_err, read_delay, write_delay, seed);
}

// -- native S3 front ---------------------------------------------------------

int dp_s3_start(uint16_t listen_port, uint16_t backend_port,
                int n_proxy_workers, uint16_t* actual_port,
                const char* listen_ip, int chan_fd) {
  return start_server(&g_s3srv, ROLE_S3, listen_port, backend_port,
                      n_proxy_workers, actual_port, listen_ip, chan_fd);
}

void dp_s3_stop(void) {
  stop_server(&g_s3srv);
  std::unique_lock<std::shared_mutex> lk(s3_mu);
  s3_idents.clear();
  s3_open_mode = true;
  s3_buckets.clear();
  s3_keycache.clear();
  {
    std::lock_guard<std::mutex> plk(s3_pool_mu);
    s3_pools.clear();
  }
  std::unique_lock<std::shared_mutex> clk(s3_cache_mu);
  s3_cache.clear();
}

// Identities as TSV lines: AK \t SECRET \t FLAGS \t wr_csv \t rd_csv
// FLAGS: 'A' admin, 'W' global write, 'R' global read (combined).
// Empty input = open mode (no identities).
void dp_s3_set_identities(const char* tsv) {
  std::unordered_map<std::string, S3Ident> idents;
  const char* p = tsv ? tsv : "";
  while (*p) {
    const char* nl = strchr(p, '\n');
    if (!nl) nl = p + strlen(p);
    std::vector<std::string> cols;
    const char* f = p;
    while (f < nl) {
      const char* tab = (const char*)memchr(f, '\t', nl - f);
      if (!tab) tab = nl;
      cols.emplace_back(f, tab - f);
      f = tab + 1;
    }
    if (cols.size() >= 3 && !cols[0].empty()) {
      S3Ident id;
      id.secret = cols[1];
      for (char ch : cols[2]) {
        if (ch == 'A') id.admin = true;
        if (ch == 'W') id.write_all = true;
        if (ch == 'R') id.read_all = true;
      }
      for (int ci = 3; ci < 5 && ci < (int)cols.size(); ci++) {
        auto& dst = ci == 3 ? id.wr : id.rd;
        size_t i = 0;
        const std::string& csv = cols[ci];
        while (i < csv.size()) {
          size_t j = csv.find(',', i);
          if (j == std::string::npos) j = csv.size();
          if (j > i) dst.insert(csv.substr(i, j - i));
          i = j + 1;
        }
      }
      idents[cols[0]] = std::move(id);
    }
    p = *nl ? nl + 1 : nl;
  }
  std::unique_lock<std::shared_mutex> lk(s3_mu);
  s3_open_mode = idents.empty();
  s3_idents.swap(idents);
  s3_keycache.clear();  // secrets may have rotated
}

// Known in-flight multipart uploads, maintained incrementally from the
// filer meta events for the .uploads marker directories: present=1 on
// initiate, 0 on complete/abort. Only marked uploads take the native
// part-upload path; unknown ids relay so python's NoSuchUpload XML is
// byte-identical.
void dp_s3_upload_mark(const char* bucket, const char* upload_id,
                       int present) {
  std::string k = std::string(bucket) + "\t" + upload_id;
  std::unique_lock<std::shared_mutex> lk(s3_upload_mu);
  if (present)
    s3_uploads.insert(std::move(k));
  else
    s3_uploads.erase(k);
}

void dp_s3_set_buckets(const char* csv) {
  std::unordered_set<std::string> buckets;
  const char* p = csv ? csv : "";
  while (*p) {
    const char* comma = strchr(p, ',');
    if (!comma) comma = p + strlen(p);
    if (comma > p) buckets.emplace(p, comma - p);
    p = *comma ? comma + 1 : comma;
  }
  std::unique_lock<std::shared_mutex> lk(s3_mu);
  s3_buckets.swap(buckets);
}

// Pre-assigned fid slots: base fid "vid,keyhexcookie" + count expands
// to (key+0..count-1), exactly the master's ?count=N slot contract.
int dp_s3_push_fids(const char* bucket, const char* fid, int count) {
  std::string path = std::string("/") + fid;
  uint32_t vid, cookie;
  uint64_t key;
  if (!parse_fid_path(path.c_str(), path.size(), &vid, &key, &cookie))
    return -EINVAL;
  std::lock_guard<std::mutex> lk(s3_pool_mu);
  auto& pool = s3_pools[bucket];
  for (int i = 0; i < count; i++)
    pool.push_back({vid, key + (uint64_t)i, cookie});
  return 0;
}

int dp_s3_pool_level(const char* bucket) {
  std::lock_guard<std::mutex> lk(s3_pool_mu);
  auto it = s3_pools.find(bucket);
  return it == s3_pools.end() ? 0 : (int)it->second.size();
}

// Cache maintenance — called ONLY from the filer's serialized meta
// event stream (under its mutation lock), so ordering matches the
// store. `meta_block` is a response-ready "x-amz-meta-k: v\r\n" blob.
int dp_s3_cache_put(const char* path, const char* fid, int64_t size,
                    const char* etag, const char* mime,
                    const char* meta_block, int64_t mtime) {
  std::string fp = std::string("/") + fid;
  S3Ent ent;
  if (!parse_fid_path(fp.c_str(), fp.size(), &ent.vid, &ent.key,
                      &ent.cookie))
    return -EINVAL;
  ent.size = size;
  ent.mtime = mtime;
  ent.etag = etag ? etag : "";
  ent.mime = mime ? mime : "";
  ent.meta = meta_block ? meta_block : "";
  std::unique_lock<std::shared_mutex> lk(s3_cache_mu);
  if (s3_cache.size() >= S3_CACHE_CAP) s3_cache.clear();
  s3_cache[path] = std::move(ent);
  return 0;
}

void dp_s3_invalidate(const char* path, int is_prefix) {
  std::unique_lock<std::shared_mutex> lk(s3_cache_mu);
  if (!is_prefix) {
    s3_cache.erase(path);
    return;
  }
  size_t plen = strlen(path);
  for (auto it = s3_cache.begin(); it != s3_cache.end();) {
    if (it->first.compare(0, plen, path) == 0)
      it = s3_cache.erase(it);
    else
      ++it;
  }
}

void dp_s3_stats(int64_t* out) {
  out[0] = n_s3_put.load();
  out[1] = n_s3_get.load();
  out[2] = n_s3_reject.load();
  out[3] = n_s3_chan_fail.load();
  out[4] = n_s3_del.load();
  out[5] = n_s3_part.load();
}

// -- native filer front ------------------------------------------------------

int dp_filer_start(uint16_t listen_port, uint16_t backend_port,
                   int n_proxy_workers, uint16_t* actual_port,
                   const char* listen_ip, int chan_fd) {
  return start_server(&g_filersrv, ROLE_FILER, listen_port, backend_port,
                      n_proxy_workers, actual_port, listen_ip, chan_fd);
}

void dp_filer_stop(void) {
  stop_server(&g_filersrv);
  filer_writes_on.store(false);
  {
    std::lock_guard<std::mutex> lk(filer_pool_mu);
    filer_pool.clear();
  }
  {
    std::unique_lock<std::shared_mutex> ulk(s3_upload_mu);
    s3_uploads.clear();  // populated via the same filer meta stream
  }
  std::unique_lock<std::shared_mutex> clk(filer_cache_mu);
  filer_cache.clear();
}

// Entry cache maintenance — like dp_s3_cache_put, called ONLY from the
// filer's serialized meta event stream so ordering matches the store.
// `ext_block` is a response-ready "x-seaweed-ext-k: v\r\n" blob.
int dp_filer_cache_put(const char* path, const char* fid, int64_t size,
                       const char* etag, const char* mime,
                       const char* ext_block, int64_t mtime) {
  std::string fp = std::string("/") + fid;
  FilerEnt ent;
  if (!parse_fid_path(fp.c_str(), fp.size(), &ent.vid, &ent.key,
                      &ent.cookie))
    return -EINVAL;
  ent.size = size;
  ent.mtime = mtime;
  ent.etag = etag ? etag : "";
  ent.mime = mime ? mime : "";
  ent.ext = ext_block ? ext_block : "";
  std::unique_lock<std::shared_mutex> lk(filer_cache_mu);
  if (filer_cache.size() >= FILER_CACHE_CAP) filer_cache.clear();
  filer_cache[path] = std::move(ent);
  return 0;
}

void dp_filer_invalidate(const char* path, int is_prefix) {
  std::unique_lock<std::shared_mutex> lk(filer_cache_mu);
  if (!is_prefix) {
    filer_cache.erase(path);
    return;
  }
  size_t plen = strlen(path);
  for (auto it = filer_cache.begin(); it != filer_cache.end();) {
    if (it->first.compare(0, plen, path) == 0)
      it = filer_cache.erase(it);
    else
      ++it;
  }
}

int dp_filer_push_fids(const char* fid, int count) {
  std::string path = std::string("/") + fid;
  uint32_t vid, cookie;
  uint64_t key;
  if (!parse_fid_path(path.c_str(), path.size(), &vid, &key, &cookie))
    return -EINVAL;
  std::lock_guard<std::mutex> lk(filer_pool_mu);
  for (int i = 0; i < count; i++)
    filer_pool.push_back({vid, key + (uint64_t)i, cookie});
  return 0;
}

int dp_filer_pool_level(void) {
  std::lock_guard<std::mutex> lk(filer_pool_mu);
  return (int)filer_pool.size();
}

// The write fast path is only sound while the filer would apply its
// defaults verbatim (no filer.conf path rules, no cipher, no
// save-inside-filer inlining); the glue re-checks each refill tick and
// flips this gate.
void dp_filer_set_writes(int on) {
  filer_writes_on.store(on != 0);
}

void dp_filer_stats(int64_t* out) {
  out[0] = n_filer_put.load();
  out[1] = n_filer_get.load();
  out[2] = n_filer_del.load();
  out[3] = n_filer_chan_fail.load();
}

// test hook: md5 hex of a buffer (validates the in-tree MD5)
void dp_md5_hex(const uint8_t* buf, int64_t n, char* out33) {
  std::string h = md5_hex(buf, (size_t)n);
  memcpy(out33, h.data(), 32);
  out33[32] = 0;
}

// Replica peer list for a volume: comma-separated "host:port" entries
// excluding this server, resolved by the python control plane from
// master lookups. Clears the stale flag — the list is authoritative as
// of this push.
int dp_set_peers(uint32_t vid, const char* peers_csv) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::vector<std::string> peers;
  if (peers_csv) {
    const char* p = peers_csv;
    while (*p) {
      const char* comma = strchr(p, ',');
      size_t n = comma ? (size_t)(comma - p) : strlen(p);
      if (n) peers.emplace_back(p, n);
      if (!comma) break;
      p = comma + 1;
    }
  }
  std::lock_guard<std::mutex> lk(v->mu);
  v->peers = std::move(peers);
  v->peers_stale = false;
  return 0;
}

// 1 = fan-out hit a dead/failed peer since the last dp_set_peers push
// (writes are relaying to python until a fresh list arrives).
int dp_peers_stale(uint32_t vid) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  return v->peers_stale ? 1 : 0;
}

// Attach a volume: open files, replay the index arrays (byte offsets,
// signed sizes, in .idx file order — load_needle_map semantics).
int dp_attach(uint32_t vid, const char* dat_path, const char* idx_path,
              int version, int offset_size, int read_only, int has_replicas,
              int64_t tail, uint64_t last_append_ns, const uint64_t* keys,
              const int64_t* byte_offsets, const int32_t* sizes, int64_t n) {
  auto v = std::make_shared<Vol>();
  v->dat_fd = open(dat_path, O_RDWR);
  if (v->dat_fd < 0) return -errno;
  v->idx_fd = open(idx_path, O_RDWR);
  if (v->idx_fd < 0) return -errno;
  struct stat st;
  fstat(v->idx_fd, &st);
  v->idx_tail = st.st_size;
  v->version = version;
  v->offset_size = offset_size;
  v->read_only = read_only != 0;
  v->has_replicas = has_replicas != 0;
  v->tail = tail;
  v->last_append_ns = last_append_ns;
  v->map.reserve((size_t)n * 2);
  for (int64_t i = 0; i < n; i++) {
    if (byte_offsets[i] > 0 && sizes[i] > 0)
      v->put(keys[i], byte_offsets[i], sizes[i]);
    else
      v->del(keys[i]);
  }
  std::unique_lock<std::shared_mutex> lk(vols_mu);
  if (vols.count(vid)) return -EEXIST;
  vols[vid] = std::move(v);
  return 0;
}

int dp_detach(uint32_t vid, int64_t* out_tail, uint64_t* out_last_ns) {
  std::unique_lock<std::shared_mutex> lk(vols_mu);
  auto it = vols.find(vid);
  if (it == vols.end()) return -ENOENT;
  {
    // taking mu drains in-flight ops; the detached flag turns away any
    // op that resolved the Vol before the erase but locks after it
    std::lock_guard<std::mutex> vk(it->second->mu);
    it->second->detached = true;
    if (out_tail) *out_tail = it->second->tail;
    if (out_last_ns) *out_last_ns = it->second->last_append_ns;
  }
  vols.erase(it);
  return 0;
}

int dp_set_readonly(uint32_t vid, int ro) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  v->read_only = ro != 0;
  return 0;
}

int dp_set_replicas(uint32_t vid, int has) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  v->has_replicas = has != 0;
  return 0;
}

// Append a pre-built record (Python Volume.append_needle delegated path).
// Returns the byte offset of the record, or -errno.
int64_t dp_append(uint32_t vid, const uint8_t* rec, int64_t len, uint64_t key,
                  int32_t size, uint64_t append_ns) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  if (v->detached) return -ENOENT;
  if (v->read_only) return -EROFS;
  if (pwrite(v->dat_fd, rec, len, v->tail) != len) return -EIO;
  int64_t off = v->tail;
  v->tail += len;
  v->put(key, off, size);
  if (v->write_idx(key, off, (uint32_t)size) != 0) return -EIO;
  if (append_ns > v->last_append_ns) v->last_append_ns = append_ns;
  return off;
}

// Append a tombstone record; returns reclaimed bytes (0 = was absent,
// tombstone NOT written then — delete_needle semantics), or -errno.
int64_t dp_delete(uint32_t vid, uint64_t key, const uint8_t* tomb, int64_t len,
                  uint64_t append_ns) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  if (v->detached) return -ENOENT;
  if (v->read_only) return -EROFS;
  auto it = v->map.find(key);
  if (it == v->map.end() || it->second.size <= 0) return 0;
  if (pwrite(v->dat_fd, tomb, len, v->tail) != len) return -EIO;
  v->tail += len;
  int64_t reclaimed = v->del(key);
  if (v->write_idx(key, 0, 0xFFFFFFFFu) != 0) return -EIO;
  if (append_ns > v->last_append_ns) v->last_append_ns = append_ns;
  return reclaimed;
}

// Live lookup. Returns 1 hit, 0 miss, -ENOENT no such volume.
int dp_lookup(uint32_t vid, uint64_t key, int64_t* out_byte_off,
              int32_t* out_size) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  auto it = v->map.find(key);
  if (it == v->map.end() || it->second.size <= 0) return 0;
  *out_byte_off = it->second.offset;
  *out_size = it->second.size;
  return 1;
}

// Raw entry including tombstones (size < 0, original offset kept) —
// the python ?readDeleted=true path needs the offset of a deleted
// needle whose record still sits in the .dat.
int dp_lookup_any(uint32_t vid, uint64_t key, int64_t* out_byte_off,
                  int32_t* out_size) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  auto it = v->map.find(key);
  if (it == v->map.end()) return 0;
  *out_byte_off = it->second.offset;
  *out_size = it->second.size;
  return 1;
}

// out[0..8] = file_count, file_bytes, deleted_count, deleted_bytes, tail,
// last_append_ns, max_key, map_len, read_only
int dp_stats(uint32_t vid, int64_t* out) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  out[0] = v->file_count;
  out[1] = v->file_bytes;
  out[2] = v->deleted_count;
  out[3] = v->deleted_bytes;
  out[4] = v->tail;
  out[5] = (int64_t)v->last_append_ns;
  out[6] = (int64_t)v->max_key;
  out[7] = (int64_t)v->map.size();
  out[8] = v->read_only ? 1 : 0;
  return 0;
}

// Dump the whole map (tombstones included, size=-1). Returns count or -errno.
int64_t dp_export(uint32_t vid, uint64_t* keys, int64_t* byte_offsets,
                  int32_t* sizes, int64_t cap) {
  std::shared_ptr<Vol> v = find_vol(vid);
  if (!v) return -ENOENT;
  std::lock_guard<std::mutex> lk(v->mu);
  int64_t n = 0;
  for (auto& [k, mv] : v->map) {
    if (n >= cap) return -ENOSPC;
    keys[n] = k;
    byte_offsets[n] = mv.offset;
    sizes[n] = mv.size;
    n++;
  }
  return n;
}

// Test hook: HMAC-SHA256 over (key, msg) -> out[32]. Lets the test
// suite cross-check the in-tree SHA-256 against python hashlib without
// going through a full HTTP round-trip.
void dp_hmac_sha256(const uint8_t* key, int64_t keylen, const uint8_t* msg,
                    int64_t msglen, uint8_t* out) {
  hmac_sha256(key, (size_t)keylen, msg, (size_t)msglen, out);
}

// out[0..7] = fast gets, fast posts, proxied, errors, fast deletes,
// native replicated posts, jwt rejects, fan-out failures
void dp_http_stats(int64_t* out) {
  out[0] = n_fast_get.load();
  out[1] = n_fast_post.load();
  out[2] = n_proxied.load();
  out[3] = n_errors.load();
  out[4] = n_fast_delete.load();
  out[5] = n_repl_post.load();
  out[6] = n_jwt_reject.load();
  out[7] = n_fanout_fail.load();
}

// out[0..5] = 2xx, 3xx, 4xx, 5xx responses written by the native
// fronts, payload bytes in (uploads), payload bytes out (served
// bodies). dp_front_stats sums all roles (the historical series);
// dp_role_front_stats snapshots one role so the host can federate
// per-front families (native_front_requests_total{front=...}).
void dp_front_stats(int64_t* out) {
  for (int i = 0; i < 6; i++) out[i] = 0;
  for (int r = 0; r < N_ROLES; r++) {
    out[0] += front_stats[r].n_2xx.load();
    out[1] += front_stats[r].n_3xx.load();
    out[2] += front_stats[r].n_4xx.load();
    out[3] += front_stats[r].n_5xx.load();
    out[4] += front_stats[r].bytes_in.load();
    out[5] += front_stats[r].bytes_out.load();
  }
}

void dp_role_front_stats(int role, int64_t* out) {
  for (int i = 0; i < 6; i++) out[i] = 0;
  if (role < 0 || role >= N_ROLES) return;
  out[0] = front_stats[role].n_2xx.load();
  out[1] = front_stats[role].n_3xx.load();
  out[2] = front_stats[role].n_4xx.load();
  out[3] = front_stats[role].n_5xx.load();
  out[4] = front_stats[role].bytes_in.load();
  out[5] = front_stats[role].bytes_out.load();
}

// ---------------------------------------------------------------------------
// Benchmark client (the `weed benchmark` load-generator loop,
// command/benchmark.go:145 benchWrite / :172 benchRead, as native code —
// the Python requests client saturates one core at ~1.5k rps and would
// measure itself, not the server).
// ---------------------------------------------------------------------------

// mode 0 = GET, 1 = POST `payload_size` random-ish bytes.
// fids: newline-separated "vid,hex" strings. auths: optional parallel
// newline-separated per-fid bearer tokens ("" lines = unauthenticated;
// NULL = none at all) — the jwt-guarded benchmark rows need the signed
// token the master minted at assign time. latencies_ns: one per fid.
// Returns wall-clock ns for the whole run, or -errno.
int64_t dp_bench(const char* host, uint16_t port, int mode, const char* fids,
                 const char* auths, int64_t n_fids, int64_t payload_size,
                 int concurrency, int64_t* latencies_ns,
                 int64_t* out_errors) {
  std::vector<std::pair<const char*, size_t>> fid_list;
  fid_list.reserve(n_fids);
  const char* p = fids;
  for (int64_t i = 0; i < n_fids; i++) {
    const char* nl = strchr(p, '\n');
    if (!nl) nl = p + strlen(p);
    fid_list.emplace_back(p, nl - p);
    if (!*nl) break;
    p = nl + 1;
  }
  std::vector<std::pair<const char*, size_t>> auth_list;
  if (auths && *auths) {
    auth_list.reserve(n_fids);
    const char* a = auths;
    for (int64_t i = 0; i < n_fids; i++) {
      const char* nl = strchr(a, '\n');
      if (!nl) nl = a + strlen(a);
      auth_list.emplace_back(a, nl - a);
      if (!*nl) break;
      a = nl + 1;
    }
  }
  std::string payload(payload_size, 'x');
  for (int64_t i = 0; i < payload_size; i++)
    payload[i] = (char)('a' + (i * 31 + 7) % 26);
  std::atomic<int64_t> next{0};
  std::atomic<int64_t> errors{0};
  struct sockaddr_in addr = {};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -EINVAL;

  auto worker = [&]() {
    int fd = -1;
    std::string resp;
    while (true) {
      int64_t i = next.fetch_add(1);
      if (i >= (int64_t)fid_list.size()) break;
      struct timespec t0, t1;
      clock_gettime(CLOCK_MONOTONIC, &t0);
      bool ok = false;
      for (int attempt = 0; attempt < 2 && !ok; attempt++) {
        if (fd < 0) {
          fd = socket(AF_INET, SOCK_STREAM, 0);
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          struct timeval tv = {30, 0};
          setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
          if (connect(fd, (struct sockaddr*)&addr, sizeof addr) != 0) {
            close(fd);
            fd = -1;
            continue;
          }
        }
        char req[1024];
        char authhdr[600] = "";
        if (i < (int64_t)auth_list.size() && auth_list[i].second &&
            auth_list[i].second < 560)
          snprintf(authhdr, sizeof authhdr,
                   "Authorization: Bearer %.*s\r\n",
                   (int)auth_list[i].second, auth_list[i].first);
        int rn;
        if (mode == 1) {
          rn = snprintf(req, sizeof req,
                        "POST /%.*s HTTP/1.1\r\nHost: bench\r\n"
                        "Content-Type: application/octet-stream\r\n"
                        "%sContent-Length: %lld\r\n\r\n",
                        (int)fid_list[i].second, fid_list[i].first, authhdr,
                        (long long)payload_size);
        } else {
          rn = snprintf(req, sizeof req,
                        "GET /%.*s HTTP/1.1\r\nHost: bench\r\n%s\r\n",
                        (int)fid_list[i].second, fid_list[i].first, authhdr);
        }
        if (rn >= (int)sizeof req) {
          close(fd);
          fd = -1;
          continue;
        }
        if (!send_all(fd, req, rn) ||
            (mode == 1 && !send_all(fd, payload.data(), payload.size()))) {
          close(fd);
          fd = -1;
          continue;
        }
        int code = read_framed_response(fd, &resp, 64 << 20, false);
        if (code >= 200 && code < 300) {
          ok = true;
        } else {
          close(fd);
          fd = -1;
        }
      }
      clock_gettime(CLOCK_MONOTONIC, &t1);
      latencies_ns[i] = (t1.tv_sec - t0.tv_sec) * 1000000000ll +
                        (t1.tv_nsec - t0.tv_nsec);
      if (!ok) {
        errors++;
        latencies_ns[i] = -latencies_ns[i];  // mark failed
      }
    }
    if (fd >= 0) close(fd);
  };

  struct timespec w0, w1;
  clock_gettime(CLOCK_MONOTONIC, &w0);
  std::vector<std::thread> threads;
  for (int t = 0; t < concurrency; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  clock_gettime(CLOCK_MONOTONIC, &w1);
  if (out_errors) *out_errors = errors.load();
  return (w1.tv_sec - w0.tv_sec) * 1000000000ll + (w1.tv_nsec - w0.tv_nsec);
}

// Replay client: send PREBUILT request blobs (offsets[i]..offsets[i+1]
// delimit request i; offsets has n+1 entries) over keep-alive
// connections and read Content-Length-framed responses. Lets Python
// pre-sign arbitrary protocols (SigV4 S3, filer paths) while every
// timed byte moves in native code — the gateway benchmark needs ~50k
// rps of signed requests, far beyond a GIL-bound client.
// 2xx/3xx = success. Returns wall ns, or -errno.
int64_t dp_bench_raw(const char* host, uint16_t port, const uint8_t* blob,
                     const int64_t* offsets, int64_t n, int concurrency,
                     int64_t* latencies_ns, int64_t* out_errors) {
  std::atomic<int64_t> next{0};
  std::atomic<int64_t> errors{0};
  struct sockaddr_in addr = {};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -EINVAL;

  auto worker = [&]() {
    int fd = -1;
    std::string resp;
    while (true) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      const char* req = (const char*)blob + offsets[i];
      size_t req_len = (size_t)(offsets[i + 1] - offsets[i]);
      struct timespec t0, t1;
      clock_gettime(CLOCK_MONOTONIC, &t0);
      bool ok = false;
      for (int attempt = 0; attempt < 2 && !ok; attempt++) {
        if (fd < 0) {
          fd = socket(AF_INET, SOCK_STREAM, 0);
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          struct timeval tv = {30, 0};
          setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
          if (connect(fd, (struct sockaddr*)&addr, sizeof addr) != 0) {
            close(fd);
            fd = -1;
            continue;
          }
        }
        if (!send_all(fd, req, req_len)) {
          close(fd);
          fd = -1;
          continue;
        }
        int code = read_framed_response(fd, &resp, 64 << 20, true);
        if (code >= 200 && code < 400) {
          ok = true;
        } else {
          close(fd);
          fd = -1;
        }
      }
      clock_gettime(CLOCK_MONOTONIC, &t1);
      latencies_ns[i] = (t1.tv_sec - t0.tv_sec) * 1000000000ll +
                        (t1.tv_nsec - t0.tv_nsec);
      if (!ok) {
        errors++;
        latencies_ns[i] = -latencies_ns[i];
      }
    }
    if (fd >= 0) close(fd);
  };

  struct timespec w0, w1;
  clock_gettime(CLOCK_MONOTONIC, &w0);
  std::vector<std::thread> threads;
  for (int t = 0; t < concurrency; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  clock_gettime(CLOCK_MONOTONIC, &w1);
  if (out_errors) *out_errors = errors.load();
  return (w1.tv_sec - w0.tv_sec) * 1000000000ll + (w1.tv_nsec - w0.tv_nsec);
}

}  // extern "C"
