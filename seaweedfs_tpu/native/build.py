"""Build the native library: g++ -> libseaweed_native.so.

Run directly (`python seaweedfs_tpu/native/build.py`) or let
seaweedfs_tpu.native build lazily on first import. No pybind11 — the
ABI is a C `extern "C"` surface consumed via ctypes.
"""
from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "gf256_codec.cc")
LIB = os.path.join(HERE, "libseaweed_native.so")


def build(verbose: bool = True) -> str:
    """Compile if missing or stale; returns the .so path."""
    if os.path.exists(LIB) and \
            os.path.getmtime(LIB) >= os.path.getmtime(SRC):
        return LIB
    # compile to a temp name + rename so a concurrent process never
    # dlopens a half-written library
    tmp = LIB + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
           "-std=c++17", "-o", tmp, SRC]
    if verbose:
        print("+", " ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    os.replace(tmp, LIB)
    return LIB


if __name__ == "__main__":
    build()
    print(LIB)
