"""Build the native libraries: g++ -> libseaweed_native.so (codec) and
libseaweed_dataplane.so (HTTP data plane).

Run directly (`python seaweedfs_tpu/native/build.py`) or let
seaweedfs_tpu.native build lazily on first import. No pybind11 — the
ABI is a C `extern "C"` surface consumed via ctypes.

Sanitizer builds: ``SEAWEEDFS_TPU_DP_SANITIZE={asan,tsan}`` selects an
instrumented data-plane build. Each mode caches its own .so
(libseaweed_dataplane.asan.so / .tsan.so) so switching modes never
races the plain library, and instrumented builds drop -O3/-march for
-O1 -g -fno-omit-frame-pointer so reports carry usable stacks.
"""
from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "gf256_codec.cc")
LIB = os.path.join(HERE, "libseaweed_native.so")
DP_SRC = os.path.join(HERE, "dataplane.cc")
DP_LIB = os.path.join(HERE, "libseaweed_dataplane.so")

SANITIZE_ENV = "SEAWEEDFS_TPU_DP_SANITIZE"
SANITIZE_FLAGS = {
    "asan": ["-fsanitize=address"],
    "tsan": ["-fsanitize=thread"],
}


def sanitize_mode() -> str:
    """'' (plain), 'asan', or 'tsan' — from the environment."""
    mode = os.environ.get(SANITIZE_ENV, "").strip().lower()
    if mode in ("", "0", "off", "none"):
        return ""
    if mode not in SANITIZE_FLAGS:
        raise ValueError(
            f"{SANITIZE_ENV}={mode!r}: expected one of "
            f"{sorted(SANITIZE_FLAGS)} (or empty)")
    return mode


def dp_lib_path(mode: str | None = None) -> str:
    mode = sanitize_mode() if mode is None else mode
    if not mode:
        return DP_LIB
    base, ext = os.path.splitext(DP_LIB)
    return f"{base}.{mode}{ext}"


def _compile(src: str, lib: str, verbose: bool,
             extra: list[str] | None = None,
             opt: list[str] | None = None) -> str:
    if os.path.exists(lib) and \
            os.path.getmtime(lib) >= os.path.getmtime(src):
        return lib
    # compile to a temp name + rename so a concurrent process never
    # dlopens a half-written library
    tmp = lib + f".tmp{os.getpid()}"
    cmd = ["g++"] + (opt or ["-O3", "-march=native"]) + \
        ["-shared", "-fPIC", "-std=c++17", "-o", tmp, src] + (extra or [])
    if verbose:
        print("+", " ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    os.replace(tmp, lib)
    return lib


def build(verbose: bool = True) -> str:
    """Compile the codec library if missing or stale; returns its path."""
    return _compile(SRC, LIB, verbose)


def build_dataplane(verbose: bool = True,
                    mode: str | None = None) -> str:
    """Compile the data-plane library; returns its path. `mode` (or
    the SEAWEEDFS_TPU_DP_SANITIZE env var) selects an instrumented
    build cached under its own name."""
    mode = sanitize_mode() if mode is None else mode
    if not mode:
        return _compile(DP_SRC, DP_LIB, verbose, extra=["-pthread"])
    return _compile(DP_SRC, dp_lib_path(mode), verbose,
                    extra=["-pthread"] + SANITIZE_FLAGS[mode],
                    opt=["-O1", "-g", "-fno-omit-frame-pointer"])


if __name__ == "__main__":
    build()
    print(LIB)
    build_dataplane()
    print(dp_lib_path())
