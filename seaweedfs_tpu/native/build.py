"""Build the native libraries: g++ -> libseaweed_native.so (codec) and
libseaweed_dataplane.so (HTTP data plane).

Run directly (`python seaweedfs_tpu/native/build.py`) or let
seaweedfs_tpu.native build lazily on first import. No pybind11 — the
ABI is a C `extern "C"` surface consumed via ctypes.
"""
from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "gf256_codec.cc")
LIB = os.path.join(HERE, "libseaweed_native.so")
DP_SRC = os.path.join(HERE, "dataplane.cc")
DP_LIB = os.path.join(HERE, "libseaweed_dataplane.so")


def _compile(src: str, lib: str, verbose: bool,
             extra: list[str] | None = None) -> str:
    if os.path.exists(lib) and \
            os.path.getmtime(lib) >= os.path.getmtime(src):
        return lib
    # compile to a temp name + rename so a concurrent process never
    # dlopens a half-written library
    tmp = lib + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
           "-std=c++17", "-o", tmp, src] + (extra or [])
    if verbose:
        print("+", " ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    os.replace(tmp, lib)
    return lib


def build(verbose: bool = True) -> str:
    """Compile the codec library if missing or stale; returns its path."""
    return _compile(SRC, LIB, verbose)


def build_dataplane(verbose: bool = True) -> str:
    """Compile the data-plane library; returns its path."""
    return _compile(DP_SRC, DP_LIB, verbose, extra=["-pthread"])


if __name__ == "__main__":
    build()
    print(LIB)
    build_dataplane()
    print(DP_LIB)
