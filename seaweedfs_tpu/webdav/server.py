"""WebDAV gateway over the filer.

Equivalent of /root/reference/weed/server/webdav_server.go (golang.org/
x/net/webdav over the filer): RFC4918 subset — OPTIONS, PROPFIND
(Depth 0/1), PROPPATCH (no-op accept), MKCOL, GET/HEAD, PUT, DELETE,
MOVE, COPY, and class-2 LOCK/UNLOCK with in-memory advisory tokens
(Windows/macOS clients refuse to write without them). Data and
namespace both ride the filer HTTP API.
"""
from __future__ import annotations

import time
import uuid
from xml.sax.saxutils import escape

import aiohttp
from aiohttp import web

from ..utils import retry, tracing

DAV_NS = "DAV:"


from ..filer.entry import entry_size as _entry_size


def _prop_xml(href: str, is_dir: bool, size: int, mtime: float,
              name: str) -> str:
    rtype = "<D:resourcetype><D:collection/></D:resourcetype>" if is_dir \
        else "<D:resourcetype/>"
    modified = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                             time.gmtime(mtime))
    return (
        f"<D:response><D:href>{escape(href)}</D:href>"
        "<D:propstat><D:prop>"
        f"{rtype}"
        f"<D:displayname>{escape(name)}</D:displayname>"
        f"<D:getcontentlength>{size}</D:getcontentlength>"
        f"<D:getlastmodified>{modified}</D:getlastmodified>"
        "<D:supportedlock><D:lockentry><D:lockscope><D:exclusive/>"
        "</D:lockscope><D:locktype><D:write/></D:locktype>"
        "</D:lockentry></D:supportedlock>"
        "</D:prop><D:status>HTTP/1.1 200 OK</D:status></D:propstat>"
        "</D:response>")


class WebDavServer:
    def __init__(self, filer_url: str, root: str = "/",
                 collection: str = "", replication: str = ""):
        self.filer_url = filer_url.rstrip("/") \
            if filer_url.startswith("http") else f"http://{filer_url}"
        self.root = root.rstrip("/")
        self.collection = collection
        self.replication = replication
        self._locks: dict[str, tuple[str, float]] = {}  # path -> (token, expiry)
        self.app = self._build_app()

    LOCK_TTL = 3600.0

    def _lock_conflict(self, req: web.Request, path: str) -> bool:
        """True when `path` is exclusively locked by a token the
        request does not present (in If or Lock-Token headers)."""
        rec = self._locks.get(path)
        if rec is None:
            return False
        token, expiry = rec
        if time.monotonic() > expiry:
            del self._locks[path]
            return False
        presented = (req.headers.get("If", "") +
                     req.headers.get("Lock-Token", ""))
        return token not in presented

    def _build_app(self) -> web.Application:
        app = web.Application(
            client_max_size=1 << 40,
            middlewares=[tracing.aiohttp_middleware("webdav"),
                         retry.aiohttp_middleware("webdav", edge=True)])
        app.add_routes([
            web.get("/debug/traces", tracing.handle_debug_traces),
            web.route("*", "/{path:.*}", self.dispatch),
        ])
        return app

    @staticmethod
    def _sess() -> aiohttp.ClientSession:
        """Filer-bound session carrying the active trace context, so
        WebDAV-originated filer hops chain to the gateway's root span."""
        return aiohttp.ClientSession(headers=tracing.inject({}))

    def _abs(self, path: str) -> str:
        return (self.root + "/" + path.strip("/")).rstrip("/") or "/"

    async def dispatch(self, req: web.Request) -> web.StreamResponse:
        method = req.method.upper()
        handler = {
            "OPTIONS": self.do_options, "PROPFIND": self.do_propfind,
            "PROPPATCH": self.do_proppatch, "MKCOL": self.do_mkcol,
            "GET": self.do_get, "HEAD": self.do_get,
            "PUT": self.do_put, "DELETE": self.do_delete,
            "MOVE": self.do_move, "COPY": self.do_copy,
            "LOCK": self.do_lock, "UNLOCK": self.do_unlock,
        }.get(method)
        if handler is None:
            return web.Response(status=405)
        return await handler(req)

    # -- plumbing to the filer -----------------------------------------
    async def _entry(self, sess: aiohttp.ClientSession,
                     full: str) -> dict | None:
        async with sess.get(f"{self.filer_url}{full}",
                            params={"meta": "1"}) as r:
            if r.status == 404:
                return None
            return await r.json()

    async def _listing(self, sess: aiohttp.ClientSession,
                       full: str) -> list[dict]:
        out, last = [], ""
        while True:
            async with sess.get(f"{self.filer_url}{full or '/'}",
                                params={"limit": "1024",
                                        "lastFileName": last}) as r:
                if r.status != 200:
                    return out
                d = await r.json()
            batch = d.get("entries", [])
            out.extend(batch)
            if not d.get("shouldDisplayLoadMore") or not batch:
                return out
            last = d.get("lastFileName", "")

    # -- methods --------------------------------------------------------
    async def do_options(self, req: web.Request) -> web.Response:
        return web.Response(status=200, headers={
            "DAV": "1, 2",
            "Allow": "OPTIONS, PROPFIND, PROPPATCH, MKCOL, GET, HEAD, "
                     "PUT, DELETE, MOVE, COPY, LOCK, UNLOCK",
            "MS-Author-Via": "DAV",
        })

    async def do_propfind(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        full = self._abs(path)
        depth = req.headers.get("Depth", "1")
        async with self._sess() as sess:
            entry = await self._entry(sess, full)
            if entry is None and full != "/":
                return web.Response(status=404)
            parts = []
            is_dir = full == "/" or bool(
                entry and entry.get("mode", 0) & 0o40000)
            size = _entry_size(entry) if entry else 0
            href = path if path.startswith("/") else "/" + path
            parts.append(_prop_xml(
                href + ("/" if is_dir and not href.endswith("/") else ""),
                is_dir, 0 if is_dir else size,
                (entry or {}).get("mtime", 0),
                href.rstrip("/").rsplit("/", 1)[-1] or "/"))
            if is_dir and depth != "0":
                for e in await self._listing(sess, full):
                    child_dir = bool(e.get("mode", 0) & 0o40000)
                    name = e["full_path"].rsplit("/", 1)[-1]
                    child_href = (href.rstrip("/") + "/" + name +
                                  ("/" if child_dir else ""))
                    child_size = _entry_size(e)
                    parts.append(_prop_xml(child_href, child_dir,
                                           child_size,
                                           e.get("mtime", 0), name))
        body = ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:multistatus xmlns:D="DAV:">' + "".join(parts) +
                "</D:multistatus>")
        return web.Response(status=207, text=body,
                            content_type="application/xml")

    async def do_proppatch(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        body = ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:multistatus xmlns:D="DAV:">'
                f"<D:response><D:href>{escape(path)}</D:href>"
                "<D:propstat><D:status>HTTP/1.1 200 OK</D:status>"
                "</D:propstat></D:response></D:multistatus>")
        return web.Response(status=207, text=body,
                            content_type="application/xml")

    async def do_mkcol(self, req: web.Request) -> web.Response:
        full = self._abs("/" + req.match_info["path"])
        async with self._sess() as sess:
            if await self._entry(sess, full) is not None:
                return web.Response(status=405)  # exists
            async with sess.put(f"{self.filer_url}{full}",
                                params={"mkdir": "1"}) as r:
                return web.Response(status=201 if r.status < 300
                                    else r.status)

    async def do_get(self, req: web.Request) -> web.StreamResponse:
        full = self._abs("/" + req.match_info["path"])
        headers = {}
        if "Range" in req.headers:
            headers["Range"] = req.headers["Range"]
        async with self._sess() as sess:
            entry = await self._entry(sess, full)
            if entry is None:
                return web.Response(status=404)
            if entry.get("mode", 0) & 0o40000:
                return web.Response(status=405)  # collection GET
            async with sess.get(f"{self.filer_url}{full}",
                                headers=headers) as r:
                resp_headers = {k: v for k, v in r.headers.items()
                                if k in ("ETag", "Content-Range",
                                         "Last-Modified",
                                         "Accept-Ranges")}
                if req.method == "HEAD":
                    resp_headers["Content-Length"] = \
                        r.headers.get("Content-Length", "0")
                    return web.Response(status=r.status,
                                        headers=resp_headers)
                # stream: a 20GB download must not materialize in the
                # gateway's RSS before the first byte goes out
                if "Content-Length" in r.headers:
                    resp_headers["Content-Length"] = \
                        r.headers["Content-Length"]
                out = web.StreamResponse(status=r.status,
                                         headers=resp_headers)
                await out.prepare(req)
                async for chunk in r.content.iter_chunked(256 << 10):
                    await out.write(chunk)
                await out.write_eof()
                return out

    async def do_put(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        if self._lock_conflict(req, path):
            return web.Response(status=423)
        full = self._abs(path)
        data = await req.read()
        params = {}
        if self.collection:
            params["collection"] = self.collection
        if self.replication:
            params["replication"] = self.replication
        async with self._sess() as sess:
            async with sess.put(f"{self.filer_url}{full}", data=data,
                                params=params,
                                headers={"Content-Type":
                                         req.content_type or
                                         "application/octet-stream"}) as r:
                return web.Response(status=201 if r.status < 300
                                    else r.status)

    async def do_delete(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        if self._lock_conflict(req, path):
            return web.Response(status=423)
        full = self._abs(path)
        async with self._sess() as sess:
            if await self._entry(sess, full) is None:
                return web.Response(status=404)
            async with sess.delete(f"{self.filer_url}{full}",
                                   params={"recursive": "true"}) as r:
                return web.Response(status=204 if r.status < 300
                                    else r.status)

    def _dest_path(self, req: web.Request) -> str | None:
        dest = req.headers.get("Destination", "")
        if not dest:
            return None
        # strip scheme://host
        if "://" in dest:
            dest = dest.split("://", 1)[1]
            dest = dest[dest.find("/"):]
        from urllib.parse import unquote

        return unquote(dest)

    async def do_move(self, req: web.Request) -> web.Response:
        src_rel = "/" + req.match_info["path"]
        src = self._abs(src_rel)
        dest_rel = self._dest_path(req)
        if dest_rel is None:
            return web.Response(status=400)
        if self._lock_conflict(req, src_rel) or \
                self._lock_conflict(req, dest_rel):
            return web.Response(status=423)
        dest = self._abs(dest_rel)
        overwrite = req.headers.get("Overwrite", "T") != "F"
        async with self._sess() as sess:
            if await self._entry(sess, src) is None:
                return web.Response(status=404)
            existed = await self._entry(sess, dest) is not None
            if existed and not overwrite:
                return web.Response(status=412)
            if existed:
                async with sess.delete(f"{self.filer_url}{dest}",
                                       params={"recursive": "true"}):
                    pass
            async with sess.put(f"{self.filer_url}{dest}",
                                params={"mv.from": src}) as r:
                if r.status >= 300:
                    return web.Response(status=r.status)
        return web.Response(status=204 if existed else 201)

    async def do_copy(self, req: web.Request) -> web.Response:
        src = self._abs("/" + req.match_info["path"])
        dest_rel = self._dest_path(req)
        if dest_rel is None:
            return web.Response(status=400)
        if self._lock_conflict(req, dest_rel):
            return web.Response(status=423)
        dest = self._abs(dest_rel)
        overwrite = req.headers.get("Overwrite", "T") != "F"
        async with self._sess() as sess:
            entry = await self._entry(sess, src)
            if entry is None:
                return web.Response(status=404)
            existed = await self._entry(sess, dest) is not None
            if existed and not overwrite:
                return web.Response(status=412)
            await self._copy_tree(sess, src, dest,
                                  bool(entry.get("mode", 0) & 0o40000))
        return web.Response(status=204 if existed else 201)

    async def _copy_tree(self, sess: aiohttp.ClientSession, src: str,
                         dest: str, is_dir: bool) -> None:
        if is_dir:
            async with sess.put(f"{self.filer_url}{dest}",
                                params={"mkdir": "1"}):
                pass
            for e in await self._listing(sess, src):
                name = e["full_path"].rsplit("/", 1)[-1]
                await self._copy_tree(sess, f"{src}/{name}",
                                      f"{dest}/{name}",
                                      bool(e.get("mode", 0) & 0o40000))
            return
        async with sess.get(f"{self.filer_url}{src}") as r:
            data = await r.read()
        async with sess.put(f"{self.filer_url}{dest}", data=data):
            pass

    # -- class-2 advisory locks ----------------------------------------
    async def do_lock(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        if self._lock_conflict(req, path):
            return web.Response(status=423)  # someone else holds it
        held = self._locks.get(path)
        if held is not None and held[0] in req.headers.get("If", ""):
            # RFC 4918 refresh: the client presented the live token —
            # extend the TTL and KEEP the token (minting a new one
            # would 423 every later request still using the original)
            token = held[0]
        else:
            token = f"opaquelocktoken:{uuid.uuid4()}"
        self._locks[path] = (token, time.monotonic() + self.LOCK_TTL)
        body = ('<?xml version="1.0" encoding="utf-8"?>'
                '<D:prop xmlns:D="DAV:"><D:lockdiscovery><D:activelock>'
                "<D:locktype><D:write/></D:locktype>"
                "<D:lockscope><D:exclusive/></D:lockscope>"
                "<D:depth>infinity</D:depth>"
                f"<D:locktoken><D:href>{token}</D:href></D:locktoken>"
                "<D:timeout>Second-3600</D:timeout>"
                "</D:activelock></D:lockdiscovery></D:prop>")
        return web.Response(status=200, text=body,
                            content_type="application/xml",
                            headers={"Lock-Token": f"<{token}>"})

    async def do_unlock(self, req: web.Request) -> web.Response:
        path = "/" + req.match_info["path"]
        held = self._locks.get(path)
        if held is not None:
            presented = req.headers.get("Lock-Token", "")
            if held[0] not in presented:
                # only the token holder may unlock (RFC 4918) — a
                # blind UNLOCK would let any client break an exclusive
                # lock and clobber the holder's in-progress edit
                return web.Response(status=409)
            self._locks.pop(path, None)
        return web.Response(status=204)
