from .server import WebDavServer

__all__ = ["WebDavServer"]
