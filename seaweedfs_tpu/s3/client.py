"""Low-level S3-compatible HTTP client shared by every outbound S3 use
(tier backend storage, remote-storage mounts, replication sink).

One place for the SigV4-vs-anonymous convention, URL building/quoting,
ranged GETs, streamed PUTs with known Content-Length, and ListObjectsV2
paging — so fixes to any of those apply to all S3 consumers at once.
Server-side verification lives in s3/auth.py; the reference's
equivalents are the aws-sdk-go wrappers under
weed/storage/backend/s3_backend and weed/remote_storage/s3.
"""
from __future__ import annotations

import hashlib
import time
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Iterator
from urllib.parse import quote
from ..rpc.httpclient import session

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


@dataclass
class ObjectInfo:
    key: str
    size: int = 0
    mtime: float = 0.0
    etag: str = ""


def _parse_iso(s: str) -> float:
    from datetime import datetime
    try:
        return datetime.fromisoformat(
            s.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return 0.0


class S3Client:
    """Bucket-scoped S3 HTTP verbs. Empty access_key => anonymous
    (unsigned) requests, which is how the in-process gateway is used in
    tests."""

    def __init__(self, endpoint: str = "", bucket: str = "",
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1", **_):
        if not endpoint or not bucket:
            raise ValueError("s3 client needs endpoint and bucket")
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def url(self, key: str = "", query: str = "") -> str:
        u = f"{self.endpoint}/{self.bucket}"
        if key:
            u += "/" + quote(key.lstrip("/"), safe="/~._-")
        if query:
            u += "?" + query
        return u

    def headers(self, method: str, url: str, payload: bytes = b"",
                unsigned_payload: bool = False) -> dict:
        if not self.access_key:
            return {}
        from .sigv4_client import sign_headers
        return sign_headers(method, url, self.access_key,
                            self.secret_key, payload=payload,
                            region=self.region,
                            unsigned_payload=unsigned_payload)

    # -- objects --------------------------------------------------------
    def get_object(self, key: str, offset: int = 0,
                   size: int = -1) -> bytes:
        import requests
        if size == 0:
            return b""
        url = self.url(key)
        h = self.headers("GET", url)
        if offset or size > 0:
            end = "" if size < 0 else str(offset + size - 1)
            h["Range"] = f"bytes={offset}-{end}"
        r = session().get(url, headers=h, timeout=600)
        r.raise_for_status()
        return r.content

    def put_object(self, key: str, data: bytes) -> ObjectInfo:
        import requests
        url = self.url(key)
        r = session().put(url, data=data,
                         headers=self.headers("PUT", url, payload=data),
                         timeout=600)
        r.raise_for_status()
        return ObjectInfo(
            key=key.lstrip("/"), size=len(data), mtime=time.time(),
            etag=r.headers.get(
                "ETag", hashlib.md5(data).hexdigest()).strip('"'))

    def put_stream(self, key: str, reader, total: int) -> int:
        """Streamed PUT of `total` bytes from a file-like `reader`
        (exposing read(n)); signs with UNSIGNED-PAYLOAD so the body
        isn't hashed/buffered up front. A __len__ wrapper gives
        requests a Content-Length (S3 rejects chunked encoding without
        the STREAMING-* signing scheme)."""
        import requests

        class _Body:
            def __init__(self):
                self.left = total

            def __len__(self):
                return self.left

            def read(self, n: int = -1) -> bytes:
                if self.left <= 0:
                    return b""
                want = self.left if n is None or n < 0 \
                    else min(n, self.left)
                blob = reader.read(want)
                self.left -= len(blob)
                return blob

        url = self.url(key)
        r = session().put(
            url, data=_Body(),
            headers=self.headers("PUT", url, unsigned_payload=True),
            timeout=3600)
        r.raise_for_status()
        return total

    def head_object(self, key: str) -> ObjectInfo | None:
        import requests
        url = self.url(key)
        r = session().head(url, headers=self.headers("HEAD", url),
                          timeout=60)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return ObjectInfo(
            key=key.lstrip("/"),
            size=int(r.headers.get("Content-Length", 0)),
            etag=r.headers.get("ETag", "").strip('"'))

    def delete_object(self, key: str) -> None:
        import requests
        url = self.url(key)
        r = session().delete(url, headers=self.headers("DELETE", url),
                            timeout=300)
        if r.status_code >= 300 and r.status_code != 404:
            r.raise_for_status()

    def download_to(self, key: str, dest_path: str) -> int:
        import requests
        url = self.url(key)
        with session().get(url, headers=self.headers("GET", url),
                           stream=True, timeout=3600) as r:
            r.raise_for_status()
            n = 0
            with open(dest_path, "wb") as out:
                for blob in r.iter_content(4 << 20):
                    out.write(blob)
                    n += len(blob)
        return n

    # -- listing --------------------------------------------------------
    def list_buckets(self) -> list[str]:
        """Service-level ListBuckets (ignores this client's bucket
        scope) — remote.mount.buckets discovery."""
        import requests
        url = f"{self.endpoint}/"
        r = session().get(url, headers=self.headers("GET", url),
                         timeout=300)
        r.raise_for_status()
        root = ET.fromstring(r.text)
        return [n.text for n in root.iter(f"{_NS}Name") if n.text]

    def list_objects(self, prefix: str = "") -> Iterator[ObjectInfo]:
        """ListObjectsV2 with continuation-token paging."""
        import requests
        token = ""
        while True:
            q = "list-type=2&max-keys=1000"
            if prefix:
                q += f"&prefix={quote(prefix.lstrip('/'), safe='~._-')}"
            if token:
                q += "&continuation-token=" + \
                    quote(token, safe="~._-")
            url = self.url(query=q)
            r = session().get(url, headers=self.headers("GET", url),
                             timeout=300)
            r.raise_for_status()
            root = ET.fromstring(r.text)
            for c in root.iter(f"{_NS}Contents"):
                yield ObjectInfo(
                    key=c.find(f"{_NS}Key").text,
                    size=int(c.find(f"{_NS}Size").text or 0),
                    mtime=_parse_iso(
                        c.findtext(f"{_NS}LastModified") or ""),
                    etag=(c.findtext(f"{_NS}ETag") or "").strip('"'))
            if (root.findtext(f"{_NS}IsTruncated") or "") != "true":
                return
            token = root.findtext(f"{_NS}NextContinuationToken") or ""
            if not token:
                return
