"""S3 gateway: AWS-S3-compatible REST API over the filer.

TPU-native re-expression of /root/reference/weed/s3api/ — see server.py
(routing + handlers) and auth.py (SigV4 + identity model).
"""
from .auth import (IdentityAccessManagement, S3AuthError, presign_url,
                   sign_request)
from .server import S3ApiServer, S3Error

__all__ = ["IdentityAccessManagement", "S3AuthError", "presign_url",
           "sign_request", "S3ApiServer", "S3Error"]
