"""S3 REST gateway over the filer.

Equivalent of /root/reference/weed/s3api/s3api_server.go:47-150 (router)
and its handler files: bucket CRUD (s3api_bucket_handlers.go), object
CRUD + copy (s3api_object_handlers*.go), ListObjects V1/V2
(s3api_objects_list_handlers.go), multipart (filer_multipart.go,
s3api_object_multipart_handlers.go), tagging (s3api_object_tagging_
handlers.go), batch delete, SigV4 auth (auth_signature_v4.go).

Buckets live at /buckets/<name> in the filer namespace and map to a
storage collection of the same name, exactly like the reference.
Multipart parts are staged under /buckets/<bucket>/.uploads/<id>/ and
stitched into the final object by a metadata-only entry create — the
bytes never move.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import re
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

import requests

from ..rpc import httpclient
from ..rpc.http import debug_index_factory
from aiohttp import web

from ..filer.entry import Entry as FilerEntry
from ..utils import extheaders, faults, metrics, qos, retry, tracing
from .auth import (ACTION_ADMIN, ACTION_LIST, ACTION_READ, ACTION_TAGGING,
                   ACTION_WRITE, IdentityAccessManagement, S3AuthError)

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
BUCKETS_DIR = "/buckets"
UPLOADS_DIR = ".uploads"
IDENTITIES_KV_KEY = "s3/identities"  # filer KV key holding the config
CIRCUIT_BREAKER_KV_KEY = "s3/circuit_breaker"  # limits, hot-reloaded


class S3Error(Exception):
    def __init__(self, code: str, message: str, status: int):
        super().__init__(message)
        self.code = code
        self.status = status


ERR_NO_SUCH_BUCKET = ("NoSuchBucket", "bucket does not exist", 404)
ERR_NO_SUCH_KEY = ("NoSuchKey", "key does not exist", 404)
ERR_BUCKET_NOT_EMPTY = ("BucketNotEmpty", "bucket is not empty", 409)
ERR_BUCKET_EXISTS = ("BucketAlreadyExists", "bucket already exists", 409)
ERR_NO_SUCH_UPLOAD = ("NoSuchUpload", "upload id not found", 404)


def _xml(tag: str, *children, text: str | None = None,
         ns: bool = True) -> ET.Element:
    el = ET.Element(tag)
    if ns:
        el.set("xmlns", XMLNS)
    if text is not None:
        el.text = text
    for c in children:
        el.append(c)
    return el


def _leaf(tag: str, text) -> ET.Element:
    el = ET.Element(tag)
    el.text = str(text)
    return el


def _find(el: ET.Element, tag: str) -> ET.Element | None:
    """Find a child with or without the S3 namespace. (`find(a) or
    find(b)` is wrong — childless Elements are falsy.)"""
    found = el.find(tag)
    if found is None:
        found = el.find(f"{{{XMLNS}}}{tag}")
    return found


def _xml_response(root: ET.Element, status: int = 200) -> web.Response:
    body = b'<?xml version="1.0" encoding="UTF-8"?>\n' + \
        ET.tostring(root)
    return web.Response(body=body, status=status,
                        content_type="application/xml")


def _error_response(code: str, message: str, status: int,
                    resource: str = "") -> web.Response:
    root = _xml("Error", ns=False)
    root.append(_leaf("Code", code))
    root.append(_leaf("Message", message))
    root.append(_leaf("Resource", resource))
    return _xml_response(root, status)


def _src_bucket_of(src: str) -> str:
    """Bucket name out of an x-amz-copy-source header value."""
    return urllib.parse.unquote(src.lstrip("/")).partition("/")[0]


OWNER_ID = "seaweedfs_tpu"


def _clear_bucket_ttls(conf, prefix_root: str) -> bool:
    """Drop the TTLs a bucket's lifecycle owns from the filer conf:
    rules that carry only a ttl are removed, rules that also hold other
    fs.configure settings (replication, readOnly, ...) keep those and
    just lose the ttl. Returns whether anything changed."""
    from ..filer.filer_conf import PathConf

    changed = False
    for r in list(conf.rules):
        if not (r.location_prefix.startswith(prefix_root) and r.ttl):
            continue
        changed = True
        bare = PathConf(location_prefix=r.location_prefix, ttl=r.ttl)
        if r == bare:
            conf.delete_rule(r.location_prefix)
        else:
            r.ttl = ""
    return changed


def _canned_from_acl_xml(payload: bytes) -> str:
    """Map an AccessControlPolicy body onto the modeled canned ACLs:
    owner-only FULL_CONTROL -> private, plus AllUsers READ ->
    public-read; any grant to another principal is unsupported
    (returned verbatim so the caller rejects with NotImplemented)."""
    if not payload.strip():
        return "private"
    try:
        root = ET.fromstring(payload)
    except ET.ParseError:
        raise S3Error("MalformedACLError", "bad ACL XML", 400)
    grants = []
    for g in root.iter():
        if not g.tag.endswith("Grant"):
            continue
        uri = perm = gid = ""
        for el in g.iter():
            if el.tag.endswith("URI") and el.text:
                uri = el.text
            if el.tag.split("}")[-1] == "ID" and el.text:
                gid = el.text
            if el.tag.endswith("Permission") and el.text:
                perm = el.text
        grants.append((uri, gid, perm))
    public = ("http://acs.amazonaws.com/groups/global/AllUsers", "",
              "READ")
    # the owner grant must actually name the owner (or no principal at
    # all); FULL_CONTROL for any other canonical ID is a real grant to
    # someone else and must not be silently dropped
    owner_full = [(u, i, p) for u, i, p in grants
                  if p == "FULL_CONTROL" and not u
                  and i in ("", OWNER_ID)]
    other = [g for g in grants if g != public and g not in owner_full]
    if other:
        return "unsupported-grants"
    return "public-read" if public in grants else "private"


def _ttl_to_days(ttl: str) -> int:
    """'5d'/'48h'/'60m'... -> whole days, 0 when under a day (mirrors
    the reference's ttl.Minutes()/60/24 truncation,
    s3api_bucket_handlers.go:338)."""
    if not ttl:
        return 0
    units = {"m": 60, "h": 3600, "d": 86400, "w": 7 * 86400,
             "M": 30 * 86400, "y": 365 * 86400}
    try:
        secs = int(ttl[:-1]) * units[ttl[-1]] if ttl[-1] in units \
            else int(ttl)
    except (ValueError, KeyError):
        return 0
    return secs // 86400


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


def _etag_from_chunks(meta: dict) -> str:
    """ETagChunks fallback (weed/filer/filechunks.go) for entries the
    filer stored without a whole-stream md5 (multi-chunk autochunked
    writes made outside the S3 gateway)."""
    chunks = meta.get("chunks") or []
    if len(chunks) == 1:
        return chunks[0].get("etag", "")
    joined = b"".join(bytes.fromhex(c["etag"])
                      for c in chunks if c.get("etag"))
    if not joined:
        return ""
    return f"{hashlib.md5(joined).hexdigest()}-{len(chunks)}"


class S3ApiServer:
    def __init__(self, filer_url: str, iam_config: dict | None = None,
                 region: str = "us-east-1",
                 identity_refresh_seconds: float = 5.0,
                 circuit_breaker_config: dict | None = None):
        from .circuit_breaker import CircuitBreaker

        self.filer_url = filer_url.rstrip("/")
        self.region = region
        self.iam = IdentityAccessManagement(iam_config)
        self.identity_refresh_seconds = identity_refresh_seconds
        self.circuit_breaker = CircuitBreaker(circuit_breaker_config)
        self._load_identities_from_filer()
        self.app = self._build_app()
        # hot reload of filer-stored identities (the reference reloads
        # via metadata subscription, auth_credentials_subscribe.go; the
        # IAM gateway mutates the same config)
        self._reload_task = None

        async def _start(app):
            import asyncio

            async def loop():
                while True:
                    await asyncio.sleep(self.identity_refresh_seconds)
                    try:
                        await asyncio.to_thread(
                            self._load_identities_from_filer)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        # malformed KV content must not kill the
                        # reload loop — revocations have to keep
                        # propagating once the config is fixed
                        pass

            self._reload_task = asyncio.create_task(loop())

        async def _stop(app):
            import asyncio

            if self._reload_task is not None:
                self._reload_task.cancel()
                try:
                    await self._reload_task
                except (asyncio.CancelledError, Exception):
                    pass
            pool = getattr(self, "_fast_pool", None)
            if pool is not None:
                await pool.close()

        self.app.on_startup.append(_start)
        self.app.on_cleanup.append(_stop)

    def _build_app(self) -> web.Application:
        @web.middleware
        async def error_mw(request, handler):
            start = time.perf_counter()
            code = "500"  # unexpected exceptions escape to aiohttp
            try:
                try:
                    resp = await handler(request)
                except S3Error as e:
                    resp = _error_response(e.code, str(e), e.status,
                                           request.path)
                    if e.status == 503:
                        resp.headers["Retry-After"] = str(
                            max(1, int(getattr(e, "retry_after", 1))))
                except S3AuthError as e:
                    resp = _error_response(e.code, str(e), e.status,
                                           request.path)
                except (KeyError, ValueError, ET.ParseError) as e:
                    resp = _error_response("InvalidRequest", str(e),
                                           400, request.path)
                code = str(resp.status)
                return resp
            finally:
                # recorded in finally: an outage (filer down raising
                # ConnectionError) is exactly when metrics must exist
                metrics.histogram_observe(
                    "s3_request_seconds", time.perf_counter() - start,
                    labels={"method": request.method})
                metrics.counter_add(
                    "s3_requests_total", labels={
                        "method": request.method, "code": code})

        # bodies are buffered for SigV4 payload hashing; 1GB caps the
        # blowup — larger objects go through multipart parts
        app = web.Application(
            client_max_size=1 << 30,
            middlewares=[tracing.aiohttp_middleware("s3"),
                         retry.aiohttp_middleware("s3", edge=True),
                         # qos AFTER retry: the deadline middleware
                         # binds the budget the admission check prices
                         # the queue delay against
                         qos.aiohttp_middleware("s3", qos.s3_tenant),
                         faults.aiohttp_middleware("s3"), error_mw])
        app.add_routes([
            web.get("/status", self.handle_status),
            web.get("/metrics", self.handle_metrics),
            # /debug index BEFORE the catch-all dispatch, or it would
            # be parsed as a bucket name
            web.get("/debug", debug_index_factory("s3", {
                "/debug/traces": "recent spans recorded in-process",
                "/debug/breakers": "circuit breaker states",
                "/debug/qos": "per-tenant admission buckets + shed "
                              "counts",
            })),
            web.get("/debug/traces", tracing.handle_debug_traces),
            web.get("/debug/breakers",
                    retry.handle_debug_breakers_factory()),
            web.get("/debug/qos", qos.handle_debug_qos_factory()),
            web.route("*", "/{tail:.*}", self.dispatch),
        ])
        return app

    async def handle_status(self, req: web.Request) -> web.Response:
        out = {"filer": self.filer_url, "open": self.iam.is_open}
        front = getattr(self, "_native_front", None)
        if front is not None:
            out["native_s3_front"] = front.stats()
        return web.json_response(out)

    async def handle_metrics(self, req: web.Request) -> web.Response:
        # per-tenant demand sketches -> workload_tenant_* gauges so
        # tenant demand rides federation to the master's aggregator
        qos.export_demand_metrics()
        return web.Response(text=metrics.render(),
                            content_type="text/plain")

    # -- auth + dispatch ------------------------------------------------
    def _load_identities_from_filer(self) -> None:
        """Pick up s3.configure-style identities stored in the filer
        (auth_credentials_subscribe.go's role), and the circuit-breaker
        limits (the reference keeps them at
        /etc/s3/circuit_breaker.json, hot-reloaded the same way)."""
        try:
            resp = httpclient.session().get(
                f"{self.filer_url}/kv/{IDENTITIES_KV_KEY}", timeout=5)
            if resp.status_code == 200:
                self.iam.load_config(json.loads(resp.content))
        except requests.RequestException:
            pass
        try:
            resp = httpclient.session().get(
                f"{self.filer_url}/kv/{CIRCUIT_BREAKER_KV_KEY}",
                timeout=5)
            if resp.status_code == 200:
                self.circuit_breaker.load_config(
                    json.loads(resp.content))
        except requests.RequestException:
            pass

    async def dispatch(self, req: web.Request) -> web.Response:
        from .circuit_breaker import CircuitOpen

        tail = req.match_info["tail"]
        bucket, _, key = tail.partition("/")
        cb_action = "write" if req.method in ("PUT", "POST", "DELETE") \
            else "read"
        # acquire BEFORE buffering the body (by declared length): the
        # writeBytes limit exists to stop concurrent uploads from
        # ballooning gateway memory, so it must gate the read itself.
        # A write with no declarable length (plain chunked) could evade
        # a configured byte limit entirely — demand a length, as AWS
        # does (411) for PUTs.
        declared = req.content_length
        if declared is None:
            decoded = req.headers.get("x-amz-decoded-content-length")
            if decoded and decoded.isdigit():
                declared = int(decoded)  # streaming-signed uploads
        if declared is None and req.method in ("PUT", "POST") and \
                self.circuit_breaker.enabled:
            # body-carrying verbs only: DELETE legitimately has no
            # Content-Length and must keep working under limits
            raise S3Error("MissingContentLength",
                          "uploads must declare a content length", 411)
        try:
            with self.circuit_breaker.acquire(
                    cb_action, bucket, declared or 0):
                payload = await req.read()
                return await self._dispatch_authed(req, bucket, key,
                                                   payload)
        except CircuitOpen as e:
            # s3api_circuit_breaker.go rejects with TooManyRequests
            raise S3Error("TooManyRequests", str(e), 503)

    async def _dispatch_authed(self, req: web.Request, bucket: str,
                               key: str, payload: bytes) -> web.Response:
        if req.method == "POST" and bucket and not key \
                and req.content_type.startswith("multipart/form-data"):
            # browser form upload (POST policy) authenticates via the
            # signed policy document, not headers
            return await self._post_policy_upload(req, bucket, payload)
        try:
            identity, stream_ctx = self.iam.authenticate_ctx(
                req.method, req.path,
                {k: v for k, v in req.query.items()},
                {k: v for k, v in req.headers.items()},
                hashlib.sha256(payload).hexdigest())
        except S3AuthError:
            # anonymous request: a public-read bucket ACL grants
            # AllUsers READ, so unauthenticated object GET/HEAD in
            # such a bucket must work — otherwise the stored ACL is
            # write-only state and the advertised grant is a lie
            if req.method in ("GET", "HEAD") and bucket and key and \
                    not set(req.query) & {"acl", "tagging", "uploads",
                                          "uploadId"} \
                    and await self._bucket_is_public_read(bucket):
                identity, stream_ctx = None, None
            else:
                raise
        if stream_ctx is not None:
            # aws-chunked framed body (SigV4 streaming upload): verify
            # the chunk-signature chain and unwrap to the real bytes
            payload = stream_ctx.decode(payload)

        def check(action: str, target: str | None = None):
            b = bucket if target is None else target
            if identity is not None and not identity.allows(action, b):
                raise S3Error("AccessDenied",
                              f"{action} denied on {b}", 403)

        q = req.query
        if not bucket:
            check(ACTION_LIST)
            return await self._list_buckets()
        if not key:
            return await self._bucket_op(req, bucket, q, payload, check)
        return await self._object_op(req, bucket, key, q, payload, check)

    async def _bucket_op(self, req, bucket, q, payload, check):
        m = req.method
        # sub-resources the reference also rejects
        # (s3api_bucket_skip_handlers.go): bucket policy, CORS
        if "policy" in q or "cors" in q:
            raise S3Error("NotImplemented",
                          "this sub-resource is not implemented", 501)
        if "acl" in q:
            check(ACTION_READ if m == "GET" else ACTION_ADMIN)
            return await self._bucket_acl_op(m, bucket, req, payload)
        if "lifecycle" in q:
            check(ACTION_READ if m == "GET" else ACTION_ADMIN)
            return await self._lifecycle_op(m, bucket, payload)
        if "ownershipControls" in q:
            check(ACTION_READ if m == "GET" else ACTION_ADMIN)
            return await self._ownership_op(m, bucket, payload)
        if m == "PUT":
            check(ACTION_ADMIN)
            return await self._put_bucket(bucket)
        if m == "DELETE":
            check(ACTION_ADMIN)
            return await self._delete_bucket(bucket)
        if m == "HEAD":
            check(ACTION_READ)
            await self._require_bucket(bucket)
            return web.Response(status=200)
        if m == "POST" and "delete" in q:
            check(ACTION_WRITE)
            return await self._delete_objects(bucket, payload)
        if m == "GET":
            check(ACTION_LIST)
            await self._require_bucket(bucket)
            if "uploads" in q:
                return await self._list_multipart_uploads(bucket)
            if "location" in q:
                root = _xml("LocationConstraint", text=self.region)
                return _xml_response(root)
            if "requestPayment" in q:
                root = _xml("RequestPaymentConfiguration")
                root.append(_leaf("Payer", "BucketOwner"))
                return _xml_response(root)
            return await self._list_objects(bucket, q)
        raise S3Error("MethodNotAllowed", f"{m} on bucket", 405)

    async def _object_op(self, req, bucket, key, q, payload, check):
        m = req.method
        if m == "POST" and "uploads" in q:
            check(ACTION_WRITE)
            return await self._initiate_multipart(bucket, key, req)
        if m == "POST" and "uploadId" in q:
            check(ACTION_WRITE)
            return await self._complete_multipart(bucket, key,
                                                  q["uploadId"], payload)
        if m == "DELETE" and "uploadId" in q:
            check(ACTION_WRITE)
            return await self._abort_multipart(bucket, q["uploadId"])
        if m == "PUT" and "partNumber" in q:
            check(ACTION_WRITE)
            src = req.headers.get("x-amz-copy-source", "")
            if src:
                # copying reads the SOURCE bucket: the writer identity
                # must hold Read there too, or part-copy becomes a
                # cross-bucket read bypass
                check(ACTION_READ, _src_bucket_of(src))
                return await self._upload_part_copy(
                    bucket, q["uploadId"], int(q["partNumber"]), src,
                    req.headers.get("x-amz-copy-source-range", ""))
            return await self._upload_part(bucket, q["uploadId"],
                                           int(q["partNumber"]), payload)
        if m == "GET" and "uploadId" in q:
            check(ACTION_READ)
            return await self._list_parts(bucket, key, q["uploadId"])
        if "tagging" in q:
            check(ACTION_TAGGING)
            return await self._tagging_op(m, bucket, key, payload)
        # object ACL / retention / legal-hold / object-lock: the
        # reference rejects all of these (s3api_object_skip_handlers.go)
        if "acl" in q or "retention" in q or "legal-hold" in q \
                or "object-lock" in q:
            raise S3Error("NotImplemented",
                          "this sub-resource is not implemented", 501)
        if m == "POST" and "select" in q:
            check(ACTION_READ)
            return await self._select_object_content(
                bucket, key, payload, ndjson=q.get("output") == "ndjson")
        if m == "PUT":
            check(ACTION_WRITE)
            src = req.headers.get("x-amz-copy-source", "")
            if src:
                check(ACTION_READ, _src_bucket_of(src))
                return await self._copy_object(bucket, key, src, req)
            return await self._put_object(bucket, key, payload, req)
        if m in ("GET", "HEAD"):
            check(ACTION_READ)
            return await self._get_object(bucket, key, req)
        if m == "DELETE":
            check(ACTION_WRITE)
            return await self._delete_object(bucket, key)
        raise S3Error("MethodNotAllowed", f"{m} on object", 405)

    # -- filer helpers --------------------------------------------------
    def _fpath(self, bucket: str, key: str = "") -> str:
        p = f"{self.filer_url}{BUCKETS_DIR}/{bucket}"
        if key:
            p += "/" + urllib.parse.quote(key)
        return p

    def _http(self):
        """Shared keep-alive pool to the filer (rpc/fastclient). The
        previous per-call `asyncio.to_thread(requests...)` paid a
        thread hop + sync-client overhead on EVERY internal round
        trip — measured ~2x the whole gateway latency on a one-core
        box; fastclient's Response keeps the .status_code / .json() /
        .text idiom all forty call sites use."""
        pool = getattr(self, "_fast_pool", None)
        if pool is None:
            from ..rpc.fastclient import HttpPool

            pool = self._fast_pool = HttpPool()
        return pool

    async def _filer(self, method: str, url: str, **kw):
        try:
            return await self._http().request(method, url, **kw)
        except retry.BreakerOpenError as e:
            # the filer's breaker is open and there is no alternate
            # filer to fail over to: shed the request instead of
            # stacking timeouts (503 + Retry-After)
            err = S3Error("ServiceUnavailable",
                          f"filer unavailable (retry in "
                          f"{e.retry_after:.1f}s)", 503)
            err.retry_after = e.retry_after
            raise err from e

    async def _bucket_is_public_read(self, bucket: str) -> bool:
        try:
            meta = await self._require_bucket(bucket)
        except S3Error:
            return False
        ext = meta.get("extended", {}) or {}
        return ext.get("s3_acl") == "public-read"

    # Bucket metadata cache. The reference keeps an in-memory bucket
    # registry fed by a metadata subscription (s3api_bucket_registry);
    # this build's analogue is a short TTL + invalidation on local
    # bucket mutations — without it every object op pays a full filer
    # ?meta=1 round trip just to learn the bucket still exists.
    _BUCKET_TTL = 2.0

    def _bucket_cache(self) -> dict:
        cache = getattr(self, "_bucket_meta_cache", None)
        if cache is None:
            cache = self._bucket_meta_cache = {}
        return cache

    def _invalidate_bucket(self, bucket: str) -> None:
        self._bucket_cache().pop(bucket, None)

    async def _require_bucket(self, bucket: str) -> dict:
        cache = self._bucket_cache()
        hit = cache.get(bucket)
        now = time.monotonic()
        if hit is not None and now - hit[1] < self._BUCKET_TTL:
            return hit[0]
        resp = await self._filer("GET", self._fpath(bucket),
                                 params={"meta": "1"})
        if resp.status_code != 200:
            cache.pop(bucket, None)  # only EXISTENCE is cached
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        meta = resp.json()
        cache[bucket] = (meta, now)
        return meta

    async def _entry_meta(self, bucket: str, key: str) -> dict:
        resp = await self._filer("GET", self._fpath(bucket, key),
                                 params={"meta": "1"})
        if resp.status_code != 200:
            raise S3Error(*ERR_NO_SUCH_KEY)
        return resp.json()

    # -- service / bucket -----------------------------------------------
    async def _list_buckets(self) -> web.Response:
        resp = await self._filer("GET", self.filer_url + BUCKETS_DIR + "/")
        entries = resp.json().get("entries", []) \
            if resp.status_code == 200 else []
        buckets = ET.Element("Buckets")
        for e in entries:
            if not (e["mode"] & 0o40000):
                continue
            b = ET.Element("Bucket")
            b.append(_leaf("Name", e["full_path"].rsplit("/", 1)[-1]))
            b.append(_leaf("CreationDate", _iso(e.get("crtime", 0))))
            buckets.append(b)
        owner = ET.Element("Owner")
        owner.append(_leaf("ID", "seaweedfs_tpu"))
        root = _xml("ListAllMyBucketsResult", owner, buckets)
        return _xml_response(root)

    async def _put_bucket(self, bucket: str) -> web.Response:
        resp = await self._filer("GET", self._fpath(bucket),
                                 params={"meta": "1"})
        if resp.status_code == 200:
            raise S3Error(*ERR_BUCKET_EXISTS)
        await self._filer("POST", self._fpath(bucket) + "/",
                          params={"mkdir": "1"})
        self._invalidate_bucket(bucket)
        return web.Response(status=200, headers={"Location": f"/{bucket}"})

    async def _delete_bucket(self, bucket: str) -> web.Response:
        await self._require_bucket(bucket)
        # .uploads sorts first, so one extra slot is needed to see a
        # real object behind an in-progress multipart upload
        listing = await self._filer("GET", self._fpath(bucket) + "/",
                                    params={"limit": "2"})
        entries = listing.json().get("entries", [])
        if any(e["full_path"].rsplit("/", 1)[-1] != UPLOADS_DIR
               for e in entries):
            raise S3Error(*ERR_BUCKET_NOT_EMPTY)
        await self._filer("DELETE", self._fpath(bucket),
                          params={"recursive": "true"})
        self._invalidate_bucket(bucket)
        return web.Response(status=204)

    async def _delete_objects(self, bucket: str,
                              payload: bytes) -> web.Response:
        root = ET.fromstring(payload)
        deleted, errors = [], []
        for obj in root.iter():
            if not obj.tag.endswith("Object"):
                continue
            key_el = _find(obj, "Key")
            if key_el is None or not key_el.text:
                continue
            key = key_el.text
            resp = await self._filer("DELETE", self._fpath(bucket, key))
            if resp.status_code in (204, 404):
                deleted.append(key)
            else:
                errors.append(key)
        out = _xml("DeleteResult")
        for k in deleted:
            d = ET.Element("Deleted")
            d.append(_leaf("Key", k))
            out.append(d)
        for k in errors:
            e = ET.Element("Error")
            e.append(_leaf("Key", k))
            e.append(_leaf("Code", "InternalError"))
            out.append(e)
        return _xml_response(out)

    # -- bucket sub-resources -------------------------------------------
    async def _update_bucket_meta(self, bucket: str,
                                  mutate) -> dict:
        """Read-modify-write the bucket directory entry's extended
        attributes (the reference keeps bucket metadata on the bucket
        entry too, s3api/bucket_metadata.go)."""
        # read-modify-write must start from a FRESH entry, never the
        # TTL cache — a stale snapshot would silently drop a concurrent
        # metadata update
        self._invalidate_bucket(bucket)
        meta = await self._require_bucket(bucket)
        ext = dict(meta.get("extended", {}))
        mutate(ext)
        meta["extended"] = ext
        meta.pop("full_path", None)
        resp = await self._filer("PUT", self._fpath(bucket) + "?meta=1",
                                 json=meta)
        self._invalidate_bucket(bucket)
        if resp.status_code >= 300:
            raise S3Error("AccessDenied" if resp.status_code == 403
                          else "InternalError", resp.text,
                          resp.status_code)
        return ext

    async def _bucket_acl_op(self, m: str, bucket: str, req,
                             payload: bytes) -> web.Response:
        """Canned-ACL subset, like the reference's
        Get/PutBucketAclHandler (s3api_bucket_handlers.go:252-313):
        only `private` and `public-read` are modeled."""
        if m == "PUT":
            canned = req.headers.get("x-amz-acl", "")
            if not canned:
                # no canned header: the intent is in the XML body — map
                # the grant sets we model, reject the rest rather than
                # silently recording an ACL the caller didn't ask for
                canned = _canned_from_acl_xml(payload)
            if canned not in ("private", "public-read"):
                raise S3Error("NotImplemented",
                              f"canned acl {canned!r} not supported",
                              501)
            await self._update_bucket_meta(
                bucket, lambda ext: ext.__setitem__("s3_acl", canned))
            return web.Response(status=200)
        if m == "GET":
            meta = await self._require_bucket(bucket)
            canned = meta.get("extended", {}).get("s3_acl", "private")
            owner = _xml("Owner")
            owner.append(_leaf("ID", "seaweedfs_tpu"))
            grants = ET.Element("AccessControlList")

            def grant(grantee_children, permission):
                g = ET.Element("Grant")
                grantee = ET.Element("Grantee")
                grantee.set("xmlns:xsi",
                            "http://www.w3.org/2001/XMLSchema-instance")
                for c in grantee_children:
                    grantee.append(c)
                g.append(grantee)
                g.append(_leaf("Permission", permission))
                grants.append(g)

            grant([_leaf("ID", "seaweedfs_tpu")], "FULL_CONTROL")
            if canned == "public-read":
                grant([_leaf("URI", "http://acs.amazonaws.com/groups/"
                             "global/AllUsers")], "READ")
            root = _xml("AccessControlPolicy", owner, grants)
            return _xml_response(root)
        raise S3Error("MethodNotAllowed", f"{m} on ?acl", 405)

    async def _lifecycle_op(self, m: str, bucket: str,
                            payload: bytes) -> web.Response:
        """Bucket lifecycle <-> filer.conf TTL rules. GET mirrors the
        reference (s3api_bucket_handlers.go:315: rules derived from the
        filer conf's TTLs for the bucket's collection); PUT goes one
        step further and writes Days-based expiration rules back as
        per-prefix TTL rules; DELETE drops them (reference DELETE is a
        204 no-op)."""
        from ..filer.filer_conf import CONF_KEY, FilerConf, PathConf

        await self._require_bucket(bucket)
        prefix_root = f"{BUCKETS_DIR}/{bucket}/"
        resp = await self._filer(
            "GET", f"{self.filer_url}/kv/{CONF_KEY}")
        conf = FilerConf.from_json(resp.content) \
            if resp.status_code == 200 else FilerConf()

        if m == "GET":
            # only whole-day TTLs surface as lifecycle rules (the
            # reference truncates the same way and skips day-0 rules,
            # s3api_bucket_handlers.go:338-341)
            rules = [r for r in conf.rules
                     if r.location_prefix.startswith(prefix_root)
                     and r.ttl and _ttl_to_days(r.ttl) > 0]
            if not rules:
                raise S3Error("NoSuchLifecycleConfiguration",
                              "no lifecycle configuration", 404)
            root = _xml("LifecycleConfiguration")
            for r in rules:
                days = _ttl_to_days(r.ttl)
                rule = ET.Element("Rule")
                rule.append(_leaf("Status", "Enabled"))
                filt = ET.Element("Filter")
                filt.append(_leaf(
                    "Prefix", r.location_prefix[len(prefix_root):]))
                rule.append(filt)
                exp = ET.Element("Expiration")
                exp.append(_leaf("Days", str(days)))
                rule.append(exp)
                root.append(rule)
            return _xml_response(root)

        if m == "PUT":
            try:
                root = ET.fromstring(payload)
            except ET.ParseError as e:
                raise S3Error("MalformedXML", str(e), 400)
            # S3 PUT replaces the entire configuration: clear this
            # bucket's previous TTLs before adding the new set — but
            # only the ttl field, so fs.configure settings that share a
            # rule (replication, readOnly, ...) survive
            _clear_bucket_ttls(conf, prefix_root)
            put_any = False
            for rule in root.iter():
                if not rule.tag.endswith("Rule"):
                    continue
                status = _find(rule, "Status")
                if status is None or status.text != "Enabled":
                    continue
                days = None
                for exp in rule.iter():
                    if exp.tag.endswith("Expiration"):
                        d = _find(exp, "Days")
                        if d is not None and d.text:
                            try:
                                days = int(d.text)
                            except ValueError:
                                raise S3Error(
                                    "MalformedXML",
                                    f"bad Days {d.text!r}", 400)
                            if days <= 0:
                                raise S3Error(
                                    "InvalidArgument",
                                    "Days must be positive", 400)
                if days is None:
                    raise S3Error("NotImplemented",
                                  "only Days-based expiration is "
                                  "supported", 501)
                prefix = ""
                for el in rule.iter():
                    if el.tag.endswith("Prefix") and el.text:
                        prefix = el.text
                loc = prefix_root + prefix
                existing = next((r for r in conf.rules
                                 if r.location_prefix == loc), None)
                if existing is not None:
                    existing.ttl = f"{days}d"
                else:
                    conf.set_rule(PathConf(location_prefix=loc,
                                           ttl=f"{days}d"))
                put_any = True
            if not put_any:
                raise S3Error("MalformedXML",
                              "no enabled rules with expiration", 400)
            await self._filer("PUT",
                              f"{self.filer_url}/kv/{CONF_KEY}",
                              data=conf.to_json().encode())
            return web.Response(status=200)

        if m == "DELETE":
            if _clear_bucket_ttls(conf, prefix_root):
                await self._filer("PUT",
                                  f"{self.filer_url}/kv/{CONF_KEY}",
                                  data=conf.to_json().encode())
            return web.Response(status=204)
        raise S3Error("MethodNotAllowed", f"{m} on ?lifecycle", 405)

    async def _ownership_op(self, m: str, bucket: str,
                            payload: bytes) -> web.Response:
        """Bucket ownership controls, stored on the bucket entry
        (s3api_bucket_handlers.go:382-498)."""
        valid = ("BucketOwnerPreferred", "ObjectWriter",
                 "BucketOwnerEnforced")
        if m == "PUT":
            try:
                root = ET.fromstring(payload)
            except ET.ParseError as e:
                raise S3Error("MalformedXML", str(e), 400)
            ownership = ""
            for el in root.iter():
                if el.tag.endswith("ObjectOwnership") and el.text:
                    ownership = el.text
            if ownership not in valid:
                raise S3Error("InvalidRequest",
                              f"ownership must be one of {valid}", 400)
            await self._update_bucket_meta(
                bucket,
                lambda ext: ext.__setitem__("s3_ownership", ownership))
            return web.Response(status=200)
        if m == "GET":
            meta = await self._require_bucket(bucket)
            ownership = meta.get("extended", {}).get("s3_ownership", "")
            if not ownership:
                raise S3Error("OwnershipControlsNotFoundError",
                              "no ownership controls", 404)
            root = _xml("OwnershipControls")
            rule = ET.Element("Rule")
            rule.append(_leaf("ObjectOwnership", ownership))
            root.append(rule)
            return _xml_response(root)
        if m == "DELETE":
            await self._update_bucket_meta(
                bucket, lambda ext: ext.pop("s3_ownership", None))
            return web.Response(status=204)
        raise S3Error("MethodNotAllowed",
                      f"{m} on ?ownershipControls", 405)

    # -- object ---------------------------------------------------------
    async def _post_policy_upload(self, req: web.Request, bucket: str,
                                  payload: bytes) -> web.Response:
        """Browser form upload with a signed POST policy
        (s3api_object_handlers_postpolicy.go + policy/post-policy.go):
        the form carries key/policy/credential/signature fields plus the
        file; authentication is the SigV4 signature over the base64
        policy document, and the decoded policy's expiration and key /
        content-length conditions are enforced."""
        import base64

        from .sigv4_client import verify_policy_signature

        fields, file_data, file_name = _parse_form(
            payload, req.headers.get("Content-Type", ""))
        key = fields.get("key", "")
        if not key:
            raise S3Error("InvalidArgument",
                          "form upload needs a key field", 400)
        key = key.replace("${filename}", file_name or "file")
        if not self.iam.is_open:
            for f in ("policy", "x-amz-credential", "x-amz-signature"):
                if f not in fields:
                    raise S3Error("AccessDenied",
                                  f"form upload missing {f}", 403)
            access_key = fields["x-amz-credential"].split("/")[0]
            identity, secret = self.iam.lookup(access_key)
            # signature first: answering permission questions before
            # proving possession of the secret would let anyone probe
            # which access keys can write where
            if not verify_policy_signature(
                    fields["policy"], fields["x-amz-credential"],
                    fields["x-amz-signature"], secret):
                raise S3Error("SignatureDoesNotMatch",
                              "policy signature mismatch", 403)
            if not identity.allows(ACTION_WRITE, bucket):
                raise S3Error("AccessDenied",
                              f"write denied on {bucket}", 403)
            try:
                policy = json.loads(base64.b64decode(fields["policy"]))
            except (ValueError, json.JSONDecodeError):
                raise S3Error("InvalidPolicyDocument",
                              "policy is not base64 JSON", 400)
            _check_policy(policy, bucket, key, len(file_data))
        await self._require_bucket(bucket)
        mime = fields.get("Content-Type", fields.get("content-type", ""))
        headers = {"Content-Type": mime} if mime else {}
        resp = await self._filer("POST", self._fpath(bucket, key),
                                 params={"collection": bucket},
                                 data=file_data, headers=headers)
        if resp.status_code >= 300:
            raise S3Error("InternalError", resp.text, 500)
        etag = resp.json().get("etag", "")
        status = int(fields.get("success_action_status", "204"))
        if status not in (200, 201, 204):
            status = 204
        if status == 201:
            root = _xml("PostResponse")
            root.append(_leaf("Bucket", bucket))
            root.append(_leaf("Key", key))
            root.append(_leaf("ETag", f'"{etag}"'))
            return _xml_response(root, status=201)
        return web.Response(status=status,
                            headers={"ETag": f'"{etag}"'})

    async def _put_object(self, bucket: str, key: str, payload: bytes,
                          req: web.Request) -> web.Response:
        await self._require_bucket(bucket)
        if key.endswith("/") and not payload:
            await self._filer("POST", self._fpath(bucket, key),
                              params={"mkdir": "1"})
            return web.Response(status=200)
        # fullmd5: AWS-exact single-PUT ETag (md5 of the whole body)
        # even when the filer autochunks a large payload — the filer
        # otherwise stores the cheaper ETagChunks form for multi-chunk
        params = {"collection": bucket, "fullmd5": "1"}
        mime = req.headers.get("Content-Type", "")
        headers = {"Content-Type": mime} if mime else {}
        # x-amz-meta-* rides the SAME filer create as the chunks
        # (x-seaweed-ext-*) — a second read-modify-write would race a
        # concurrent PUT of the same key and strand freed chunks
        # (SaveAmzMetaData, s3api_object_handlers_put.go)
        for k, v in req.headers.items():
            if k.lower().startswith("x-amz-meta-"):
                # AWS requires US-ASCII metadata values; raw non-ASCII
                # header bytes (latin-1 clients) arrive as surrogates
                # and get a clean 400, not a codec traceback
                if not v.isascii():
                    raise S3Error(
                        "InvalidArgument",
                        f"x-amz-meta-* values must be US-ASCII ({k})",
                        400)
                name = k.lower()[len("x-amz-meta-"):]
                headers[f"x-seaweed-ext-s3_meta_{name}"] = \
                    extheaders.armor(v)
        resp = await self._filer("POST", self._fpath(bucket, key),
                                 params=params, data=payload,
                                 headers=headers)
        if resp.status_code >= 300:
            raise S3Error("InternalError", resp.text, 500)
        etag = resp.json().get("etag", "")
        metrics.counter_add("s3_put_bytes", len(payload))
        return web.Response(status=200, headers={"ETag": f'"{etag}"'})

    async def _get_object(self, bucket: str, key: str,
                          req: web.Request) -> web.Response:
        # ONE filer round trip: the data response carries the entry
        # kind (X-Seaweed-Entry) and the s3_* extended attributes as
        # headers, so the old ?meta=1 pre-flight — a full extra filer
        # round trip per GET — is gone. A key that exists only as a
        # directory/prefix is NoSuchKey in S3 (the dir response is
        # flagged, never leaked as the object body).
        headers = {}
        if "Range" in req.headers:
            headers["Range"] = req.headers["Range"]
        resp = await self._filer(
            "GET" if req.method == "GET" else "HEAD",
            self._fpath(bucket, key), headers=headers)
        if resp.status_code == 404:
            # S3 distinguishes a missing BUCKET from a missing KEY
            await self._require_bucket(bucket)
            raise S3Error(*ERR_NO_SUCH_KEY)
        if resp.status_code == 416:
            # range past EOF is a client condition, not a server error
            # (multipart downloaders probe ranges routinely)
            raise S3Error("InvalidRange",
                          "the requested range is not satisfiable", 416)
        if resp.status_code >= 400:
            raise S3Error("InternalError", resp.text, 500)
        if resp.headers.get("X-Seaweed-Entry") == "dir":
            raise S3Error(*ERR_NO_SUCH_KEY)
        out_headers = {"ETag": resp.headers.get("ETag", "")}
        for h in ("Content-Range", "Accept-Ranges", "Last-Modified",
                  "Content-Length"):
            if h in resp.headers:
                out_headers[h] = resp.headers[h]
        pfx = "x-seaweed-ext-s3_meta_"
        for k, v in resp.headers.items():
            if k.lower().startswith(pfx):
                out_headers[f"x-amz-meta-{k[len(pfx):]}"] = \
                    extheaders.unarmor(v)
        body = resp.content if req.method == "GET" else b""
        if req.method == "HEAD":
            return web.Response(
                status=resp.status_code, headers=out_headers,
                content_type=resp.headers.get("Content-Type"))
        return web.Response(
            body=body, status=resp.status_code, headers=out_headers,
            content_type=resp.headers.get("Content-Type"))

    async def _delete_object(self, bucket: str, key: str) -> web.Response:
        """Deleting a key that is really a directory (a 'folder
        marker') must NOT wipe nested objects — AWS deletes exactly one
        key. Non-recursive delete; a non-empty dir is left alone."""
        await self._filer("DELETE", self._fpath(bucket, key))
        return web.Response(status=204)

    async def _copy_object(self, bucket: str, key: str, src: str,
                           req: web.Request) -> web.Response:
        await self._require_bucket(bucket)
        src = urllib.parse.unquote(src.lstrip("/"))
        src_bucket, _, src_key = src.partition("/")
        # x-amz-metadata-directive (CopyObject API): COPY (default)
        # carries the source's user metadata; REPLACE takes the
        # request's x-amz-meta-* instead. A self-copy without REPLACE
        # is rejected exactly like real S3 — it would be a no-op.
        directive = req.headers.get(
            "x-amz-metadata-directive", "COPY").upper()
        if directive not in ("COPY", "REPLACE"):
            raise S3Error("InvalidArgument",
                          f"bad metadata directive {directive}", 400)
        if (src_bucket, src_key) == (bucket, key) and \
                directive == "COPY":
            raise S3Error(
                "InvalidRequest",
                "This copy request is illegal because it is trying to "
                "copy an object to itself without changing the "
                "object's metadata", 400)
        meta = await self._entry_meta(src_bucket, src_key)
        data = await self._filer("GET", self._fpath(src_bucket, src_key))
        if data.status_code != 200:
            raise S3Error(*ERR_NO_SUCH_KEY)
        headers = {"Content-Type": meta.get(
            "mime", "application/octet-stream")}
        if directive == "REPLACE":
            # REPLACE swaps ALL metadata — including Content-Type,
            # the field `aws s3 cp --metadata-directive REPLACE
            # --content-type ...` self-copies exist to fix. Header
            # PRESENCE decides (req.content_type defaults to
            # octet-stream and can't distinguish "explicitly
            # octet-stream" from "absent")
            if "Content-Type" in req.headers:
                headers["Content-Type"] = req.headers["Content-Type"]
            for k, v in req.headers.items():
                if k.lower().startswith("x-amz-meta-"):
                    name = k.lower()[len("x-amz-meta-"):]
                    headers[f"x-seaweed-ext-s3_meta_{name}"] = \
                        extheaders.armor(v)
        else:
            for k, v in (meta.get("extended") or {}).items():
                if k.startswith("s3_meta_"):
                    headers[f"x-seaweed-ext-{k}"] = extheaders.armor(v)
        resp = await self._filer(
            "POST", self._fpath(bucket, key),
            params={"collection": bucket}, data=data.content,
            headers=headers)
        if resp.status_code >= 300:
            raise S3Error("InternalError", resp.text, 500)
        etag = resp.json().get("etag", "")
        root = _xml("CopyObjectResult")
        root.append(_leaf("ETag", f'"{etag}"'))
        root.append(_leaf("LastModified", _iso(time.time())))
        return _xml_response(root)

    # -- listing --------------------------------------------------------
    async def _list_objects(self, bucket: str, q) -> web.Response:
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        v2 = q.get("list-type") == "2"
        start_after = q.get("start-after", "") if v2 else \
            q.get("marker", "")
        token = q.get("continuation-token", "")
        if token:
            start_after = urllib.parse.unquote(token)

        # encoding-type=url: keys/prefixes are percent-encoded in the
        # XML (clients with control chars in keys require it).
        # Validated BEFORE the walk — a bad argument must not pay a
        # full bucket traversal first.
        enc = q.get("encoding-type", "")
        if enc not in ("", "url"):
            raise S3Error("InvalidArgument",
                          f"invalid encoding-type {enc}", 400)

        def _enc(s: str) -> str:
            return urllib.parse.quote(s, safe="/") if enc == "url" else s

        items, truncated = await asyncio.to_thread(
            self._walk_keys, bucket, prefix, delimiter, start_after,
            max_keys)

        from ..filer.entry import entry_size

        root = _xml("ListBucketResult")
        root.append(_leaf("Name", bucket))
        root.append(_leaf("Prefix", _enc(prefix)))
        root.append(_leaf("MaxKeys", max_keys))
        root.append(_leaf("IsTruncated", "true" if truncated else "false"))
        if enc:
            root.append(_leaf("EncodingType", enc))
        if delimiter:
            root.append(_leaf("Delimiter", _enc(delimiter)))
        for kind, name, meta in items:
            if kind != "key":
                continue
            c = ET.Element("Contents")
            c.append(_leaf("Key", _enc(name)))
            c.append(_leaf("LastModified", _iso(meta.get("mtime", 0))))
            etag = meta.get("md5", "") or _etag_from_chunks(meta)
            c.append(_leaf("ETag", f'"{etag}"'))
            # max(offset+size), NOT the chunk-size sum: overlapping
            # rewrites keep superseded chunks in the list
            c.append(_leaf("Size", entry_size(meta)))
            c.append(_leaf("StorageClass", "STANDARD"))
            root.append(c)
        for kind, name, _ in items:
            if kind == "prefix":
                cp = ET.Element("CommonPrefixes")
                cp.append(_leaf("Prefix", _enc(name)))
                root.append(cp)
        if v2:
            root.append(_leaf("KeyCount", len(items)))
            if truncated and items:
                root.append(_leaf("NextContinuationToken",
                                  urllib.parse.quote(items[-1][1])))
        elif truncated and items:
            root.append(_leaf("NextMarker", _enc(items[-1][1])))
        return _xml_response(root)

    def _walk_keys(self, bucket: str, prefix: str, delimiter: str,
                   start_after: str, max_keys: int):
        """Walk the bucket subtree in lexical order, grouping by
        delimiter. Returns (items, truncated) where items is an ordered
        list of ("key", name, meta) / ("prefix", name, {}) — prefixes
        count toward max_keys and pagination resumes after the LAST
        item of either kind, matching S3 semantics."""
        base = f"{BUCKETS_DIR}/{bucket}"
        items: list[tuple[str, str, dict]] = []
        seen_prefixes: set[str] = set()
        truncated = False

        def list_dir(dirpath: str, last: str = ""):
            out = []
            while True:
                r = httpclient.session().get(
                    f"{self.filer_url}{urllib.parse.quote(dirpath)}/",
                    params={"limit": "1024", "lastFileName": last},
                    timeout=60)
                if r.status_code != 200:
                    return out
                body = r.json()
                out.extend(body.get("entries", []))
                if not body.get("shouldDisplayLoadMore"):
                    return out
                last = body.get("lastFileName", "")

        def walk(dirpath: str) -> bool:
            nonlocal truncated
            entries = list_dir(dirpath)
            # S3 key order, not filer name order: a directory 'dir'
            # emits keys 'dir/...', which sort AFTER 'dir.txt'
            # ('.' 0x2E < '/' 0x2F) — walking it first would emit keys
            # out of order and break marker-based pagination (resume
            # after 'dir/a' would skip 'dir.txt' forever)
            entries.sort(key=lambda e: e["full_path"].rsplit("/", 1)[-1]
                         + ("/" if e["mode"] & 0o40000 else ""))
            for e in entries:
                name = e["full_path"].rsplit("/", 1)[-1]
                rel = e["full_path"][len(base) + 1:]
                is_dir = bool(e["mode"] & 0o40000)
                if rel.split("/")[0] == UPLOADS_DIR:
                    continue
                probe = rel + ("/" if is_dir else "")
                if prefix and not (probe.startswith(prefix)
                                   or prefix.startswith(probe)):
                    continue
                if is_dir:
                    sub = rel + "/"
                    # group only dirs strictly below the prefix; a dir
                    # equal to the prefix must be recursed into
                    # (prefix=dir1/ delimiter=/ lists dir1/'s files)
                    if delimiter == "/" and sub != prefix and \
                            sub.startswith(prefix):
                        grouped = prefix + \
                            sub[len(prefix):].split("/")[0] + "/"
                        if grouped > (start_after or "") and \
                                grouped not in seen_prefixes:
                            if len(items) >= max_keys:
                                truncated = True
                                return False
                            seen_prefixes.add(grouped)
                            items.append(("prefix", grouped, {}))
                        continue
                    if not walk(e["full_path"]):
                        return False
                else:
                    if not rel.startswith(prefix):
                        continue
                    if start_after and rel <= start_after:
                        continue
                    if delimiter == "/" and \
                            "/" in rel[len(prefix):]:
                        grouped = prefix + \
                            rel[len(prefix):].split("/")[0] + "/"
                        if grouped > (start_after or "") and \
                                grouped not in seen_prefixes:
                            if len(items) >= max_keys:
                                truncated = True
                                return False
                            seen_prefixes.add(grouped)
                            items.append(("prefix", grouped, {}))
                        continue
                    if len(items) >= max_keys:
                        truncated = True
                        return False
                    items.append(("key", rel, e))
            return True

        walk(base)
        return items, truncated

    # -- multipart ------------------------------------------------------
    def _upload_dir(self, bucket: str, upload_id: str) -> str:
        return f"{UPLOADS_DIR}/{upload_id}"

    async def _initiate_multipart(self, bucket: str, key: str,
                                  req: web.Request) -> web.Response:
        await self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        marker = {"full_path": "", "mime": "application/json",
                  "extended": {"s3_key": key, "mime": req.headers.get(
                      "Content-Type", "")}, "mode": 0o40775}
        await self._filer(
            "PUT", self._fpath(bucket, self._upload_dir(
                bucket, upload_id)) + "?meta=1",
            json=marker)
        root = _xml("InitiateMultipartUploadResult")
        root.append(_leaf("Bucket", bucket))
        root.append(_leaf("Key", key))
        root.append(_leaf("UploadId", upload_id))
        return _xml_response(root)

    async def _upload_marker(self, bucket: str, upload_id: str) -> dict:
        resp = await self._filer(
            "GET", self._fpath(bucket, self._upload_dir(bucket,
                                                        upload_id)),
            params={"meta": "1"})
        if resp.status_code != 200:
            raise S3Error(*ERR_NO_SUCH_UPLOAD)
        return resp.json()

    @staticmethod
    def _check_part_number(part_number: int) -> None:
        # AWS contract; also keeps the %05d name <-> int round-trip
        # exact (a 6-digit number would truncate through the parse)
        if not 1 <= part_number <= 10000:
            raise S3Error("InvalidArgument",
                          "part number must be between 1 and 10000",
                          400)

    async def _upload_part(self, bucket: str, upload_id: str,
                           part_number: int,
                           payload: bytes) -> web.Response:
        self._check_part_number(part_number)
        await self._upload_marker(bucket, upload_id)
        part_path = f"{self._upload_dir(bucket, upload_id)}/" \
            f"{part_number:05d}.part"
        # fullmd5: the part entry's md5 must be the md5 of the PART
        # bytes — CompleteMultipartUpload composes the final "-N" etag
        # from them, exactly as AWS does
        # saveInside=false: complete-multipart assembles the object
        # from the parts' CHUNKS — a part inlined by -saveToFilerLimit
        # would contribute nothing and silently truncate the object
        resp = await self._filer("POST", self._fpath(bucket, part_path),
                                 params={"collection": bucket,
                                         "fullmd5": "1",
                                         "saveInside": "false"},
                                 data=payload)
        if resp.status_code >= 300:
            raise S3Error("InternalError", resp.text, 500)
        etag = resp.json().get("etag", "")
        return web.Response(status=200, headers={"ETag": f'"{etag}"'})

    async def _upload_part_copy(self, bucket: str, upload_id: str,
                                part_number: int, src: str,
                                src_range: str) -> web.Response:
        """UploadPartCopy (s3api_object_copy_handlers.go:135
        CopyObjectPartHandler): copy a source object — optionally an
        `x-amz-copy-source-range: bytes=a-b` slice — in as a part."""
        self._check_part_number(part_number)
        await self._upload_marker(bucket, upload_id)
        src = urllib.parse.unquote(src.lstrip("/"))
        src_bucket, _, src_key = src.partition("/")
        await self._entry_meta(src_bucket, src_key)
        headers = {}
        if src_range:
            m = re.fullmatch(r"bytes=(\d+)-(\d+)", src_range.strip())
            if not m:
                raise S3Error("InvalidArgument",
                              f"bad copy range {src_range!r}", 400)
            headers["Range"] = src_range
        data = await self._filer("GET", self._fpath(src_bucket, src_key),
                                 headers=headers)
        if data.status_code == 416:
            raise S3Error("InvalidRange",
                          f"copy range {src_range!r} is outside the "
                          "source object", 416)
        if data.status_code not in (200, 206):
            raise S3Error(*ERR_NO_SUCH_KEY)
        part_path = f"{self._upload_dir(bucket, upload_id)}/" \
            f"{part_number:05d}.part"
        resp = await self._filer("POST", self._fpath(bucket, part_path),
                                 params={"collection": bucket,
                                         "saveInside": "false"},
                                 data=data.content)
        if resp.status_code >= 300:
            raise S3Error("InternalError", resp.text, 500)
        etag = resp.json().get("etag", "")
        root = _xml("CopyPartResult")
        root.append(_leaf("ETag", f'"{etag}"'))
        root.append(_leaf("LastModified", _iso(time.time())))
        return _xml_response(root)

    async def _complete_multipart(self, bucket: str, key: str,
                                  upload_id: str,
                                  payload: bytes) -> web.Response:
        marker = await self._upload_marker(bucket, upload_id)
        want_parts = []
        if payload:
            root = ET.fromstring(payload)
            for p in root.iter():
                if p.tag.endswith("Part"):
                    num = _find(p, "PartNumber")
                    if num is not None:
                        want_parts.append(int(num.text))
        updir = self._upload_dir(bucket, upload_id)
        listing = await self._filer("GET", self._fpath(bucket, updir)
                                    + "/")
        parts = sorted(
            (e for e in listing.json().get("entries", [])
             if e["full_path"].endswith(".part")),
            key=lambda e: e["full_path"])
        if want_parts:
            by_num = {int(e["full_path"].rsplit("/", 1)[-1][:5]): e
                      for e in parts}
            try:
                parts = [by_num[n] for n in sorted(want_parts)]
            except KeyError:
                raise S3Error("InvalidPart", "listed part missing", 400)
        offset, chunks, etags = 0, [], []
        for e in parts:
            for ch in e.get("chunks", []):
                chunks.append({"fid": ch["fid"],
                               "offset": offset + ch["offset"],
                               "size": ch["size"],
                               "mtime_ns": ch["mtime_ns"],
                               "etag": ch.get("etag", "")})
            psize = sum(ch["size"] for ch in e.get("chunks", []))
            offset += psize
            if e.get("md5"):
                etags.append(e["md5"])
        final_etag = hashlib.md5(
            b"".join(bytes.fromhex(t) for t in etags)).hexdigest() + \
            f"-{len(parts)}"
        entry = {"mime": marker.get("extended", {}).get("mime", "") or
                 "application/octet-stream",
                 "md5": "", "collection": bucket, "chunks": chunks,
                 "extended": {"s3_etag": final_etag}}
        resp = await self._filer("PUT",
                                 self._fpath(bucket, key) + "?meta=1",
                                 json=entry)
        if resp.status_code >= 300:
            raise S3Error("InternalError", resp.text, 500)
        # drop part entries without touching the shared chunks
        await self._filer("DELETE", self._fpath(bucket, updir),
                          params={"recursive": "true",
                                  "skipChunkDeletion": "true"})
        root = _xml("CompleteMultipartUploadResult")
        root.append(_leaf("Bucket", bucket))
        root.append(_leaf("Key", key))
        root.append(_leaf("ETag", f'"{final_etag}"'))
        return _xml_response(root)

    async def _abort_multipart(self, bucket: str,
                               upload_id: str) -> web.Response:
        await self._filer(
            "DELETE",
            self._fpath(bucket, self._upload_dir(bucket, upload_id)),
            params={"recursive": "true"})
        return web.Response(status=204)

    async def _list_multipart_uploads(self, bucket: str) -> web.Response:
        listing = await self._filer(
            "GET", self._fpath(bucket, UPLOADS_DIR) + "/")
        root = _xml("ListMultipartUploadsResult")
        root.append(_leaf("Bucket", bucket))
        if listing.status_code == 200:
            for e in listing.json().get("entries", []):
                up = ET.Element("Upload")
                up.append(_leaf("UploadId",
                                e["full_path"].rsplit("/", 1)[-1]))
                up.append(_leaf("Key", e.get("extended", {}).get(
                    "s3_key", "")))
                up.append(_leaf("Initiated", _iso(e.get("crtime", 0))))
                root.append(up)
        return _xml_response(root)

    async def _list_parts(self, bucket: str, key: str,
                          upload_id: str) -> web.Response:
        await self._upload_marker(bucket, upload_id)
        updir = self._upload_dir(bucket, upload_id)
        listing = await self._filer("GET",
                                    self._fpath(bucket, updir) + "/")
        root = _xml("ListPartsResult")
        root.append(_leaf("Bucket", bucket))
        root.append(_leaf("Key", key))
        root.append(_leaf("UploadId", upload_id))
        for e in listing.json().get("entries", []):
            if not e["full_path"].endswith(".part"):
                continue
            p = ET.Element("Part")
            p.append(_leaf("PartNumber",
                           int(e["full_path"].rsplit("/", 1)[-1][:5])))
            p.append(_leaf("ETag", f'"{e.get("md5", "")}"'))
            p.append(_leaf("Size", sum(ch["size"]
                                       for ch in e.get("chunks", []))))
            root.append(p)
        return _xml_response(root)

    # -- tagging --------------------------------------------------------
    # select scans are buffered in gateway memory; bound the blast
    # radius of one query (streaming NDJSON would lift this)
    SELECT_MAX_OBJECT_BYTES = 256 << 20

    async def _select_object_content(self, bucket: str, key: str,
                                     payload: bytes,
                                     ndjson: bool = False) -> web.Response:
        """SelectObjectContent subset: SQL over JSON objects
        (POST /{key}?select&select-type=2). The projection/filter engine
        is the same one behind the volume server's Query rpc
        (weed/query/json); records are framed as an AWS binary
        event-stream (Records*, Stats, End) so stock SDK clients can
        parse them. `?output=ndjson` keeps the raw-lines extension."""
        from ..query import parse_select, query_json_bytes
        from .eventstream import select_response

        try:
            root = ET.fromstring(payload)
        except ET.ParseError:
            raise S3Error("MalformedXML", "bad select request", 400)
        expr_el = _find(root, "Expression")
        if expr_el is None or not (expr_el.text or "").strip():
            raise S3Error("MissingRequiredParameter",
                          "Expression is required", 400)
        try:
            selections, filt = parse_select(expr_el.text)
        except ValueError as e:
            raise S3Error("InvalidTextEncoding", str(e), 400)
        meta = await self._entry_meta(bucket, key)
        if meta.get("mode", 0) & 0o40000:
            raise S3Error(*ERR_NO_SUCH_KEY)
        size = max((c["offset"] + c["size"]
                    for c in meta.get("chunks", [])), default=0)
        if size > self.SELECT_MAX_OBJECT_BYTES:
            raise S3Error("OverMaxRecordSize",
                          f"select is limited to objects under "
                          f"{self.SELECT_MAX_OBJECT_BYTES} bytes", 400)
        resp = await self._filer("GET", self._fpath(bucket, key))
        if resp.status_code != 200:
            raise S3Error(*ERR_NO_SUCH_KEY)
        try:
            lines = [json.dumps(doc, separators=(",", ":"))
                     for doc in query_json_bytes(resp.content,
                                                 selections, filt)]
        except (json.JSONDecodeError, ValueError) as e:
            raise S3Error("InvalidTextEncoding",
                          f"object is not valid JSON: {e}", 400)
        body = ("\n".join(lines) + "\n").encode() if lines else b""
        if ndjson:
            return web.Response(body=body,
                                content_type="application/octet-stream")
        return web.Response(
            body=select_response(body, scanned=len(resp.content),
                                 processed=len(resp.content)),
            content_type="application/vnd.amazon.eventstream")

    async def _tagging_op(self, method: str, bucket: str, key: str,
                          payload: bytes) -> web.Response:
        meta = await self._entry_meta(bucket, key)
        ext = meta.get("extended", {})
        if method == "GET":
            root = _xml("Tagging")
            tagset = ET.Element("TagSet")
            for k, v in ext.items():
                if k.startswith("s3_tag_"):
                    t = ET.Element("Tag")
                    t.append(_leaf("Key", k[len("s3_tag_"):]))
                    t.append(_leaf("Value", v))
                    tagset.append(t)
            root.append(tagset)
            return _xml_response(root)
        ext = {k: v for k, v in ext.items()
               if not k.startswith("s3_tag_")}
        if method == "PUT":
            root = ET.fromstring(payload)
            for t in root.iter():
                if t.tag.endswith("Tag"):
                    k_el = _find(t, "Key")
                    v_el = _find(t, "Value")
                    if k_el is not None and v_el is not None:
                        ext[f"s3_tag_{k_el.text}"] = v_el.text or ""
        meta["extended"] = ext
        meta.pop("full_path", None)
        await self._filer("PUT", self._fpath(bucket, key) + "?meta=1",
                          json=meta)
        return web.Response(status=200 if method == "PUT" else 204)


def _parse_form(payload: bytes, content_type: str) \
        -> tuple[dict, bytes, str]:
    """multipart/form-data body -> (fields, file bytes, file name)."""
    import email
    import email.policy

    msg = email.message_from_bytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n"
        + payload, policy=email.policy.HTTP)
    fields: dict[str, str] = {}
    file_data, file_name = b"", ""
    for part in msg.iter_parts():
        name = part.get_param("name", header="content-disposition")
        if not name:
            continue
        body = part.get_payload(decode=True) or b""
        if name == "file":
            file_data = body
            file_name = part.get_filename("") or ""
        else:
            fields[name] = body.decode("utf-8", "replace")
    return fields, file_data, file_name


def _check_policy(policy: dict, bucket: str, key: str,
                  size: int) -> None:
    """Enforce a decoded POST policy's expiration + conditions
    (policy/post-policy.go). A signed policy is a bearer credential:
    expiration is mandatory and bucket conditions must be honored, or a
    leaked form could be replayed forever / against other buckets."""
    import calendar

    exp = policy.get("expiration", "")
    if not exp:
        raise S3Error("InvalidPolicyDocument",
                      "policy must carry an expiration", 400)
    try:
        dead = calendar.timegm(time.strptime(
            exp.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        raise S3Error("InvalidPolicyDocument",
                      f"bad expiration {exp!r}", 400)
    if time.time() > dead:
        raise S3Error("AccessDenied", "policy expired", 403)

    values = {"key": key, "bucket": bucket}

    def enforce(op: str, field: str, val) -> None:
        got = values.get(field)
        if got is None:
            return  # fields we don't model (acl, content-type, ...)
        if op == "eq" and got != val:
            raise S3Error("AccessDenied",
                          f"{field} must equal {val!r}", 403)
        if op == "starts-with" and not got.startswith(val):
            raise S3Error("AccessDenied",
                          f"{field} must start with {val!r}", 403)

    for cond in policy.get("conditions", []):
        if isinstance(cond, dict):
            for ck, cv in cond.items():
                enforce("eq", ck, cv)
        elif isinstance(cond, list) and len(cond) == 3:
            op, field = cond[0], str(cond[1]).lstrip("$")
            if op == "content-length-range":
                lo, hi = int(cond[1]), int(cond[2])
                if not lo <= size <= hi:
                    raise S3Error(
                        "EntityTooLarge" if size > hi
                        else "EntityTooSmall",
                        f"size {size} outside [{lo}, {hi}]", 400)
            else:
                enforce(op, field, cond[2])
