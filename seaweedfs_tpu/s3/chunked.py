"""aws-chunked payload decoding with SigV4 chunk-signature verification.

Equivalent of /root/reference/weed/s3api/chunked_reader_v4.go: the AWS
CLI / SDKs upload large PUTs with
`x-amz-content-sha256: STREAMING-AWS4-HMAC-SHA256-PAYLOAD` and a body
of framed chunks, each carrying a signature chained from the previous
one (seed = the request's Authorization signature):

    <hex size>;chunk-signature=<64 hex>\r\n
    <size bytes>\r\n
    ...
    0;chunk-signature=<sig>\r\n
    [trailers]\r\n

Per-chunk string-to-sign (chunked_reader_v4.go getChunkSignature):

    AWS4-HMAC-SHA256-PAYLOAD\n<amz date>\n<scope>\n
    <previous signature>\nSHA256("")\nSHA256(chunk data)

`STREAMING-UNSIGNED-PAYLOAD-TRAILER` frames the same way without the
chunk-signature field (newer SDKs with trailing checksums).
"""
from __future__ import annotations

import hashlib
import hmac
from collections import OrderedDict

from ..utils import metrics

STREAMING_SIGNED = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_UNSIGNED = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"

EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


class ChunkSignatureError(Exception):
    pass


_KEY_CACHE: OrderedDict[tuple[str, str, str, str], bytes] = OrderedDict()
_KEY_CACHE_CAP = 1024


def signing_key(secret: str, datestamp: str, region: str,
                service: str) -> bytes:
    """Derived AWS4 signing key, memoized: the derivation chain is 4
    HMACs but its inputs only change once per DAY per identity —
    re-deriving per request was ~half the gateway's SigV4 verify cost.
    LRU-bounded at 1024 entries: identity churn at high tenant counts
    evicts only the coldest key, instead of the old clear-everything
    policy whose rollover re-derived every live identity's key at
    once (a thundering herd exactly when the gateway is busiest)."""
    ck = (secret, datestamp, region, service)
    hit = _KEY_CACHE.get(ck)
    if hit is not None:
        _KEY_CACHE.move_to_end(ck)
        metrics.counter_add("s3_signing_key_cache_total",
                            labels={"outcome": "hit"})
        return hit
    k = hmac.new(("AWS4" + secret).encode(), datestamp.encode(),
                 hashlib.sha256).digest()
    for msg in (region, service, "aws4_request"):
        k = hmac.new(k, msg.encode(), hashlib.sha256).digest()
    metrics.counter_add("s3_signing_key_cache_total",
                        labels={"outcome": "miss"})
    _KEY_CACHE[ck] = k
    if len(_KEY_CACHE) > _KEY_CACHE_CAP:
        _KEY_CACHE.popitem(last=False)  # coldest (identity, day) only
        metrics.counter_add("s3_signing_key_cache_total",
                            labels={"outcome": "evict"})
    return k


def chunk_signature(key: bytes, amz_date: str, scope: str,
                    prev_signature: str, chunk: bytes) -> str:
    sts = "\n".join([
        "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev_signature,
        EMPTY_SHA256, hashlib.sha256(chunk).hexdigest()])
    return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()


def decode_chunked(payload: bytes, *, key: bytes | None = None,
                   amz_date: str = "", scope: str = "",
                   seed_signature: str = "",
                   expected_length: int | None = None) -> bytes:
    """Decode an aws-chunked body, verifying the signature chain when
    `key` is given (signed streaming) and skipping verification when it
    is None (unsigned streaming with trailers). The final zero-size
    chunk is mandatory — without it a truncated-at-a-frame-boundary
    stream would pass every per-chunk check — and `expected_length`
    (x-amz-decoded-content-length, a signed header) is enforced when
    given."""
    out = bytearray()
    prev = seed_signature
    pos = 0
    sealed = False
    n = len(payload)
    while pos < n:
        eol = payload.find(b"\r\n", pos)
        if eol < 0:
            raise ChunkSignatureError("truncated chunk header")
        header = payload[pos:eol].decode("ascii", "replace")
        size_part, _, ext = header.partition(";")
        try:
            size = int(size_part, 16)
        except ValueError:
            raise ChunkSignatureError(f"bad chunk size {size_part!r}")
        sig = ""
        if ext.startswith("chunk-signature="):
            sig = ext[len("chunk-signature="):]
        pos = eol + 2
        chunk = payload[pos:pos + size]
        if len(chunk) != size:
            raise ChunkSignatureError("truncated chunk data")
        pos += size
        if key is not None:
            expect = chunk_signature(key, amz_date, scope, prev, chunk)
            if not hmac.compare_digest(expect, sig):
                raise ChunkSignatureError("chunk signature mismatch")
            prev = expect
        if size == 0:
            sealed = True
            break  # final chunk; anything after is trailers
        out += chunk
        # data chunks are terminated by \r\n (tolerate its absence on
        # the final frame boundary)
        if payload[pos:pos + 2] == b"\r\n":
            pos += 2
    if not sealed:
        raise ChunkSignatureError("stream ended before the final chunk")
    if expected_length is not None and len(out) != expected_length:
        raise ChunkSignatureError(
            f"decoded {len(out)} bytes, declared {expected_length}")
    return bytes(out)


def encode_chunked(data: bytes, *, key: bytes | None = None,
                   amz_date: str = "", scope: str = "",
                   seed_signature: str = "",
                   chunk_size: int = 64 * 1024) -> bytes:
    """Client-side framing (tests + sigv4_client): signed when `key` is
    given, unsigned-trailer style otherwise."""
    out = bytearray()
    prev = seed_signature
    offsets = list(range(0, len(data), chunk_size)) + [len(data)]
    chunks = [data[a:b] for a, b in zip(offsets, offsets[1:])] + [b""]
    if not data:
        chunks = [b""]
    for chunk in chunks:
        if key is not None:
            prev = chunk_signature(key, amz_date, scope, prev, chunk)
            out += (f"{len(chunk):x};chunk-signature={prev}\r\n"
                    .encode())
        else:
            out += f"{len(chunk):x}\r\n".encode()
        out += chunk
        out += b"\r\n"
    return bytes(out)
