"""AWS binary event-stream framing (application/vnd.amazon.eventstream).

The wire format used by S3 SelectObjectContent responses: each message
is

    [ total_len u32 | headers_len u32 | prelude_crc u32 ]
    [ headers ... ] [ payload ... ] [ message_crc u32 ]

with CRC32 (IEEE) over the prelude for prelude_crc and over the whole
message up to (but excluding) message_crc. Each header is

    [ name_len u8 | name | value_type u8 | value... ]

and Select only ever uses value type 7 (string: u16 length + bytes).

The reference gateway does not implement SelectObjectContent (its
query engine lives behind the volume server's Query rpc,
weed/pb/volume_server.proto:107); this module completes our gateway's
Select subset so stock AWS SDK clients can parse the response.
"""
from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field

HEADER_STRING = 7


def _encode_headers(headers: dict[str, str]) -> bytes:
    out = bytearray()
    for name, value in headers.items():
        nb, vb = name.encode(), value.encode()
        out.append(len(nb))
        out += nb
        out.append(HEADER_STRING)
        out += struct.pack(">H", len(vb))
        out += vb
    return bytes(out)


def encode_message(headers: dict[str, str], payload: bytes = b"") -> bytes:
    hdr = _encode_headers(headers)
    total = 4 + 4 + 4 + len(hdr) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hdr))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


@dataclass
class Message:
    headers: dict[str, str] = field(default_factory=dict)
    payload: bytes = b""

    @property
    def event_type(self) -> str:
        return self.headers.get(":event-type", "")


def decode_messages(data: bytes) -> list[Message]:
    """Parse a byte string of concatenated messages (raises ValueError
    on framing or CRC errors)."""
    msgs = []
    pos = 0
    while pos < len(data):
        if len(data) - pos < 16:
            raise ValueError("truncated prelude")
        total, hdr_len = struct.unpack_from(">II", data, pos)
        (pre_crc,) = struct.unpack_from(">I", data, pos + 8)
        if zlib.crc32(data[pos:pos + 8]) != pre_crc:
            raise ValueError("prelude crc mismatch")
        if pos + total > len(data):
            raise ValueError("truncated message")
        (msg_crc,) = struct.unpack_from(">I", data, pos + total - 4)
        if zlib.crc32(data[pos:pos + total - 4]) != msg_crc:
            raise ValueError("message crc mismatch")
        headers = {}
        hp, hend = pos + 12, pos + 12 + hdr_len
        while hp < hend:
            nlen = data[hp]
            hp += 1
            name = data[hp:hp + nlen].decode()
            hp += nlen
            vtype = data[hp]
            hp += 1
            if vtype != HEADER_STRING:
                raise ValueError(f"unsupported header type {vtype}")
            (vlen,) = struct.unpack_from(">H", data, hp)
            hp += 2
            headers[name] = data[hp:hp + vlen].decode()
            hp += vlen
        payload = data[hend:pos + total - 4]
        msgs.append(Message(headers=headers, payload=payload))
        pos += total
    return msgs


# -- S3 Select event constructors --------------------------------------

def _event(event_type: str, payload: bytes = b"",
           content_type: str | None = None) -> bytes:
    headers = {":message-type": "event", ":event-type": event_type}
    if content_type:
        headers[":content-type"] = content_type
    return encode_message(headers, payload)


def records_event(data: bytes) -> bytes:
    return _event("Records", data, "application/octet-stream")


def stats_event(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f"<Stats><BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Stats>")
    return _event("Stats", xml.encode(), "text/xml")


def cont_event() -> bytes:
    return _event("Cont")


def end_event() -> bytes:
    return _event("End")


def select_response(records: bytes, scanned: int, processed: int) -> bytes:
    """Full SelectObjectContent response body: Records* Stats End."""
    out = b""
    # AWS chunks records into <=1MB events; match that so huge results
    # don't produce one oversized frame
    CHUNK = 1 << 20
    for off in range(0, len(records), CHUNK):
        out += records_event(records[off:off + CHUNK])
    out += stats_event(scanned, processed, len(records))
    out += end_event()
    return out
