"""S3 gateway circuit breaker.

Equivalent of /root/reference/weed/s3api/s3api_circuit_breaker.go:
caps concurrent requests and in-flight upload bytes, globally and
per-bucket, split by read/write action. When a limit trips the request
is rejected with 503 TooManyRequests instead of letting a burst take
the gateway (and the filer behind it) down.

Config shape (stored hot-reloadable in the filer KV under
`s3.circuit_breaker`, the reference keeps it at
/etc/s3/circuit_breaker.json):

    {"global": {"readCount": 64, "writeCount": 32,
                "writeBytes": 268435456},
     "buckets": {"media": {"writeCount": 4}}}

Absent keys mean unlimited (the reference's disabled-by-default).
"""
from __future__ import annotations

import threading

LIMIT_KEYS = ("readCount", "writeCount", "readBytes", "writeBytes")


class CircuitOpen(Exception):
    def __init__(self, scope: str, what: str):
        super().__init__(f"{scope} {what} limit reached")
        self.scope = scope
        self.what = what


class _Counters:
    __slots__ = ("read_count", "write_count", "read_bytes",
                 "write_bytes")

    def __init__(self):
        self.read_count = 0
        self.write_count = 0
        self.read_bytes = 0
        self.write_bytes = 0


class CircuitBreaker:
    def __init__(self, config: dict | None = None):
        self._lock = threading.Lock()
        self._global = _Counters()
        self._buckets: dict[str, _Counters] = {}
        self.config: dict = {}
        self.load_config(config or {})

    def load_config(self, config: dict) -> None:
        with self._lock:
            self.config = config or {}

    @property
    def enabled(self) -> bool:
        return bool(self.config.get("global")
                    or self.config.get("buckets"))

    def _limits(self, bucket: str) -> list[tuple[str, dict, _Counters]]:
        out = [("global", self.config.get("global") or {}, self._global)]
        bconf = (self.config.get("buckets") or {}).get(bucket)
        if bconf:
            counters = self._buckets.setdefault(bucket, _Counters())
            out.append((f"bucket {bucket}", bconf, counters))
        return out

    def acquire(self, action: str, bucket: str, nbytes: int = 0):
        """-> context manager guarding one request. `action` is "read"
        or "write"; raises CircuitOpen when a limit would be exceeded."""
        return _Guard(self, action, bucket, nbytes)


class _Guard:
    def __init__(self, cb: CircuitBreaker, action: str, bucket: str,
                 nbytes: int):
        self.cb = cb
        self.action = "write" if action == "write" else "read"
        self.bucket = bucket
        self.nbytes = max(0, nbytes)
        self._held: list[_Counters] = []

    def __enter__(self):
        cb = self.cb
        with cb._lock:
            if not cb.enabled:
                return self
            count_key = f"{self.action}Count"
            bytes_key = f"{self.action}Bytes"
            scopes = cb._limits(self.bucket)
            for scope, conf, counters in scopes:
                limit = conf.get(count_key)
                inflight = getattr(counters, f"{self.action}_count")
                if limit is not None and inflight + 1 > limit:
                    raise CircuitOpen(scope, count_key)
                blimit = conf.get(bytes_key)
                bheld = getattr(counters, f"{self.action}_bytes")
                if blimit is not None and bheld + self.nbytes > blimit:
                    raise CircuitOpen(scope, bytes_key)
            for _scope, _conf, counters in scopes:
                setattr(counters, f"{self.action}_count",
                        getattr(counters, f"{self.action}_count") + 1)
                setattr(counters, f"{self.action}_bytes",
                        getattr(counters, f"{self.action}_bytes")
                        + self.nbytes)
                self._held.append(counters)
        return self

    def __exit__(self, *exc):
        with self.cb._lock:
            for counters in self._held:
                setattr(counters, f"{self.action}_count",
                        getattr(counters, f"{self.action}_count") - 1)
                setattr(counters, f"{self.action}_bytes",
                        getattr(counters, f"{self.action}_bytes")
                        - self.nbytes)
            self._held.clear()
        return False
