"""S3 request authentication: AWS Signature V4 + legacy V2 + the
identity/action model.

Equivalent of /root/reference/weed/s3api/auth_signature_v4.go (header
and presigned-query SigV4 verification), auth_signature_v2.go:32
(legacy header + presigned V2, still emitted by old SDKs), and
auth_credentials.go (the `IdentityAccessManagement` identity ->
credentials -> actions model, hot-reloadable config).

Identities config (JSON, same shape idea as s3.configure):
  {"identities": [{"name": "admin",
                   "credentials": [{"accessKey": "K", "secretKey": "S"}],
                   "actions": ["Admin"]}]}
Actions: Admin, Read, Write, List, Tagging — optionally scoped
":bucket" (e.g. "Read:public-bucket"). No identities -> open access.
"""
from __future__ import annotations

import hashlib
import hmac
import threading
import urllib.parse
from datetime import datetime, timedelta, timezone

ALGORITHM = "AWS4-HMAC-SHA256"
MAX_CLOCK_SKEW_SECONDS = 15 * 60

# query subresources that participate in the V2 canonicalized
# resource — EXACTLY auth_signature_v2.go:39 resourceList (notably,
# no "tagging": adding anything the clients don't sign 403s them)
V2_SUBRESOURCES = (
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type",
    "response-expires", "torrent", "uploadId", "uploads",
    "versionId", "versioning", "versions", "website",
)

ACTION_ADMIN = "Admin"
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"


class S3AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


class StreamingContext:
    """Everything needed to verify + decode an aws-chunked body after
    header authentication (chunked_reader_v4.go newSignV4ChunkedReader).
    `key` is None for STREAMING-UNSIGNED-PAYLOAD-TRAILER."""

    def __init__(self, key: bytes | None, amz_date: str, scope: str,
                 seed_signature: str, decoded_length: int | None = None):
        self.key = key
        self.amz_date = amz_date
        self.scope = scope
        self.seed_signature = seed_signature
        self.decoded_length = decoded_length

    def decode(self, payload: bytes) -> bytes:
        from .chunked import ChunkSignatureError, decode_chunked
        try:
            return decode_chunked(
                payload, key=self.key, amz_date=self.amz_date,
                scope=self.scope, seed_signature=self.seed_signature,
                expected_length=self.decoded_length)
        except ChunkSignatureError as e:
            raise S3AuthError("SignatureDoesNotMatch", str(e))


class Identity:
    def __init__(self, name: str, credentials: list[dict],
                 actions: list[str]):
        self.name = name
        self.credentials = credentials
        self.actions = set(actions)

    def allows(self, action: str, bucket: str) -> bool:
        if ACTION_ADMIN in self.actions:
            return True
        return action in self.actions or \
            f"{action}:{bucket}" in self.actions


class IdentityAccessManagement:
    def __init__(self, config: dict | None = None):
        self._lock = threading.Lock()
        self._identities: list[Identity] = []
        self._by_access_key: dict[str, tuple[Identity, str]] = {}
        if config:
            self.load_config(config)

    @property
    def is_open(self) -> bool:
        with self._lock:
            return not self._identities

    def load_config(self, config: dict) -> None:
        """Replace all identities (hot reload — the reference reloads on
        s3.configure metadata events, auth_credentials_subscribe.go)."""
        identities, by_key = [], {}
        for id_cfg in config.get("identities", []):
            ident = Identity(id_cfg.get("name", ""),
                             id_cfg.get("credentials", []),
                             id_cfg.get("actions", []))
            identities.append(ident)
            for cred in ident.credentials:
                by_key[cred["accessKey"]] = (ident, cred["secretKey"])
        with self._lock:
            self._identities = identities
            self._by_access_key = by_key

    def lookup(self, access_key: str) -> tuple[Identity, str]:
        with self._lock:
            found = self._by_access_key.get(access_key)
        if found is None:
            raise S3AuthError("InvalidAccessKeyId",
                              f"access key {access_key!r} not found")
        return found

    # -- request verification -------------------------------------------
    def authenticate(self, method: str, path: str, query: dict[str, str],
                     headers: dict[str, str],
                     payload_hash: str) -> Identity | None:
        """Verify a request; returns the Identity (None if open mode).
        Raises S3AuthError on bad signatures."""
        return self.authenticate_ctx(method, path, query, headers,
                                     payload_hash)[0]

    def authenticate_ctx(
            self, method: str, path: str, query: dict[str, str],
            headers: dict[str, str], payload_hash: str,
    ) -> tuple[Identity | None, "StreamingContext | None"]:
        """Like authenticate(), but also returns a StreamingContext when
        the request body is aws-chunked framed (signed or unsigned
        streaming) and must be decoded before use."""
        from .chunked import STREAMING_UNSIGNED

        declared = headers.get(
            "x-amz-content-sha256",
            headers.get("X-Amz-Content-Sha256", ""))
        if "X-Amz-Signature" in query or "X-Amz-Algorithm" in query:
            return self._verify_presigned(method, path, query,
                                          headers), None
        if "Signature" in query and "AWSAccessKeyId" in query:
            return self._verify_presigned_v2(method, path, query,
                                             headers), None
        auth = headers.get("Authorization", "")
        if auth.startswith(ALGORITHM):
            identity, ctx = self._verify_header(
                method, path, query, headers, payload_hash, auth)
            return identity, ctx
        if auth.startswith("AWS ") and ":" in auth:
            return self._verify_header_v2(method, path, query,
                                          headers, auth), None
        if self.is_open:
            ctx = None
            if declared == STREAMING_UNSIGNED:
                ctx = StreamingContext(None, "", "", "")
            return None, ctx
        raise S3AuthError("AccessDenied", "no credentials provided")

    # -- Signature V2 (auth_signature_v2.go:32) -------------------------
    def _string_to_sign_v2(self, method: str, path: str,
                           query: dict[str, str],
                           headers: dict[str, str],
                           expires_or_date: str) -> str:
        """The legacy V2 string-to-sign, matching
        auth_signature_v2.go:312 getStringToSignV2 exactly: method,
        content-md5, content-type, date (Expires for presigned, else
        the Date header), canonicalized x-amz-* headers (x-amz-date
        INCLUDED — clients sign it), canonicalized resource (path +
        the resourceList subresources in list order)."""
        h = {k.lower(): v for k, v in headers.items()}
        amz = "\n".join(
            f"{k}:{h[k].strip()}" for k in sorted(h)
            if k.startswith("x-amz-"))
        if amz:
            amz += "\n"
        resource = urllib.parse.quote(path, safe="/~._-")
        parts = [(f"{k}={query[k]}" if query[k] else k)
                 for k in V2_SUBRESOURCES if k in query]
        if parts:
            resource += "?" + "&".join(parts)
        return "\n".join([
            method,
            h.get("content-md5", ""),
            h.get("content-type", ""),
            expires_or_date,
            amz,
        ]) + resource

    @staticmethod
    def _sig_v2(secret: str, sts: str) -> str:
        import base64

        return base64.b64encode(hmac.new(
            secret.encode(), sts.encode(), hashlib.sha1).digest()
        ).decode()

    def _verify_header_v2(self, method, path, query, headers,
                          auth) -> Identity:
        """`Authorization: AWS <accessKey>:<base64 hmac-sha1>`."""
        access_key, _, got = auth[len("AWS "):].partition(":")
        identity, secret = self.lookup(access_key)
        h = {k.lower(): v for k, v in headers.items()}
        # the date line is always the Date header; a client's
        # x-amz-date rides the canonicalized amz headers instead
        sts = self._string_to_sign_v2(method, path, query, headers,
                                      h.get("date", ""))
        if not hmac.compare_digest(self._sig_v2(secret, sts), got):
            raise S3AuthError("SignatureDoesNotMatch",
                              "v2 signature mismatch")
        return identity

    def _verify_presigned_v2(self, method, path, query,
                             headers) -> Identity:
        """?AWSAccessKeyId=..&Expires=<unix>&Signature=<b64>."""
        identity, secret = self.lookup(query["AWSAccessKeyId"])
        expires = query.get("Expires", "")
        try:
            if datetime.now(timezone.utc).timestamp() > float(expires):
                raise S3AuthError("AccessDenied",
                                  "presigned V2 request has expired")
        except ValueError:
            raise S3AuthError("AccessDenied", "bad Expires") from None
        sts = self._string_to_sign_v2(method, path, query, headers,
                                      expires)
        if not hmac.compare_digest(self._sig_v2(secret, sts),
                                   query.get("Signature", "")):
            raise S3AuthError("SignatureDoesNotMatch",
                              "v2 signature mismatch")
        return identity

    def _verify_header(self, method, path, query, headers, payload_hash,
                       auth) -> tuple[Identity, "StreamingContext | None"]:
        from .chunked import (STREAMING_SIGNED, STREAMING_UNSIGNED,
                              signing_key)
        fields = {}
        for part in auth[len(ALGORITHM):].strip().split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        cred_parts = fields.get("Credential", "").split("/")
        if len(cred_parts) != 5:
            raise S3AuthError("AuthorizationHeaderMalformed",
                              "bad Credential")
        access_key, datestamp, region, service, _ = cred_parts
        identity, secret = self.lookup(access_key)
        signed_headers = fields.get("SignedHeaders", "").split(";")
        amz_date = headers.get("x-amz-date") or headers.get("X-Amz-Date", "")
        # the declared payload hash must match the actual body, or a
        # captured signature authorizes arbitrary substituted bodies.
        # Streaming uploads declare a sentinel instead: the body is
        # integrity-checked per chunk by the signature chain.
        declared = headers.get(
            "x-amz-content-sha256",
            headers.get("X-Amz-Content-Sha256", payload_hash))
        streaming = declared in (STREAMING_SIGNED, STREAMING_UNSIGNED)
        if not streaming and declared != "UNSIGNED-PAYLOAD" \
                and declared != payload_hash:
            raise S3AuthError("XAmzContentSHA256Mismatch",
                              "payload hash does not match body", 400)
        # SigV4 requires rejecting stale requests or any captured
        # signed request replays forever
        try:
            t = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=timezone.utc)
        except ValueError:
            raise S3AuthError("AccessDenied", "missing/bad x-amz-date")
        skew = abs((datetime.now(timezone.utc) - t).total_seconds())
        if skew > MAX_CLOCK_SKEW_SECONDS:
            raise S3AuthError("RequestTimeTooSkewed",
                              f"request time skewed by {skew:.0f}s")
        payload_hash = declared
        creq = _canonical_request(method, path, query, headers,
                                  signed_headers, payload_hash)
        scope = f"{datestamp}/{region}/{service}/aws4_request"
        expect = _signature(secret, amz_date, scope, creq)
        if not hmac.compare_digest(expect, fields.get("Signature", "")):
            raise S3AuthError("SignatureDoesNotMatch",
                              "signature mismatch")
        ctx = None
        if streaming:
            decoded_len = headers.get(
                "x-amz-decoded-content-length",
                headers.get("X-Amz-Decoded-Content-Length", ""))
            if not decoded_len.isdigit():
                raise S3AuthError("MissingContentLength",
                                  "streaming upload must declare "
                                  "x-amz-decoded-content-length", 411)
            key = signing_key(secret, datestamp, region, service) \
                if declared == STREAMING_SIGNED else None
            ctx = StreamingContext(key, amz_date, scope, expect,
                                   int(decoded_len))
        return identity, ctx

    def _verify_presigned(self, method, path, query, headers) -> Identity:
        q = dict(query)
        sig = q.pop("X-Amz-Signature", "")
        cred_parts = q.get("X-Amz-Credential", "").split("/")
        if len(cred_parts) != 5:
            raise S3AuthError("AuthorizationQueryParametersError",
                              "bad X-Amz-Credential")
        access_key, datestamp, region, service, _ = cred_parts
        identity, secret = self.lookup(access_key)
        amz_date = q.get("X-Amz-Date", "")
        try:
            t = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
                tzinfo=timezone.utc)
            expires = int(q.get("X-Amz-Expires", "900"))
        except ValueError as e:
            raise S3AuthError("AuthorizationQueryParametersError", str(e))
        if datetime.now(timezone.utc) > t + timedelta(seconds=expires):
            raise S3AuthError("AccessDenied", "request has expired")
        signed_headers = q.get("X-Amz-SignedHeaders", "host").split(";")
        creq = _canonical_request(method, path, q, headers,
                                  signed_headers, "UNSIGNED-PAYLOAD")
        scope = f"{datestamp}/{region}/{service}/aws4_request"
        expect = _signature(secret, amz_date, scope, creq)
        if not hmac.compare_digest(expect, sig):
            raise S3AuthError("SignatureDoesNotMatch",
                              "signature mismatch")
        return identity


def _canonical_request(method: str, path: str, query: dict[str, str],
                       headers: dict[str, str],
                       signed_headers: list[str],
                       payload_hash: str) -> str:
    canonical_uri = urllib.parse.quote(path, safe="/-_.~")
    q_items = sorted((urllib.parse.quote(k, safe="-_.~"),
                      urllib.parse.quote(str(v), safe="-_.~"))
                     for k, v in query.items())
    canonical_query = "&".join(f"{k}={v}" for k, v in q_items)
    lower = {k.lower(): " ".join(str(v).split())
             for k, v in headers.items()}
    signed_headers = sorted(h.lower() for h in signed_headers)
    canonical_headers = "".join(
        f"{h}:{lower.get(h, '')}\n" for h in signed_headers)
    return "\n".join([method.upper(), canonical_uri, canonical_query,
                      canonical_headers, ";".join(signed_headers),
                      payload_hash])


def _signature(secret: str, amz_date: str, scope: str, creq: str) -> str:
    from .chunked import signing_key

    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    datestamp, region, service, _ = scope.split("/")
    k = signing_key(secret, datestamp, region, service)
    return hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()


def sign_request(method: str, url: str, access_key: str, secret: str,
                 region: str = "us-east-1",
                 payload: bytes = b"",
                 extra_headers: dict | None = None,
                 content_sha256: str | None = None) -> dict[str, str]:
    """Client-side SigV4 header signing (for tests and the shell's s3
    commands). Returns headers to attach. `content_sha256` overrides
    the payload hash (e.g. the STREAMING-* sentinels)."""
    parsed = urllib.parse.urlsplit(url)
    query = dict(urllib.parse.parse_qsl(parsed.query,
                                        keep_blank_values=True))
    now = datetime.now(timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = content_sha256 or hashlib.sha256(payload).hexdigest()
    headers = {"host": parsed.netloc, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    if extra_headers:
        headers.update({k.lower(): v for k, v in extra_headers.items()})
    signed = sorted(headers)
    creq = _canonical_request(method, parsed.path or "/", query, headers,
                              signed, payload_hash)
    scope = f"{datestamp}/{region}/s3/aws4_request"
    sig = _signature(secret, amz_date, scope, creq)
    headers["Authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


def presign_url(method: str, url: str, access_key: str, secret: str,
                region: str = "us-east-1", expires: int = 900) -> str:
    """Generate a presigned URL (client side)."""
    parsed = urllib.parse.urlsplit(url)
    now = datetime.now(timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    scope = f"{datestamp}/{region}/s3/aws4_request"
    q = dict(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
    q.update({
        "X-Amz-Algorithm": ALGORITHM,
        "X-Amz-Credential": f"{access_key}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    })
    headers = {"host": parsed.netloc}
    creq = _canonical_request(method, parsed.path or "/", q, headers,
                              ["host"], "UNSIGNED-PAYLOAD")
    sig = _signature(secret, amz_date, scope, creq)
    q["X-Amz-Signature"] = sig
    return urllib.parse.urlunsplit(
        (parsed.scheme, parsed.netloc, parsed.path,
         urllib.parse.urlencode(q), ""))
