"""Client-side AWS SigV4 signer for talking TO S3-compatible endpoints
(the server-side verifier lives in s3/auth.py). Used by the S3
replication sink and remote-storage client; compatible with the
gateway's verifier and with AWS.
"""
from __future__ import annotations

import hashlib
import hmac
from datetime import datetime, timezone
from urllib.parse import quote, urlsplit


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_headers(method: str, url: str, access_key: str,
                 secret_key: str, payload: bytes = b"",
                 region: str = "us-east-1",
                 service: str = "s3",
                 unsigned_payload: bool = False) -> dict:
    """-> headers dict carrying a SigV4 Authorization for `url`.

    `unsigned_payload=True` signs with x-amz-content-sha256 =
    UNSIGNED-PAYLOAD (the standard escape hatch for streamed bodies
    whose hash isn't known up front, e.g. tier uploads of multi-GB
    .dat files)."""
    parts = urlsplit(url)
    host = parts.netloc
    path = quote(parts.path or "/", safe="/~._-")
    now = datetime.now(timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = ("UNSIGNED-PAYLOAD" if unsigned_payload
                    else hashlib.sha256(payload).hexdigest())

    # canonical query: sorted key=value with rfc3986 escaping
    q = []
    if parts.query:
        for kv in parts.query.split("&"):
            k, _, v = kv.partition("=")
            q.append((quote(k, safe="~._-"), quote(v, safe="~._-")))
    q.sort()
    canonical_query = "&".join(f"{k}={v}" for k, v in q)

    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n"
                                for k in sorted(headers))
    creq = "\n".join([method.upper(), path, canonical_query,
                      canonical_headers, signed, payload_hash])
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    key = _hmac(_hmac(_hmac(_hmac(
        ("AWS4" + secret_key).encode(), datestamp), region), service),
        "aws4_request")
    signature = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}"),
    }


def verify_policy_signature(policy_b64: str, credential: str,
                            signature: str, secret: str) -> bool:
    """Verify a POST-policy SigV4 signature: the string-to-sign is the
    base64 policy itself, signed with the standard derived key
    (post-policy-fanout of auth_signature_v4.go)."""
    parts = credential.split("/")
    if len(parts) != 5:
        return False
    _ak, datestamp, region, service, terminal = parts
    if terminal != "aws4_request":
        return False
    key = _hmac(_hmac(_hmac(_hmac(
        ("AWS4" + secret).encode(), datestamp), region), service),
        "aws4_request")
    want = hmac.new(key, policy_b64.encode(),
                    hashlib.sha256).hexdigest()
    return hmac.compare_digest(want, signature)


def sign_policy(policy_b64: str, access_key: str, secret: str,
                region: str = "us-east-1",
                datestamp: str | None = None) -> dict:
    """Client side: produce the form fields for a POST-policy upload."""
    import time as _time
    datestamp = datestamp or _time.strftime("%Y%m%d", _time.gmtime())
    credential = f"{access_key}/{datestamp}/{region}/s3/aws4_request"
    key = _hmac(_hmac(_hmac(_hmac(
        ("AWS4" + secret).encode(), datestamp), region), "s3"),
        "aws4_request")
    sig = hmac.new(key, policy_b64.encode(),
                   hashlib.sha256).hexdigest()
    return {"policy": policy_b64,
            "x-amz-credential": credential,
            "x-amz-algorithm": "AWS4-HMAC-SHA256",
            "x-amz-date": f"{datestamp}T000000Z",
            "x-amz-signature": sig}
