"""Native S3 front orchestration (combined `server -s3` mode).

The C++ front (dataplane.cc, ROLE_S3) owns the public S3 port and
serves small-object SigV4 PUT/GET/HEAD natively against the local
volume store; this module is its python control plane:

- the APPLIER thread: receives entry records over a socketpair and
  applies them through the in-process `Filer.create_entry` (parent
  dirs, old-chunk GC, event log — the metadata semantics keep their
  one implementation), then acks so the front can answer the PUT.
- the META listener: registered as a sync listener on the filer's
  event log (called under the mutation lock), it keeps the front's
  read cache and bucket set in exact store order — any mutation path,
  native or python, invalidates or refreshes the cache with a ZERO
  staleness window (read-after-write holds like AWS).
- the REFILL thread: keeps per-bucket pre-assigned fid pools topped up
  from the master (one `?count=N` slot batch per refill) and re-pushes
  the identity table when the IAM config hot-reloads.

Reference equivalents: s3api_object_handlers_put.go (the compiled PUT
path this front mirrors), auth_credentials.go (identity sync),
s3api_bucket_registry (the bucket set).
"""
from __future__ import annotations

import socket
import threading
import time

from ..filer import Entry, FileChunk
from ..utils import faults
from .auth import ACTION_ADMIN, ACTION_READ, ACTION_WRITE

BUCKETS_DIR = "/buckets"
UPLOADS_DIR = ".uploads"
POOL_LOW = 512
POOL_BATCH = 2048
CACHEABLE_MAX = 8 << 20


class NativeS3Front:
    def __init__(self, s3_server, filer, master_url: str,
                 listen_port: int, backend_port: int,
                 listen_ip: str = ""):
        from ..native.dataplane import S3Front

        self.s3 = s3_server  # S3ApiServer (for iam)
        self.filer = filer   # the in-process Filer
        self.master_url = master_url.rstrip("/")
        self.front = S3Front()
        self._stop = threading.Event()
        self._iam_snapshot = None
        self._buckets: set[str] = set()
        # C++ end / python end of the entry channel
        self._chan_c, self._chan_py = socket.socketpair(
            socket.AF_UNIX, socket.SOCK_STREAM)
        self.port = self.front.start(listen_port, backend_port,
                                     self._chan_c.fileno(),
                                     listen_ip=listen_ip)
        # the C side now owns that fd (dp_s3_stop closes it): detach so
        # this object's GC can't double-close a number the OS may have
        # already handed to an unrelated socket
        self._chan_c.detach()
        if faults.enabled():
            # this front's share of -fault.spec (service 's3'), same
            # mirror-at-spawn contract as the volume front
            re_, we, rd, wd = faults.native_params("s3")
            self.front.set_faults(re_, we, rd, wd, seed=faults.seed())
        self._sync_identities()
        self._load_buckets()
        self._load_uploads()
        self.filer.meta_log.sync_listeners.append(self._on_meta_event)
        self._applier = threading.Thread(target=self._applier_loop,
                                         daemon=True,
                                         name="s3front-applier")
        self._applier.start()
        self._refill = threading.Thread(target=self._refill_loop,
                                        daemon=True,
                                        name="s3front-refill")
        self._refill.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.filer.meta_log.sync_listeners.remove(self._on_meta_event)
        except ValueError:
            pass
        try:
            self._chan_py.close()
        except OSError:
            pass
        self.front.stop()  # closes the C side of the channel

    def stats(self) -> dict:
        return self.front.stats()

    # -- identities -----------------------------------------------------
    def _sync_identities(self) -> None:
        """Project the IAM config into the front's flat table: per
        access key, global/bucket-scoped read+write booleans (anything
        richer relays to python per request)."""
        iam = self.s3.iam
        with iam._lock:
            idents = list(iam._identities)
        snapshot = [(i.name, tuple(sorted(i.actions)),
                     tuple(c["accessKey"] for c in i.credentials))
                    for i in idents]
        if snapshot == self._iam_snapshot:
            return
        self._iam_snapshot = snapshot
        rows = []
        for ident in idents:
            flags = ""
            if ACTION_ADMIN in ident.actions:
                flags += "A"
            if ACTION_WRITE in ident.actions:
                flags += "W"
            if ACTION_READ in ident.actions:
                flags += "R"
            wr = ",".join(sorted(
                a.split(":", 1)[1] for a in ident.actions
                if a.startswith(f"{ACTION_WRITE}:")))
            rd = ",".join(sorted(
                a.split(":", 1)[1] for a in ident.actions
                if a.startswith(f"{ACTION_READ}:")))
            for cred in ident.credentials:
                rows.append((cred["accessKey"], cred["secretKey"],
                             flags, wr, rd))
        self.front.set_identities(rows)

    # -- buckets --------------------------------------------------------
    def _load_buckets(self) -> None:
        buckets = set()
        entries = self.filer.list_entries(BUCKETS_DIR, limit=10000)
        for e in entries:
            if e.is_directory:
                buckets.add(e.name)
        self._buckets = buckets
        self.front.set_buckets(sorted(buckets))

    def _load_uploads(self) -> None:
        """Mark multipart uploads already in flight at spawn; the meta
        listener keeps the set exact from here on."""
        for bucket in self._buckets:
            entries = self.filer.list_entries(
                f"{BUCKETS_DIR}/{bucket}/{UPLOADS_DIR}", limit=10000)
            for e in entries:
                if e.is_directory:
                    self.front.upload_mark(bucket, e.name, True)

    # -- meta events (SYNC: under the filer mutation lock) --------------
    def _on_meta_event(self, ev: dict) -> None:
        d = ev["directory"]
        if not (d == BUCKETS_DIR or d.startswith(BUCKETS_DIR + "/")):
            return
        for which in ("old_entry", "new_entry"):
            ent = ev[which]
            if ent is None:
                continue
            full = ent["full_path"]
            rel = full[len(BUCKETS_DIR):]
            if not rel:
                continue
            is_dir = bool(ent.get("mode", 0) & 0o40000)
            if rel.count("/") == 1:  # /bucket — bucket set changes
                name = rel[1:]
                if is_dir:
                    if which == "old_entry" and ev["new_entry"] is None:
                        self._buckets.discard(name)
                        self.front.invalidate(rel + "/", prefix=True)
                    else:
                        self._buckets.add(name)
                    self.front.set_buckets(sorted(self._buckets))
                continue
            # /bucket/.uploads/<id> marker dirs gate the native
            # part-upload path: present from initiate until
            # complete/abort deletes the directory
            segs = rel.split("/")
            if is_dir and len(segs) == 4 and segs[2] == UPLOADS_DIR:
                present = not (which == "old_entry"
                               and ev["new_entry"] is None)
                self.front.upload_mark(segs[1], segs[3], present)
            if which == "old_entry" or ev["new_entry"] is None \
                    or is_dir:
                self.front.invalidate(rel, prefix=is_dir)
                continue
            self._maybe_cache(rel, ent)

    def _maybe_cache(self, s3_path: str, ent: dict) -> None:
        chunks = ent.get("chunks") or []
        if (len(chunks) != 1 or ent.get("hard_link_id")
                or ent.get("symlink_target") or ent.get("ttl_sec")):
            # TTL'd entries never enter the cache: python-side expiry
            # (filer._expire) emits no meta event, so a cached copy
            # would outlive the object
            self.front.invalidate(s3_path)
            return
        ch = chunks[0]
        if (ch.get("offset", 0) != 0 or ch.get("cipher_key")
                or ch.get("is_compressed") or ch.get("is_chunk_manifest")
                or ch.get("size", 0) > CACHEABLE_MAX):
            self.front.invalidate(s3_path)
            return
        etag = ent.get("md5") or ch.get("etag", "")
        meta_lines = []
        for k, v in (ent.get("extended") or {}).items():
            if not k.startswith("s3_meta_"):
                continue
            if not (isinstance(v, str) and v.isascii() and v.isprintable()):
                self.front.invalidate(s3_path)
                return
            meta_lines.append(f"x-amz-meta-{k[8:]}: {v}\r\n")
        try:
            self.front.cache_put(
                s3_path, ch["fid"], ch.get("size", 0), etag,
                ent.get("mime") or "", "".join(meta_lines),
                int(ent.get("mtime", 0)))
        except ValueError:
            self.front.invalidate(s3_path)

    # -- the applier ----------------------------------------------------
    def _applier_loop(self) -> None:
        buf = b""
        sock = self._chan_py
        while not self._stop.is_set():
            try:
                data = sock.recv(1 << 16)
            except OSError:
                break
            if not data:
                break
            buf += data
            acks = []
            store = self.filer.store
            store.begin_batch()  # ONE WAL flush for the whole burst
            try:
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line, buf = buf[:nl], buf[nl + 1:]
                    acks.append(self._apply_one(line))
            finally:
                store.end_batch()  # durable BEFORE any ack goes out
            if acks:
                try:
                    sock.sendall("".join(acks).encode())
                except OSError:
                    break

    def _apply_one(self, line: bytes) -> str:
        # TSV record from the front (see s3_handle_put/_delete/_part):
        #   id \t put \t bucket \t key \t fid \t size \t etag \t mime
        #   [\t k=v]...          |  id \t del \t bucket \t key
        #   |  id \t part \t bucket \t upload_id \t part_number \t fid
        #   \t size \t etag
        rec_id = b"0"
        try:
            cols = line.split(b"\t")
            rec_id = cols[0]
            op = cols[1]
            bucket = cols[2].decode()
            key = cols[3].decode()
            if op == b"part":
                # same entry _upload_part's filer POST would create:
                # part md5 = md5 of the PART bytes (fullmd5), one chunk,
                # never inlined (saveInside=false)
                etag = cols[7].decode()
                path = (f"{BUCKETS_DIR}/{bucket}/{UPLOADS_DIR}/{key}/"
                        f"{int(cols[4]):05d}.part")
                entry = Entry(
                    full_path=path, mime="application/octet-stream",
                    md5=etag, collection=bucket,
                    chunks=[FileChunk(fid=cols[5].decode(), offset=0,
                                      size=int(cols[6]),
                                      mtime_ns=time.time_ns(),
                                      etag=etag)])
                self.filer.create_entry(entry, gc_old_chunks=True)
                return f"{rec_id.decode()} 200\n"
            path = f"{BUCKETS_DIR}/{bucket}/{key}"
            if op == b"del":
                # delete_entry of a missing path is a no-op — S3
                # DeleteObject answers 204 either way
                self.filer.delete_entry(path)
                return f"{rec_id.decode()} 200\n"
            etag = cols[6].decode()
            extended = {}
            for pair in cols[8:]:
                k, _, v = pair.partition(b"=")
                extended[f"s3_meta_{k.decode()}"] = v.decode()
            entry = Entry(
                full_path=path,
                mime=cols[7].decode(), md5=etag, collection=bucket,
                chunks=[FileChunk(fid=cols[4].decode(), offset=0,
                                  size=int(cols[5]),
                                  mtime_ns=time.time_ns(), etag=etag)],
                extended=extended)
            self.filer.create_entry(entry, gc_old_chunks=True)
            return f"{rec_id.decode()} 200\n"
        except Exception:
            try:
                return f"{int(rec_id)} 500\n"
            except ValueError:
                return "0 500\n"

    # -- fid pools + identity refresh -----------------------------------
    def _refill_loop(self) -> None:
        from ..operation import verbs

        while not self._stop.wait(0.1):
            try:
                self._sync_identities()
            except Exception:
                pass
            for bucket in list(self._buckets):
                try:
                    if self.front.pool_level(bucket) >= POOL_LOW:
                        continue
                    a = verbs.assign(self.master_url, count=POOL_BATCH,
                                     collection=bucket)
                    self.front.push_fids(bucket, a.fid, a.count)
                except Exception:
                    pass  # master busy/unreachable: PUTs relay meanwhile
