"""MasterClient: client-side volume-location cache.

Equivalent of /root/reference/weed/wdclient/masterclient.go:20 +
vid_map.go:37 — a vid -> locations map kept fresh by the master's
KeepConnected push stream (here a WebSocket consumed on a background
thread), with HTTP lookup fallback and master failover.
"""
from __future__ import annotations

import asyncio
import json
import threading
import time

import requests
from ..rpc.httpclient import session
from ..utils import retry


def _order_by_breaker(urls: list[str]) -> list[str]:
    """Peers whose circuit breaker is closed/half-open first, open-
    breaker peers last (still listed — when every replica is open the
    caller should try anyway rather than fail without a request).
    Reads breaker state without consuming the half-open probe slot."""
    good, bad = [], []
    for u in urls:
        peer = u.split("//", 1)[-1].split("/", 1)[0]
        (bad if retry.breaker_for(peer).state == retry.OPEN
         else good).append(u)
    return good + bad


class MasterClient:
    def __init__(self, master_urls: list[str] | str,
                 subscribe: bool = False):
        if isinstance(master_urls, str):
            master_urls = [master_urls]
        self.masters = [u.rstrip("/") for u in master_urls]
        self._current = 0
        self._vid_cache: dict[int, list[dict]] = {}
        self._cache_time: dict[int, float] = {}
        # EC per-shard locations: vid -> {shard_id: [urls]}
        # (vid_map.go:169-236 ecVidMap — kept fresh by the same
        # KeepConnected stream, so EC reads never poll the master)
        self._ec_cache: dict[int, dict[int, list[str]]] = {}
        self._ec_cache_time: dict[int, float] = {}
        self._lock = threading.Lock()
        self._ws_thread: threading.Thread | None = None
        self._stop = threading.Event()
        if subscribe:
            self.start_subscription()

    @property
    def master_url(self) -> str:
        return self.masters[self._current]

    def _failover(self) -> None:
        self._current = (self._current + 1) % len(self.masters)

    # -- lookups --------------------------------------------------------
    def lookup(self, vid: int, max_age: float = 600.0) -> list[dict]:
        """-> [{'url':..., 'publicUrl':...}] for a volume id, cached."""
        with self._lock:
            locs = self._vid_cache.get(vid)
            if locs is not None and \
                    time.monotonic() - self._cache_time.get(vid, 0) < max_age:
                return locs
        for _ in range(len(self.masters)):
            try:
                resp = session().get(f"{self.master_url}/dir/lookup",
                                    params={"volumeId": str(vid)},
                                    timeout=10)
                if resp.status_code == 404:
                    return []
                resp.raise_for_status()
                locs = resp.json().get("locations", [])
                with self._lock:
                    self._vid_cache[vid] = locs
                    self._cache_time[vid] = time.monotonic()
                return locs
            except requests.RequestException:
                self._failover()
        return []

    def lookup_file_id(self, fid: str) -> str:
        """fid -> full url (GetLookupFileIdFunction equivalent).
        Replica-aware: a location whose circuit breaker is open is
        skipped while an alternative replica exists."""
        return self.lookup_file_id_urls(fid)[0]

    def lookup_file_id_urls(self, fid: str) -> list[str]:
        """All replica urls for a fid, healthiest (breaker-closed)
        first — callers iterate for failover, or hedge the second."""
        vid = int(fid.split(",")[0])
        locs = self.lookup(vid)
        if not locs:
            raise LookupError(f"volume {vid} has no locations")
        return _order_by_breaker(
            [f"http://{loc['url']}/{fid}" for loc in locs])

    def lookup_file_id_cached(self, fid: str,
                              max_age: float = 600.0) -> str | None:
        """Cache-only probe: the url when the vid is fresh in the map,
        else None — NO network, safe to call on an event loop."""
        vid = int(fid.split(",")[0])
        with self._lock:
            locs = self._vid_cache.get(vid)
            if not locs or time.monotonic() - \
                    self._cache_time.get(vid, 0) >= max_age:
                return None
        return _order_by_breaker(
            [f"http://{loc['url']}/{fid}" for loc in locs])[0]

    def lookup_urls_cached(self, fid: str,
                           max_age: float = 600.0) -> list[str] | None:
        """Cache-only replica list (breaker-healthy first), None on a
        cold/stale vid — NO network, safe on an event loop."""
        vid = int(fid.split(",")[0])
        with self._lock:
            locs = self._vid_cache.get(vid)
            if not locs or time.monotonic() - \
                    self._cache_time.get(vid, 0) >= max_age:
                return None
        return _order_by_breaker(
            [f"http://{loc['url']}/{fid}" for loc in locs])

    def lookup_ec(self, vid: int,
                  max_age: float = 600.0) -> dict[int, list[str]]:
        """-> {shard_id: [urls]} for an EC volume, cached; refreshed by
        the KeepConnected ec_updates stream when subscribed."""
        with self._lock:
            shards = self._ec_cache.get(vid)
            if shards is not None and \
                    time.monotonic() - self._ec_cache_time.get(vid, 0) \
                    < max_age:
                return shards
        for _ in range(len(self.masters)):
            try:
                resp = session().get(f"{self.master_url}/cluster/ec_shards",
                                    params={"volumeId": str(vid)},
                                    timeout=10)
                resp.raise_for_status()
                shards = {int(sid): urls for sid, urls in
                          resp.json().get("shards", {}).items()}
                with self._lock:
                    self._ec_cache[vid] = shards
                    self._ec_cache_time[vid] = time.monotonic()
                return shards
            except requests.RequestException:
                self._failover()
        # master unreachable: a stale map beats no map — the shards
        # themselves are still where they were for almost all reads
        with self._lock:
            return self._ec_cache.get(vid, {})

    def invalidate(self, vid: int) -> None:
        with self._lock:
            self._vid_cache.pop(vid, None)
            self._cache_time.pop(vid, None)
            self._ec_cache.pop(vid, None)
            self._ec_cache_time.pop(vid, None)

    # -- KeepConnected subscription -------------------------------------
    def start_subscription(self) -> None:
        if self._ws_thread is not None:
            return
        self._ws_thread = threading.Thread(target=self._ws_loop, daemon=True)
        self._ws_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _ws_loop(self) -> None:
        asyncio.run(self._ws_main())

    async def _ws_main(self) -> None:
        import aiohttp

        while not self._stop.is_set():
            url = self.master_url.replace("http", "ws", 1) + \
                "/ws/keepconnected"
            try:
                got_data = redirected = False
                async with aiohttp.ClientSession() as sess:
                    async with sess.ws_connect(url, heartbeat=30) as ws:
                        async for msg in ws:
                            if msg.type != aiohttp.WSMsgType.TEXT:
                                break
                            d = json.loads(msg.data)
                            if "leader" in d:
                                # follower refusing the stream; follow
                                # its leader hint (masterclient.go:172)
                                self._follow_leader(d["leader"])
                                redirected = True
                                break
                            got_data = True
                            self._apply(d)
                            if self._stop.is_set():
                                break
                # graceful close: rotate masters unless this stream
                # served us or named the leader, and never hot-spin
                if not got_data and not redirected:
                    self._failover()
                await asyncio.sleep(0.2 if (got_data or redirected) else 1)
            except Exception:
                self._failover()
                await asyncio.sleep(1)

    def _follow_leader(self, leader: str) -> None:
        if not leader:
            return
        url = leader if leader.startswith("http") else f"http://{leader}"
        if url in self.masters:
            self._current = self.masters.index(url)
        else:
            self.masters.append(url)
            self._current = len(self.masters) - 1

    def _apply(self, msg: dict) -> None:
        now = time.monotonic()
        with self._lock:
            if "snapshot" in msg:
                self._vid_cache = {
                    int(vid): locs for vid, locs in msg["snapshot"].items()}
                self._cache_time = {v: now for v in self._vid_cache}
            for vid, locs in msg.get("updates", {}).items():
                self._vid_cache[int(vid)] = locs
                self._cache_time[int(vid)] = now
            if "ec_snapshot" in msg:
                self._ec_cache = {
                    int(vid): {int(s): urls for s, urls in shards.items()}
                    for vid, shards in msg["ec_snapshot"].items()}
                self._ec_cache_time = {v: now for v in self._ec_cache}
            for vid, shards in msg.get("ec_updates", {}).items():
                self._ec_cache[int(vid)] = {
                    int(s): urls for s, urls in shards.items()}
                self._ec_cache_time[int(vid)] = now
