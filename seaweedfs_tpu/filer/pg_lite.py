"""Minimal PostgreSQL frontend/backend protocol v3 client (stdlib only).

Implemented from the public protocol docs for the postgres filer store
— wire protocol #6 in this tree; the reference reaches postgres
through lib/pq (/root/reference/weed/filer/postgres/postgres_store.go:14).

Scope: StartupMessage, cleartext (AuthenticationCleartextPassword) and
md5 (AuthenticationMD5Password) auth, simple Query protocol
('Q' -> 'T'/'D'/'C'/'E'/'Z'), client-side literal interpolation with
standard_conforming_strings quoting, bytea as hex literals with an
explicit ::bytea cast, and bytea (oid 17) result decoding.

Exposes the same DB-API-ish surface as mysql_lite (cursor / execute /
fetchall / description / commit) for AbstractSqlStore.
"""
from __future__ import annotations

import hashlib
import socket
import struct

BYTEA_OID = 17


class PgError(IOError):
    def __init__(self, fields: dict[str, str]):
        self.fields = fields
        super().__init__(
            f"postgres error {fields.get('C', '?')}: "
            f"{fields.get('M', '')}")


def escape_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return "'\\x" + bytes(v).hex() + "'::bytea"
    if isinstance(v, str):
        # standard_conforming_strings: only '' needs doubling, but a
        # backslash-free guarantee is worth keeping explicit — E''
        # syntax is deliberately NOT used
        return "'" + v.replace("'", "''") + "'"
    raise TypeError(f"unsupported SQL value type {type(v)}")


class Cursor:
    def __init__(self, conn: "PgConnection"):
        self._conn = conn
        self.description = None
        self._rows: list = []

    def execute(self, sql: str, args: tuple = ()) -> None:
        if args:
            sql = sql % tuple(escape_literal(a) for a in args)
        cols, rows = self._conn.query(sql)
        self.description = [(c, None, None, None, None, None, None)
                            for c, _oid in cols] if cols else None
        self._rows = rows

    def fetchall(self) -> list:
        return self._rows

    def close(self) -> None:
        pass


class PgConnection:
    def __init__(self, host: str, port: int = 5432,
                 user: str = "postgres", password: str = "",
                 database: str = "", timeout: float = 30.0):
        self._sock = socket.create_connection((host, int(port)), timeout)
        self._startup(user, password, database or user)

    # -- framing --------------------------------------------------------
    def _send_msg(self, kind: bytes, payload: bytes) -> None:
        self._sock.sendall(kind + struct.pack(">I", len(payload) + 4) +
                           payload)

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            piece = self._sock.recv(n - len(out))
            if not piece:
                raise IOError("postgres connection closed")
            out += piece
        return out

    def _recv_msg(self) -> tuple[bytes, bytes]:
        kind = self._recv_exact(1)
        (length,) = struct.unpack(">I", self._recv_exact(4))
        return kind, self._recv_exact(length - 4)

    @staticmethod
    def _error(payload: bytes) -> PgError:
        fields: dict[str, str] = {}
        at = 0
        while at < len(payload) and payload[at] != 0:
            code = chr(payload[at])
            end = payload.index(b"\x00", at + 1)
            fields[code] = payload[at + 1:end].decode()
            at = end + 1
        return PgError(fields)

    # -- handshake ------------------------------------------------------
    def _startup(self, user: str, password: str, database: str) -> None:
        params = (b"user\x00" + user.encode() + b"\x00" +
                  b"database\x00" + database.encode() + b"\x00\x00")
        payload = struct.pack(">I", 196608) + params
        self._sock.sendall(struct.pack(">I", len(payload) + 4) + payload)
        while True:
            kind, body = self._recv_msg()
            if kind == b"E":
                raise self._error(body)
            if kind == b"R":
                (auth,) = struct.unpack_from(">I", body)
                if auth == 0:
                    continue  # AuthenticationOk
                if auth == 3:  # cleartext
                    self._send_msg(b"p", password.encode() + b"\x00")
                elif auth == 5:  # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        password.encode() + user.encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send_msg(b"p", b"md5" + outer.encode() +
                                   b"\x00")
                else:
                    raise IOError(
                        f"unsupported postgres auth method {auth}")
            elif kind == b"Z":  # ReadyForQuery
                return
            # 'S' ParameterStatus / 'K' BackendKeyData: ignored

    # -- simple query protocol ------------------------------------------
    def query(self, sql: str) -> tuple[list, list]:
        """-> ([(name, type oid)...], rows). Text results arrive as
        bytes; bytea columns are hex-decoded to real bytes."""
        self._send_msg(b"Q", sql.encode() + b"\x00")
        cols: list[tuple[str, int]] = []
        rows: list[list] = []
        err: PgError | None = None
        while True:
            kind, body = self._recv_msg()
            if kind == b"T":
                (n,) = struct.unpack_from(">H", body)
                at = 2
                for _ in range(n):
                    end = body.index(b"\x00", at)
                    name = body[at:end].decode()
                    at = end + 1
                    _table, _attr, oid, _len, _mod, _fmt = \
                        struct.unpack_from(">IHIhiH", body, at)
                    at += 18
                    cols.append((name, oid))
            elif kind == b"D":
                (n,) = struct.unpack_from(">H", body)
                at = 2
                row: list = []
                for i in range(n):
                    (ln,) = struct.unpack_from(">i", body, at)
                    at += 4
                    if ln < 0:
                        row.append(None)
                        continue
                    val = body[at:at + ln]
                    at += ln
                    if i < len(cols) and cols[i][1] == BYTEA_OID and \
                            val[:2] == b"\\x":
                        val = bytes.fromhex(val[2:].decode())
                    row.append(val)
                rows.append(row)
            elif kind == b"E":
                err = self._error(body)
            elif kind == b"Z":
                if err is not None:
                    raise err
                return cols, rows
            # 'C' CommandComplete / 'N' notices: ignored

    # -- DB-API surface -------------------------------------------------
    def cursor(self) -> Cursor:
        return Cursor(self)

    def commit(self) -> None:
        pass  # simple-query protocol autocommits single statements

    def close(self) -> None:
        try:
            self._send_msg(b"X", b"")  # Terminate
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
