"""RocksDB filer store via the stable C API (ctypes on librocksdb).

The reference gates its rocksdb store behind a build tag + cgo
(/root/reference/weed/filer/rocksdb/rocksdb_store.go:47, tag
`rocksdb`); the analogue here is runtime gating: when librocksdb.so is
on the loader path this store activates, otherwise constructing it
raises ImportError exactly like the reference binary built without the
tag. The always-available embedded-KV slot is weedkv.py — this
build's own LSM (memtable + SSTables + compaction), which the
leveldb/rocksdb rows redesign into one in-tree engine.

Key layout matches the etcd store (one lexicographic keyspace):
  E<dir>\\x00<name> -> entry JSON     K<key> -> kv side-channel
RocksDB iterators give the prefix scans listings need.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import json

from .entry import Entry
from .filerstore import (FilerStore, _list_filter, _norm, _split,
                         register_store)

SEP = b"\x00"


def _load_librocksdb():
    name = ctypes.util.find_library("rocksdb")
    if not name:
        raise ImportError(
            "filer store 'rocksdb' needs librocksdb.so on this host "
            "(the reference gates the same store behind its `rocksdb` "
            "build tag); the always-available embedded store here is "
            "-store=leveldb (weedkv LSM)")
    lib = ctypes.CDLL(name)
    c = ctypes.c_char_p
    p = ctypes.POINTER(c)
    sz = ctypes.c_size_t
    szp = ctypes.POINTER(sz)
    v = ctypes.c_void_p
    sigs = {
        "rocksdb_options_create": ([], v),
        "rocksdb_options_set_create_if_missing": ([v, ctypes.c_ubyte],
                                                  None),
        "rocksdb_open": ([v, c, p], v),
        "rocksdb_close": ([v], None),
        "rocksdb_writeoptions_create": ([], v),
        "rocksdb_readoptions_create": ([], v),
        "rocksdb_put": ([v, v, c, sz, c, sz, p], None),
        "rocksdb_get": ([v, v, c, sz, szp, p], v),
        "rocksdb_delete": ([v, v, c, sz, p], None),
        "rocksdb_create_iterator": ([v, v], v),
        "rocksdb_iter_destroy": ([v], None),
        "rocksdb_iter_seek": ([v, c, sz], None),
        "rocksdb_iter_next": ([v], None),
        "rocksdb_iter_valid": ([v], ctypes.c_ubyte),
        "rocksdb_iter_key": ([v, szp], v),
        "rocksdb_iter_value": ([v, szp], v),
        "rocksdb_free": ([v], None),
    }
    for fname, (argtypes, restype) in sigs.items():
        fn = getattr(lib, fname)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


@register_store("rocksdb")
class RocksdbStore(FilerStore):
    """`-store=rocksdb -store.path=/data/filer.rdb` (needs
    librocksdb)."""

    def __init__(self, path: str = "filer.rdb", **_):
        self.lib = _load_librocksdb()
        opts = self.lib.rocksdb_options_create()
        self.lib.rocksdb_options_set_create_if_missing(opts, 1)
        err = ctypes.c_char_p()
        self.db = self.lib.rocksdb_open(opts, path.encode(),
                                        ctypes.byref(err))
        if err.value:
            raise IOError(f"rocksdb open {path}: "
                          f"{err.value.decode('utf-8', 'replace')}")
        self.wo = self.lib.rocksdb_writeoptions_create()
        self.ro = self.lib.rocksdb_readoptions_create()

    # -- raw kv ---------------------------------------------------------
    def _check(self, err: ctypes.c_char_p, op: str) -> None:
        if err.value:
            msg = err.value.decode("utf-8", "replace")
            self.lib.rocksdb_free(
                ctypes.cast(err, ctypes.c_void_p))
            raise IOError(f"rocksdb {op}: {msg}")

    def _put(self, key: bytes, value: bytes) -> None:
        err = ctypes.c_char_p()
        self.lib.rocksdb_put(self.db, self.wo, key, len(key),
                             value, len(value), ctypes.byref(err))
        self._check(err, "put")

    def _get(self, key: bytes) -> bytes | None:
        err = ctypes.c_char_p()
        vlen = ctypes.c_size_t()
        ptr = self.lib.rocksdb_get(self.db, self.ro, key, len(key),
                                   ctypes.byref(vlen),
                                   ctypes.byref(err))
        self._check(err, "get")
        if not ptr:
            return None
        out = ctypes.string_at(ptr, vlen.value)
        self.lib.rocksdb_free(ptr)
        return out

    def _delete(self, key: bytes) -> None:
        err = ctypes.c_char_p()
        self.lib.rocksdb_delete(self.db, self.wo, key, len(key),
                                ctypes.byref(err))
        self._check(err, "delete")

    def _scan(self, prefix: bytes, start: bytes):
        """Yield (key, value) for keys >= start with `prefix`."""
        it = self.lib.rocksdb_create_iterator(self.db, self.ro)
        try:
            self.lib.rocksdb_iter_seek(it, start, len(start))
            while self.lib.rocksdb_iter_valid(it):
                klen = ctypes.c_size_t()
                kptr = self.lib.rocksdb_iter_key(it,
                                                 ctypes.byref(klen))
                key = ctypes.string_at(kptr, klen.value)
                if not key.startswith(prefix):
                    return
                vlen = ctypes.c_size_t()
                vptr = self.lib.rocksdb_iter_value(
                    it, ctypes.byref(vlen))
                yield key, ctypes.string_at(vptr, vlen.value)
                self.lib.rocksdb_iter_next(it)
        finally:
            self.lib.rocksdb_iter_destroy(it)

    # -- entries --------------------------------------------------------
    @staticmethod
    def _entry_key(dirpath: str, name: str) -> bytes:
        return b"E" + _norm(dirpath).encode() + SEP + name.encode()

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self._put(self._entry_key(d, n),
                  json.dumps(entry.to_dict()).encode())

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        raw = self._get(self._entry_key(d, n))
        if raw is None:
            return None
        return Entry.from_dict(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        if n:
            self._delete(self._entry_key(d, n))

    def delete_folder_children(self, path: str) -> None:
        norm = _norm(path)
        prefixes = [b"E/"] if norm == "/" else [
            b"E" + norm.encode() + SEP,  # direct children
            b"E" + norm.encode() + b"/",  # nested directories
        ]
        for pfx in prefixes:
            doomed = [k for k, _ in self._scan(pfx, pfx)]
            for k in doomed:
                self._delete(k)

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        base = b"E" + dirpath.encode() + SEP
        first = prefix or start_from or ""
        if prefix and start_from and start_from > prefix:
            first = start_from
        out: list[Entry] = []
        for key, val in self._scan(base, base + first.encode()):
            name = key[len(base):].decode("utf-8", "replace")
            verdict = _list_filter(name, prefix, start_from, inclusive)
            if verdict == "stop":
                break
            if verdict == "skip":
                continue
            out.append(Entry.from_dict(json.loads(val)))
            if len(out) >= limit:
                break
        return out

    # -- kv side-channel ------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self._put(b"K" + key.encode(), value)

    def kv_get(self, key: str) -> bytes | None:
        return self._get(b"K" + key.encode())

    def kv_delete(self, key: str) -> None:
        self._delete(b"K" + key.encode())

    def close(self) -> None:
        if getattr(self, "db", None):
            self.lib.rocksdb_close(self.db)
            self.db = None
