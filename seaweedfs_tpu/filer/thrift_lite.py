"""Minimal Apache Thrift client: binary protocol, buffered or framed
transport, generic value model — enough to speak the HBase Thrift1
gateway (mutateRow / getRowWithColumns / scannerOpenWithScan / ...).

The reference's hbase store rides the gohbase native RPC
(/root/reference/weed/filer/hbase/hbase_store.go:39); every HBase
deployment also ships the Thrift gateway (port 9090), which is the
protocol class this tree had not written yet — implemented here from
the Thrift wire spec, zero SDK, same in-tree-protocol approach as
cql_lite / mysql_lite / kafka_lite.

Wire format (TBinaryProtocol, strict):
  message: i32 (0x80010000 | type)  string name  i32 seqid  <struct>
  struct:  fields (i8 type, i16 id, value...) terminated by STOP (0)
  types:   BOOL=2 BYTE=3 DOUBLE=4 I16=6 I32=8 I64=10 STRING=11
           STRUCT=12 MAP=13 SET=14 LIST=15
Values decode into a generic model: structs -> {field_id: value},
maps -> dict, lists/sets -> list, strings -> bytes.
"""
from __future__ import annotations

import socket
import struct
import threading

STOP, BOOL, BYTE, DOUBLE = 0, 2, 3, 4
I16, I32, I64, STRING, STRUCT, MAP, SET, LIST = 6, 8, 10, 11, 12, 13, 14, 15
MSG_CALL, MSG_REPLY, MSG_EXCEPTION = 1, 2, 3
VERSION_1 = 0x80010000


class Writer:
    """Append-only binary-protocol encoder."""

    def __init__(self):
        self.buf = bytearray()

    def message(self, name: str, seqid: int,
                mtype: int = MSG_CALL) -> "Writer":
        self.i32(VERSION_1 | mtype)
        self.string(name.encode())
        self.i32(seqid)
        return self

    def field(self, ftype: int, fid: int) -> "Writer":
        self.buf.append(ftype)
        self.buf += struct.pack(">h", fid)
        return self

    def stop(self) -> "Writer":
        self.buf.append(STOP)
        return self

    def bool_(self, v: bool) -> "Writer":
        self.buf.append(1 if v else 0)
        return self

    def i16(self, v: int) -> "Writer":
        self.buf += struct.pack(">h", v)
        return self

    def i32(self, v: int) -> "Writer":
        # wrap to signed: the message-version word is 0x8001xxxx
        self.buf += struct.pack(
            ">i", ((v + 0x80000000) & 0xFFFFFFFF) - 0x80000000)
        return self

    def i64(self, v: int) -> "Writer":
        self.buf += struct.pack(">q", v)
        return self

    def string(self, v: bytes) -> "Writer":
        self.buf += struct.pack(">i", len(v))
        self.buf += v
        return self

    def list_header(self, etype: int, n: int) -> "Writer":
        self.buf.append(etype)
        self.buf += struct.pack(">i", n)
        return self

    def map_header(self, ktype: int, vtype: int, n: int) -> "Writer":
        self.buf.append(ktype)
        self.buf.append(vtype)
        self.buf += struct.pack(">i", n)
        return self


class Truncated(IOError):
    """Message ends mid-value — the unframed transport reads more
    bytes on this, and ONLY this (structural corruption must not be
    mistaken for 'need more': that recv loop would never end)."""


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise Truncated("thrift: truncated message")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> bytes:
        n = self.i32()
        if n < 0:
            raise IOError("thrift: negative string length")
        return self._take(n)

    def value(self, ftype: int):
        if ftype == BOOL:
            return self.u8() != 0
        if ftype == BYTE:
            return self.u8()
        if ftype == DOUBLE:
            return struct.unpack(">d", self._take(8))[0]
        if ftype == I16:
            return self.i16()
        if ftype == I32:
            return self.i32()
        if ftype == I64:
            return self.i64()
        if ftype == STRING:
            return self.string()
        if ftype == STRUCT:
            return self.struct()
        if ftype == MAP:
            kt, vt = self.u8(), self.u8()
            n = self.i32()
            return {self._hashable(self.value(kt)): self.value(vt)
                    for _ in range(n)}
        if ftype in (SET, LIST):
            et = self.u8()
            n = self.i32()
            return [self.value(et) for _ in range(n)]
        raise IOError(f"thrift: unknown type {ftype}")

    @staticmethod
    def _hashable(v):
        return bytes(v) if isinstance(v, (bytearray, memoryview)) else v

    def struct(self) -> dict[int, object]:
        out: dict[int, object] = {}
        while True:
            ftype = self.u8()
            if ftype == STOP:
                return out
            fid = self.i16()
            out[fid] = self.value(ftype)


class ThriftError(IOError):
    """Server-side thrift exception (IOError / IllegalArgument /
    TApplicationException), surfaced with its message string."""


class ThriftClient:
    """One connection, binary protocol, thread-safe via a call lock
    (the filer store contract serializes per-call anyway). Reconnects
    on socket failure; the caller retries idempotent ops."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9090,
                 framed: bool = False, timeout: float = 30.0):
        self.host, self.port = host, int(port)
        self.framed = framed
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._seq = 0
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _recv_exactly(self, s: socket.socket, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            got = s.recv(n - len(out))
            if not got:
                raise IOError("thrift: connection closed")
            out += got
        return bytes(out)

    def call(self, name: str, build_args) -> object:
        """Invoke `name`; `build_args(w)` writes the argument struct
        fields (without the trailing stop). Returns the success value
        (field 0 of the reply struct; None for void). Raises
        ThriftError on declared exceptions, IOError on transport
        failure after one reconnect retry."""
        with self._lock:
            last: Exception | None = None
            for attempt in (0, 1):
                self._seq += 1
                w = Writer().message(name, self._seq)
                build_args(w)
                w.stop()
                payload = bytes(w.buf)
                if self.framed:
                    payload = struct.pack(">i", len(payload)) + payload
                try:
                    s = self._connect()
                    s.sendall(payload)
                    raw = self._read_reply(s)
                except (OSError, IOError) as e:
                    self._close_locked()  # _lock is already held here
                    last = e
                    continue
                return self._parse_reply(name, raw)
            raise IOError(f"thrift call {name}: {last}")

    def _read_reply(self, s: socket.socket) -> bytes:
        if self.framed:
            n = struct.unpack(">i", self._recv_exactly(s, 4))[0]
            if n < 0 or n > (64 << 20):
                raise IOError("thrift: bad frame length")
            return self._recv_exactly(s, n)
        # unframed (TBufferedTransport): the message has no length
        # prefix, so parse incrementally from a growing buffer until a
        # complete header+struct decodes. Only Truncated means "need
        # more bytes"; any other parse error is a non-Thrift peer and
        # fails immediately instead of recv-looping forever
        buf = bytearray(self._recv_exactly(s, 4))
        while True:
            try:
                r = Reader(bytes(buf))
                r.i32()      # version | message type
                r.string()   # method name
                r.i32()      # seqid
                r.struct()   # reply struct
                return bytes(buf[:r.pos])
            except Truncated:
                if len(buf) > (64 << 20):
                    raise IOError("thrift: reply exceeds 64MB")
                got = s.recv(64 << 10)
                if not got:
                    raise IOError("thrift: connection closed mid-reply")
                buf += got

    def _parse_reply(self, name: str, raw: bytes) -> object:
        r = Reader(raw)
        ver = r.i32()
        mtype = ver & 0xFF
        rname = r.string().decode("utf-8", "replace")
        r.i32()  # seqid
        if mtype == MSG_EXCEPTION:
            exc = r.struct()
            raise ThriftError(
                f"{name}: {exc.get(1, b'').decode('utf-8', 'replace')!s}")
        if rname != name:
            raise IOError(f"thrift: reply for {rname!r}, wanted {name!r}")
        result = r.struct()
        for fid, val in result.items():
            if fid != 0:
                msg = val.get(1, b"") if isinstance(val, dict) else val
                if isinstance(msg, (bytes, bytearray)):
                    msg = msg.decode("utf-8", "replace")
                raise ThriftError(f"{name}: {msg}")
        return result.get(0)
