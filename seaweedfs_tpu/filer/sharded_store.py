"""Sharded filer store: one namespace partitioned across N child stores.

The reference solves metadata growth with FilerStore *multiplicity* —
path-specific stores layered over the default one (weed/filer/
filerstore_wrapper.go pathSpecific + filer.conf). This module is the
same idea turned into a first-class composite: `make_store("sharded",
shards=N, child="leveldb", path=DIR)` routes every entry to one of N
independent child engines, each in its own directory, so LSM memtable
flushes and compactions parallelize and one hot bucket's churn can't
stall reads against the rest of the namespace (BENCH_GATEWAY.json
measured the grown single store paying ~2x with p99 ~114 ms).

Routing — bucket/first-segment with a consistent-hash ring:
- `/buckets/<bucket>/**` routes by `buckets/<bucket>`: every S3 bucket
  gets its own shard assignment, the reference's per-bucket store
  split.
- everything else routes by its first path segment (`/x/**` -> "x"),
  the flat-namespace fallback, so a directory and its whole subtree
  stay on ONE shard and directory listings below the top level hit
  exactly one child.
- route keys map to shards through a consistent-hash ring (md5-based,
  stable across processes — python's builtin hash is salted) with
  virtual nodes, so growing the shard count moves ~1/N of the keys.

Only the two fan-out directories — "/" and "/buckets", whose children
own their routing keys — list across shards; those listings k-way
merge the per-shard sorted pages, preserving byte-identical order and
pagination seams with a single store (the contract the property test
pins). kv records route by key hash; begin/end_batch fan out so the
native S3 applier's group-commit window covers every shard it touched.
"""
from __future__ import annotations

import bisect
import hashlib
import heapq
import os

from .entry import Entry
from .filerstore import FilerStore, _norm, _split, make_store, register_store

BUCKETS_SEG = "buckets"
_VNODES = 64  # ring points per shard: smooths the key distribution


def _stable_hash(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


class _HashRing:
    """Consistent-hash ring over shard indices (stable, md5-based)."""

    def __init__(self, n_shards: int):
        points = []
        for shard in range(n_shards):
            for v in range(_VNODES):
                points.append((_stable_hash(f"shard-{shard}-{v}"), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._shards = [s for _, s in points]

    def shard_for(self, key: str) -> int:
        i = bisect.bisect(self._hashes, _stable_hash(key))
        if i == len(self._hashes):
            i = 0
        return self._shards[i]


def _route_key(path: str) -> str:
    """Routing key of an entry at `path`: 'buckets/<bucket>' under
    /buckets, else the first segment. '' for '/' itself."""
    segs = path.strip("/").split("/")
    if not segs or not segs[0]:
        return ""
    if segs[0] == BUCKETS_SEG and len(segs) >= 2:
        return f"{BUCKETS_SEG}/{segs[1]}"
    return segs[0]


@register_store("sharded")
class ShardedStore(FilerStore):
    """Composite store: `shards` child stores of kind `child`, each in
    its own subdirectory of `path` (so leveldb children compact
    independently). Extra child constructor kwargs ride in
    `child_options`."""

    def __init__(self, path: str = "filerdb", shards: int = 4,
                 child: str = "leveldb",
                 child_options: dict | None = None, **_):
        if shards < 2:
            raise ValueError(f"sharded store needs >= 2 shards, "
                             f"got {shards}")
        self.shards = int(shards)
        self.child_kind = child
        self.path = path
        self._ring = _HashRing(self.shards)
        opts = dict(child_options or {})
        self.children: list[FilerStore] = []
        if child not in ("memory",):
            os.makedirs(path, exist_ok=True)
        for i in range(self.shards):
            self.children.append(make_store(
                child, path=os.path.join(path, f"shard-{i:02d}"), **opts))

    # -- routing --------------------------------------------------------
    def _shard_of(self, path: str) -> FilerStore:
        return self.children[self._ring.shard_for(_route_key(path))]

    def _dir_fans_out(self, dirpath: str) -> bool:
        """True when `dirpath`'s children own their routing keys (so a
        listing spans shards): the root and /buckets."""
        return dirpath == "/" or dirpath == "/" + BUCKETS_SEG

    # -- entry CRUD -----------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        self._shard_of(entry.full_path).insert_entry(entry)

    def insert_entry_encoded(self, entry: Entry, entry_dict: dict) -> None:
        # the filer's hot-path primitive: route it, don't flatten it
        self._shard_of(entry.full_path).insert_entry_encoded(
            entry, entry_dict)

    def update_entry(self, entry: Entry) -> None:
        self._shard_of(entry.full_path).update_entry(entry)

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        return self._shard_of(path).find_entry(path)

    def delete_entry(self, path: str) -> None:
        self._shard_of(path).delete_entry(path)

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        if self._dir_fans_out(path):
            # children own their routing keys: the subtree spans shards
            for c in self.children:
                c.delete_folder_children(path)
        else:
            # the whole subtree shares `path`'s routing key
            self._shard_of(path).delete_folder_children(path)

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        if not self._dir_fans_out(dirpath):
            # a non-fan-out directory's children all share one routing
            # key — that of any child path under it
            child_path = dirpath.rstrip("/") + "/x"
            return self._shard_of(child_path).list_directory_entries(
                dirpath, start_from, inclusive, limit, prefix)
        # fan-out directory: k-way merge the per-shard sorted pages.
        # Each shard applies start_from/prefix/limit itself; merging by
        # name and truncating reproduces the single-store page exactly.
        pages = [c.list_directory_entries(dirpath, start_from,
                                          inclusive, limit, prefix)
                 for c in self.children]
        merged = heapq.merge(*pages, key=lambda e: e.name)
        if limit:
            out = []
            for e in merged:
                out.append(e)
                if len(out) >= limit:
                    break
            return out
        return list(merged)

    # -- kv side-channel ------------------------------------------------
    def _kv_shard(self, key: str) -> FilerStore:
        return self.children[self._ring.shard_for("kv/" + key)]

    def kv_put(self, key: str, value: bytes) -> None:
        self._kv_shard(key).kv_put(key, value)

    def kv_get(self, key: str) -> bytes | None:
        return self._kv_shard(key).kv_get(key)

    def kv_delete(self, key: str) -> None:
        self._kv_shard(key).kv_delete(key)

    # -- batching / lifecycle -------------------------------------------
    def begin_batch(self) -> None:
        for c in self.children:
            c.begin_batch()

    def end_batch(self) -> None:
        for c in self.children:
            c.end_batch()

    def close(self) -> None:
        for c in self.children:
            c.close()

    # -- observability --------------------------------------------------
    def debug_snapshot(self) -> dict:
        return {
            "kind": "sharded",
            "shards": self.shards,
            "child": self.child_kind,
            "path": self.path,
            "routing": "buckets/<bucket> | first-segment, "
                       f"md5 ring x{_VNODES} vnodes",
            "per_shard": [_child_snapshot(c) for c in self.children],
        }

    def publish_metrics(self) -> None:
        """Refresh per-shard gauges (scraped at /metrics, federated
        into /cluster/metrics). Approximate entry counts: memtable +
        segment index sizes, O(1) per shard."""
        from ..utils import metrics

        for i, c in enumerate(self.children):
            snap = _child_snapshot(c)
            lab = {"shard": f"{i:02d}"}
            if snap.get("entries") is not None:
                metrics.gauge_set("filer_store_shard_entries",
                                  snap["entries"], labels=lab)
            if snap.get("segments") is not None:
                metrics.gauge_set("filer_store_shard_segments",
                                  snap["segments"], labels=lab)


def _child_snapshot(store: FilerStore) -> dict:
    """Best-effort stats for one child store (exact for weedkv)."""
    snap = getattr(store, "debug_snapshot", None)
    if snap is not None:
        return snap()
    db = getattr(store, "db", None)
    if db is not None and hasattr(db, "_segments"):  # weedkv engine
        with db._lock:
            seg_keys = sum(len(s.keys) for s in db._segments)
            disk = 0
            for s in db._segments:
                try:
                    disk += os.path.getsize(s.path)
                except OSError:
                    pass
            return {"kind": store.name,
                    # memtable + segment index sizes: counts tombstones
                    # and shadowed versions until the next compaction
                    "entries": len(db._mem) + seg_keys,
                    "memtable_entries": len(db._mem),
                    "segments": len(db._segments),
                    "compaction_debt_segments": max(
                        0, len(db._segments) - 1),
                    "disk_bytes": disk}
    dirs = getattr(store, "_dirs", None)
    if dirs is not None:  # memory store
        return {"kind": store.name,
                "entries": sum(len(v) for v in dirs.values())}
    return {"kind": store.name, "entries": None}
