"""TiKV filer store over the RawKV gRPC API (utils/grpc_lite.py).

The reference's store (/root/reference/weed/filer/tikv/
tikv_store.go:30-80) rides client-go's transactional KV through PD
region routing; this build speaks TiKV's RawKV service
(tikvpb.Tikv/RawGet|RawPut|RawDelete|RawScan|RawDeleteRange,
kvrpcpb messages) through the in-tree gRPC client — no SDK.

Key layout mirrors the reference (tikv_store.go:373 generateKey):
entries live at sha1(dir) + name so one directory's children form a
contiguous scan range; a 1-byte namespace tag ('m' entries, 'k' kv)
keeps the kv side-channel out of entry scans (the reference splits
namespaces the same way in its kv file).

Deployment note: RawKV addresses a tikv node directly
(`-store.host=<tikv> -store.port=20160`). Multi-region clusters route
via PD, which client-go embeds; that routing layer (the reference's
txnkv client) is PD's job, not a wire protocol, and is out of scope
here — single-node/region TiKV and any RawKV-compatible endpoint work
as-is.
"""
from __future__ import annotations

import hashlib
import json

from ..utils import grpc_lite as g
from .entry import Entry
from .filerstore import (FilerStore, _delete_subtree_by_walk,
                         _list_filter, _norm, _split, register_store)

SVC = "/tikvpb.Tikv"


def _dir_hash(dirpath: str) -> bytes:
    return hashlib.sha1(dirpath.encode()).digest()


def _entry_key(dirpath: str, name: str) -> bytes:
    return b"m" + _dir_hash(dirpath) + name.encode()


def _prefix_end(prefix: bytes) -> bytes:
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] < 0xFF:
            p[i] += 1
            return bytes(p[:i + 1])
    return b""  # unbounded


@register_store("tikv")
class TikvStore(FilerStore):
    """`-store=tikv -store.host=... -store.port=20160`."""

    SCAN_LIMIT = 1024

    def __init__(self, host: str = "127.0.0.1", port: int = 20160,
                 **_):
        self.ch = g.GrpcChannel(host, int(port))
        # fail fast on a wrong endpoint, like the reference's client
        # construction + first region lookup
        self._raw_get(b"k__probe__")

    # -- RawKV verbs -----------------------------------------------------
    # kvrpcpb field numbers (public proto): requests carry context=1;
    # responses region_error=1, error=2.
    def _call(self, method: str, req: bytes,
              err_field: int | None = 2) -> dict[int, list]:
        """region_error is field 1 on every Raw* response; the string
        `error` rides field 2 on get/put/delete/delete-range — but NOT
        on RawScan, where 2 is the kvs list."""
        resp = g.pb_decode(self.ch.unary(f"{SVC}/{method}", req))
        err = g.pb_first(resp, 1)
        if isinstance(err, bytes) and err:
            raise IOError(f"tikv {method} region error: {err[:200]!r}")
        if err_field is not None:
            err = g.pb_first(resp, err_field)
            if isinstance(err, bytes) and err:
                raise IOError(f"tikv {method}: {err[:200]!r}")
        return resp

    def _raw_get(self, key: bytes) -> bytes | None:
        # RawGetRequest {context=1, key=2, cf=3}; resp value=3,
        # not_found=4
        resp = self._call("RawGet", g.pb_bytes(2, key))
        if g.pb_first(resp, 4, 0):
            return None
        # proto3 omits empty bytes: an existing key with value b"" has
        # NEITHER field set — only not_found distinguishes absence
        val = g.pb_first(resp, 3)
        return bytes(val) if val is not None else b""

    def _raw_put(self, key: bytes, value: bytes) -> None:
        # RawPutRequest {context=1, key=2, value=3, cf=4}
        self._call("RawPut", g.pb_bytes(2, key) + g.pb_bytes(3, value))

    def _raw_delete(self, key: bytes) -> None:
        # RawDeleteRequest {context=1, key=2, cf=3}
        self._call("RawDelete", g.pb_bytes(2, key))

    def _raw_delete_range(self, start: bytes, end: bytes) -> None:
        # RawDeleteRangeRequest {context=1, start_key=2, end_key=3}
        self._call("RawDeleteRange",
                   g.pb_bytes(2, start) + g.pb_bytes(3, end))

    def _raw_scan(self, start: bytes, end: bytes,
                  limit: int) -> list[tuple[bytes, bytes]]:
        # RawScanRequest {context=1, start_key=2, limit=3, key_only=4,
        # cf=5, reverse=6, end_key=7}; resp kvs=2 of
        # KvPair {error=1, key=2, value=3}
        req = g.pb_bytes(2, start) + g.pb_uint(3, limit)
        if end:
            req += g.pb_bytes(7, end)
        resp = self._call("RawScan", req, err_field=None)
        out = []
        for raw in resp.get(2, []):
            pair = g.pb_decode(bytes(raw))
            out.append((bytes(g.pb_first(pair, 2, b"")),
                        bytes(g.pb_first(pair, 3, b""))))
        return out

    # -- entries --------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self._raw_put(_entry_key(d, n),
                      json.dumps(entry.to_dict()).encode())

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        raw = self._raw_get(_entry_key(d, n))
        if raw is None:
            return None
        return Entry.from_dict(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        if n:
            self._raw_delete(_entry_key(d, n))

    def delete_folder_children(self, path: str) -> None:
        # directory hashes scatter the keyspace: shared recursive walk,
        # then one contiguous RawDeleteRange per directory
        _delete_subtree_by_walk(self, path)

    def delete_directory_range(self, d: str) -> None:
        base = b"m" + _dir_hash(d)
        self._raw_delete_range(base, _prefix_end(base))

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        base = b"m" + _dir_hash(dirpath)
        first = prefix or start_from or ""
        if prefix and start_from and start_from > prefix:
            first = start_from
        cursor = base + first.encode()
        out: list[Entry] = []
        while len(out) < limit:
            batch = self._raw_scan(cursor, _prefix_end(base),
                                   min(self.SCAN_LIMIT,
                                       limit - len(out) + 1))
            if not batch:
                break
            for key, val in batch:
                name = key[len(base):].decode("utf-8", "replace")
                verdict = _list_filter(name, prefix, start_from,
                                       inclusive)
                if verdict == "stop":
                    return out
                if verdict == "skip":
                    continue
                out.append(Entry.from_dict(json.loads(val)))
                if len(out) >= limit:
                    return out
            if len(batch) < self.SCAN_LIMIT and \
                    len(batch) < limit - len(out) + 1:
                break
            cursor = batch[-1][0] + b"\x00"
        return out

    # -- kv side-channel ------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self._raw_put(b"k" + key.encode(), value)

    def kv_get(self, key: str) -> bytes | None:
        return self._raw_get(b"k" + key.encode())

    def kv_delete(self, key: str) -> None:
        self._raw_delete(b"k" + key.encode())

    def close(self) -> None:
        self.ch.close()
