"""HBase filer store over the HBase Thrift1 gateway (thrift_lite.py).

The reference's store (/root/reference/weed/filer/hbase/
hbase_store.go:20-108, hbase_store_kv.go) rides the gohbase native
RPC; this build speaks the Thrift gateway every HBase ships (default
port 9090, `hbase thrift start`) through the in-tree binary-protocol
client — no SDK.

Same data model as the reference: one table, column family ``meta``
holds entries (row key = full path, qualifier ``a``, value = entry
JSON) and column family ``kv`` holds the kv side-channel under the
same qualifier. Directory listings scan the path keyspace from
``<dir>/<prefix>`` and keep only direct children, exactly like the
reference's ListDirectoryPrefixedEntries scan loop (hbase_store.go:155
checks ``dir != string(dirPath)`` and skips deeper descendants).

`-store=hbase -store.host=... -store.port=9090 -store.table=seaweedfs`
"""
from __future__ import annotations

import json

from .entry import Entry
from .filerstore import (FilerStore, _list_filter, _norm,
                         register_store)
from .thrift_lite import (LIST, MAP, STRING, STRUCT, BOOL, I32,
                          ThriftClient, ThriftError, Writer)

META_COL = b"meta:a"
KV_COL = b"kv:a"
SCAN_BATCH = 256


def _w_attributes(w: Writer, fid: int) -> None:
    """The trailing `map<Text,Text> attributes` every Thrift1 verb
    takes — always empty here."""
    w.field(MAP, fid).map_header(STRING, STRING, 0)


class _Hbase:
    """The handful of Hbase.thrift verbs the store needs."""

    def __init__(self, host: str, port: int, framed: bool,
                 table: str):
        self.c = ThriftClient(host, port, framed=framed)
        self.table = table.encode()

    def create_table_if_missing(self) -> None:
        try:
            self.c.call("createTable", self._create_args)
        except ThriftError as e:
            # AlreadyExists (or a gateway that forbids DDL): the store
            # works as long as the table is there — probe it
            if "exist" not in str(e).lower():
                self.get_row(b"__probe__", META_COL)

    def _create_args(self, w: Writer) -> None:
        w.field(STRING, 1).string(self.table)
        w.field(LIST, 2).list_header(STRUCT, 2)
        for family in (b"meta:", b"kv:"):
            w.field(STRING, 1).string(family)
            w.stop()

    def put(self, row: bytes, column: bytes, value: bytes) -> None:
        def args(w: Writer) -> None:
            w.field(STRING, 1).string(self.table)
            w.field(STRING, 2).string(row)
            w.field(LIST, 3).list_header(STRUCT, 1)
            # Mutation {1: isDelete, 2: column, 3: value, 4: writeToWAL}
            w.field(BOOL, 1).bool_(False)
            w.field(STRING, 2).string(column)
            w.field(STRING, 3).string(value)
            w.field(BOOL, 4).bool_(True)
            w.stop()
            _w_attributes(w, 4)

        self.c.call("mutateRow", args)

    def delete_column(self, row: bytes, column: bytes) -> None:
        def args(w: Writer) -> None:
            w.field(STRING, 1).string(self.table)
            w.field(STRING, 2).string(row)
            w.field(LIST, 3).list_header(STRUCT, 1)
            w.field(BOOL, 1).bool_(True)  # isDelete
            w.field(STRING, 2).string(column)
            w.stop()
            _w_attributes(w, 4)

        self.c.call("mutateRow", args)

    def get_row(self, row: bytes, column: bytes) -> bytes | None:
        def args(w: Writer) -> None:
            w.field(STRING, 1).string(self.table)
            w.field(STRING, 2).string(row)
            w.field(LIST, 3).list_header(STRING, 1).string(column)
            _w_attributes(w, 4)

        rows = self.c.call("getRowWithColumns", args) or []
        for r in rows:
            # TRowResult {1: row, 2: map<Text, TCell{1: value}>}
            cells = r.get(2) or {}
            cell = cells.get(column)
            if cell is not None:
                return bytes(cell.get(1, b""))
        return None

    def scan(self, start_row: bytes, column: bytes):
        """Yield (row, value) from start_row to table end — the caller
        breaks when rows leave its prefix window, mirroring the
        reference's open-ended NewScanRange + prefix check."""
        def open_args(w: Writer) -> None:
            w.field(STRING, 1).string(self.table)
            w.field(STRUCT, 2)  # TScan
            w.field(STRING, 1).string(start_row)
            w.field(LIST, 4).list_header(STRING, 1).string(column)
            w.field(I32, 5).i32(SCAN_BATCH)  # caching
            w.stop()
            _w_attributes(w, 3)

        scanner = self.c.call("scannerOpenWithScan", open_args)
        try:
            while True:
                def get_args(w: Writer, sid=scanner) -> None:
                    w.field(I32, 1).i32(sid)
                    w.field(I32, 2).i32(SCAN_BATCH)

                rows = self.c.call("scannerGetList", get_args) or []
                if not rows:
                    return
                for r in rows:
                    cells = r.get(2) or {}
                    cell = cells.get(column)
                    if cell is not None:
                        yield bytes(r.get(1, b"")), \
                            bytes(cell.get(1, b""))
        finally:
            try:
                self.c.call(
                    "scannerClose",
                    lambda w: w.field(I32, 1).i32(scanner))
            except (IOError, ThriftError):
                pass  # server reaps leaked scanners by lease timeout


@register_store("hbase")
class HbaseStore(FilerStore):
    """`-store=hbase -store.host=... -store.port=9090`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9090,
                 table: str = "seaweedfs", framed: bool = False, **_):
        self.h = _Hbase(host, int(port), framed, table)
        self.h.create_table_if_missing()

    # -- entries --------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        self.h.put(entry.full_path.encode(), META_COL,
                   json.dumps(entry.to_dict()).encode())

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        raw = self.h.get_row(_norm(path).encode(), META_COL)
        if raw is None:
            return None
        return Entry.from_dict(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        self.h.delete_column(_norm(path).encode(), META_COL)

    def delete_folder_children(self, path: str) -> None:
        # path-keyed rows: the subtree is exactly the rows prefixed by
        # "<path>/" (one contiguous scan window; "/t" and "/tother"
        # cannot collide because the separator byte is fixed)
        norm = _norm(path)
        pfx = b"/" if norm == "/" else (norm + "/").encode()
        doomed = []
        for row, _val in self.h.scan(pfx, META_COL):
            if not row.startswith(pfx):
                break
            doomed.append(row)
        for row in doomed:
            self.h.delete_column(row, META_COL)

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        base = (b"/" if dirpath == "/" else (dirpath + "/").encode())
        start = base + (start_from or prefix or "").encode()
        if prefix and start_from and prefix > start_from:
            start = base + prefix.encode()
        out: list[Entry] = []
        for row, val in self.h.scan(start, META_COL):
            if not row.startswith(base):
                break
            name_b = row[len(base):]
            if b"/" in name_b:
                continue  # deeper descendant (hbase_store.go:155)
            name = name_b.decode("utf-8", "replace")
            verdict = _list_filter(name, prefix, start_from, inclusive)
            if verdict == "stop":
                break
            if verdict == "skip":
                continue
            out.append(Entry.from_dict(json.loads(val)))
            if len(out) >= limit:
                break
        return out

    # -- kv side-channel ------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self.h.put(key.encode(), KV_COL, value)

    def kv_get(self, key: str) -> bytes | None:
        return self.h.get_row(key.encode(), KV_COL)

    def kv_delete(self, key: str) -> None:
        self.h.delete_column(key.encode(), KV_COL)

    def close(self) -> None:
        self.h.c.close()
