"""Minimal BSON + MongoDB OP_MSG wire codec (stdlib only).

Implemented from the public BSON spec (bsonspec.org) and the MongoDB
wire-protocol documentation for the mongodb filer store — the same
zero-SDK approach as the redis RESP and etcd gateway stores. Covers the
types the store needs: document, array, utf8 string, binary (subtype
0), int32/int64, double, bool, null.

OP_MSG framing: (messageLength, requestID, responseTo, opCode=2013)
then flagBits:int32 and one section of kind 0 (a single BSON document).
"""
from __future__ import annotations

import itertools
import socket
import struct

OP_MSG = 2013
_req_ids = itertools.count(1)


class MongoError(IOError):
    """Server-side {ok: 0} reply. The connection stays synced (the
    full reply was read) — callers must not treat this as a transport
    failure worth a reconnect."""


class Int64(int):
    """Force int64 encoding: some wire fields (getMore's cursor id)
    must be BSON type long even when the value fits in 31 bits."""


# -- BSON ---------------------------------------------------------------

def _enc_cstring(s: str) -> bytes:
    b = s.encode()
    if b"\x00" in b:
        raise ValueError("BSON keys cannot contain NUL")
    return b + b"\x00"


def _enc_value(key: str, v) -> bytes:
    k = _enc_cstring(key)
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + k + (b"\x01" if v else b"\x00")
    if isinstance(v, Int64):
        return b"\x12" + k + struct.pack("<q", v)
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + k + struct.pack("<i", v)
        return b"\x12" + k + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + k + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + k + struct.pack("<i", len(b) + 1) + b + b"\x00"
    if isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        return b"\x05" + k + struct.pack("<i", len(b)) + b"\x00" + b
    if v is None:
        return b"\x0a" + k
    if isinstance(v, dict):
        return b"\x03" + k + encode_doc(v)
    if isinstance(v, (list, tuple)):
        inner = b"".join(_enc_value(str(i), x) for i, x in enumerate(v))
        return b"\x04" + k + struct.pack(
            "<i", len(inner) + 5) + inner + b"\x00"
    raise TypeError(f"bson_lite cannot encode {type(v)!r}")


def encode_doc(doc: dict) -> bytes:
    body = b"".join(_enc_value(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _dec_value(t: int, buf: bytes, at: int):
    if t == 0x01:
        return struct.unpack_from("<d", buf, at)[0], at + 8
    if t == 0x02:
        n = struct.unpack_from("<i", buf, at)[0]
        return buf[at + 4:at + 3 + n].decode(), at + 4 + n
    if t in (0x03, 0x04):
        n = struct.unpack_from("<i", buf, at)[0]
        inner = decode_doc(buf[at:at + n])
        if t == 0x04:
            inner = [inner[k] for k in sorted(inner, key=int)]
        return inner, at + n
    if t == 0x05:
        n = struct.unpack_from("<i", buf, at)[0]
        return buf[at + 5:at + 5 + n], at + 5 + n
    if t == 0x08:
        return buf[at] != 0, at + 1
    if t == 0x0a:
        return None, at
    if t == 0x10:
        return struct.unpack_from("<i", buf, at)[0], at + 4
    if t == 0x12:
        return struct.unpack_from("<q", buf, at)[0], at + 8
    raise ValueError(f"bson_lite cannot decode type 0x{t:02x}")


def decode_doc(buf: bytes) -> dict:
    out: dict = {}
    at = 4
    while buf[at] != 0:
        t = buf[at]
        end = buf.index(b"\x00", at + 1)
        key = buf[at + 1:end].decode()
        out[key], at = _dec_value(t, buf, end + 1)
    return out


# -- OP_MSG -------------------------------------------------------------

class MongoWire:
    """One mongod connection speaking OP_MSG kind-0 commands."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout)

    def command(self, doc: dict) -> dict:
        payload = b"\x00\x00\x00\x00\x00" + encode_doc(doc)
        rid = next(_req_ids)
        header = struct.pack("<iiii", 16 + len(payload), rid, 0, OP_MSG)
        self._sock.sendall(header + payload)
        raw = self._recv_exact(16)
        length, _reply_id, response_to, _op = struct.unpack_from(
            "<iiii", raw)
        if response_to != rid:
            # a stray frame (e.g. the unread reply left behind by an
            # earlier timeout) must not be attributed to this command;
            # the connection is desynced beyond recovery
            self.close()
            raise IOError(
                f"mongodb reply desync: responseTo {response_to} "
                f"!= requestID {rid}")
        body = self._recv_exact(length - 16)
        # flagBits:4 then kind byte then the reply document
        if body[4] != 0:
            raise IOError("unexpected OP_MSG section kind")
        reply = decode_doc(body[5:])
        if reply.get("ok") != 1:  # 1 == 1.0 covers the double form
            raise MongoError(f"mongodb error: {reply}")
        return reply

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            piece = self._sock.recv(n - len(out))
            if not piece:
                raise IOError("mongodb connection closed")
            out += piece
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
