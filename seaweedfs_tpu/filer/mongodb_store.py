"""MongoDB filer store speaking the real wire protocol (OP_MSG + BSON).

The slot of /root/reference/weed/filer/mongodb/mongodb_store.go, with
the client written in-tree (filer/bson_lite.py) instead of pymongo —
the third fully-implemented external wire protocol after redis RESP
and the etcd v3 gateway.

Layout (mirrors the reference: one collection, entries keyed by path):
  collection "filemeta": {_id: "<dir>\\x7f<name>", dir: "<dir>",
                          name: "<name>", meta: <entry-json bytes>}
  collection "filemeta_kv": {_id: <key>, value: <bytes>}

Directory listing filters on the indexed `dir` field with a `name`
range — no delimiter tricks needed because dir equality can't match
nested paths. 0x7f in _id merely keeps ids readable/unique; listing
never parses it.
"""
from __future__ import annotations

import json
import threading

from .bson_lite import Int64, MongoError, MongoWire
from .entry import Entry
from .filerstore import FilerStore, _norm, _split, register_store

ID_SEP = "\x7f"


@register_store("mongodb")
class MongodbStore(FilerStore):
    """`-store=mongodb -store.host=... -store.port=27017
    -store.database=seaweedfs`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 database: str = "seaweedfs", **_):
        self.db = database
        self._host, self._port = host, int(port)
        self._wire = MongoWire(self._host, self._port)
        self._lock = threading.Lock()  # one socket, serialized cmds
        # fail fast like the reference's initial ping
        self._cmd({"ping": 1})
        # the reference ensures the directory+name index on startup
        # (mongodb_store.go indexUnique); harmless if it exists
        try:
            self._cmd({"createIndexes": "filemeta", "indexes": [
                {"key": {"dir": 1, "name": 1}, "name": "dir_name"}]})
        except IOError:
            pass  # server without createIndexes (e.g. a thin double)

    def _cmd(self, doc: dict) -> dict:
        doc = dict(doc)
        doc["$db"] = self.db
        with self._lock:
            try:
                return self._wire.command(doc)
            except MongoError:
                raise  # server-side error; connection still synced
            except (IOError, OSError):
                # transport failure: the wire closes itself on
                # timeout/desync (an unread reply would be
                # mis-attributed); reconnect so a single slow query
                # doesn't wedge the store forever. Retry only
                # IDEMPOTENT commands — a getMore consumes the cursor
                # server-side, so re-sending it after a lost reply
                # would silently skip a whole batch.
                self._wire.close()
                self._wire = MongoWire(self._host, self._port)
                if "getMore" in doc:
                    raise
                return self._wire.command(doc)

    # -- entries --------------------------------------------------------
    @staticmethod
    def _eid(dirpath: str, name: str) -> str:
        return f"{_norm(dirpath)}{ID_SEP}{name}"

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self._cmd({"update": "filemeta", "updates": [{
            "q": {"_id": self._eid(d, n)},
            "u": {"_id": self._eid(d, n), "dir": _norm(d), "name": n,
                  "meta": json.dumps(entry.to_dict()).encode()},
            "upsert": True}]})

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        got = self._cmd({"find": "filemeta",
                         "filter": {"_id": self._eid(d, n)},
                         "limit": 1})
        batch = got["cursor"]["firstBatch"]
        if not batch:
            return None
        return Entry.from_dict(json.loads(batch[0]["meta"]))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        if not n:
            return
        self._cmd({"delete": "filemeta", "deletes": [
            {"q": {"_id": self._eid(d, n)}, "limit": 1}]})

    def delete_folder_children(self, path: str) -> None:
        norm = _norm(path)
        sub = {"dir": {"$gte": norm + "/",
                       "$lt": norm + "0"}}  # '0' = '/' + 1
        if norm == "/":
            sub = {"dir": {"$gte": "/"}}  # every dir is absolute
        self._cmd({"delete": "filemeta", "deletes": [
            {"q": {"dir": norm}, "limit": 0},
            {"q": sub, "limit": 0}]})

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        name_cond: dict = {}
        if prefix:
            name_cond["$gte"] = prefix
            name_cond["$lt"] = prefix[:-1] + chr(ord(prefix[-1]) + 1)
        if start_from:
            op = "$gte" if inclusive else "$gt"
            # >=, not >: an exclusive start equal to the prefix must
            # REPLACE the $gte bound or the boundary entry repeats on
            # every continuation page
            if "$gte" not in name_cond or \
                    start_from >= name_cond["$gte"]:
                name_cond.pop("$gte", None)
                name_cond[op] = start_from
        filt: dict = {"dir": dirpath}
        if name_cond:
            filt["name"] = name_cond
        out: list[Entry] = []
        got = self._cmd({"find": "filemeta", "filter": filt,
                         "sort": {"name": 1}, "limit": limit,
                         "batchSize": limit})
        cursor = got["cursor"]
        while True:
            for row in cursor.get("firstBatch",
                                  cursor.get("nextBatch", [])):
                name = row["name"]
                if prefix and not name.startswith(prefix):
                    continue
                out.append(Entry.from_dict(json.loads(row["meta"])))
                if len(out) >= limit:
                    break
            if len(out) >= limit or not cursor.get("id"):
                return out
            # a real mongod REQUIRES getMore to be BSON long, even
            # for small ids (wire-typed field, not a plain number)
            got = self._cmd({"getMore": Int64(cursor["id"]),
                             "collection": "filemeta",
                             "batchSize": limit - len(out)})
            cursor = got["cursor"]

    # -- kv side-channel ------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self._cmd({"update": "filemeta_kv", "updates": [{
            "q": {"_id": key}, "u": {"_id": key, "value": value},
            "upsert": True}]})

    def kv_get(self, key: str) -> bytes | None:
        got = self._cmd({"find": "filemeta_kv",
                         "filter": {"_id": key}, "limit": 1})
        batch = got["cursor"]["firstBatch"]
        return bytes(batch[0]["value"]) if batch else None

    def kv_delete(self, key: str) -> None:
        self._cmd({"delete": "filemeta_kv", "deletes": [
            {"q": {"_id": key}, "limit": 1}]})

    def close(self) -> None:
        self._wire.close()
