"""Minimal Cassandra CQL binary protocol v4 client (stdlib only).

Implemented from the public native-protocol spec
(cassandra/doc/native_protocol_v4.spec) for the cassandra filer store —
wire protocol #4 after redis RESP, the etcd v3 gateway, and MongoDB
OP_MSG, and the same zero-SDK approach. Covers what the store needs:
STARTUP (+ PLAIN SASL auth), QUERY/PREPARE/EXECUTE with bound values,
and RESULT rows decoding (void / rows / set_keyspace / prepared /
schema_change kinds).

Frame: version(1) flags(1) stream(i16) opcode(1) length(i32) body.
Requests use version 0x04, responses arrive as 0x84.
"""
from __future__ import annotations

import socket
import struct

# opcodes
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

CONSISTENCY_ONE = 0x0001
CONSISTENCY_LOCAL_QUORUM = 0x0006

RESULT_VOID = 1
RESULT_ROWS = 2
RESULT_SET_KEYSPACE = 3
RESULT_PREPARED = 4
RESULT_SCHEMA_CHANGE = 5


class CqlError(IOError):
    def __init__(self, code: int, message: str):
        super().__init__(f"cql error 0x{code:04x}: {message}")
        self.code = code
        self.message = message


# -- primitive encoders (spec section 3) --------------------------------

def enc_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def enc_long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">i", len(b)) + b


def enc_string_map(m: dict[str, str]) -> bytes:
    out = struct.pack(">H", len(m))
    for k, v in m.items():
        out += enc_string(k) + enc_string(v)
    return out


def enc_bytes(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def enc_value(v) -> bytes:
    """Python value -> [bytes] in the type cassandra expects for the
    bound column: str->utf8, bytes->blob, int->int(4), None->null."""
    if v is None:
        return enc_bytes(None)
    if isinstance(v, bool):
        return enc_bytes(b"\x01" if v else b"\x00")
    if isinstance(v, int):
        return enc_bytes(struct.pack(">i", v))
    if isinstance(v, str):
        return enc_bytes(v.encode())
    if isinstance(v, (bytes, bytearray, memoryview)):
        return enc_bytes(bytes(v))
    raise TypeError(f"unsupported CQL value type {type(v)}")


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.at = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.at:self.at + n]
        if len(b) != n:
            raise IOError("short CQL frame")
        self.at += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def string(self) -> str:
        return self.take(self.u16()).decode()

    def short_bytes(self) -> bytes:
        return self.take(self.u16())

    def bytes_(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self.take(n)

    def skip_option(self) -> None:
        """Skip one type <option> (spec 4.2.5.2)."""
        tid = self.u16()
        if tid == 0x0000:  # custom: class name string
            self.string()
        elif tid in (0x0020, 0x0022):  # list / set: one inner option
            self.skip_option()
        elif tid == 0x0021:  # map: two inner options
            self.skip_option()
            self.skip_option()
        elif tid == 0x0030:  # UDT
            self.string()
            self.string()
            for _ in range(self.u16()):
                self.string()
                self.skip_option()
        elif tid == 0x0031:  # tuple
            for _ in range(self.u16()):
                self.skip_option()
        # all other ids are leaf types with no payload


class CqlClient:
    """One connection to a cassandra node, v4, synchronous."""

    def __init__(self, host: str, port: int = 9042, username: str = "",
                 password: str = "", keyspace: str = "",
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, int(port)), timeout)
        self._stream = 0
        self._startup(username, password)
        if keyspace:
            self.query(f'USE "{keyspace}"', consistency=CONSISTENCY_ONE)

    # -- framing --------------------------------------------------------
    def _send(self, opcode: int, body: bytes) -> None:
        self._stream = (self._stream + 1) % 32768
        hdr = struct.pack(">BBhBI", 0x04, 0, self._stream, opcode,
                          len(body))
        self._sock.sendall(hdr + body)

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            piece = self._sock.recv(n - len(out))
            if not piece:
                raise IOError("cassandra connection closed")
            out += piece
        return out

    def _recv(self) -> tuple[int, bytes]:
        hdr = self._recv_exact(9)
        _ver, flags, stream, opcode, length = struct.unpack(">BBhBI", hdr)
        body = self._recv_exact(length)
        if stream != self._stream:
            # one request in flight at a time: a stray frame means the
            # connection is desynced (same contract as MongoWire)
            self.close()
            raise IOError(f"cql stream desync: {stream} != {self._stream}")
        if flags & 0x01:
            # compression is never negotiated in STARTUP, so a
            # compressed frame is unreadable
            self.close()
            raise IOError("unexpected compressed CQL frame")
        if flags & (0x02 | 0x08):
            # tracing id and/or server warnings prefix the body
            # (e.g. tombstone-scan warnings on RESULT frames); strip
            # them so the payload parse starts at the real body
            r = _Reader(body)
            if flags & 0x02:
                r.take(16)  # tracing uuid
            if flags & 0x08:
                for _ in range(r.u16()):  # [string list] of warnings
                    r.string()
            body = body[r.at:]
        if opcode == OP_ERROR:
            r = _Reader(body)
            raise CqlError(r.i32(), r.string())
        return opcode, body

    # -- handshake ------------------------------------------------------
    def _startup(self, username: str, password: str) -> None:
        self._send(OP_STARTUP, enc_string_map({"CQL_VERSION": "3.0.0"}))
        opcode, body = self._recv()
        if opcode == OP_AUTHENTICATE:
            # SASL PLAIN (PasswordAuthenticator)
            token = b"\x00" + username.encode() + b"\x00" + \
                password.encode()
            self._send(OP_AUTH_RESPONSE, enc_bytes(token))
            opcode, body = self._recv()
            if opcode != OP_AUTH_SUCCESS:
                raise IOError(f"cassandra auth failed (opcode {opcode})")
        elif opcode != OP_READY:
            raise IOError(f"unexpected startup reply opcode {opcode}")

    # -- queries --------------------------------------------------------
    @staticmethod
    def _query_params(values, consistency: int) -> bytes:
        out = struct.pack(">H", consistency)
        if values:
            out += bytes([0x01])  # flags: values follow
            out += struct.pack(">H", len(values))
            for v in values:
                out += enc_value(v)
        else:
            out += bytes([0x00])
        return out

    def query(self, cql: str, values: list | tuple = (),
              consistency: int = CONSISTENCY_LOCAL_QUORUM):
        self._send(OP_QUERY, enc_long_string(cql) +
                   self._query_params(values, consistency))
        return self._result(self._recv())

    def prepare(self, cql: str) -> bytes:
        self._send(OP_PREPARE, enc_long_string(cql))
        opcode, body = self._recv()
        r = _Reader(body)
        kind = r.i32()
        if kind != RESULT_PREPARED:
            raise IOError(f"PREPARE returned result kind {kind}")
        return r.short_bytes()  # metadata after the id is irrelevant

    def execute(self, stmt_id: bytes, values: list | tuple = (),
                consistency: int = CONSISTENCY_LOCAL_QUORUM):
        self._send(OP_EXECUTE, struct.pack(">H", len(stmt_id)) + stmt_id +
                   self._query_params(values, consistency))
        return self._result(self._recv())

    # -- RESULT decoding ------------------------------------------------
    def _result(self, frame):
        opcode, body = frame
        if opcode != OP_RESULT:
            raise IOError(f"unexpected opcode {opcode}")
        r = _Reader(body)
        kind = r.i32()
        if kind in (RESULT_VOID, RESULT_SET_KEYSPACE,
                    RESULT_SCHEMA_CHANGE):
            return None
        if kind != RESULT_ROWS:
            raise IOError(f"unexpected result kind {kind}")
        flags = r.i32()
        col_count = r.i32()
        if flags & 0x0002:  # has_more_pages
            r.bytes_()  # paging state (unused: LIMIT bounds our reads)
        names: list[str] = []
        if not flags & 0x0004:  # no_metadata unset -> specs present
            if flags & 0x0001:  # global_tables_spec
                r.string()
                r.string()
            for _ in range(col_count):
                if not flags & 0x0001:
                    r.string()
                    r.string()
                names.append(r.string())
                r.skip_option()
        rows_count = r.i32()
        rows = []
        for _ in range(rows_count):
            rows.append([r.bytes_() for _ in range(col_count)])
        return rows

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
