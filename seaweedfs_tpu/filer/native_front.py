"""Native filer front orchestration (combined `server` mode).

The C++ front (dataplane.cc, ROLE_FILER) owns the public filer port and
serves GET/PUT/HEAD/DELETE of plain files natively against the local
volume store; this module is its python control plane, mirroring
s3/native_front.py:

- the APPLIER thread: receives entry mutations over a socketpair and
  applies them through the in-process `Filer.create_entry` /
  `delete_entry` (parent dirs, old-chunk GC, event log — the metadata
  semantics keep their one implementation), then acks so the front can
  answer the PUT/DELETE.
- the META listener: registered as a sync listener on the filer's
  event log (called under the mutation lock), it keeps the front's
  entry cache in exact store order — any mutation path, native or
  python, invalidates or refreshes the cache with a ZERO staleness
  window across both fronts.
- the REFILL thread: keeps the pre-assigned fid pool topped up from
  the master and re-evaluates the WRITES GATE each tick — the native
  PUT/DELETE fast path is enabled only while the python filer would
  apply its defaults verbatim (no filer.conf path rules, no cipher,
  no -saveToFilerLimit inlining, no default replication), so a rule
  edit flips hot writes back to the python path within a tick.
"""
from __future__ import annotations

import mimetypes
import socket
import threading
import time

from ..utils import extheaders, faults, metrics
from .entry import Entry, FileChunk

POOL_LOW = 512
POOL_BATCH = 2048
CACHEABLE_MAX = 8 << 20


class NativeFilerFront:
    def __init__(self, filer_server, master_url: str,
                 listen_port: int, backend_port: int,
                 listen_ip: str = "", workers: int = 2):
        from ..native.dataplane import FilerFront

        self.fs = filer_server        # the FilerServer (python app)
        self.filer = filer_server.filer
        self.master_url = master_url.rstrip("/")
        self.front = FilerFront()
        self._stop = threading.Event()
        self._writes_on = False
        # C++ end / python end of the entry channel
        self._chan_c, self._chan_py = socket.socketpair(
            socket.AF_UNIX, socket.SOCK_STREAM)
        self.port = self.front.start(listen_port, backend_port,
                                     self._chan_c.fileno(),
                                     workers=workers, listen_ip=listen_ip)
        # the C side now owns that fd (dp_filer_stop closes it): detach
        # so this object's GC can't double-close a number the OS may
        # have already handed to an unrelated socket
        self._chan_c.detach()
        if faults.enabled():
            # this front's share of -fault.spec (service 'filer'), same
            # mirror-at-spawn contract as the volume front
            re_, we, rd, wd = faults.native_params("filer")
            self.front.set_faults(re_, we, rd, wd, seed=faults.seed())
        self._check_writes_gate()
        self.filer.meta_log.sync_listeners.append(self._on_meta_event)
        self._applier = threading.Thread(target=self._applier_loop,
                                         daemon=True,
                                         name="filerfront-applier")
        self._applier.start()
        self._refill = threading.Thread(target=self._refill_loop,
                                        daemon=True,
                                        name="filerfront-refill")
        self._refill.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.filer.meta_log.sync_listeners.remove(self._on_meta_event)
        except ValueError:
            pass
        try:
            self._chan_py.close()
        except OSError:
            pass
        self.front.stop()  # closes the C side of the channel

    def stats(self) -> dict:
        return self.front.stats()

    # -- meta events (SYNC: under the filer mutation lock) --------------
    def _on_meta_event(self, ev: dict) -> None:
        for which in ("old_entry", "new_entry"):
            ent = ev[which]
            if ent is None:
                continue
            full = ent["full_path"]
            if full == "/":
                continue
            is_dir = bool(ent.get("mode", 0) & 0o40000)
            if which == "old_entry" or ev["new_entry"] is None or is_dir:
                self.front.invalidate(full, prefix=is_dir)
                continue
            self._maybe_cache(full, ent)

    def _maybe_cache(self, path: str, ent: dict) -> None:
        """Admit only entries the C front can serve byte-identically to
        handle_get: one plain local chunk, nothing that changes the
        read path (inline content, manifests, cipher, compression,
        links, TTL expiry — python-side expiry emits no meta event, so
        a cached copy would outlive the object)."""
        chunks = ent.get("chunks") or []
        if (len(chunks) != 1 or ent.get("content")
                or ent.get("hard_link_id") or ent.get("symlink_target")
                or ent.get("ttl_sec")):
            self.front.invalidate(path)
            return
        ch = chunks[0]
        if (ch.get("offset", 0) != 0 or ch.get("cipher_key")
                or ch.get("is_compressed") or ch.get("is_chunk_manifest")
                or ch.get("size", 0) > CACHEABLE_MAX):
            self.front.invalidate(path)
            return
        # the exact header set handle_get derives per request,
        # precomputed once per mutation
        etag = ent.get("md5") or ch.get("etag", "")
        mime = (ent.get("mime") or mimetypes.guess_type(path)[0]
                or "application/octet-stream")
        ext_lines = [f"x-seaweed-ext-{k}: {extheaders.armor(v)}\r\n"
                     for k, v in (ent.get("extended") or {}).items()
                     if k.startswith("s3_")]
        try:
            self.front.cache_put(
                path, ch["fid"], ch.get("size", 0), etag, mime,
                "".join(ext_lines), int(ent.get("mtime", 0)))
        except ValueError:
            self.front.invalidate(path)

    # -- the applier ----------------------------------------------------
    def _applier_loop(self) -> None:
        buf = b""
        sock = self._chan_py
        while not self._stop.is_set():
            try:
                data = sock.recv(1 << 16)
            except OSError:
                break
            if not data:
                break
            buf += data
            acks = []
            store = self.filer.store
            store.begin_batch()  # ONE WAL flush for the whole burst
            try:
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line, buf = buf[:nl], buf[nl + 1:]
                    acks.append(self._apply_one(line))
            finally:
                store.end_batch()  # durable BEFORE any ack goes out
            if acks:
                try:
                    sock.sendall("".join(acks).encode())
                except OSError:
                    break

    def _apply_one(self, line: bytes) -> str:
        # TSV record from the front (see filer_handle_put/_delete):
        #   id \t put \t path \t fid \t size \t etag \t mime
        #   |  id \t del \t path
        rec_id = b"0"
        try:
            cols = line.split(b"\t")
            rec_id = cols[0]
            op = cols[1]
            path = cols[2].decode()
            if op == b"del":
                # same call handle_delete makes (non-recursive,
                # chunks reclaimed); missing path is a no-op — the
                # python DELETE answers 204 either way
                self.filer.delete_entry(path)
                return f"{rec_id.decode()} 200\n"
            size = int(cols[4])
            etag = cols[5].decode()
            # the entry handle_put would create for a single-chunk
            # body: chunk md5 IS the file md5, server-default
            # collection/replication (the writes gate guarantees no
            # filer.conf rule would have said otherwise)
            entry = Entry(
                full_path=path, mime=cols[6].decode(), md5=etag,
                collection=self.fs.collection,
                replication=self.fs.replication,
                chunks=[FileChunk(fid=cols[3].decode(), offset=0,
                                  size=size, mtime_ns=time.time_ns(),
                                  etag=etag)])
            self.filer.create_entry(entry, gc_old_chunks=True)
            metrics.counter_add("filer_write_bytes", size)
            return f"{rec_id.decode()} 200\n"
        except Exception:
            try:
                return f"{int(rec_id)} 500\n"
            except ValueError:
                return "0 500\n"

    # -- writes gate + fid pool -----------------------------------------
    def _check_writes_gate(self) -> None:
        """Native PUT/DELETE only while the python write path would be
        a pure default single-chunk create: any filer.conf rule (ttl,
        fsync, read-only, per-path collection...), cipher, inline
        threshold, or replicated default placement must flow through
        the python handler."""
        fs = self.fs
        ok = (not fs.cipher and fs.save_to_filer_limit <= 0
              and fs.replication in ("", "000"))
        if ok:
            try:
                ok = not fs._filer_conf().rules
            except Exception:
                ok = False
        if ok != self._writes_on:
            self._writes_on = ok
            self.front.set_writes(ok)

    def _refill_loop(self) -> None:
        from ..operation import verbs

        while not self._stop.wait(0.1):
            try:
                self._check_writes_gate()
            except Exception:
                pass
            if not self._writes_on:
                continue
            try:
                if self.front.pool_level() >= POOL_LOW:
                    continue
                a = verbs.assign(self.master_url, count=POOL_BATCH,
                                 collection=self.fs.collection)
                self.front.push_fids(a.fid, a.count)
            except Exception:
                pass  # master busy/unreachable: PUTs relay meanwhile
