"""Embedded sorted-KV engine ("weedkv") — the leveldb-class store.

Equivalent of the role vendored goleveldb plays for the reference's
default filer store (/root/reference/weed/filer/leveldb/
leveldb_store.go): an embedded, ordered, durable key-value log —
re-designed small instead of ported:

- writes go to a write-ahead log, then a memtable (dict)
- the memtable flushes to immutable sorted segment files (.sst, JSON
  lines sorted by key) when it grows past a threshold
- reads check memtable then segments newest-to-oldest; deletes are
  tombstones until compaction
- when segments pile up they are merge-compacted into one (tombstones
  dropped)
- reopen = load segment indexes + replay the WAL

Keys are bytes and sort lexicographically (the property the filer
store's directory scans rely on). Values are bytes.
"""
from __future__ import annotations

import base64
import bisect
import heapq
import json
import os
import struct
import threading
import zlib

TOMBSTONE = None  # in-memory marker

MEMTABLE_FLUSH_ENTRIES = 4096
MEMTABLE_FLUSH_BYTES = 4 << 20
COMPACT_SEGMENT_COUNT = 8


def _enc(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _dec(s: str) -> bytes:
    return base64.b64decode(s)


def _encode_record(key: bytes, value: bytes | None) -> str:
    """One segment line: {"k": ..} + either "t" (tombstone) or
    "v". The single place the SEGMENT record format lives (segments
    are written at flush time, off the hot path; the WAL uses the
    binary v2 framing below)."""
    rec = {"k": _enc(key)}
    if value is None:
        rec["t"] = 1
    else:
        rec["v"] = _enc(value)
    return json.dumps(rec, separators=(",", ":")) + "\n"


# -- WAL v2 binary framing ----------------------------------------------
# The original WAL shared the segment's JSON-lines format; base64 +
# json.dumps per record measured as the single largest slice of the S3
# applier's create_entry budget. v2 frames are binary:
#   [u8 tag 0=tombstone 1=put][u32le klen][u32le vlen][key][value]
#   [u32le crc32(frame minus crc)]
# The trailing CRC gives the same torn-tail detection the JSON parse
# failure used to provide. Legacy (JSON) WALs are still replayed and
# are rewritten as v2 on open — see _replay_wal.
WAL2_MAGIC = b"WKV2\n"
_WAL2_HDR = struct.Struct("<BII")


def _encode_wal2(key: bytes, value: bytes | None) -> bytes:
    frame = _WAL2_HDR.pack(0 if value is None else 1,
                           len(key), len(value or b"")) + key + (
                               value or b"")
    return frame + struct.pack("<I", zlib.crc32(frame))


def _decode_record(d: dict) -> tuple[bytes, bytes | None]:
    return _dec(d["k"]), (None if d.get("t")
                          else _dec(d.get("v", "")))


class _Segment:
    """One immutable sorted file with its key index in memory."""

    def __init__(self, path: str,
                 items: list[tuple[bytes, bytes | None]] | None = None):
        """Load from `path`, or adopt already-sorted `items` without
        re-reading the file just written from them."""
        self.path = path
        self.keys: list[bytes] = []
        self.values: list[bytes | None] = []
        if items is not None:
            self.keys = [k for k, _ in items]
            self.values = [v for _, v in items]
            return
        with open(path, "r") as f:
            for line in f:
                if not line.strip():
                    continue
                k, v = _decode_record(json.loads(line))
                self.keys.append(k)
                self.values.append(v)

    def get(self, key: bytes) -> tuple[bool, bytes | None]:
        """-> (found, value-or-tombstone)."""
        i = bisect.bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return True, self.values[i]
        return False, None

    @staticmethod
    def write(path: str, items: list[tuple[bytes, bytes | None]]) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for k, v in items:
                f.write(_encode_record(k, v))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


class WeedKV:
    def __init__(self, dirpath: str):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self._lock = threading.RLock()
        self._mem: dict[bytes, bytes | None] = {}
        # sorted view of _mem's keys, maintained on write: a scan page
        # must cost O(log mem + page), not a full-memtable filter+sort
        # per page (the redis3 chunked-skiplist concern — a million-
        # entry directory pages through MANY scans while its inserts
        # keep landing in the memtable)
        self._mem_keys: list[bytes] = []
        self._mem_bytes = 0
        self._segments: list[_Segment] = []  # oldest .. newest
        self._next_seg = 0
        for name in sorted(os.listdir(dirpath)):
            if name.endswith(".sst"):
                self._segments.append(
                    _Segment(os.path.join(dirpath, name)))
                self._next_seg = max(self._next_seg,
                                     int(name[:-4]) + 1)
        self._wal_path = os.path.join(dirpath, "wal.log")
        self._flush_local = threading.local()
        self._replay_wal()
        self._mem_keys = sorted(self._mem)
        # binary + buffered: the hot path writes pre-encoded bytes
        # (a TextIOWrapper re-encodes every record on this path)
        self._open_wal(fresh=False)

    # -- WAL ------------------------------------------------------------
    def _open_wal(self, fresh: bool) -> None:
        """(Re)open self._wal for appending; a fresh/empty file gets
        the v2 magic so replay never misreads it as legacy JSON. The
        one place the 'start a v2 WAL' ritual lives."""
        self._wal = open(self._wal_path, "wb" if fresh else "ab")
        if self._wal.tell() == 0:
            self._wal.write(WAL2_MAGIC)
            self._wal.flush()

    def _replay_wal(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            raw = f.read()
        legacy = not raw.startswith(WAL2_MAGIC)
        good = self._replay_legacy(raw) if legacy \
            else self._replay_v2(raw)
        if legacy and raw:
            # migrate in place: rewrite the replayed records as v2 via
            # tmp+rename so a crash mid-rewrite still leaves the old
            # acknowledged WAL intact
            tmp = self._wal_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(WAL2_MAGIC)
                for k, v in self._mem.items():
                    f.write(_encode_wal2(k, v))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._wal_path)
        elif good < len(raw):
            # drop the torn tail NOW: appending new records after the
            # garbage would make every later replay stop at the same
            # spot and silently lose those acknowledged writes
            with open(self._wal_path, "r+b") as f:
                f.truncate(good)

    def _replay_legacy(self, raw: bytes) -> int:
        good = 0
        for line in raw.splitlines(keepends=True):
            try:
                k, v = _decode_record(json.loads(line))
            except (json.JSONDecodeError, UnicodeDecodeError,
                    KeyError, ValueError):
                break  # torn tail from a crash mid-append
            self._mem[k] = v
            self._mem_bytes += len(k) + len(v or b"")
            good += len(line)
        return good

    def _replay_v2(self, raw: bytes) -> int:
        off = len(WAL2_MAGIC)
        hdr = _WAL2_HDR.size
        while True:
            if off + hdr > len(raw):
                break
            tag, klen, vlen = _WAL2_HDR.unpack_from(raw, off)
            end = off + hdr + klen + vlen + 4
            if tag > 1 or end > len(raw):
                break  # torn/garbage tail
            (crc,) = struct.unpack_from("<I", raw, end - 4)
            if zlib.crc32(raw[off:end - 4]) != crc:
                break
            k = raw[off + hdr:off + hdr + klen]
            v = raw[off + hdr + klen:end - 4] if tag else None
            self._mem[k] = v
            self._mem_bytes += len(k) + len(v or b"")
            off = end
        return off

    def _wal_append(self, key: bytes, value: bytes | None) -> None:
        self._wal.write(_encode_wal2(key, value))
        if not getattr(self._flush_local, "deferred", False):
            self._wal.flush()

    def defer_flush(self, deferred: bool) -> None:
        """Group-commit window for THE CALLING THREAD only: while
        deferred, its puts skip the per-record WAL flush; turning
        deferral off flushes the accumulated tail. Thread-local on
        purpose — other writers sharing the store keep their
        flush-before-ack durability (their flush also carries any
        deferred records ahead of them in the sequential WAL, which is
        harmless over-flushing). The deferring caller must not ack its
        own batch until the window closes."""
        self._flush_local.deferred = deferred
        if not deferred:
            with self._lock:
                self._wal.flush()

    # -- core ops -------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._wal_append(key, value)
            if key not in self._mem:
                bisect.insort(self._mem_keys, key)
            self._mem[key] = value
            self._mem_bytes += len(key) + len(value)
            self._maybe_flush()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._wal_append(key, None)
            if key not in self._mem:
                bisect.insort(self._mem_keys, key)
            self._mem[key] = TOMBSTONE
            self._mem_bytes += len(key)
            self._maybe_flush()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            if key in self._mem:
                return self._mem[key]
            for seg in reversed(self._segments):
                found, v = seg.get(key)
                if found:
                    return v
            return None

    def scan(self, start: bytes, end: bytes,
             limit: int = 0) -> list[tuple[bytes, bytes]]:
        """Live (key, value) with start <= key < end, sorted; at most
        `limit` rows when given. Lazily k-way-merges the sorted sources
        so a paged directory listing doesn't materialize the whole
        range (the role of leveldb's iterator)."""

        with self._lock:
            def seg_rows(seg: _Segment, rank: int):
                lo = bisect.bisect_left(seg.keys, start)
                hi = bisect.bisect_left(seg.keys, end)
                for i in range(lo, hi):
                    yield seg.keys[i], rank, seg.values[i]

            sources = [seg_rows(seg, rank)
                       for rank, seg in enumerate(self._segments)]

            def mem_rows():
                lo = bisect.bisect_left(self._mem_keys, start)
                hi = bisect.bisect_left(self._mem_keys, end)
                rank = len(self._segments)
                for i in range(lo, hi):
                    k = self._mem_keys[i]
                    yield k, rank, self._mem[k]

            sources.append(mem_rows())
            out: list[tuple[bytes, bytes]] = []
            cur_key: bytes | None = None
            cur_rank, cur_val = -1, None
            for k, rank, v in heapq.merge(*sources):
                if k != cur_key:
                    if cur_key is not None and cur_val is not None:
                        out.append((cur_key, cur_val))
                        if limit and len(out) >= limit:
                            return out
                    cur_key, cur_rank, cur_val = k, rank, v
                elif rank > cur_rank:  # newer source shadows older
                    cur_rank, cur_val = rank, v
            if cur_key is not None and cur_val is not None:
                out.append((cur_key, cur_val))
            return out[:limit] if limit else out

    # -- flush / compact ------------------------------------------------
    def _maybe_flush(self) -> None:
        if len(self._mem) >= MEMTABLE_FLUSH_ENTRIES or \
                self._mem_bytes >= MEMTABLE_FLUSH_BYTES:
            self.flush()

    def flush(self) -> None:
        """Memtable -> a new sorted segment; truncate the WAL."""
        with self._lock:
            if not self._mem:
                return
            items = [(k, self._mem[k]) for k in self._mem_keys]
            path = os.path.join(self.dir, f"{self._next_seg:06d}.sst")
            _Segment.write(path, items)
            self._segments.append(_Segment(path, items=items))
            self._next_seg += 1
            self._mem = {}
            self._mem_keys = []
            self._mem_bytes = 0
            self._wal.close()
            self._open_wal(fresh=True)
            if len(self._segments) >= COMPACT_SEGMENT_COUNT:
                self.compact()

    SLOW_COMPACTION_SECONDS = 1.0

    def compact(self) -> None:
        """Merge all segments into one, dropping tombstones and
        shadowed versions. Reads and writes stall on the store lock
        for the whole merge — which is why the time and volume are
        first-class metrics (filer_store_compaction_*): a grown
        store's read p99 IS this pause."""
        import time

        from ..utils import glog, metrics

        with self._lock:
            if len(self._segments) <= 1:
                return
            t0 = time.perf_counter()
            n_segments = len(self._segments)
            merged: dict[bytes, bytes | None] = {}
            read_bytes = 0
            for seg in self._segments:  # oldest first
                for k, v in zip(seg.keys, seg.values):
                    merged[k] = v
                    read_bytes += len(k) + len(v or b"")
            live = sorted((k, v) for k, v in merged.items()
                          if v is not None)
            path = os.path.join(self.dir, f"{self._next_seg:06d}.sst")
            _Segment.write(path, live)
            old = self._segments
            self._segments = [_Segment(path, items=live)]
            self._next_seg += 1
            for seg in old:
                try:
                    os.remove(seg.path)
                except OSError:
                    pass
            dt = time.perf_counter() - t0
        metrics.histogram_observe("filer_store_compaction_seconds", dt)
        metrics.counter_add("filer_store_compaction_bytes_total",
                            read_bytes)
        if dt >= self.SLOW_COMPACTION_SECONDS:
            glog.warning(
                "slow compaction: %s merged %d segments "
                "(%d keys, %d bytes) in %.2fs — reads stalled for the "
                "duration", self.dir, n_segments, len(live),
                read_bytes, dt)
        else:
            glog.v(1, "compacted %s: %d segments -> 1 (%d keys, "
                   "%d bytes) in %.3fs", self.dir, n_segments,
                   len(live), read_bytes, dt)

    def close(self) -> None:
        with self._lock:
            self.flush()
            self._wal.close()
