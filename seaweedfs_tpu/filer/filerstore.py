"""Pluggable filer metadata stores.

Equivalent of /root/reference/weed/filer/filerstore.go:21-44
(`FilerStore` interface) and its registration pattern — concrete stores
register themselves in `STORES` by type string, like the reference's
`init()` -> `filer.Stores` (weed/filer/leveldb/leveldb_store.go:29-31).

Three embedded stores ship in-tree:
- `memory`: dict-backed, for tests and ephemeral filers.
- `sqlite`: stdlib sqlite3, the durable single-file embedded store
  (weed/filer/sqlite/).
- `leveldb`: the weedkv LSM engine (WAL + memtable + sorted segments),
  the counterpart of the reference's default goleveldb store
  (weed/filer/leveldb/).
External-DB plugins (redis/mysql/...) would register the same way.
"""
from __future__ import annotations

import json
import sqlite3
import threading
from typing import Callable

from .entry import Entry


class FilerStore:
    """Interface every metadata store implements. Paths are passed as
    (dir, name); list order is by name ascending."""

    name = "abstract"

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def insert_entry_encoded(self, entry: Entry, entry_dict: dict) -> None:
        """Insert with the caller's already-built entry.to_dict() —
        the filer builds that dict once per mutation for the event log
        and serializing stores reuse it instead of re-walking the
        entry (a measured slice of the S3 applier's per-op budget).
        Default: ignore the dict. NOTE the filer's hot path calls
        THIS method, so a store that overrides it (weedkv, sqlite)
        must treat it as the primitive — overriding only insert_entry
        on such a subclass would be bypassed."""
        self.insert_entry(entry)

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry | None:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    # generic KV side-channel (weed/filer/filerstore.go KvPut/KvGet)
    def kv_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def kv_delete(self, key: str) -> None:
        raise NotImplementedError

    # group-commit window (BeginTransaction/CommitTransaction in
    # filerstore.go, reduced to its durability essence): between begin
    # and end the store may defer per-write log flushing; end_batch
    # makes everything since begin durable. Bulk ingest (the native S3
    # applier) wraps each record batch so N inserts pay ONE flush.
    # Default: no-op (stores that flush per write are already durable).
    def begin_batch(self) -> None:
        pass

    def end_batch(self) -> None:
        pass

    def close(self) -> None:
        pass


STORES: dict[str, Callable[..., FilerStore]] = {}


def register_store(name: str):
    def deco(cls):
        cls.name = name
        STORES[name] = cls
        return cls
    return deco


def make_store(kind: str, **kwargs) -> FilerStore:
    if kind not in STORES:
        raise KeyError(f"unknown filer store {kind!r}; "
                       f"have {sorted(STORES)}")
    return STORES[kind](**kwargs)


def _norm(path: str) -> str:
    path = "/" + path.strip("/")
    return path


def _like_escape(s: str) -> str:
    """Escape LIKE wildcards so paths match literally (pair with
    ESCAPE '\\' — sqlite treats backslash as plain text otherwise)."""
    return s.replace("\\", r"\\").replace("%", r"\%").replace("_", r"\_")


def _split(path: str) -> tuple[str, str]:
    path = _norm(path)
    if path == "/":
        return "", ""
    d, _, n = path.rpartition("/")
    return (d or "/", n)


def _delete_subtree_by_walk(store: "FilerStore", path: str,
                            page: int = 1024) -> None:
    """Shared subtree delete for stores whose keyspace scatters
    directories (hash partitions): walk directory entries recursively,
    then drop each directory's own children range via the store's
    delete_directory_range hook. ONE copy of the stack/seen/cursor
    pagination — four stores used to carry private variants."""
    stack = [_norm(path)]
    seen: set[str] = set()
    while stack:
        d = stack.pop()
        if d in seen:
            continue
        seen.add(d)
        cursor = ""
        while True:
            batch = store.list_directory_entries(d, start_from=cursor,
                                                 limit=page)
            for e in batch:
                if e.is_directory:
                    stack.append(e.full_path)
            if not batch:
                break
            cursor = batch[-1].name
            if len(batch) < page:
                break
        store.delete_directory_range(d)


def _list_filter(name: str, prefix: str, start_from: str,
                 inclusive: bool) -> str:
    """Shared pagination gate for sorted child scans: 'keep' | 'skip' |
    'stop'. Used by every scan-based store so the prefix-window and
    start_from/inclusive edges have exactly ONE implementation."""
    if prefix and not name.startswith(prefix):
        return "stop" if name > prefix else "skip"
    if start_from and (name < start_from or
                       (name == start_from and not inclusive)):
        return "skip"
    return "keep"


@register_store("memory")
class MemoryStore(FilerStore):
    def __init__(self, **_):
        self._lock = threading.RLock()
        # dir -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._kv: dict[str, bytes] = {}

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        with self._lock:
            self._dirs.setdefault(d, {})[n] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        with self._lock:
            return self._dirs.get(d, {}).get(n)

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        with self._lock:
            self._dirs.get(d, {}).pop(n, None)

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        with self._lock:
            prefix = path if path.endswith("/") else path + "/"
            for d in [d for d in self._dirs
                      if d == path or d.startswith(prefix)]:
                del self._dirs[d]

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        with self._lock:
            names = sorted(self._dirs.get(dirpath, {}))
            out = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                if start_from:
                    if n < start_from or (n == start_from and not inclusive):
                        continue
                out.append(self._dirs[dirpath][n])
                if len(out) >= limit:
                    break
            return out

    def kv_put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key: str) -> bytes | None:
        with self._lock:
            return self._kv.get(key)

    def kv_delete(self, key: str) -> None:
        with self._lock:
            self._kv.pop(key, None)


@register_store("sqlite")
class SqliteStore(FilerStore):
    """Durable embedded store: one table keyed (dir, name), JSON entry
    blobs — the same layout idea as the reference's abstract_sql store
    (weed/filer/abstract_sql/abstract_sql_store.go)."""

    def __init__(self, path: str = ":memory:", **_):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.executescript("""
                CREATE TABLE IF NOT EXISTS filemeta(
                    dir TEXT NOT NULL, name TEXT NOT NULL,
                    meta TEXT NOT NULL, PRIMARY KEY(dir, name));
                CREATE TABLE IF NOT EXISTS kv(
                    k TEXT PRIMARY KEY, v BLOB NOT NULL);
            """)
            self._conn.commit()

    def insert_entry(self, entry: Entry) -> None:
        self.insert_entry_encoded(entry, entry.to_dict())

    def insert_entry_encoded(self, entry: Entry, entry_dict: dict) -> None:
        d, n = entry.dir_and_name
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO filemeta(dir,name,meta) "
                "VALUES(?,?,?)", (d, n, json.dumps(entry_dict)))
            self._conn.commit()

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        with self._lock:
            row = self._conn.execute(
                "SELECT meta FROM filemeta WHERE dir=? AND name=?",
                (d, n)).fetchone()
        return Entry.from_dict(json.loads(row[0])) if row else None

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE dir=? AND name=?", (d, n))
            self._conn.commit()

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        like = _like_escape(
            path if path.endswith("/") else path + "/") + "%"
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE dir=? "
                r"OR dir LIKE ? ESCAPE '\'", (path, like))
            self._conn.commit()

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        cmp = ">=" if inclusive else ">"
        q = "SELECT meta FROM filemeta WHERE dir=?"
        args: list = [dirpath]
        if start_from:
            q += f" AND name {cmp} ?"
            args.append(start_from)
        if prefix:
            q += r" AND name LIKE ? ESCAPE '\'"
            args.append(_like_escape(prefix) + "%")
        q += " ORDER BY name LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def kv_put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv(k,v) VALUES(?,?)", (key, value))
            self._conn.commit()

    def kv_get(self, key: str) -> bytes | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k=?", (key,)).fetchone()
        return bytes(row[0]) if row else None

    def kv_delete(self, key: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k=?", (key,))
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


@register_store("leveldb")
class WeedKvStore(FilerStore):
    """Filer store over the embedded weedkv sorted-KV engine — the
    counterpart of the reference's default leveldb store
    (weed/filer/leveldb/leveldb_store.go, including its
    dir + 0x00 + name key layout, genDirectoryKeyPrefix)."""

    SEP = b"\x00"
    KV_PREFIX = b"kv\x01"
    ENTRY_PREFIX = b"e\x01"

    def __init__(self, path: str = "filerdb", **_):
        from .weedkv import WeedKV

        if path in ("", ":memory:"):
            raise ValueError("leveldb store needs a directory path")
        self.db = WeedKV(path)

    def _ekey(self, d: str, n: str) -> bytes:
        return self.ENTRY_PREFIX + d.encode() + self.SEP + n.encode()

    def insert_entry(self, entry: Entry) -> None:
        self.insert_entry_encoded(entry, entry.to_dict())

    def insert_entry_encoded(self, entry: Entry, entry_dict: dict) -> None:
        d, n = entry.dir_and_name
        self.db.put(self._ekey(d, n),
                    json.dumps(entry_dict, separators=(",", ":"),
                               ensure_ascii=False).encode())

    update_entry = insert_entry

    def begin_batch(self) -> None:
        self.db.defer_flush(True)

    def end_batch(self) -> None:
        self.db.defer_flush(False)  # flushes the deferred WAL tail

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        raw = self.db.get(self._ekey(d, n))
        return Entry.from_dict(json.loads(raw)) if raw else None

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        self.db.delete(self._ekey(d, n))

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        # every directory at or under `path` is a contiguous key range
        # per-directory; enumerate them via the entry scan
        prefix = self.ENTRY_PREFIX + path.encode()
        for k, _v in self.db.scan(prefix, _range_end(prefix)):
            rest = k[len(self.ENTRY_PREFIX):]
            d = rest.split(self.SEP, 1)[0].decode()
            if d == path or d.startswith(
                    path if path.endswith("/") else path + "/"):
                self.db.delete(k)

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        base = self.ENTRY_PREFIX + dirpath.encode() + self.SEP
        lo = base + max(prefix, start_from).encode() \
            if (prefix or start_from) else base
        out: list[Entry] = []
        # +1 covers the possibly-skipped exclusive start_from row
        for k, v in self.db.scan(lo, _range_end(base),
                                 limit=limit + 1 if limit else 0):
            name = k[len(base):].decode()
            if prefix and not name.startswith(prefix):
                break  # sorted scan: past the prefix range
            if start_from and name == start_from and not inclusive:
                continue
            out.append(Entry.from_dict(json.loads(v)))
            if len(out) >= limit:
                break
        return out

    def kv_put(self, key: str, value: bytes) -> None:
        self.db.put(self.KV_PREFIX + key.encode(), value)

    def kv_get(self, key: str) -> bytes | None:
        return self.db.get(self.KV_PREFIX + key.encode())

    def kv_delete(self, key: str) -> None:
        self.db.delete(self.KV_PREFIX + key.encode())

    def close(self) -> None:
        self.db.close()


def _range_end(prefix: bytes) -> bytes:
    """Smallest byte string greater than every string with `prefix`."""
    out = bytearray(prefix)
    while out:
        if out[-1] != 0xFF:
            out[-1] += 1
            return bytes(out)
        out.pop()
    return b"\xff" * 16


class _GatedStore(FilerStore):
    """Placeholder for store plugins whose client SDK isn't installed.
    Registered so `-store=<name>` errors with guidance instead of an
    unknown-store KeyError. (redis/etcd/mongodb/cassandra/mysql/
    postgres graduated to real in-tree wire clients.)"""

    KIND = ""
    NEEDS = ""

    def __init__(self, **_):
        raise ImportError(
            f"filer store {self.KIND!r} needs the {self.NEEDS} "
            "package, which is not installed; embedded stores "
            "available everywhere: memory, sqlite, leveldb")


# Every reference store family now has a real implementation — see
# redis_store.py (RESP), cassandra_store.py (CQL v4 via cql_lite.py),
# abstract_sql.py (shared SQL layer for mysql/postgres),
# elastic_store.py (ES7 REST), arango_store.py (HTTP docs + AQL),
# hbase_store.py (Thrift1 via thrift_lite.py), tikv_store.py and
# ydb_store.py (gRPC via utils/grpc_lite.py), rocksdb_store.py
# (ctypes on librocksdb, runtime-gated like the reference's build
# tag). _GatedStore remains for stores whose native library is absent
# at runtime.
