"""YDB filer store over the Table service gRPC API (grpc_lite).

The reference's store (/root/reference/weed/filer/ydb/ydb_store.go,
itself gated behind `//go:build ydb` — NOT in default reference
builds) rides ydb-go-sdk with the YQL statements in ydb_queries.go;
this build speaks Ydb.Table.V1.TableService directly through the
in-tree gRPC client with the same (dir_hash, name, directory, meta)
schema the abstract_sql family uses, and the same YQL shapes as the
reference's queries (DECLARE'd parameters, UPSERT/DELETE/SELECT,
`name LIKE` prefix windows).

Message encoding follows the public ydb-api-protos surface
(ydb_operation.proto, ydb_table.proto, ydb_value.proto) via the
generic protobuf helpers; the in-repo mini-ydb double (a real
grpc-core server) validates the full round trip. Until a live YDB run
is recorded, treat the field numbering as double-validated — the
reference's own build never ships this store either.

`-store=ydb -store.host=... -store.port=2136 -store.database=/local`
"""
from __future__ import annotations

import json

from ..utils import grpc_lite as g
from .abstract_sql import dir_hash
from .entry import Entry
from .filerstore import (FilerStore, _delete_subtree_by_walk,
                         _like_escape, _norm, _split, register_store)

SVC = "/Ydb.Table.V1.TableService"
STATUS_SUCCESS = 400000   # Ydb.StatusIds.SUCCESS
STATUS_BAD_SESSION = 400100
# real YDB caps an ExecuteDataQuery result set at 1000 rows
# (truncated=true past that); page below the cap and LOOP on the flag
RESULT_SET_CAP = 1000

# Ydb.Type.PrimitiveTypeId
T_INT64 = 3
T_UINT64 = 4
T_STRING = 0x1001  # bytes
T_UTF8 = 0x1200    # text


def _typed(type_id: int, value_field: int, raw) -> bytes:
    """TypedValue{type{type_id}, value{<field>: raw}} bytes."""
    t = g.pb_uint(1, type_id)
    if value_field in (8, 9):  # bytes_value / text_value
        v = g.pb_bytes(value_field,
                       raw if isinstance(raw, bytes) else raw.encode())
    else:
        v = g.pb_tag(value_field, 0) + g.pb_varint(raw)
    return g.pb_bytes(1, t) + g.pb_bytes(2, v)


def p_int64(v: int) -> bytes:
    return _typed(T_INT64, 4, v)


def p_uint64(v: int) -> bytes:
    return _typed(T_UINT64, 5, v)


def p_utf8(s: str) -> bytes:
    return _typed(T_UTF8, 9, s)


def p_string(b: bytes) -> bytes:
    return _typed(T_STRING, 8, b)


UPSERT = """DECLARE $dir_hash AS Int64; DECLARE $directory AS Utf8;
DECLARE $name AS Utf8; DECLARE $meta AS String;
UPSERT INTO filemeta (dir_hash, name, directory, meta)
VALUES ($dir_hash, $name, $directory, $meta);"""

DELETE = """DECLARE $dir_hash AS Int64; DECLARE $name AS Utf8;
DELETE FROM filemeta WHERE dir_hash = $dir_hash AND name = $name;"""

FIND = """DECLARE $dir_hash AS Int64; DECLARE $name AS Utf8;
SELECT meta FROM filemeta
WHERE dir_hash = $dir_hash AND name = $name;"""

DELETE_CHILDREN = """DECLARE $dir_hash AS Int64;
DECLARE $directory AS Utf8;
DELETE FROM filemeta
WHERE dir_hash = $dir_hash AND directory = $directory;"""

LIST = """DECLARE $dir_hash AS Int64; DECLARE $directory AS Utf8;
DECLARE $start_name AS Utf8; DECLARE $prefix AS Utf8;
DECLARE $limit AS Uint64;
SELECT name, meta FROM filemeta
WHERE dir_hash = $dir_hash AND directory = $directory
AND name {op} $start_name AND name LIKE $prefix ESCAPE '\\\\'
ORDER BY name ASC LIMIT $limit;"""

KV_UPSERT = """DECLARE $k AS Utf8; DECLARE $v AS String;
UPSERT INTO kv (k, v) VALUES ($k, $v);"""

KV_GET = """DECLARE $k AS Utf8;
SELECT v FROM kv WHERE k = $k;"""

KV_DELETE = """DECLARE $k AS Utf8;
DELETE FROM kv WHERE k = $k;"""

SCHEME = ("CREATE TABLE IF NOT EXISTS filemeta (dir_hash Int64, "
          "name Utf8, directory Utf8, meta String, "
          "PRIMARY KEY (dir_hash, name));\n"
          "CREATE TABLE IF NOT EXISTS kv (k Utf8, v String, "
          "PRIMARY KEY (k));")


class YdbError(IOError):
    pass


class _Ydb:
    """The TableService subset the store needs: one session, YQL
    data/scheme queries in auto-commit serializable transactions."""

    def __init__(self, host: str, port: int, database: str,
                 token: str = ""):
        self.ch = g.GrpcChannel(host, port)
        self.meta = [("x-ydb-database", database)]
        if token:
            self.meta.append(("x-ydb-auth-ticket", token))
        self.database = database
        self.session = ""

    def _call(self, method: str, req: bytes) -> dict[int, list]:
        """-> the decoded result message from Operation.result (Any)."""
        raw = self.ch.unary(f"{SVC}/{method}", req, metadata=self.meta)
        resp = g.pb_decode(raw)
        op_raw = g.pb_first(resp, 1)
        if op_raw is None:
            raise YdbError(f"ydb {method}: response without operation")
        op = g.pb_decode(bytes(op_raw))
        status = g.pb_first(op, 3, 0)
        if status != STATUS_SUCCESS:
            issues = op.get(4, [])
            raise YdbError(f"ydb {method}: status {status} "
                           f"({len(issues)} issues)")
        any_raw = g.pb_first(op, 5)
        if any_raw is None:
            return {}
        any_msg = g.pb_decode(bytes(any_raw))
        return g.pb_decode(bytes(g.pb_first(any_msg, 2, b"")))

    def ensure_session(self) -> str:
        if not self.session:
            result = self._call("CreateSession", b"")
            sid = g.pb_first(result, 1)
            if not sid:
                raise YdbError("ydb: CreateSession returned no id")
            self.session = bytes(sid).decode()
        return self.session

    def _with_session(self, method: str, build) -> dict[int, list]:
        """Run `build(session_id) -> request bytes` with one retry on
        BAD_SESSION / transport failure — an idle-expired or node-lost
        session must recover with a fresh CreateSession, never poison
        the store until restart (the family convention: abstract_sql
        and cassandra reconnect the same way)."""
        for attempt in (0, 1):
            try:
                return self._call(method, build(self.ensure_session()))
            except YdbError as e:
                if attempt == 0 and str(STATUS_BAD_SESSION) in str(e):
                    self.session = ""
                    continue
                raise
            except (OSError, IOError):
                if attempt == 0:
                    self.session = ""  # channel redials on next call
                    continue
                raise

    def scheme(self, yql: str) -> None:
        # ExecuteSchemeQueryRequest {session_id=1, yql_text=2}
        self._with_session(
            "ExecuteSchemeQuery",
            lambda sid: g.pb_str(1, sid) + g.pb_str(2, yql))

    def execute(self, yql: str, params: dict[str, bytes]
                ) -> tuple[list[list[dict]], bool]:
        """-> (rows of the FIRST result set — each row a list of
        decoded Ydb.Value field maps — and the ResultSet.truncated
        flag). Auto-commit serializable tx, like the reference's
        table.DefaultTxControl."""
        def build(sid: str) -> bytes:
            # TransactionControl {begin_tx=2
            # {serializable_read_write=1 {}}, commit_tx=10}
            txc = g.pb_bytes(2, g.pb_bytes(1, b"")) + g.pb_bool(10, True)
            req = g.pb_str(1, sid)
            req += g.pb_bytes(2, txc)
            req += g.pb_bytes(3, g.pb_str(1, yql))  # Query{yql_text=1}
            for name, tv in params.items():
                entry = g.pb_str(1, name) + g.pb_bytes(2, tv)
                req += g.pb_bytes(4, entry)  # map<string, TypedValue>
            return req

        result = self._with_session("ExecuteDataQuery", build)
        sets = result.get(1, [])
        if not sets:
            return [], False
        rs = g.pb_decode(bytes(sets[0]))
        rows = []
        for row_raw in rs.get(2, []):  # ResultSet.rows
            row = g.pb_decode(bytes(row_raw))
            rows.append([g.pb_decode(bytes(item))
                         for item in row.get(12, [])])  # Value.items
        return rows, bool(g.pb_first(rs, 3, 0))  # truncated

    def close(self) -> None:
        self.ch.close()


def _cell_bytes(cell: dict[int, list]) -> bytes:
    """Ydb.Value scalar -> bytes (bytes_value=8 or text_value=9)."""
    v = g.pb_first(cell, 8)
    if v is None:
        v = g.pb_first(cell, 9, b"")
    return bytes(v)


@register_store("ydb")
class YdbStore(FilerStore):
    """`-store=ydb -store.host=... -store.port=2136
    -store.database=/local`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2136,
                 database: str = "/local", password: str = "", **_):
        self.db = _Ydb(host, int(port), database, token=password)
        self.db.scheme(SCHEME)

    # -- entries --------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self.db.execute(UPSERT, {
            "$dir_hash": p_int64(dir_hash(d)),
            "$directory": p_utf8(d),
            "$name": p_utf8(n),
            "$meta": p_string(json.dumps(entry.to_dict()).encode()),
        })

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        rows, _ = self.db.execute(FIND, {
            "$dir_hash": p_int64(dir_hash(d)),
            "$name": p_utf8(n),
        })
        if not rows:
            return None
        return Entry.from_dict(json.loads(_cell_bytes(rows[0][0])))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        if not n:
            return
        self.db.execute(DELETE, {
            "$dir_hash": p_int64(dir_hash(d)),
            "$name": p_utf8(n),
        })

    def delete_folder_children(self, path: str) -> None:
        # dirhash partitions scatter nested directories: recursive walk
        # via the shared helper, then one range delete per directory
        _delete_subtree_by_walk(self, path)

    def delete_directory_range(self, d: str) -> None:
        self.db.execute(DELETE_CHILDREN, {
            "$dir_hash": p_int64(dir_hash(d)),
            "$directory": p_utf8(d),
        })

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        out: list[Entry] = []
        cursor, cur_inclusive = start_from, inclusive or not start_from
        while len(out) < limit:
            want = min(limit - len(out), RESULT_SET_CAP)
            op = ">=" if cur_inclusive else ">"
            rows, truncated = self.db.execute(LIST.format(op=op), {
                "$dir_hash": p_int64(dir_hash(dirpath)),
                "$directory": p_utf8(dirpath),
                "$start_name": p_utf8(cursor),
                # LIKE wildcards in names must match literally — every
                # other store escapes the same way (filerstore
                # _like_escape + ESCAPE)
                "$prefix": p_utf8(_like_escape(prefix) + "%"),
                "$limit": p_uint64(want),
            })
            for r in rows:
                out.append(Entry.from_dict(json.loads(_cell_bytes(r[1]))))
            # a full page OR a truncated result set may hide more rows;
            # continue from the last name (exclusive)
            if not rows or (len(rows) < want and not truncated):
                break
            cursor = bytes(g.pb_first(rows[-1][0], 9, b"")).decode()
            cur_inclusive = False
        return out[:limit]

    # -- kv side-channel ------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self.db.execute(KV_UPSERT, {"$k": p_utf8(key),
                                    "$v": p_string(value)})

    def kv_get(self, key: str) -> bytes | None:
        rows, _ = self.db.execute(KV_GET, {"$k": p_utf8(key)})
        return _cell_bytes(rows[0][0]) if rows else None

    def kv_delete(self, key: str) -> None:
        self.db.execute(KV_DELETE, {"$k": p_utf8(key)})

    def close(self) -> None:
        self.db.close()
