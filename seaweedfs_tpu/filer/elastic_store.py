"""Elasticsearch 7 filer store over the raw REST API.

The slot of /root/reference/weed/filer/elastic/v7/elastic_store.go:30
with plain HTTP instead of olivere/elastic — wire protocol #7 in this
tree. Same data model as the reference:

* one index per top-level directory: `.seaweedfs_<bucket>` (documents
  of deeper paths land in their bucket's index; the two-segment root
  level lives in `.seaweedfs_`),
* document id = md5(full path), with `ParentId` = md5(parent dir) for
  listing; this build adds a keyword `Name` field so listings are a
  proper term-filter + range + sort instead of client-side paging,
* KV entries in `.seaweedfs_kv_entries` with base64 values,
* deleting a bucket directory drops its whole index
  (elastic_store.go:163 deleteIndex).

Writes use `refresh=true` so the filer's read-your-writes contract
holds (the reference calls Refresh before every list instead).
"""
from __future__ import annotations

import base64
import hashlib
import json
import urllib.parse

import requests

from .entry import Entry
from .filerstore import (FilerStore, _delete_subtree_by_walk, _norm,
                         _split, register_store)

INDEX_PREFIX = ".seaweedfs_"
KV_INDEX = ".seaweedfs_kv_entries"


def _md5(s: str) -> str:
    return hashlib.md5(s.encode()).hexdigest()


def _index_of(path: str, is_directory: bool) -> str:
    parts = path.split("/")
    if is_directory and len(parts) >= 2 and parts[1]:
        return INDEX_PREFIX + parts[1].lower()
    if len(parts) > 2:
        return INDEX_PREFIX + parts[1].lower()
    return INDEX_PREFIX


@register_store("elastic")
@register_store("elastic7")
class ElasticStore(FilerStore):
    """`-store=elastic -store.host=... -store.port=9200` (optional
    -store.user/-store.password for basic auth)."""

    name = "elastic7"

    def __init__(self, host: str = "127.0.0.1", port: int = 9200,
                 user: str = "", username: str = "",
                 password: str = "", max_page: int = 10000, **_):
        self.base = f"http://{host}:{int(port)}"
        self.max_page = max_page
        self._sess = requests.Session()
        username = user or username
        if username:
            self._sess.auth = (username, password)
        # fail fast + ensure the KV index exists (initialize())
        r = self._sess.head(f"{self.base}/{KV_INDEX}", timeout=10)
        if r.status_code == 404:
            self._sess.put(f"{self.base}/{KV_INDEX}", json={
                "mappings": {"properties": {
                    "Value": {"type": "binary"}}}},
                timeout=30).raise_for_status()
        elif r.status_code >= 500:
            r.raise_for_status()

    # -- plumbing -------------------------------------------------------
    def _doc_url(self, index: str, doc_id: str) -> str:
        return (f"{self.base}/{urllib.parse.quote(index)}/_doc/"
                f"{urllib.parse.quote(doc_id)}")

    # -- entries --------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        path = _norm(entry.full_path)
        d, n = entry.dir_and_name
        doc = {"ParentId": _md5(_norm(d)), "Name": n,
               "Entry": entry.to_dict()}
        r = self._sess.put(
            self._doc_url(_index_of(path, False), _md5(path)),
            params={"refresh": "true"}, json=doc, timeout=30)
        r.raise_for_status()

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        path = _norm(path)
        r = self._sess.get(
            self._doc_url(_index_of(path, False), _md5(path)),
            timeout=30)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        doc = r.json()
        if not doc.get("found"):
            return None
        return Entry.from_dict(doc["_source"]["Entry"])

    def delete_entry(self, path: str) -> None:
        path = _norm(path)
        if path.count("/") == 1 and path != "/":
            # a bucket-level directory owns a whole index: drop it
            # (elastic_store.go:163 deleteIndex)
            r = self._sess.delete(
                f"{self.base}/{urllib.parse.quote(_index_of(path, True))}",
                timeout=60)
            if r.status_code not in (200, 404):
                r.raise_for_status()
            return
        r = self._sess.delete(
            self._doc_url(_index_of(path, False), _md5(path)),
            params={"refresh": "true"}, timeout=30)
        if r.status_code not in (200, 404):
            r.raise_for_status()

    def delete_folder_children(self, path: str) -> None:
        # ParentId-walk the subtree via the shared helper (the
        # reference lists and deletes one level, leaving recursion to
        # its filer; this tree's store contract is whole-subtree)
        _delete_subtree_by_walk(self, path, page=self.max_page)

    def delete_directory_range(self, d: str) -> None:
        # writes use refresh=true (read-your-writes), so re-listing
        # after a deleted page always converges
        while True:
            batch = self.list_directory_entries(d, limit=self.max_page)
            if not batch:
                return
            for e in batch:
                self.delete_entry(d.rstrip("/") + "/" + e.name)

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        index = _index_of(dirpath, True)
        filt: list[dict] = [{"term": {"ParentId": _md5(dirpath)}}]
        if start_from:
            op = "gte" if inclusive else "gt"
            filt.append({"range": {"Name": {op: start_from}}})
        if prefix:
            filt.append({"prefix": {"Name": prefix}})
        body = {"query": {"bool": {"filter": filt}},
                "sort": [{"Name": "asc"}],
                "size": min(limit, self.max_page)}
        r = self._sess.post(
            f"{self.base}/{urllib.parse.quote(index)}/_search",
            json=body, timeout=60)
        if r.status_code == 404:
            return []  # index not created yet: empty directory
        r.raise_for_status()
        hits = r.json().get("hits", {}).get("hits", [])
        return [Entry.from_dict(h["_source"]["Entry"]) for h in hits]

    # -- kv -------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        r = self._sess.put(
            self._doc_url(KV_INDEX, _md5(key)),
            params={"refresh": "true"},
            json={"Value": base64.b64encode(value).decode()},
            timeout=30)
        r.raise_for_status()

    def kv_get(self, key: str) -> bytes | None:
        r = self._sess.get(self._doc_url(KV_INDEX, _md5(key)),
                           timeout=30)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        doc = r.json()
        if not doc.get("found"):
            return None
        return base64.b64decode(doc["_source"]["Value"])

    def kv_delete(self, key: str) -> None:
        r = self._sess.delete(self._doc_url(KV_INDEX, _md5(key)),
                              params={"refresh": "true"}, timeout=30)
        if r.status_code not in (200, 404):
            r.raise_for_status()

    def close(self) -> None:
        self._sess.close()
