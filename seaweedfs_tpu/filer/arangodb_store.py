"""ArangoDB filer store over the raw HTTP API (documents + AQL).

The slot of /root/reference/weed/filer/arangodb/arangodb_store.go:23
with plain HTTP instead of the go-driver — REST store family #8.
Reference model preserved:

* a collection per bucket for paths under /buckets/<name> (collection
  name mangled to arango's charset), everything else in
  `seaweed_no_bucket`; KV pairs in `seaweed_kvmeta`
  (arangodb_store_bucket.go / helpers.go extractBucket),
* document _key = md5(full path), fields directory / name / meta,
* listings and subtree deletes are AQL over the `directory` field.

One deliberate divergence: `meta` is base64 text, not the reference's
[]uint64 chunking (helpers.go bytesToArray works around a go-driver
binary-marshal limitation that plain JSON doesn't have).
"""
from __future__ import annotations

import base64
import hashlib
import json

import requests

from .entry import Entry
from .filerstore import FilerStore, _norm, register_store

DEFAULT_COLLECTION = "seaweed_no_bucket"
KV_COLLECTION = "seaweed_kvmeta"
BUCKET_PREFIX = "/buckets/"


def _key_of(path: str) -> str:
    return hashlib.md5(path.encode()).hexdigest()


def _collection_of(path: str) -> str:
    """Paths INSIDE a bucket get the bucket's collection; the bucket
    directory entry itself stays in the default collection (helpers.go
    extractBucket requires >= 3 slashes for exactly this reason: the
    /buckets listing must find the bucket entries)."""
    if not path.startswith(BUCKET_PREFIX):
        return DEFAULT_COLLECTION
    bucket, _, rest = path[len(BUCKET_PREFIX):].partition("/")
    if not bucket or not rest:
        return DEFAULT_COLLECTION
    safe = "".join(c if c.isalnum() or c in "_-" else
                   f"_{ord(c):02x}" for c in bucket)
    return f"seaweedfs_{safe}"


@register_store("arangodb")
class ArangodbStore(FilerStore):
    """`-store=arangodb -store.host=... -store.port=8529
    -store.database=seaweedfs` (optional -store.user/-store.password
    for basic auth)."""

    name = "arangodb"

    def __init__(self, host: str = "127.0.0.1", port: int = 8529,
                 database: str = "seaweedfs", user: str = "",
                 username: str = "", password: str = "", **_):
        self.base = f"http://{host}:{int(port)}/_db/{database}"
        self._sess = requests.Session()
        username = user or username
        if username:
            self._sess.auth = (username, password)
        self._collections: set[str] = set()
        self._ensure_collection(KV_COLLECTION)  # fail fast too
        self._ensure_collection(DEFAULT_COLLECTION)

    # -- plumbing -------------------------------------------------------
    def _ensure_collection(self, name: str) -> None:
        if name in self._collections:
            return
        r = self._sess.post(f"{self.base}/_api/collection",
                            json={"name": name}, timeout=30)
        if r.status_code not in (200, 409):  # 409 = already exists
            r.raise_for_status()
        self._collections.add(name)

    def _aql(self, query: str, bind: dict) -> list:
        r = self._sess.post(f"{self.base}/_api/cursor",
                            json={"query": query, "bindVars": bind,
                                  "batchSize": 1000}, timeout=60)
        r.raise_for_status()
        d = r.json()
        out = list(d.get("result", []))
        while d.get("hasMore"):
            r = self._sess.put(
                f"{self.base}/_api/cursor/{d['id']}", timeout=60)
            r.raise_for_status()
            d = r.json()
            out.extend(d.get("result", []))
        return out

    # -- entries --------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        path = _norm(entry.full_path)
        d, n = entry.dir_and_name
        coll = _collection_of(path)
        self._ensure_collection(coll)
        doc = {"_key": _key_of(path), "directory": _norm(d), "name": n,
               "meta": base64.b64encode(json.dumps(
                   entry.to_dict()).encode()).decode()}
        r = self._sess.post(
            f"{self.base}/_api/document/{coll}",
            params={"overwriteMode": "replace"}, json=doc, timeout=30)
        r.raise_for_status()

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        path = _norm(path)
        r = self._sess.get(
            f"{self.base}/_api/document/{_collection_of(path)}/"
            f"{_key_of(path)}", timeout=30)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return Entry.from_dict(json.loads(
            base64.b64decode(r.json()["meta"])))

    def delete_entry(self, path: str) -> None:
        path = _norm(path)
        r = self._sess.delete(
            f"{self.base}/_api/document/{_collection_of(path)}/"
            f"{_key_of(path)}", timeout=30)
        if r.status_code not in (200, 202, 404):
            r.raise_for_status()
        # a bucket-level directory owns a whole collection: drop it
        # with the bucket (the reference's OnBucketDeletion; the
        # elastic sibling drops its index the same way) or dead
        # collections accumulate under churn
        inner = _collection_of(path + "/x")
        if inner != DEFAULT_COLLECTION and \
                _collection_of(path) == DEFAULT_COLLECTION:
            r = self._sess.delete(
                f"{self.base}/_api/collection/{inner}", timeout=30)
            if r.status_code not in (200, 404):
                r.raise_for_status()
            self._collections.discard(inner)

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        like = path.rstrip("/") + "/"
        # one AQL REMOVE per affected collection sweeps the subtree
        # (the reference's deleteFolderChildren query,
        # arangodb_store.go:268-282); names are backtick-quoted like
        # the reference's — bucket names with '-' are valid AQL
        # operators otherwise
        for coll in self._subtree_collections(path):
            self._aql(
                f"FOR d IN `{coll}` FILTER d.directory == @dir OR "
                f"STARTS_WITH(d.directory, @pfx) REMOVE d IN `{coll}`",
                {"dir": path, "pfx": like})

    def _subtree_collections(self, path: str) -> list[str]:
        if path == "/" or path == BUCKET_PREFIX.rstrip("/"):
            # the subtree may span every bucket collection
            r = self._sess.get(f"{self.base}/_api/collection",
                               timeout=30)
            r.raise_for_status()
            return sorted(
                c["name"] for c in r.json().get("result", [])
                if c["name"].startswith("seaweedfs_") or
                c["name"] == DEFAULT_COLLECTION)
        # children of a bucket DIRECTORY live in the bucket collection
        # even though the dir entry itself sits in the default one
        return [_collection_of(path.rstrip("/") + "/x")]

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        # listing DIR contents = entries whose collection is keyed by
        # a child path (the bucket dir itself lists into its bucket)
        coll = _collection_of(dirpath.rstrip("/") + "/x")
        self._ensure_collection(coll)
        q = f"FOR d IN `{coll}` FILTER d.directory == @dir"
        bind: dict = {"dir": dirpath, "limit": limit}
        if start_from:
            q += f" FILTER d.name {'>=' if inclusive else '>'} @start"
            bind["start"] = start_from
        if prefix:
            q += " FILTER STARTS_WITH(d.name, @prefix)"
            bind["prefix"] = prefix
        q += " SORT d.name ASC LIMIT @limit RETURN d"
        rows = self._aql(q, bind)
        return [Entry.from_dict(json.loads(base64.b64decode(r["meta"])))
                for r in rows]

    # -- kv -------------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        doc = {"_key": _key_of(key),
               "value": base64.b64encode(value).decode()}
        r = self._sess.post(
            f"{self.base}/_api/document/{KV_COLLECTION}",
            params={"overwriteMode": "replace"}, json=doc, timeout=30)
        r.raise_for_status()

    def kv_get(self, key: str) -> bytes | None:
        r = self._sess.get(
            f"{self.base}/_api/document/{KV_COLLECTION}/{_key_of(key)}",
            timeout=30)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return base64.b64decode(r.json()["value"])

    def kv_delete(self, key: str) -> None:
        r = self._sess.delete(
            f"{self.base}/_api/document/{KV_COLLECTION}/{_key_of(key)}",
            timeout=30)
        if r.status_code not in (200, 202, 404):
            r.raise_for_status()

    def close(self) -> None:
        self._sess.close()
