"""Redis filer store over a self-contained RESP client.

Equivalent of /root/reference/weed/filer/redis2/ (redis_store.go +
universal_redis_store.go): every entry lives at its full path as an
encoded blob, and each directory keeps a sorted set of child names so
listings are ordered server-side (ZRANGEBYLEX). No third-party redis
package: the client below speaks RESP2 over a plain socket, which is
all the store needs (SET/GET/DEL/ZADD/ZREM/ZRANGEBYLEX).

Works against real redis; tests run it against the in-process
mini-redis in tests/miniredis.py.
"""
from __future__ import annotations

import json
import socket
import threading

from .entry import Entry
from .filerstore import FilerStore, _norm, _split, register_store

DIR_LIST_SUFFIX = "\x00children"  # NUL can't appear in filer paths


class RespError(Exception):
    pass


class RespClient:
    """Minimal RESP2 client: one socket, one outstanding command."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: str = "", db: int = 0,
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._buf = b""
        self._lock = threading.Lock()
        if password:
            self.cmd("AUTH", password)
        if db:
            self.cmd("SELECT", str(db))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    # -- wire ----------------------------------------------------------
    def cmd(self, *args: str | bytes):
        out = bytearray(f"*{len(args)}\r\n".encode())
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out += f"${len(b)}\r\n".encode() + b + b"\r\n"
        with self._lock:
            self._sock.sendall(out)
            return self._read_reply()

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RespError("connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RespError("connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RespError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if t == b"*":
            n = int(rest)
            return None if n < 0 else \
                [self._read_reply() for _ in range(n)]
        raise RespError(f"bad reply type {line!r}")

    def pipeline(self, cmds: list[tuple]) -> list:
        """Send every command in one write, read every reply in order.
        Error replies come back as RespError VALUES (not raised) so
        one redirected key doesn't mask the rest of the batch — the
        cluster client retries those individually."""
        out = bytearray()
        for args in cmds:
            out += f"*{len(args)}\r\n".encode()
            for a in args:
                b = a if isinstance(a, bytes) else str(a).encode()
                out += f"${len(b)}\r\n".encode() + b + b"\r\n"
        with self._lock:
            self._sock.sendall(out)
            replies = []
            for _ in cmds:
                try:
                    replies.append(self._read_reply())
                except RespError as e:
                    replies.append(e)
            return replies

    def mget(self, keys: list[str]) -> list:
        return self.cmd("MGET", *keys) or []


@register_store("redis")
class RedisStore(FilerStore):
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: str = "", db: int = 0, **_):
        self._r = RespClient(host, int(port), password, int(db))

    @staticmethod
    def _dir_key(dirpath: str) -> str:
        return _norm(dirpath) + DIR_LIST_SUFFIX

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self._r.cmd("SET", entry.full_path,
                    json.dumps(entry.to_dict()))
        if n:
            self._r.cmd("ZADD", self._dir_key(d), "0", n)

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        raw = self._r.cmd("GET", _norm(path))
        return Entry.from_dict(json.loads(raw)) if raw else None

    def delete_entry(self, path: str) -> None:
        path = _norm(path)
        d, n = _split(path)
        self._r.cmd("DEL", path)
        self._r.cmd("DEL", self._dir_key(path))
        if n:
            self._r.cmd("ZREM", self._dir_key(d), n)

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        key = self._dir_key(path)
        children = self._r.cmd("ZRANGE", key, "0", "-1") or []
        for name in children:
            child = path.rstrip("/") + "/" + name.decode()
            self.delete_folder_children(child)
            self._r.cmd("DEL", child)
            self._r.cmd("DEL", self._dir_key(child))
        self._r.cmd("DEL", key)

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        key = self._dir_key(dirpath)
        if start_from:
            lo = (("[" if inclusive else "(") + start_from).encode()
        elif prefix:
            lo = b"[" + prefix.encode()
        else:
            lo = b"-"
        # \xff upper bound covers every utf-8 name continuation byte
        hi = b"[" + prefix.encode() + b"\xff" if prefix else b"+"
        names = self._r.cmd("ZRANGEBYLEX", key, lo, hi,
                            "LIMIT", "0", str(limit)) or []
        base = _norm(dirpath).rstrip("/")
        wanted = [nb.decode() for nb in names
                  if not prefix or nb.decode().startswith(prefix)]
        if not wanted:
            return []
        # one MGET for the whole page instead of a GET per child — on a
        # 100k-entry directory the per-name round trips were the cost,
        # not redis (whose sorted sets are already skiplists; the
        # reference's redis3 chunked ItemList solves a cluster-slot
        # concern this single-keyspace store doesn't have)
        raws = self._r.mget([f"{base}/{n}" for n in wanted])
        out: list[Entry] = []
        for raw in raws:
            if raw is not None:
                out.append(Entry.from_dict(json.loads(raw)))
        return out

    def kv_put(self, key: str, value: bytes) -> None:
        self._r.cmd("SET", "kv\x00" + key, value)

    def kv_get(self, key: str) -> bytes | None:
        v = self._r.cmd("GET", "kv\x00" + key)
        return bytes(v) if v is not None else None

    def kv_delete(self, key: str) -> None:
        self._r.cmd("DEL", "kv\x00" + key)

    def close(self) -> None:
        self._r.close()
