"""Chunk-overlap algebra: resolve a chunk list into visible intervals.

Equivalent of /root/reference/weed/filer/filechunks.go:183-307
(NonOverlappingVisibleIntervals / ViewFromChunks) and
filechunk_manifest.go (manifest chunks compressing huge chunk lists).
Later-modified chunks shadow earlier ones wherever they overlap.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Callable

from .entry import FileChunk

# A file with more than this many chunks gets its chunk list folded into
# manifest chunks stored on volume servers (filechunk_manifest.go
# ManifestBatch).
MANIFEST_BATCH = 1000


@dataclass
class VisibleInterval:
    """A [start, stop) range of the file served by one chunk."""
    start: int
    stop: int
    fid: str
    mtime_ns: int
    offset_in_chunk: int  # where `start` falls inside the chunk's data
    chunk_size: int
    is_compressed: bool = False
    cipher_key: bytes = b""


@dataclass
class ChunkView:
    """A read instruction: fetch view_size bytes at offset_in_chunk of
    chunk `fid`, place them at view_offset of the file. A non-empty
    cipher_key means the stored bytes are AES-GCM ciphertext: readers
    must fetch the WHOLE chunk, decrypt, then slice (a ranged read of
    ciphertext is undecryptable)."""
    fid: str
    offset_in_chunk: int
    view_size: int
    view_offset: int
    is_compressed: bool = False
    cipher_key: bytes = b""


def non_overlapping_visible_intervals(
        chunks: list[FileChunk]) -> list[VisibleInterval]:
    """Resolve overlaps: chunks applied in mtime order, later wins."""
    visibles: list[VisibleInterval] = []
    for c in sorted(chunks, key=lambda c: (c.mtime_ns, c.fid)):
        visibles = _insert(visibles, c)
    return visibles


def _insert(visibles: list[VisibleInterval],
            c: FileChunk) -> list[VisibleInterval]:
    start, stop = c.offset, c.offset + c.size
    out: list[VisibleInterval] = []
    for v in visibles:
        if v.stop <= start or v.start >= stop:
            out.append(v)
            continue
        if v.start < start:  # left remnant survives
            out.append(VisibleInterval(
                v.start, start, v.fid, v.mtime_ns, v.offset_in_chunk,
                v.chunk_size, v.is_compressed, v.cipher_key))
        if v.stop > stop:  # right remnant survives
            out.append(VisibleInterval(
                stop, v.stop, v.fid, v.mtime_ns,
                v.offset_in_chunk + (stop - v.start), v.chunk_size,
                v.is_compressed, v.cipher_key))
    out.append(VisibleInterval(start, stop, c.fid, c.mtime_ns, 0, c.size,
                               c.is_compressed, c.cipher_key))
    out.sort(key=lambda v: v.start)
    return out


def view_from_chunks(chunks: list[FileChunk], offset: int = 0,
                     size: int | None = None) -> list[ChunkView]:
    """Chunk views covering [offset, offset+size) of the file
    (weed/filer/filechunks.go ViewFromChunks)."""
    visibles = non_overlapping_visible_intervals(chunks)
    stop = (1 << 62) if size is None else offset + size
    views: list[ChunkView] = []
    for v in visibles:
        s, e = max(offset, v.start), min(stop, v.stop)
        if s < e:
            views.append(ChunkView(
                fid=v.fid, offset_in_chunk=s - v.start + v.offset_in_chunk,
                view_size=e - s, view_offset=s,
                is_compressed=v.is_compressed, cipher_key=v.cipher_key))
    return views


def compact_file_chunks(
        chunks: list[FileChunk]
) -> tuple[list[FileChunk], list[FileChunk]]:
    """Split into (still-visible, garbage) chunks
    (weed/filer/filechunks.go CompactFileChunks)."""
    live_fids = {v.fid for v in non_overlapping_visible_intervals(chunks)}
    compacted = [c for c in chunks if c.fid in live_fids]
    garbage = [c for c in chunks if c.fid not in live_fids]
    return compacted, garbage


def etag_chunks(chunks: list[FileChunk]) -> str:
    """ETag from per-chunk md5s (weed/filer/filechunks.go ETagChunks)."""
    if not chunks:
        return hashlib.md5(b"").hexdigest()
    if len(chunks) == 1:
        return chunks[0].etag
    joined = b"".join(bytes.fromhex(c.etag) for c in chunks if c.etag)
    return f"{hashlib.md5(joined).hexdigest()}-{len(chunks)}"


# -- manifest chunks ----------------------------------------------------
# For files with huge chunk lists the list itself is stored as data on
# volume servers, and the entry keeps only small "manifest" chunks
# (filechunk_manifest.go maybeManifestize / ResolveChunkManifest).

def separate_manifest_chunks(
        chunks: list[FileChunk]
) -> tuple[list[FileChunk], list[FileChunk]]:
    manifests = [c for c in chunks if c.is_chunk_manifest]
    data = [c for c in chunks if not c.is_chunk_manifest]
    return manifests, data


def maybe_manifestize(
        save_fn: Callable[[bytes], str], chunks: list[FileChunk],
        batch: int = MANIFEST_BATCH) -> list[FileChunk]:
    """Fold runs of `batch` data chunks into manifest chunks. save_fn
    uploads bytes and returns the new fid — or (fid, cipher_key) when
    the payload was stored encrypted (the key lands on the manifest
    chunk so resolve_chunk_manifest can decrypt it)."""
    manifests, data = separate_manifest_chunks(chunks)
    if len(data) < batch:
        return chunks
    out = list(manifests)
    i = 0
    while i + batch <= len(data):
        group = data[i:i + batch]
        payload = json.dumps(
            {"chunks": [c.to_dict() for c in group]}).encode()
        res = save_fn(payload)
        fid, ckey = res if isinstance(res, tuple) else (res, b"")
        out.append(FileChunk(
            fid=fid, offset=min(c.offset for c in group),
            size=max(c.offset + c.size for c in group)
            - min(c.offset for c in group),
            mtime_ns=max(c.mtime_ns for c in group),
            etag=hashlib.md5(payload).hexdigest(),
            is_chunk_manifest=True, cipher_key=ckey))
        i += batch
    out.extend(data[i:])
    out.sort(key=lambda c: c.offset)
    return out


def resolve_chunk_manifest(
        read_fn: Callable[[str], bytes],
        chunks: list[FileChunk]) -> list[FileChunk]:
    """Expand manifest chunks back into their data chunks. read_fn
    fetches a fid's bytes."""
    out: list[FileChunk] = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        raw = read_fn(c.fid)
        if c.cipher_key:
            from ..utils import cipher as _cipher

            raw = _cipher.decrypt(raw, c.cipher_key)
        payload = json.loads(raw)
        nested = [FileChunk.from_dict(d) for d in payload["chunks"]]
        out.extend(resolve_chunk_manifest(read_fn, nested))
    return out
