"""etcd filer store over the official etcd v3 HTTP/JSON gateway.

The reference's etcd store (/root/reference/weed/filer/etcd/
etcd_store.go) rides clientv3 gRPC; etcd ships a first-party HTTP/JSON
gateway for the same v3 KV API (grpc-gateway: POST /v3/kv/put,
/v3/kv/range, /v3/kv/deleterange with base64 keys — the /v3 path since
etcd 3.4; older 3.x used /v3alpha//v3beta), which this store speaks
directly — a REAL wire protocol against a real etcd, with zero client
SDK (same in-tree-protocol approach as the redis RESP store).

Key layout (etcd ranges are lexicographic over bytes):
  E<dir>\\x00<name>  -> entry JSON   (\\x00 sorts before every path
                                     char, so a directory's children
                                     form one contiguous range that
                                     CANNOT collide with deeper paths)
  K<key>             -> kv side-channel value
"""
from __future__ import annotations

import base64
import json

from ..rpc.httpclient import session
from .entry import Entry
from .filerstore import FilerStore, _norm, _split, register_store

SEP = "\x00"


def _b64(s: bytes) -> str:
    return base64.b64encode(s).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _prefix_end(prefix: bytes) -> bytes:
    """etcd range_end for 'every key with this prefix': the prefix with
    its last byte incremented (the gateway's getPrefix)."""
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] < 0xFF:
            p[i] += 1
            return bytes(p[:i + 1])
    return b"\x00"  # all-0xff prefix: range to the keyspace end


@register_store("etcd")
class EtcdStore(FilerStore):
    """`-store=etcd -store.host=... -store.port=2379`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2379,
                 password: str = "", user: str = "root", **_):
        self.base = f"http://{host}:{int(port)}/v3"
        self._user = user
        self._password = password
        self._headers: dict = {}
        if password:
            self._authenticate()
        # fail fast on a wrong endpoint, like the reference's
        # clientv3.New + initial status rpc
        self._call("kv/range", {"key": _b64(b"\x00"), "limit": 1})

    def _authenticate(self) -> None:
        """v3/auth/authenticate: etcd simple tokens EXPIRE (default
        300s TTL) — callers re-auth on token rejection, not just once
        at startup."""
        r = session().post(f"{self.base}/auth/authenticate",
                          json={"name": self._user,
                                "password": self._password}, timeout=10)
        r.raise_for_status()
        self._headers = {"Authorization": r.json()["token"]}

    def _call(self, path: str, body: dict) -> dict:
        for attempt in (0, 1):
            r = session().post(f"{self.base}/{path}", json=body,
                              headers=self._headers, timeout=30)
            if r.status_code < 300:
                return r.json()
            if attempt == 0 and self._password and \
                    ("invalid auth token" in r.text
                     or r.status_code == 401):
                self._authenticate()
                continue
            raise IOError(f"etcd {path}: {r.status_code} {r.text[:200]}")

    # -- entries --------------------------------------------------------
    @staticmethod
    def _entry_key(dirpath: str, name: str) -> bytes:
        return f"E{_norm(dirpath)}{SEP}{name}".encode()

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self._call("kv/put", {
            "key": _b64(self._entry_key(d, n)),
            "value": _b64(json.dumps(entry.to_dict()).encode())})

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        got = self._call("kv/range",
                         {"key": _b64(self._entry_key(d, n))})
        kvs = got.get("kvs", [])
        if not kvs:
            return None
        return Entry.from_dict(json.loads(_unb64(kvs[0]["value"])))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        if not n:
            return
        self._call("kv/deleterange",
                   {"key": _b64(self._entry_key(d, n))})

    def delete_folder_children(self, path: str) -> None:
        # two contiguous ranges cover the subtree without touching a
        # sibling that merely shares a name prefix (/t vs /tother):
        #   E<path>\x00*  — path's DIRECT children
        #   E<path>/*     — every nested directory's entries
        norm = _norm(path)
        if norm == "/":
            # root: every entry key starts with "E/" (dirs are
            # normalized absolute), one range covers the world —
            # base+"/" would be "E//", which matches nothing
            pfx = b"E/"
            self._call("kv/deleterange", {
                "key": _b64(pfx), "range_end": _b64(_prefix_end(pfx))})
            return
        base = f"E{norm}".encode()
        for pfx in (base + SEP.encode(), base + b"/"):
            self._call("kv/deleterange", {
                "key": _b64(pfx),
                "range_end": _b64(_prefix_end(pfx))})

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        base = f"E{dirpath}{SEP}".encode()
        start = base + (prefix or start_from or "").encode()
        if start_from and (not prefix or start_from > prefix):
            start = base + start_from.encode()
        out: list[Entry] = []
        while len(out) < limit:
            got = self._call("kv/range", {
                "key": _b64(start),
                "range_end": _b64(_prefix_end(base)),
                "limit": limit - len(out) + 1,
                "sort_order": "ASCEND", "sort_target": "KEY"})
            kvs = got.get("kvs", [])
            for kv in kvs:
                # slice BYTES by the byte-length prefix, then decode —
                # slicing the decoded str by len(bytes) mangles names
                # under non-ASCII directory paths
                name = _unb64(kv["key"])[len(base):].decode()
                if prefix and not name.startswith(prefix):
                    if name > prefix:
                        return out  # past the prefix window: done
                    continue
                if start_from:
                    if name < start_from or \
                            (name == start_from and not inclusive):
                        continue
                out.append(Entry.from_dict(
                    json.loads(_unb64(kv["value"]))))
                if len(out) >= limit:
                    return out
            if not got.get("more") and len(kvs) <= limit:
                return out
            if not kvs:
                return out
            start = _unb64(kvs[-1]["key"]) + b"\x00"
        return out

    # -- kv side-channel ------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        self._call("kv/put", {"key": _b64(b"K" + key.encode()),
                              "value": _b64(value)})

    def kv_get(self, key: str) -> bytes | None:
        got = self._call("kv/range",
                         {"key": _b64(b"K" + key.encode())})
        kvs = got.get("kvs", [])
        return _unb64(kvs[0]["value"]) if kvs else None

    def kv_delete(self, key: str) -> None:
        self._call("kv/deleterange",
                   {"key": _b64(b"K" + key.encode())})
