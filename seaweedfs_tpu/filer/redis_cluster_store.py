"""Redis Cluster filer store — MOVED/ASK-aware RESP over the in-tree
client, no third-party SDK.

Equivalent of /root/reference/weed/filer/redis/redis_cluster_store.go:35
(and redis2/redis3's cluster variants), which lean on go-redis's
NewClusterClient. That client's essential behaviors are implemented
here directly, per the public Redis Cluster spec:

- key -> slot: CRC16/XMODEM mod 16384, honoring {hash tags};
- the slot map comes from CLUSTER SLOTS against any live node, and is
  rebuilt whenever a node answers -MOVED (the authoritative "your map
  is stale" signal) or a connection dies;
- -ASK redirects are one-shot: follow to the target with ASKING
  prefixed, WITHOUT touching the slot map (the slot is mid-migration);
- multi-key reads (the listing page's MGET) become per-node pipelines
  of single-key GETs — cluster redis rejects cross-slot MGET, and a
  pipelined batch preserves the one-round-trip-per-node economy.

The store schema is untouched RedisStore (entry blob at its path key,
one sorted set of child names per directory): every command it issues
is single-key, which is exactly why the reference ships a cluster
variant of this same layout.
"""
from __future__ import annotations

import random
import threading

from .filerstore import register_store
from .redis_store import RedisStore, RespClient, RespError

SLOTS = 16384


def _crc16_table():
    table = []
    for i in range(256):
        crc = i << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021 if crc & 0x8000 else crc << 1) \
                & 0xFFFF
        table.append(crc)
    return table


_CRC16 = _crc16_table()


def key_slot(key: str | bytes) -> int:
    """CRC16(key) mod 16384 with the {hash tag} rule: when the key
    contains a non-empty brace section, only that section hashes."""
    k = key.encode() if isinstance(key, str) else key
    lb = k.find(b"{")
    if lb >= 0:
        rb = k.find(b"}", lb + 1)
        if rb > lb + 1:
            k = k[lb + 1:rb]
    crc = 0
    for byte in k:
        crc = ((crc << 8) ^ _CRC16[((crc >> 8) ^ byte) & 0xFF]) & 0xFFFF
    return crc % SLOTS


class ClusterRespClient:
    """Slot-routed RESP: one keep-alive RespClient per master node."""

    MAX_REDIRECTS = 8

    def __init__(self, seeds: list[tuple[str, int]], password: str = "",
                 timeout: float = 30.0):
        self._seeds = seeds
        self._password = password
        self._timeout = timeout
        self._lock = threading.Lock()
        self._conns: dict[tuple[str, int], RespClient] = {}
        # slot -> (host, port); filled by _refresh
        self._slot_owner: list[tuple[str, int] | None] = [None] * SLOTS
        self.moved_seen = 0  # observability: redirects handled
        self._refresh()

    def close(self) -> None:
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()

    # -- connections + slot map -----------------------------------------
    def _conn(self, addr: tuple[str, int]) -> RespClient:
        with self._lock:
            c = self._conns.get(addr)
            if c is None:
                c = RespClient(addr[0], addr[1], self._password,
                               timeout=self._timeout)
                self._conns[addr] = c
            return c

    def _drop_conn(self, addr: tuple[str, int]) -> None:
        with self._lock:
            c = self._conns.pop(addr, None)
        if c is not None:
            c.close()

    def _refresh(self) -> None:
        """Rebuild the slot map from the first node that answers
        CLUSTER SLOTS; node lists are tried seeds-first then known."""
        candidates = list(self._seeds) + [
            a for a in self._slot_owner if a is not None]
        seen = set()
        for addr in candidates:
            if addr in seen:
                continue
            seen.add(addr)
            try:
                rows = self._conn(addr).cmd("CLUSTER", "SLOTS") or []
            except (RespError, OSError):
                self._drop_conn(addr)
                continue
            owner: list[tuple[str, int] | None] = [None] * SLOTS
            for row in rows:
                lo, hi, master = int(row[0]), int(row[1]), row[2]
                node = (master[0].decode()
                        if isinstance(master[0], bytes) else master[0],
                        int(master[1]))
                for s in range(lo, hi + 1):
                    owner[s] = node
            self._slot_owner = owner
            return
        raise RespError("no cluster node answered CLUSTER SLOTS")

    def _addr_for(self, key) -> tuple[str, int]:
        addr = self._slot_owner[key_slot(key)]
        return addr if addr is not None else random.choice(self._seeds)

    @staticmethod
    def _parse_redirect(msg: str) -> tuple[str, int]:
        # "MOVED 3999 127.0.0.1:7002" / "ASK 3999 127.0.0.1:7002"
        hostport = msg.split()[2]
        host, _, port = hostport.rpartition(":")
        return host, int(port)

    # -- command routing -------------------------------------------------
    def cmd(self, *args, key=None):
        """Route by args[1] (the key for every command RedisStore
        speaks); follow MOVED (with a map rebuild) and ASK (one-shot)
        up to MAX_REDIRECTS, and retry once through a fresh
        connection when a node drops."""
        k = key if key is not None else args[1]
        addr = self._addr_for(k)
        asking = False
        last = None
        for _ in range(self.MAX_REDIRECTS):
            try:
                conn = self._conn(addr)
                if asking:
                    # ASKING + the command must be one locked exchange:
                    # a concurrent thread's command on this shared conn
                    # would otherwise consume the one-shot grant
                    asking = False
                    reply = conn.pipeline([("ASKING",), args])[1]
                    if isinstance(reply, RespError):
                        raise reply
                    return reply
                return conn.cmd(*args)
            except RespError as e:
                msg = str(e)
                if msg.startswith("MOVED "):
                    self.moved_seen += 1
                    self._refresh()  # MOVED = the whole map is stale
                    # the redirect target is authoritative for THIS
                    # slot even when the refreshed node's view lags
                    addr = self._parse_redirect(msg)
                    self._slot_owner[key_slot(k)] = addr
                    continue
                if msg.startswith("ASK "):
                    addr = self._parse_redirect(msg)
                    asking = True
                    continue
                raise
            except OSError as e:
                self._drop_conn(addr)
                self._refresh()
                addr = self._addr_for(k)
                last = e
        raise RespError(f"redirect loop for {k!r} (last={last})")

    def mget(self, keys: list[str]) -> list:
        """Cross-slot MGET replacement: pipeline single-key GETs per
        owning node, then patch up any redirected stragglers
        individually."""
        by_addr: dict[tuple[str, int], list[int]] = {}
        for i, k in enumerate(keys):
            by_addr.setdefault(self._addr_for(k), []).append(i)
        out: list = [None] * len(keys)
        for addr, idxs in by_addr.items():
            try:
                replies = self._conn(addr).pipeline(
                    [("GET", keys[i]) for i in idxs])
            except OSError:
                self._drop_conn(addr)
                self._refresh()
                replies = [RespError("retry")] * len(idxs)
            for i, rep in zip(idxs, replies):
                if isinstance(rep, RespError):
                    out[i] = self.cmd("GET", keys[i])  # full redirect path
                else:
                    out[i] = rep
        return out


@register_store("redis_cluster")
class RedisClusterStore(RedisStore):
    """`-store redis_cluster -store.host host1:port1,host2:port2`.
    Same keyspace schema as the single-node store; only the transport
    changes (redis_cluster_store.go keeps the same universal layout)."""

    def __init__(self, host: str = "127.0.0.1:7000", port: int = 0,
                 password: str = "", **_):
        seeds = []
        for part in str(host).split(","):
            part = part.strip()
            if not part:
                continue
            h, _, p = part.rpartition(":")
            seeds.append((h or "127.0.0.1", int(p)))
        if not seeds and port:
            seeds = [("127.0.0.1", int(port))]
        if not seeds:
            raise ValueError(
                "redis_cluster needs -store.host host:port[,host:port…]")
        self._r = ClusterRespClient(seeds, password)
