"""Minimal MySQL client protocol implementation (stdlib only).

Implemented from the public MySQL client/server protocol docs for the
mysql filer store — wire protocol #5 in this tree (after redis RESP,
etcd v3, MongoDB OP_MSG, cassandra CQL v4); the reference reaches
MySQL through go-sql-driver/mysql
(/root/reference/weed/filer/mysql/mysql_store.go:14).

Scope: HandshakeV10 + HandshakeResponse41 with mysql_native_password,
COM_QUERY text protocol with client-side parameter interpolation
(go-sql-driver's interpolateParams=true approach — every value is
escaped into the statement text, so the text protocol carries the
whole conversation), OK/ERR/resultset parsing with EOF framing
(CLIENT_DEPRECATE_EOF intentionally not negotiated).

Exposes a DB-API-ish surface (connect / cursor / execute / fetchall /
description / commit) — exactly what AbstractSqlStore consumes.
"""
from __future__ import annotations

import hashlib
import socket
import struct

CLIENT_LONG_PASSWORD = 0x1
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000


class MysqlError(IOError):
    def __init__(self, errno: int, message: str):
        super().__init__(f"mysql error {errno}: {message}")
        self.errno = errno


def native_password_token(password: str, nonce: bytes) -> bytes:
    """SHA1(pass) XOR SHA1(nonce + SHA1(SHA1(pass))) —
    the mysql_native_password scramble."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def escape_literal(v) -> str:
    """Value -> MySQL SQL literal (the client-side interpolation).
    Bytes go as hex literals (X'..') — charset-independent, unlike
    quoted binary whose high bytes would be mangled by the connection
    charset."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return "X'" + bytes(v).hex() + "'"
    if isinstance(v, str):
        return "'" + _escape_str(v) + "'"
    raise TypeError(f"unsupported SQL value type {type(v)}")


_ESCAPES = {"\x00": "\\0", "\n": "\\n", "\r": "\\r", "\x1a": "\\Z",
            "'": "\\'", "\\": "\\\\", '"': '\\"'}


def _escape_str(s: str) -> str:
    return "".join(_ESCAPES.get(ch, ch) for ch in s)


def _lenenc(buf: bytes, at: int) -> tuple[int | None, int]:
    """Length-encoded integer -> (value, next offset); 0xFB = NULL."""
    first = buf[at]
    if first < 0xFB:
        return first, at + 1
    if first == 0xFB:
        return None, at + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, at + 1)[0], at + 3
    if first == 0xFD:
        return int.from_bytes(buf[at + 1:at + 4], "little"), at + 4
    return struct.unpack_from("<Q", buf, at + 1)[0], at + 9


class Cursor:
    def __init__(self, conn: "MysqlConnection"):
        self._conn = conn
        self.description = None
        self._rows: list = []

    def execute(self, sql: str, args: tuple = ()) -> None:
        if args:
            sql = sql % tuple(escape_literal(a) for a in args)
        cols, rows = self._conn.query(sql)
        self.description = [(c, None, None, None, None, None, None)
                            for c in cols] if cols else None
        self._rows = rows

    def fetchall(self) -> list:
        return self._rows

    def close(self) -> None:
        pass


class MysqlConnection:
    """One authenticated connection, autocommit on."""

    def __init__(self, host: str, port: int = 3306, user: str = "root",
                 password: str = "", database: str = "",
                 timeout: float = 30.0):
        self._sock = socket.create_connection((host, int(port)), timeout)
        self._seq = 0
        self._handshake(user, password, database)

    # -- packet framing -------------------------------------------------
    def _send(self, payload: bytes) -> None:
        # payloads >= 16MB-1 are split into 0xFFFFFF chunks, terminated
        # by a shorter (possibly empty) packet — protocol framing rule
        at = 0
        while True:
            chunk = payload[at:at + 0xFFFFFF]
            hdr = len(chunk).to_bytes(3, "little") + bytes([self._seq])
            self._seq = (self._seq + 1) & 0xFF
            self._sock.sendall(hdr + chunk)
            at += len(chunk)
            if len(chunk) < 0xFFFFFF:
                return

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            piece = self._sock.recv(n - len(out))
            if not piece:
                raise IOError("mysql connection closed")
            out += piece
        return out

    def _recv(self) -> bytes:
        out = b""
        while True:
            hdr = self._recv_exact(4)
            length = int.from_bytes(hdr[:3], "little")
            self._seq = (hdr[3] + 1) & 0xFF
            out += self._recv_exact(length)
            if length < 0xFFFFFF:  # 0xFFFFFF = continuation follows
                return out

    # -- handshake ------------------------------------------------------
    def _handshake(self, user: str, password: str, database: str) -> None:
        greet = self._recv()
        if greet and greet[0] == 0xFF:
            raise self._err(greet)
        if greet[0] != 10:
            raise IOError(f"unsupported handshake protocol {greet[0]}")
        at = greet.index(b"\x00", 1) + 1  # server version
        at += 4  # thread id
        nonce = greet[at:at + 8]
        at += 8 + 1  # auth-data-1 + filler
        at += 2 + 1 + 2 + 2  # caps-low, charset, status, caps-high
        auth_len = greet[at] if at < len(greet) else 0
        at += 1 + 10  # auth data len + reserved
        if auth_len:
            # part 2 is max(13, auth_len - 8) incl. trailing NUL
            part2 = greet[at:at + max(13, auth_len - 8)]
            nonce += part2.rstrip(b"\x00")[:12]
        caps = (CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41 |
                CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION |
                CLIENT_PLUGIN_AUTH |
                (CLIENT_CONNECT_WITH_DB if database else 0))
        token = native_password_token(password, nonce[:20])
        # charset 45 = utf8mb4_general_ci: 4-byte UTF-8 (emoji and
        # non-BMP CJK in file names) must survive the connection
        resp = struct.pack("<IIB23x", caps, 1 << 24, 45)
        resp += user.encode() + b"\x00"
        resp += bytes([len(token)]) + token
        if database:
            resp += database.encode() + b"\x00"
        resp += b"mysql_native_password\x00"
        self._send(resp)
        ok = self._recv()
        if ok and ok[0] == 0xFF:
            raise self._err(ok)
        if ok and ok[0] == 0xFE:
            raise IOError("server requested an auth method switch; "
                          "only mysql_native_password is supported")

    @staticmethod
    def _err(payload: bytes) -> MysqlError:
        errno = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:]
        if msg[:1] == b"#":
            msg = msg[6:]  # sql state marker + 5 chars
        return MysqlError(errno, msg.decode("utf-8", "replace"))

    # -- text protocol --------------------------------------------------
    def query(self, sql: str) -> tuple[list[str], list[list]]:
        """COM_QUERY -> (column names, rows of bytes|None)."""
        self._seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._recv()
        if first[0] == 0xFF:
            raise self._err(first)
        if first[0] == 0x00:  # OK packet: no result set
            return [], []
        n_cols, _ = _lenenc(first, 0)
        cols = []
        for _ in range(n_cols):
            col = self._recv()
            # column definition: catalog, schema, table, org_table,
            # name, org_name (all lenenc strings)
            at = 0
            name = b""
            for field_i in range(5):
                ln, at = _lenenc(col, at)
                if field_i == 4:
                    name = col[at:at + (ln or 0)]
                at += ln or 0
            cols.append(name.decode())
        eof = self._recv()
        if eof[0] == 0xFF:  # server may still error at this point
            raise self._err(eof)
        if eof[0] != 0xFE:
            raise IOError("expected EOF after column definitions")
        rows: list[list] = []
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                return cols, rows
            if pkt[0] == 0xFF:
                raise self._err(pkt)
            at = 0
            row: list = []
            for _ in range(n_cols):
                ln, at = _lenenc(pkt, at)
                if ln is None:
                    row.append(None)
                else:
                    row.append(pkt[at:at + ln])
                    at += ln
            rows.append(row)

    # -- DB-API surface -------------------------------------------------
    def cursor(self) -> Cursor:
        return Cursor(self)

    def commit(self) -> None:
        pass  # autocommit; AbstractSqlStore calls this after each op

    def close(self) -> None:
        try:
            self._sock.sendall(b"\x01\x00\x00\x00\x01")  # COM_QUIT
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
