"""The Filer: a directory namespace over a pluggable metadata store.

Equivalent of /root/reference/weed/filer/filer.go:36 (Filer) —
path -> Entry CRUD with parent-directory auto-creation (CreateEntry
filer.go:197), TTL expiry on read/list, recursive delete that hands the
dead chunks back for volume-server deletion
(filer_delete_entry.go), and rename via move.  Every mutation is
appended to the metadata event log (filer_notify.go).
"""
from __future__ import annotations

import base64
import fnmatch
import json
import threading
import time
import uuid
from dataclasses import replace
from typing import Callable

from .entry import DIR_MODE_FLAG, Entry, FileChunk
from .event_log import MetaEventLog
from .filerstore import FilerStore, make_store

LIST_BATCH = 1024


class DirectoryNotEmptyError(OSError):
    pass


def norm_path(path: str) -> str:
    out = "/" + "/".join(p for p in path.split("/") if p and p != ".")
    return out


def _split_pattern(pattern: str) -> tuple[str, str]:
    """Literal head / glob tail of a name pattern (filer_search.go:11):
    the head feeds the store's prefix index, the tail is fnmatch'd."""
    for i, ch in enumerate(pattern):
        if ch in "*?[":
            return pattern[:i], pattern[i:]
    return pattern, ""  # wildcard-less: pure literal, exact match


class _TrackedRLock:
    """RLock with a portable is-held-by-this-thread probe, for
    interpreters whose RLock lacks the private _is_owned API. The
    deferred chunk-free drain depends on that probe to never run
    deletions while a metadata lock is held (see _drain_freed)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._owner: int | None = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._depth += 1
        return got

    def release(self):
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()


def _owned_rlock():
    lock = threading.RLock()
    return lock if hasattr(lock, "_is_owned") else _TrackedRLock()


class Filer:
    def __init__(self, store: FilerStore | str = "memory",
                 on_delete_chunks: Callable[[list[FileChunk]], None]
                 | None = None, signature: int = 0, **store_kwargs):
        self.store = (store if isinstance(store, FilerStore)
                      else make_store(store, **store_kwargs))
        self.meta_log = MetaEventLog(signature=signature)
        self.on_delete_chunks = on_delete_chunks or (lambda chunks: None)
        # lock order: _mutation_lock (all metadata writes) outer,
        # _hardlink_lock (shared-record read-modify-write) inner. Both
        # reentrant: the TTL-expiry path runs inside readers that a
        # mutation may invoke on its own thread.
        self._mutation_lock = _owned_rlock()
        self._hardlink_lock = _owned_rlock()
        # chunks freed by TTL expiry hit volume servers over HTTP; when
        # expiry fires inside a locked mutation the frees are queued
        # here and drained once the locks are released
        self._free_lock = threading.Lock()
        self._free_queue: list[FileChunk] = []
        # known-directory cache for _ensure_parents (mutation-lock
        # protected; see _invalidate_dir)
        self._parent_cache: set[str] = set()

    # -- hard links (filerstore_hardlink.go) ----------------------------
    # Linked entries share one content record in the store's KV space:
    # {"count": refs, "chunks": [...]}. Entries carry hard_link_id and
    # no chunks of their own; reads resolve through the record, so a
    # write via any name is visible through all names, and the chunks
    # are reclaimed only when the last name goes away.
    HARDLINK_KV_PREFIX = "hardlink/"

    def _hardlink_record(self, hid: str) -> dict | None:
        raw = self.store.kv_get(self.HARDLINK_KV_PREFIX + hid)
        return json.loads(raw) if raw else None

    def _put_hardlink_record(self, hid: str, rec: dict) -> None:
        self.store.kv_put(self.HARDLINK_KV_PREFIX + hid,
                          json.dumps(rec).encode())

    def _resolve_hardlink(self, e: Entry) -> Entry:
        if e.hard_link_id and not e.is_directory:
            rec = self._hardlink_record(e.hard_link_id)
            if rec is not None:
                e.chunks = [FileChunk.from_dict(c)
                            for c in rec.get("chunks", [])]
                if rec.get("content"):
                    e.content = base64.b64decode(rec["content"])
                # version stamp: a later save of this entry proves it
                # saw THIS content (guards metadata-only saves built
                # from a stale read from clobbering newer writes)
                e.extended["hardlink_ver"] = str(rec.get("ver", 0))
        return e

    def link(self, src_path: str, dst_path: str,
             signatures: list[int] | None = None) -> Entry:
        """Create a hard link: dst becomes another name for src's
        content (mount link(), filer_pb AppendToEntry-style sharing)."""
        src_path, dst_path = norm_path(src_path), norm_path(dst_path)
        with self._mutation_lock, self._hardlink_lock:
            # src is (re)read under the lock: two concurrent first-links
            # must not each mint their own record for the same file
            src = self.find_entry(src_path)
            if src is None:
                raise FileNotFoundError(src_path)
            if src.is_directory:
                raise IsADirectoryError(f"cannot hard-link a "
                                        f"directory: {src_path}")
            if self.find_entry(dst_path) is not None:
                raise FileExistsError(dst_path)
            if not src.hard_link_id:
                hid = uuid.uuid4().hex
                rec0 = {"count": 1,
                        "chunks": [c.to_dict() for c in src.chunks]}
                if src.content:
                    # inline small file: its bytes live in the shared
                    # record so every NAME serves them
                    rec0["content"] = base64.b64encode(
                        src.content).decode()
                self._put_hardlink_record(hid, rec0)
                old_src = replace(src)
                src.hard_link_id = hid
                self.store.insert_entry(
                    replace(src, chunks=[], content=b""))
                # src changed shape: event consumers (meta backups,
                # other mounts) must learn its hard_link_id
                d, _ = src.dir_and_name
                self.meta_log.append(d, old_src, src, signatures)
            rec = self._hardlink_record(src.hard_link_id)
            rec["count"] = int(rec.get("count", 1)) + 1
            self._put_hardlink_record(src.hard_link_id, rec)
            # dst insert stays under the lock: a racing link() to the
            # same dst must hit FileExistsError, not clobber-and-leak
            dst = Entry(full_path=dst_path, mode=src.mode, uid=src.uid,
                        gid=src.gid, mime=src.mime, md5=src.md5,
                        collection=src.collection,
                        replication=src.replication,
                        ttl_sec=src.ttl_sec,
                        hard_link_id=src.hard_link_id)
            self._ensure_parents(dst_path)
            self.store.insert_entry(replace(dst, chunks=[]))
            dst = self._resolve_hardlink(dst)
            d, _ = dst.dir_and_name
            # log the RESOLVED entry (subscribers must see real
            # chunks) INSIDE the lock: a racing delete of dst would
            # otherwise log its delete first and subscribers would
            # apply create-after-delete, resurrecting the name
            self.meta_log.append(d, None, dst, signatures)
        self._drain_freed()
        return dst

    def _hardlink_unref(self, e: Entry) -> list[FileChunk]:
        """Drop one reference; returns the chunks to reclaim when this
        was the last name."""
        with self._hardlink_lock:
            rec = self._hardlink_record(e.hard_link_id)
            if rec is None:
                return []
            rec["count"] = int(rec.get("count", 1)) - 1
            if rec["count"] <= 0:
                self.store.kv_delete(
                    self.HARDLINK_KV_PREFIX + e.hard_link_id)
                return [FileChunk.from_dict(c)
                        for c in rec.get("chunks", [])]
            self._put_hardlink_record(e.hard_link_id, rec)
            return []

    def _expire(self, e: Entry) -> None:
        """Drop a TTL-expired name; a hardlinked name must release its
        record reference or the shared chunks leak forever. Frees are
        queued — this can run inside a locked mutation's read."""
        self.store.delete_entry(e.full_path)
        if e.is_directory:
            # a cached parent that expired must be re-created by the
            # next write under it
            self._invalidate_dir(e.full_path)
        if e.hard_link_id and not e.is_directory:
            freed = self._hardlink_unref(e)
            if freed:
                with self._free_lock:
                    self._free_queue.extend(freed)

    def _drain_freed(self) -> None:
        """Run queued chunk deletions — only once no metadata lock is
        held by this thread (mutations drain on their way out)."""
        if self._mutation_lock._is_owned() or \
                self._hardlink_lock._is_owned():
            return
        with self._free_lock:
            chunks, self._free_queue = self._free_queue, []
        if chunks:
            self.on_delete_chunks(chunks)

    # -- reads ----------------------------------------------------------
    def find_entry(self, path: str) -> Entry | None:
        path = norm_path(path)
        if path == "/":
            return Entry(full_path="/", mode=0o775 | DIR_MODE_FLAG)
        e = self.store.find_entry(path)
        if e is not None and e.is_expired():
            self._expire(e)
            self._drain_freed()
            return None
        return self._resolve_hardlink(e) if e is not None else None

    def list_entries(self, dirpath: str, start_from: str = "",
                     inclusive: bool = False, limit: int = LIST_BATCH,
                     prefix: str = "", name_pattern: str = "",
                     name_pattern_exclude: str = "") -> list[Entry]:
        """`name_pattern`/`name_pattern_exclude` are shell globs applied
        over the page stream (filer_search.go:24 ListDirectoryEntries):
        the literal head of the pattern becomes the store prefix filter
        (splitPattern, filer_search.go:11) and the wildcard tail is
        glob-matched against the remainder, paging past misses so a
        page of non-matches can't be misread as end-of-directory.
        Divergence from the reference: a wildcard-less pattern is an
        exact-name filter here (the reference silently ignores it)."""
        dirpath = norm_path(dirpath)
        pat_prefix, rest = _split_pattern(name_pattern)
        if pat_prefix:
            prefix = pat_prefix
        out, now = [], time.time()
        # TTL-expired entries are filtered AFTER the raw page, so keep
        # paging until `limit` live entries are in hand or the raw
        # stream truly ends — otherwise a page with expired entries
        # under-fills and callers misread it as end-of-directory
        last, first = start_from, True
        while len(out) < limit:
            want = limit - len(out)
            batch = self.store.list_directory_entries(
                dirpath, last, inclusive if first else False, want,
                prefix)
            for e in batch:
                if e.is_expired(now):
                    self._expire(e)
                    continue
                name = e.name
                if name_pattern_exclude and fnmatch.fnmatchcase(
                        name, name_pattern_exclude):
                    continue
                if name_pattern and not fnmatch.fnmatchcase(
                        name[len(pat_prefix):], rest):
                    continue
                out.append(self._resolve_hardlink(e))
            if len(batch) < want:
                break
            last, first = batch[-1].name, False
        self._drain_freed()
        return out

    def iter_tree(self, dirpath: str):
        """Depth-first generator over a subtree, expired entries
        skipped. Pagination is driven by the RAW store batch size —
        list_entries filters expired entries post-page, so its result
        length cannot signal end-of-directory."""
        dirpath = norm_path(dirpath)
        start, now = "", time.time()
        while True:
            batch = self.store.list_directory_entries(
                dirpath, start_from=start, limit=LIST_BATCH)
            for e in batch:
                if e.is_expired(now):
                    continue
                yield self._resolve_hardlink(e)
                if e.is_directory:
                    yield from self.iter_tree(e.full_path)
            if len(batch) < LIST_BATCH:
                return
            start = batch[-1].name

    # -- writes ---------------------------------------------------------
    def create_entry(self, entry: Entry,
                     signatures: list[int] | None = None,
                     gc_old_chunks: bool = False) -> Entry:
        """Insert/overwrite one entry. gc_old_chunks=True also reclaims
        the replaced entry's chunks that the new entry dropped —
        computed inside the mutation lock so two concurrent overwrites
        of one path can't both snapshot the same predecessor and leak
        the loser's chunks (the find+create+GC TOCTOU)."""
        entry.full_path = norm_path(entry.full_path)
        if entry.full_path == "/":
            return entry
        freed: list[FileChunk] = []
        with self._mutation_lock:
            self._ensure_parents(entry.full_path)
            old = self.store.find_entry(entry.full_path)
            if old is not None and old.is_directory \
                    and not entry.is_directory:
                raise IsADirectoryError(entry.full_path)
            if old is not None and old.hard_link_id and \
                    entry.hard_link_id != old.hard_link_id:
                # this NAME now points elsewhere: drop one reference on
                # the old record; chunks free only at the last name
                freed.extend(self._hardlink_unref(old))
            logged = entry
            if entry.hard_link_id and not entry.is_directory:
                # content lives in the shared record: a write through
                # any name must be visible through every name. A save
                # whose hardlink_ver doesn't match saw STALE content
                # (chmod built from an old read racing a writer): its
                # metadata lands but its chunk list is ignored — it
                # must not resurrect freed chunks or delete newer ones.
                try:
                    caller_ver = int(
                        entry.extended.pop("hardlink_ver"))
                except (KeyError, TypeError, ValueError):
                    caller_ver = None
                with self._hardlink_lock:
                    rec = self._hardlink_record(entry.hard_link_id) \
                        or {"count": 1, "ver": 0, "chunks": []}
                    current = int(rec.get("ver", 0))
                    # ver 0 = record never written (fresh link target);
                    # an empty chunk list at ver>=1 is a real truncate
                    # and must NOT readmit stale saves
                    accept = caller_ver == current or current == 0
                    if accept:
                        keep = {c.fid for c in entry.chunks}
                        freed.extend(
                            FileChunk.from_dict(c)
                            for c in rec.get("chunks", [])
                            if c.get("fid") not in keep)
                        rec["chunks"] = [c.to_dict()
                                         for c in entry.chunks]
                        # the record holds EITHER chunks or inline
                        # content — a chunked rewrite must not leave
                        # stale inline bytes shadowing it (reads
                        # prefer content)
                        if entry.content:
                            rec["content"] = base64.b64encode(
                                entry.content).decode()
                        else:
                            rec.pop("content", None)
                        rec["ver"] = current + 1
                        self._put_hardlink_record(entry.hard_link_id,
                                                  rec)
                    else:
                        # rejected: free NOTHING here — the discarded
                        # list may be a stale reader's historical view
                        # (those chunks were already reclaimed when
                        # they were replaced, and must not be "freed"
                        # again) or a losing writer's fresh uploads
                        # (left for volume.fsck's orphan sweep). The
                        # event log must carry what the record ACTUALLY
                        # contains, not the discarded list.
                        logged = replace(
                            logged,
                            chunks=[FileChunk.from_dict(c)
                                    for c in rec.get("chunks", [])],
                            content=base64.b64decode(rec["content"])
                            if rec.get("content") else b"")
                entry = replace(entry, chunks=[], content=b"")
            if gc_old_chunks and old is not None and \
                    not old.is_directory and not old.hard_link_id:
                # logged always carries the REAL new content (even for
                # hardlinked entries whose stored chunks are cleared)
                keep = {c.fid for c in logged.chunks}
                freed.extend(c for c in old.chunks
                             if c.fid not in keep)
            ed = entry.to_dict()  # built once: store encode + event
            self.store.insert_entry_encoded(entry, ed)
            d, _ = entry.dir_and_name
            # the event carries the RESOLVED shape (real chunks):
            # subscribers must not see hardlinked files as empty
            self.meta_log.append(d, old, logged, signatures,
                                 new_dict=ed if logged is entry else None)
        if freed:
            # chunk deletion does volume-server round trips: never
            # under the metadata locks
            self.on_delete_chunks(freed)
        self._drain_freed()
        return self._resolve_hardlink(entry)

    def update_entry(self, entry: Entry,
                     signatures: list[int] | None = None) -> Entry:
        return self.create_entry(entry, signatures)

    def mkdir(self, path: str, mode: int = 0o775,
              signatures: list[int] | None = None) -> Entry:
        path = norm_path(path)
        e = self.find_entry(path)
        if e is not None:
            if not e.is_directory:
                raise NotADirectoryError(path)
            return e
        return self.create_entry(
            Entry(full_path=path, mode=mode | DIR_MODE_FLAG),
            signatures=signatures)

    def _ensure_parents(self, path: str) -> None:
        # known-directory cache: bulk ingest repeats the same parent
        # chain for every entry (S3 keys under one bucket), and the
        # store round trips measured as a third of create_entry's cost.
        # Only positive knowledge is cached, under the mutation lock;
        # directory deletes/renames invalidate in _invalidate_dir.
        cache = self._parent_cache
        parts = path.strip("/").split("/")[:-1]
        cur = ""
        for p in parts:
            cur += "/" + p
            if cur in cache:
                continue
            if self.store.find_entry(cur) is None:
                ent = Entry(full_path=cur, mode=0o775 | DIR_MODE_FLAG)
                self.store.insert_entry(ent)
                d, _ = ent.dir_and_name
                self.meta_log.append(d, None, ent)
            if len(cache) >= 65536:
                cache.clear()
            cache.add(cur)

    def _invalidate_dir(self, path: str) -> None:
        """Drop `path` and everything under it from the known-directory
        cache (a deleted dir must be re-created by the next write)."""
        cache = self._parent_cache
        sub = path + "/"
        for p in [p for p in cache if p == path or p.startswith(sub)]:
            cache.discard(p)

    def delete_entry(self, path: str, recursive: bool = False,
                     delete_chunks: bool = True,
                     signatures: list[int] | None = None) -> None:
        """delete_chunks=False removes names only, leaving volume data
        alive (the reference's isDeleteData=false — used when another
        entry still references the same chunks, e.g. multipart
        completion)."""
        path = norm_path(path)
        with self._mutation_lock:
            dead = self._delete_entry_locked(path, recursive,
                                             signatures)
        if dead and delete_chunks:
            # volume-server round trips happen outside the lock
            self.on_delete_chunks(dead)
        self._drain_freed()

    def _delete_entry_locked(self, path, recursive,
                             signatures) -> list[FileChunk]:
        e = self.find_entry(path)
        if e is None:
            return []
        dead_chunks: list[FileChunk] = []
        if e.is_directory:
            # list_entries pages past TTL-expired entries internally,
            # so one live result == genuinely non-empty
            if not recursive and self.list_entries(path, limit=1):
                raise DirectoryNotEmptyError(
                    f"directory not empty: {path}")
            for sub in self.iter_tree(path):
                if not sub.is_directory:
                    if sub.hard_link_id:
                        dead_chunks.extend(self._hardlink_unref(sub))
                    else:
                        dead_chunks.extend(sub.chunks)
                d, _ = sub.dir_and_name
                self.meta_log.append(d, sub, None, signatures)
            self.store.delete_folder_children(path)
        elif e.hard_link_id:
            dead_chunks.extend(self._hardlink_unref(e))
        else:
            dead_chunks.extend(e.chunks)
        self.store.delete_entry(path)
        if e.is_directory:
            self._invalidate_dir(path)
        d, _ = e.dir_and_name
        self.meta_log.append(d, e, None, signatures)
        return dead_chunks

    def rename(self, old_path: str, new_path: str,
               signatures: list[int] | None = None) -> None:
        """Move an entry (recursively for directories) — the metadata-
        only streaming rename of filer_grpc_server_rename.go; chunks
        stay where they are."""
        old_path, new_path = norm_path(old_path), norm_path(new_path)
        if new_path == old_path or \
                new_path.startswith(old_path.rstrip("/") + "/"):
            # moving a directory into its own subtree would copy the
            # children under the new name and then delete_folder_children
            # the old tree — INCLUDING the copies (the reference filer
            # rejects this too)
            raise ValueError(
                f"cannot move {old_path} into itself ({new_path})")
        with self._mutation_lock:
            e = self.find_entry(old_path)
            if e is None:
                raise FileNotFoundError(old_path)
            if self.find_entry(new_path) is not None:
                raise FileExistsError(new_path)
            self._move(e, new_path, signatures)
        self._drain_freed()

    def _move(self, e: Entry, new_path: str,
              signatures: list[int] | None) -> None:
        old_path = e.full_path
        children = []
        if e.is_directory:
            children = list(self.iter_tree(old_path))
        moved = Entry.from_dict(e.to_dict())
        moved.full_path = new_path
        self.create_entry(moved, signatures)
        for sub in children:
            rel = sub.full_path[len(old_path):]
            sub_new = Entry.from_dict(sub.to_dict())
            sub_new.full_path = new_path + rel
            self.create_entry(sub_new, signatures)
        # delete old names only (not data)
        if e.is_directory:
            for sub in children:
                d, _ = sub.dir_and_name
                self.meta_log.append(d, sub, None, signatures)
            self.store.delete_folder_children(old_path)
            self._invalidate_dir(old_path)
        self.store.delete_entry(old_path)
        d, _ = e.dir_and_name
        self.meta_log.append(d, e, None, signatures)

    def close(self) -> None:
        self.store.close()
