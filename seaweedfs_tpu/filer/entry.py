"""Filer entry model: a path plus attributes plus a list of file chunks.

Equivalent of /root/reference/weed/filer/entry.go (Entry/Attr) and the
FileChunk message (weed/pb/filer.proto) — a file's bytes are a list of
(fid, offset, size, mtime) spans stored on volume servers; directories
are entries with no chunks and the directory mode bit set.
"""
from __future__ import annotations

import base64
import os
import time
from dataclasses import dataclass, field


@dataclass
class FileChunk:
    """One span of a file's content living at `fid` on a volume server.

    mtime_ns orders overlapping chunks: the latest write wins
    (weed/filer/filechunks.go readResolvedChunks).
    """
    fid: str
    offset: int
    size: int
    mtime_ns: int
    etag: str = ""  # hex md5 of the chunk bytes
    is_compressed: bool = False
    is_chunk_manifest: bool = False  # chunk holds a manifest, not data
    # per-chunk AES-256-GCM key (filer_pb FileChunk.cipher_key); the
    # stored bytes at `fid` are ciphertext when this is non-empty.
    # offset/size always describe the PLAINTEXT span.
    cipher_key: bytes = b""

    def to_dict(self) -> dict:
        d = {"fid": self.fid, "offset": self.offset, "size": self.size,
             "mtime_ns": self.mtime_ns}
        if self.etag:
            d["etag"] = self.etag
        if self.is_compressed:
            d["is_compressed"] = True
        if self.is_chunk_manifest:
            d["is_chunk_manifest"] = True
        if self.cipher_key:
            d["cipher_key"] = self.cipher_key.hex()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(fid=d["fid"], offset=d["offset"], size=d["size"],
                   mtime_ns=d["mtime_ns"], etag=d.get("etag", ""),
                   is_compressed=d.get("is_compressed", False),
                   is_chunk_manifest=d.get("is_chunk_manifest", False),
                   cipher_key=bytes.fromhex(d.get("cipher_key", "")))


DIR_MODE_FLAG = 0o40000  # os.S_IFDIR bit, as the reference uses os.ModeDir


@dataclass
class Entry:
    full_path: str  # always absolute, '/'-separated, no trailing slash
    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    ttl_sec: int = 0
    md5: str = ""  # hex md5 of the whole file when known
    collection: str = ""
    replication: str = ""
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict[str, str] = field(default_factory=dict)
    hard_link_id: str = ""
    symlink_target: str = ""
    # small-file bytes stored INSIDE the metadata entry instead of a
    # volume chunk (filer_pb Entry.Content — the -saveToFilerLimit /
    # ?saveInside=true path, filer_server_handlers_write_upload.go:83)
    content: bytes = b""

    def __post_init__(self):
        if not self.mtime:
            self.mtime = time.time()
        if not self.crtime:
            self.crtime = self.mtime

    @property
    def dir_and_name(self) -> tuple[str, str]:
        d, n = os.path.split(self.full_path.rstrip("/"))
        return (d or "/", n)

    @property
    def name(self) -> str:
        return self.dir_and_name[1]

    @property
    def is_directory(self) -> bool:
        return bool(self.mode & DIR_MODE_FLAG)

    @property
    def file_size(self) -> int:
        return max(total_size(self.chunks), len(self.content))

    def is_expired(self, now: float | None = None) -> bool:
        if self.ttl_sec <= 0:
            return False
        return (now or time.time()) >= self.crtime + self.ttl_sec

    def to_dict(self) -> dict:
        d = {"full_path": self.full_path, "mtime": self.mtime,
             "crtime": self.crtime, "mode": self.mode}
        for k in ("uid", "gid", "ttl_sec"):
            if getattr(self, k):
                d[k] = getattr(self, k)
        for k in ("mime", "md5", "collection", "replication",
                  "hard_link_id", "symlink_target"):
            if getattr(self, k):
                d[k] = getattr(self, k)
        if self.chunks:
            d["chunks"] = [c.to_dict() for c in self.chunks]
        if self.extended:
            d["extended"] = dict(self.extended)
        if self.content:
            d["content"] = base64.b64encode(self.content).decode()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Entry":
        return cls(
            full_path=d["full_path"], mtime=d.get("mtime", 0.0),
            crtime=d.get("crtime", 0.0), mode=d.get("mode", 0o660),
            uid=d.get("uid", 0), gid=d.get("gid", 0),
            mime=d.get("mime", ""), ttl_sec=d.get("ttl_sec", 0),
            md5=d.get("md5", ""), collection=d.get("collection", ""),
            replication=d.get("replication", ""),
            chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
            extended=d.get("extended", {}),
            hard_link_id=d.get("hard_link_id", ""),
            symlink_target=d.get("symlink_target", ""),
            content=base64.b64decode(d["content"])
            if d.get("content") else b"")


def total_size(chunks: list[FileChunk]) -> int:
    """Max extent of the chunk list (weed/filer/filechunks.go TotalSize)."""
    size = 0
    for c in chunks:
        size = max(size, c.offset + c.size)
    return size


def entry_size(entry: dict | None) -> int:
    """total_size for a JSON entry dict (the gateways' wire shape).
    File size is max(offset+size) over chunks, NOT the chunk-size sum —
    overlapping rewrites keep superseded chunks in the list. Inline
    small files carry their bytes in `content` (base64) instead."""
    d = entry or {}
    chunk_max = max((c.get("offset", 0) + c["size"]
                     for c in d.get("chunks", [])), default=0)
    if d.get("content"):
        # 4 base64 chars encode 3 bytes; padding '=' trims the tail
        raw = d["content"]
        inline = len(raw) * 3 // 4 - raw.count("=")
        return max(chunk_max, inline)
    return chunk_max
