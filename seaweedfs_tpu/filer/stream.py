"""Assemble file bytes from chunk views via volume-server reads.

Equivalent of /root/reference/weed/filer/stream.go:69-144 — turn an
entry's chunk list into ranged HTTP reads against volume servers,
with manifest resolution and a small per-reader chunk cache
(reader_cache.go's role).
"""
from __future__ import annotations

import contextvars
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable

from ..rpc.httpclient import session
from ..utils import metrics, retry

from .entry import FileChunk
from .filechunks import resolve_chunk_manifest, view_from_chunks

LookupFn = Callable[[str], str]  # fid -> full http url

# shared hedge pool for sync replica reads; sized small on purpose —
# a hedge is the exception (one slow replica), not the common path
_hedge_pool: ThreadPoolExecutor | None = None


def _hedge_pool_get() -> ThreadPoolExecutor:
    global _hedge_pool
    if _hedge_pool is None:
        _hedge_pool = ThreadPoolExecutor(max_workers=8,
                                         thread_name_prefix="hedge")
    return _hedge_pool


def _replica_urls(lookup: LookupFn, fid: str) -> list[str]:
    """All replica urls for a fid when the lookup's owner can list
    them (MasterClient / FilerServer expose lookup_file_id_urls),
    else the single url the plain lookup returns."""
    owner = getattr(lookup, "__self__", None)
    fn = getattr(owner, "lookup_file_id_urls", None)
    if fn is not None:
        return fn(fid)
    return [lookup(fid)]


def _hedged_fetch(fetch: Callable[[str], bytes], urls: list[str],
                  hedge_delay: float) -> bytes:
    """First-success-wins across the primary and (after hedge_delay,
    or immediately on primary failure) one alternate replica.  The
    tail-latency move from "The Tail at Scale": a replica that is
    slow — sick disk, GC pause, injected 30ms delay — costs at most
    hedge_delay extra, not its whole timeout."""
    pool = _hedge_pool_get()
    futs = {pool.submit(contextvars.copy_context().run, fetch, urls[0])}
    errors: list[BaseException] = []
    # phase 1: give the primary hedge_delay to answer
    done, _ = wait(futs, timeout=hedge_delay, return_when=FIRST_COMPLETED)
    for fut in done:
        exc = fut.exception()
        if exc is None:
            return fut.result()
        errors.append(exc)
        futs.discard(fut)
    # primary slow (or failed fast): fire one alternate replica
    hedge_fut = None
    if len(urls) > 1:
        metrics.counter_add("replica_read_hedges", 1)
        hedge_fut = pool.submit(
            contextvars.copy_context().run, fetch, urls[1])
        futs.add(hedge_fut)
    # phase 2: first success wins, losers are cancelled best-effort
    while futs:
        done, _ = wait(futs, return_when=FIRST_COMPLETED)
        for fut in done:
            exc = fut.exception()
            if exc is None:
                if fut is hedge_fut:
                    # win-rate vs replica_read_hedges is the tuning
                    # signal for -hedge.delay (ROADMAP open item)
                    metrics.counter_add("replica_read_hedge_wins", 1)
                for p in futs:
                    if p is not fut:
                        p.cancel()
                return fut.result()
            errors.append(exc)
            futs.discard(fut)
    raise errors[-1]


class ReaderPattern:
    """Sequential-vs-random read classifier (reader_pattern.go:17):
    a read resuming exactly where the last one stopped bumps a
    saturating counter, anything else decrements it; negative =
    random mode, where whole-chunk caching and readahead are pure
    amplification (a 4KB random read must not fetch an 8MB chunk)."""

    MODE_CHANGE_LIMIT = 3

    def __init__(self):
        self._counter = 0
        self._last_stop = 0

    def monitor(self, offset: int, size: int) -> None:
        last, self._last_stop = self._last_stop, offset + size
        if last == offset:
            if self._counter < self.MODE_CHANGE_LIMIT:
                self._counter += 1
        elif self._counter > -self.MODE_CHANGE_LIMIT:
            self._counter -= 1

    @property
    def is_random(self) -> bool:
        return self._counter < 0

    @property
    def is_streaming(self) -> bool:
        """Saturated-sequential: enough consecutive reads to justify
        whole-chunk caching for SUB-chunk views (a one-shot ranged
        read never warms up, so it never pays 8MB for 64KB)."""
        return self._counter >= self.MODE_CHANGE_LIMIT


def read_fid(lookup: LookupFn, fid: str, offset: int = 0,
             size: int | None = None) -> bytes:
    headers = {}
    if size is not None:
        headers["Range"] = f"bytes={offset}-{offset + size - 1}"
    elif offset:
        headers["Range"] = f"bytes={offset}-"

    def fetch(url: str) -> bytes:
        resp = session().get(url, headers=headers, timeout=60)
        if resp.status_code not in (200, 206):
            raise IOError(f"read {fid}: http {resp.status_code}")
        return resp.content

    urls = _replica_urls(lookup, fid)
    if len(urls) == 1:
        return fetch(urls[0])
    return _hedged_fetch(fetch, urls, retry.HEDGE_DELAY)


class ChunkStreamReader:
    """Random-access reads over an entry's chunks, caching whole chunks
    (weed/filer/reader_cache.go keeps recently-read chunks in memory for
    sequential readers)."""

    def __init__(self, lookup: LookupFn, chunks: list[FileChunk],
                 cache_chunks: int = 8, readahead: bool = True):
        self.lookup = lookup
        self.chunks = resolve_chunk_manifest(
            lambda fid: read_fid(lookup, fid), chunks)
        self._cache: dict[str, bytes] = {}
        self._cache_order: list[str] = []
        self._cache_chunks = cache_chunks
        self.pattern = ReaderPattern()
        self._readahead = readahead
        self._prefetch = {}  # fid -> Future[bytes] (plaintext chunks)
        self._pool = None
        # offset-ordered plain chunks, for next-chunk readahead
        self._seq = sorted(
            (c for c in self.chunks if not c.is_chunk_manifest),
            key=lambda c: c.offset)

    @property
    def size(self) -> int:
        return max((c.offset + c.size for c in self.chunks), default=0)

    def _cache_put(self, fid: str, data: bytes) -> bytes:
        self._cache[fid] = data
        self._cache_order.append(fid)
        if len(self._cache_order) > self._cache_chunks:
            self._cache.pop(self._cache_order.pop(0), None)
        return data

    def _chunk_bytes(self, fid: str, cipher_key: bytes = b"") -> bytes:
        if fid in self._cache:
            return self._cache[fid]
        fut = self._prefetch.pop(fid, None)
        if fut is not None and not cipher_key:
            try:
                return self._cache_put(fid, fut.result(timeout=60))
            except Exception:
                pass  # readahead is best-effort; fall through
        data = read_fid(self.lookup, fid)
        if cipher_key:
            # stored bytes are nonce||AES-GCM ciphertext; the cache
            # holds PLAINTEXT so repeat reads don't re-decrypt
            from ..utils import cipher as _cipher

            data = _cipher.decrypt(data, cipher_key)
        return self._cache_put(fid, data)

    def _maybe_readahead(self, cur_fid: str, limit_off: int) -> None:
        """Sequential mode: start fetching the chunk AFTER `cur_fid`
        on a background thread so network and assembly overlap
        (reader_cache.go MaybeCache). One chunk ahead, best-effort,
        plain chunks only (ciphered ones must decrypt whole anyway).
        `limit_off` bounds the prefetch to chunks this read actually
        touches — a per-request reader must never fetch a chunk past
        its range just to throw it away on close()."""
        if not self._readahead or self.pattern.is_random:
            return
        nxt = None
        for i, c in enumerate(self._seq):
            if c.fid == cur_fid and i + 1 < len(self._seq):
                nxt = self._seq[i + 1]
                break
        if nxt is None or nxt.offset >= limit_off or nxt.cipher_key \
                or nxt.fid in self._cache or nxt.fid in self._prefetch:
            return
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=1)
        # copy_context: keep the reader's trace/deadline on the
        # prefetch thread (pool.submit drops contextvars)
        self._prefetch[nxt.fid] = self._pool.submit(
            contextvars.copy_context().run, read_fid, self.lookup,
            nxt.fid)

    def read(self, offset: int = 0, size: int | None = None) -> bytes:
        if size is None:
            size = self.size - offset
        size = max(0, min(size, self.size - offset))
        if size == 0:
            return b""
        self.pattern.monitor(offset, size)
        chunk_sizes = {c.fid: c.size for c in self.chunks}
        out = bytearray(size)  # sparse gaps read as zeros
        views = view_from_chunks(self.chunks, offset, size)
        streaming = self.pattern.is_streaming
        for v in views:
            full = v.view_size >= chunk_sizes.get(v.fid, 0)
            whole = (v.cipher_key or v.fid in self._cache or full or
                     v.fid in self._prefetch or streaming)
            if whole:
                self._maybe_readahead(v.fid, offset + size)
                # ciphered chunks must always come back whole (a ranged
                # read of GCM ciphertext cannot decrypt); warmed-up
                # sequential readers take whole chunks too so the NEXT
                # sub-chunk reads hit the cache instead of the network
                data = self._chunk_bytes(v.fid, v.cipher_key)
                piece = data[v.offset_in_chunk:
                             v.offset_in_chunk + v.view_size]
            else:
                # partial view of an uncached chunk on a cold or random
                # reader: ranged read, no whole-chunk amplification
                piece = read_fid(self.lookup, v.fid, v.offset_in_chunk,
                                 v.view_size)
            at = v.view_offset - offset
            out[at:at + len(piece)] = piece
        return bytes(out)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None


def stream_content(lookup: LookupFn, chunks: list[FileChunk],
                   offset: int = 0, size: int | None = None) -> bytes:
    r = ChunkStreamReader(lookup, chunks)
    try:
        return r.read(offset, size)
    finally:
        r.close()
