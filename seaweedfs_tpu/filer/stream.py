"""Assemble file bytes from chunk views via volume-server reads.

Equivalent of /root/reference/weed/filer/stream.go:69-144 — turn an
entry's chunk list into ranged HTTP reads against volume servers,
with manifest resolution and a small per-reader chunk cache
(reader_cache.go's role).
"""
from __future__ import annotations

from typing import Callable

import requests
from ..rpc.httpclient import session

from .entry import FileChunk
from .filechunks import resolve_chunk_manifest, view_from_chunks

LookupFn = Callable[[str], str]  # fid -> full http url


def read_fid(lookup: LookupFn, fid: str, offset: int = 0,
             size: int | None = None) -> bytes:
    url = lookup(fid)
    headers = {}
    if size is not None:
        headers["Range"] = f"bytes={offset}-{offset + size - 1}"
    elif offset:
        headers["Range"] = f"bytes={offset}-"
    resp = session().get(url, headers=headers, timeout=60)
    if resp.status_code not in (200, 206):
        raise IOError(f"read {fid}: http {resp.status_code}")
    return resp.content


class ChunkStreamReader:
    """Random-access reads over an entry's chunks, caching whole chunks
    (weed/filer/reader_cache.go keeps recently-read chunks in memory for
    sequential readers)."""

    def __init__(self, lookup: LookupFn, chunks: list[FileChunk],
                 cache_chunks: int = 8):
        self.lookup = lookup
        self.chunks = resolve_chunk_manifest(
            lambda fid: read_fid(lookup, fid), chunks)
        self._cache: dict[str, bytes] = {}
        self._cache_order: list[str] = []
        self._cache_chunks = cache_chunks

    @property
    def size(self) -> int:
        return max((c.offset + c.size for c in self.chunks), default=0)

    def _chunk_bytes(self, fid: str, cipher_key: bytes = b"") -> bytes:
        if fid in self._cache:
            return self._cache[fid]
        data = read_fid(self.lookup, fid)
        if cipher_key:
            # stored bytes are nonce||AES-GCM ciphertext; the cache
            # holds PLAINTEXT so repeat reads don't re-decrypt
            from ..utils import cipher as _cipher

            data = _cipher.decrypt(data, cipher_key)
        self._cache[fid] = data
        self._cache_order.append(fid)
        if len(self._cache_order) > self._cache_chunks:
            evict = self._cache_order.pop(0)
            self._cache.pop(evict, None)
        return data

    def read(self, offset: int = 0, size: int | None = None) -> bytes:
        if size is None:
            size = self.size - offset
        size = max(0, min(size, self.size - offset))
        if size == 0:
            return b""
        chunk_sizes = {c.fid: c.size for c in self.chunks}
        out = bytearray(size)  # sparse gaps read as zeros
        for v in view_from_chunks(self.chunks, offset, size):
            if v.cipher_key or v.fid in self._cache or \
                    v.view_size >= chunk_sizes.get(v.fid, 0):
                # ciphered chunks must always come back whole: a ranged
                # read of GCM ciphertext cannot be decrypted
                data = self._chunk_bytes(v.fid, v.cipher_key)
                piece = data[v.offset_in_chunk:
                             v.offset_in_chunk + v.view_size]
            else:
                # partial view of an uncached chunk: ranged read, no
                # whole-chunk amplification
                piece = read_fid(self.lookup, v.fid, v.offset_in_chunk,
                                 v.view_size)
            at = v.view_offset - offset
            out[at:at + len(piece)] = piece
        return bytes(out)


def stream_content(lookup: LookupFn, chunks: list[FileChunk],
                   offset: int = 0, size: int | None = None) -> bytes:
    return ChunkStreamReader(lookup, chunks).read(offset, size)
