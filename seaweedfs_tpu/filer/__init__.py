"""Filer: directory namespace + chunked files over volume storage.

TPU-native re-expression of /root/reference/weed/filer/ — see
entry.py (Entry/FileChunk), filechunks.py (visible-interval algebra),
filerstore.py (pluggable metadata stores), event_log.py (metadata
subscription log), filer.py (the Filer), stream.py (chunked reads).
"""
from .entry import DIR_MODE_FLAG, Entry, FileChunk, total_size
from .event_log import MetaEventLog, event_kind
from .filechunks import (ChunkView, VisibleInterval, compact_file_chunks,
                         etag_chunks, maybe_manifestize,
                         non_overlapping_visible_intervals,
                         resolve_chunk_manifest, view_from_chunks)
from .filer import Filer, norm_path
from . import abstract_sql as _abstract_sql  # registers mysql/postgres
# (both driven by the in-tree mysql_lite / pg_lite wire clients)
from . import arangodb_store as _arangodb_store  # registers arangodb
from . import cassandra_store as _cassandra_store  # registers cassandra
from . import elastic_store as _elastic_store  # registers elastic (REST)
from . import etcd_store as _etcd_store      # registers etcd (v3 http)
from . import hbase_store as _hbase_store    # registers hbase (thrift)
from . import tikv_store as _tikv_store      # registers tikv (grpc)
from . import ydb_store as _ydb_store        # registers ydb (grpc+yql)
from . import rocksdb_store as _rocksdb_store  # registers rocksdb (C API)
from . import mongodb_store as _mongodb_store  # registers mongodb (OP_MSG)
from . import redis_store as _redis_store    # registers redis
from . import redis_cluster_store as _redis_cluster  # registers redis_cluster
from . import sharded_store as _sharded_store  # registers "sharded"
from .filerstore import (STORES, FilerStore, MemoryStore, SqliteStore,
                         make_store, register_store)
from .sharded_store import ShardedStore
from .store_cache import CachingStore
from .stream import ChunkStreamReader, read_fid, stream_content

__all__ = [
    "DIR_MODE_FLAG", "Entry", "FileChunk", "total_size",
    "MetaEventLog", "event_kind",
    "ChunkView", "VisibleInterval", "compact_file_chunks", "etag_chunks",
    "maybe_manifestize", "non_overlapping_visible_intervals",
    "resolve_chunk_manifest", "view_from_chunks",
    "Filer", "norm_path",
    "STORES", "FilerStore", "MemoryStore", "SqliteStore", "make_store",
    "register_store", "ShardedStore", "CachingStore",
    "ChunkStreamReader", "read_fid", "stream_content",
]
