"""Cassandra filer store speaking the native CQL v4 wire protocol.

The slot of /root/reference/weed/filer/cassandra/cassandra_store.go:23
(and its kv side, cassandra_store_kv.go), with the client written
in-tree (filer/cql_lite.py) instead of gocql — the fourth
fully-implemented external wire protocol after redis RESP, the etcd v3
gateway, and MongoDB OP_MSG.

Schema (cassandra/README.txt):
    CREATE TABLE filemeta (
        directory varchar, name varchar, meta blob,
        PRIMARY KEY (directory, name)
    ) WITH CLUSTERING ORDER BY (name ASC);

Entries are one row per (directory, name) with the entry JSON in
`meta`; listing is the clustering-ordered name range scan the
reference uses (SELECT ... WHERE directory=? AND name>? LIMIT ?).
TTL rides cassandra's row TTL (INSERT ... USING TTL ?). The KV
side-channel packs keys into (directory, name) by base64-splitting at
8 bytes exactly like genDirAndName (cassandra_store_kv.go:53-60).
Prefix listing is not supported natively by the reference
(ErrUnsupportedListDirectoryPrefixed) — here it pages the plain range
scan and filters, which keeps the wrapper behavior without the
unsupported error."""
from __future__ import annotations

import base64
import json
import threading

from .cql_lite import CqlClient, CqlError
from .entry import Entry
from .filerstore import (FilerStore, _delete_subtree_by_walk, _norm,
                         _split, register_store)


@register_store("cassandra")
class CassandraStore(FilerStore):
    """`-store=cassandra -store.host=... -store.port=9042
    -store.database=seaweedfs` (database = keyspace; optional
    -store.user/-store.password for PasswordAuthenticator)."""

    name = "cassandra"

    def __init__(self, host: str = "127.0.0.1", port: int = 9042,
                 database: str = "seaweedfs", user: str = "",
                 username: str = "", password: str = "", **_):
        username = user or username
        self._conn_args = (host, int(port), username, password, database)
        self._cql = CqlClient(host, int(port), username=username,
                              password=password, keyspace=database)
        self._lock = threading.Lock()  # one socket, serialized requests
        # prepared statements (gocql prepares transparently; the wire
        # client does it explicitly once per connection)
        self._prep: dict[str, bytes] = {}

    # -- plumbing -------------------------------------------------------
    def _reconnect(self) -> None:
        host, port, username, password, database = self._conn_args
        self._cql.close()
        self._cql = CqlClient(host, port, username=username,
                              password=password, keyspace=database)
        self._prep.clear()

    UNPREPARED = 0x2500

    def _exec(self, cql: str, values: tuple):
        """Prepared execute with a one-shot reconnect on transport
        failure. A CqlError is a server answer on a healthy, synced
        connection and is never retried — except UNPREPARED: the
        server evicts prepared-statement cache entries under memory
        pressure, and the contract (gocql does the same) is to
        re-prepare and re-execute."""
        with self._lock:
            try:
                return self._exec_locked(cql, values)
            except CqlError as e:
                if e.code != self.UNPREPARED:
                    raise
                self._prep.pop(cql, None)
                return self._exec_locked(cql, values)
            except (IOError, OSError):
                self._reconnect()
                return self._exec_locked(cql, values)

    def _exec_locked(self, cql: str, values: tuple):
        stmt = self._prep.get(cql)
        if stmt is None:
            stmt = self._cql.prepare(cql)
            self._prep[cql] = stmt
        return self._cql.execute(stmt, values)

    # -- entries --------------------------------------------------------
    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        meta = json.dumps(entry.to_dict()).encode()
        # row TTL carries the entry TTL exactly like the reference
        # (InsertEntry USING TTL ?, cassandra_store.go:108-112)
        self._exec(
            "INSERT INTO filemeta (directory,name,meta) "
            "VALUES (?,?,?) USING TTL ?",
            (_norm(d), n, meta, max(0, int(entry.ttl_sec))))

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        rows = self._exec(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (_norm(d), n))
        if not rows or rows[0][0] is None:
            return None
        return Entry.from_dict(json.loads(rows[0][0]))

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        if not n:
            return
        self._exec(
            "DELETE FROM filemeta WHERE directory=? AND name=?",
            (_norm(d), n))

    def delete_folder_children(self, path: str) -> None:
        """Whole-subtree delete. Directories are partitions, so there
        is no single range statement — this walks child directories
        (entries flagged is_directory) and drops partitions bottom-up.
        The reference deletes only the top partition
        (cassandra_store.go:173-183) and leaves grandchildren to gocql
        users' recursive delete; the filer contract in this tree is
        subtree semantics, matching every other store here."""
        _delete_subtree_by_walk(self, path)

    def delete_directory_range(self, d: str) -> None:
        self._exec("DELETE FROM filemeta WHERE directory=?", (d,))

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        out: list[Entry] = []
        cursor = start_from
        first = True
        while len(out) < limit:
            op = ">=" if (inclusive and first and cursor) else ">"
            batch = self._exec(
                "SELECT name, meta FROM filemeta WHERE directory=? "
                f"AND name{op}? LIMIT ?",
                (dirpath, cursor, limit + 1))
            if not batch:
                break
            first = False
            for name_b, meta_b in batch:
                name = (name_b or b"").decode()
                cursor = name
                if prefix and not name.startswith(prefix):
                    if prefix and name > prefix + "\xff":
                        return out  # past the prefix range: done
                    continue
                if meta_b is None:
                    continue
                out.append(Entry.from_dict(json.loads(meta_b)))
                if len(out) >= limit:
                    return out
            if len(batch) <= limit:
                break  # exhausted the partition
        return out

    # -- kv side-channel (cassandra_store_kv.go) ------------------------
    @staticmethod
    def _kv_dir_name(key: str) -> tuple[str, str]:
        raw = key.encode()
        while len(raw) < 8:
            raw += b"\x00"
        return (base64.b64encode(raw[:8]).decode(),
                base64.b64encode(raw[8:]).decode())

    def kv_put(self, key: str, value: bytes) -> None:
        d, n = self._kv_dir_name(key)
        self._exec(
            "INSERT INTO filemeta (directory,name,meta) "
            "VALUES (?,?,?) USING TTL ?", (d, n, value, 0))

    def kv_get(self, key: str) -> bytes | None:
        d, n = self._kv_dir_name(key)
        rows = self._exec(
            "SELECT meta FROM filemeta WHERE directory=? AND name=?",
            (d, n))
        return rows[0][0] if rows else None

    def kv_delete(self, key: str) -> None:
        d, n = self._kv_dir_name(key)
        self._exec(
            "DELETE FROM filemeta WHERE directory=? AND name=?", (d, n))

    def close(self) -> None:
        self._cql.close()
