"""Metadata event log: every namespace mutation, in order, subscribable.

Equivalent of /root/reference/weed/filer/filer_notify.go (EventNotify)
+ weed/util/log_buffer/log_buffer.go:25-44 — the filer appends every
create/update/delete/rename to a local log that powers metadata
subscriptions (filer.proto:57-60), replication, filer.sync, S3 events,
and mount cache invalidation.

Events are dicts:
  {"ts_ns": int, "directory": str,
   "old_entry": dict|None, "new_entry": dict|None,
   "signatures": [int, ...]}
old=None -> create; new=None -> delete; both -> update/rename.
Signatures mark which peers have already seen an event, preventing
active-active sync loops (weed/command/filer_sync.go).
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Callable

from .entry import Entry


class MetaEventLog:
    def __init__(self, capacity: int = 100_000, signature: int = 0):
        self.signature = signature or (hash(id(self)) & 0x7FFFFFFF)
        self._buf: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._subs: dict[int, queue.Queue] = {}
        self._sub_ids = itertools.count()
        self._last_ts_ns = 0
        # called INSIDE append, under the log lock (which is inside the
        # filer mutation lock): consumers that must observe mutations
        # in exact store order with no queue delay — e.g. the native S3
        # front's read cache, whose staleness window must be zero for
        # read-after-write consistency. Keep these callbacks tiny and
        # lock-free; exceptions are swallowed (a cache maintainer must
        # never fail a filer write).
        self.sync_listeners: list[Callable[[dict], None]] = []

    def append(self, directory: str, old_entry: Entry | None,
               new_entry: Entry | None,
               signatures: list[int] | None = None,
               new_dict: dict | None = None) -> dict:
        """new_dict: the caller's already-built new_entry.to_dict(),
        when it has one (the filer shares one dict between the store
        encode and this event on the hot path)."""
        with self._lock:
            ts = time.time_ns()
            if ts <= self._last_ts_ns:  # keep strictly ordered
                ts = self._last_ts_ns + 1
            self._last_ts_ns = ts
            if new_dict is None and new_entry is not None:
                new_dict = new_entry.to_dict()
            ev = {"ts_ns": ts, "directory": directory,
                  "old_entry": old_entry.to_dict() if old_entry else None,
                  "new_entry": new_dict,
                  "signatures": list(signatures or []) + [self.signature]}
            self._buf.append(ev)
            for q in self._subs.values():
                q.put(ev)
            for fn in self.sync_listeners:
                try:
                    fn(ev)
                except Exception:
                    pass
            return ev

    def subscribe(self, since_ts_ns: int = 0) -> tuple[int, queue.Queue]:
        """Register a live subscriber; returns (id, queue) with any
        buffered events newer than since_ts_ns already enqueued."""
        with self._lock:
            q: queue.Queue = queue.Queue()
            for ev in self._buf:
                if ev["ts_ns"] > since_ts_ns:
                    q.put(ev)
            sid = next(self._sub_ids)
            self._subs[sid] = q
            return sid, q

    def unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._subs.pop(sid, None)

    def replay(self, since_ts_ns: int = 0,
               prefix: str | None = None) -> list[dict]:
        with self._lock:
            return [ev for ev in self._buf if ev["ts_ns"] > since_ts_ns
                    and (prefix is None
                         or ev["directory"].startswith(prefix))]


def event_kind(ev: dict) -> str:
    if ev["old_entry"] is None and ev["new_entry"] is not None:
        return "create"
    if ev["old_entry"] is not None and ev["new_entry"] is None:
        return "delete"
    if ev["old_entry"] is not None and ev["new_entry"] is not None:
        return "update"
    return "noop"


def iter_events(q: queue.Queue, stop: threading.Event,
                handler: Callable[[dict], None],
                poll_s: float = 0.2) -> None:
    """Drain a subscription queue until `stop` is set."""
    while not stop.is_set():
        try:
            ev = q.get(timeout=poll_s)
        except queue.Empty:
            continue
        handler(ev)
