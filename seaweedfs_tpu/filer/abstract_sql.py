"""Shared SQL filer-store layer + mysql/postgres adapters.

Equivalent of /root/reference/weed/filer/abstract_sql/ (the 472-LoC
abstract_sql_store.go shared by the mysql/postgres/sqlite plugins):
one table keyed (dir, name) holding encoded entry blobs, plus a KV
table, with the dialect differences (parameter placeholders, upsert
syntax, LIKE escaping) isolated in a small Dialect object.

The sqlite store in filerstore.py predates this layer and stays
self-contained; mysql and postgres register here, gated on their
drivers (pymysql / psycopg2·pg8000) being importable — the build image
ships neither, mirroring how the reference compiles those stores in
but only activates them when configured.
"""
from __future__ import annotations

import json
import threading
from dataclasses import dataclass

from .entry import Entry
from .filerstore import FilerStore, _like_escape, _norm, _split, \
    register_store


@dataclass
class Dialect:
    placeholder: str               # "?" or "%s"
    upsert_meta: str               # full upsert statement for filemeta
    upsert_kv: str                 # full upsert statement for kv
    create_meta: str
    create_kv: str
    like_escape_clause: str = r" ESCAPE '\'"


def _ph(d: Dialect, n: int) -> str:
    return ",".join([d.placeholder] * n)


MYSQL_DIALECT = Dialect(
    placeholder="%s",
    create_meta="""CREATE TABLE IF NOT EXISTS filemeta(
        dir VARCHAR(766) NOT NULL, name VARCHAR(766) NOT NULL,
        meta LONGTEXT NOT NULL, PRIMARY KEY(dir, name))""",
    create_kv="""CREATE TABLE IF NOT EXISTS kv(
        k VARCHAR(766) PRIMARY KEY, v LONGBLOB NOT NULL)""",
    upsert_meta="""INSERT INTO filemeta(dir,name,meta) VALUES(%s,%s,%s)
        ON DUPLICATE KEY UPDATE meta=VALUES(meta)""",
    upsert_kv="""INSERT INTO kv(k,v) VALUES(%s,%s)
        ON DUPLICATE KEY UPDATE v=VALUES(v)""",
    like_escape_clause=" ESCAPE '\\\\'",
)

POSTGRES_DIALECT = Dialect(
    placeholder="%s",
    create_meta="""CREATE TABLE IF NOT EXISTS filemeta(
        dir TEXT NOT NULL, name TEXT NOT NULL,
        meta TEXT NOT NULL, PRIMARY KEY(dir, name))""",
    create_kv="""CREATE TABLE IF NOT EXISTS kv(
        k TEXT PRIMARY KEY, v BYTEA NOT NULL)""",
    upsert_meta="""INSERT INTO filemeta(dir,name,meta) VALUES(%s,%s,%s)
        ON CONFLICT(dir,name) DO UPDATE SET meta=EXCLUDED.meta""",
    upsert_kv="""INSERT INTO kv(k,v) VALUES(%s,%s)
        ON CONFLICT(k) DO UPDATE SET v=EXCLUDED.v""",
)


class AbstractSqlStore(FilerStore):
    """FilerStore over any DB-API 2.0 connection."""

    def __init__(self, conn, dialect: Dialect):
        self._conn = conn
        self._d = dialect
        self._lock = threading.RLock()
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(dialect.create_meta)
            cur.execute(dialect.create_kv)
            self._conn.commit()

    def _exec(self, sql: str, args: tuple = ()) -> list:
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(sql, args)
            rows = cur.fetchall() if cur.description else []
            self._conn.commit()
            return rows

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        self._exec(self._d.upsert_meta,
                   (d, n, json.dumps(entry.to_dict())))

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        ph = self._d.placeholder
        rows = self._exec(
            f"SELECT meta FROM filemeta WHERE dir={ph} AND name={ph}",
            (d, n))
        return Entry.from_dict(json.loads(rows[0][0])) if rows else None

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        ph = self._d.placeholder
        self._exec(
            f"DELETE FROM filemeta WHERE dir={ph} AND name={ph}", (d, n))

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        like = _like_escape(
            path if path.endswith("/") else path + "/") + "%"
        ph = self._d.placeholder
        self._exec(
            f"DELETE FROM filemeta WHERE dir={ph} OR dir LIKE {ph}"
            f"{self._d.like_escape_clause}", (path, like))

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        ph = self._d.placeholder
        cmp = ">=" if inclusive else ">"
        q = f"SELECT meta FROM filemeta WHERE dir={ph}"
        args: list = [dirpath]
        if start_from:
            q += f" AND name {cmp} {ph}"
            args.append(start_from)
        if prefix:
            q += f" AND name LIKE {ph}{self._d.like_escape_clause}"
            args.append(_like_escape(prefix) + "%")
        q += f" ORDER BY name LIMIT {ph}"
        args.append(limit)
        rows = self._exec(q, tuple(args))
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def kv_put(self, key: str, value: bytes) -> None:
        self._exec(self._d.upsert_kv, (key, value))

    def kv_get(self, key: str) -> bytes | None:
        ph = self._d.placeholder
        rows = self._exec(f"SELECT v FROM kv WHERE k={ph}", (key,))
        return bytes(rows[0][0]) if rows else None

    def kv_delete(self, key: str) -> None:
        ph = self._d.placeholder
        self._exec(f"DELETE FROM kv WHERE k={ph}", (key,))

    def close(self) -> None:
        with self._lock:
            self._conn.close()


@register_store("mysql")
class MysqlStore(AbstractSqlStore):
    """weed/filer/mysql equivalent; requires the pymysql driver."""

    def __init__(self, host: str = "127.0.0.1", port: int = 3306,
                 user: str = "root", password: str = "",
                 database: str = "seaweedfs", **_):
        try:
            import pymysql
        except ImportError as e:
            raise ImportError(
                "filer store 'mysql' needs the pymysql driver, which "
                "is not installed in this environment") from e
        conn = pymysql.connect(host=host, port=port, user=user,
                               password=password, database=database,
                               autocommit=False)
        super().__init__(conn, MYSQL_DIALECT)


@register_store("postgres")
class PostgresStore(AbstractSqlStore):
    """weed/filer/postgres equivalent; requires psycopg2 or pg8000."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5432,
                 user: str = "postgres", password: str = "",
                 database: str = "seaweedfs", **_):
        conn = None
        try:
            import psycopg2
            conn = psycopg2.connect(host=host, port=port, user=user,
                                    password=password, dbname=database)
        except ImportError:
            try:
                import pg8000.dbapi
                conn = pg8000.dbapi.Connection(
                    user, host=host, port=port, password=password,
                    database=database)
            except ImportError as e:
                raise ImportError(
                    "filer store 'postgres' needs psycopg2 or pg8000, "
                    "neither of which is installed") from e
        super().__init__(conn, POSTGRES_DIALECT)
