"""Shared SQL filer-store layer + mysql/postgres adapters.

Equivalent of /root/reference/weed/filer/abstract_sql/ (the 472-LoC
abstract_sql_store.go shared by the mysql/postgres/sqlite plugins):
one table keyed (dir, name) holding encoded entry blobs, plus a KV
table, with the dialect differences (parameter placeholders, upsert
syntax, LIKE escaping) isolated in a small Dialect object.

The sqlite store in filerstore.py predates this layer and stays
self-contained; mysql and postgres register here over the in-tree
wire clients (mysql_lite.py / pg_lite.py) — no external drivers, the
same zero-SDK approach as the redis/etcd/mongodb/cassandra stores.
"""
from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass

from .entry import Entry
from .filerstore import FilerStore, _like_escape, _norm, _split, \
    register_store


def dir_hash(directory: str) -> int:
    """First 64 bits of MD5(dir) as a signed big-endian int64 — the
    reference's util.HashStringToLong (util/bytes.go:77), which keys
    the filemeta primary index. The full directory still rides every
    WHERE clause, so a 64-bit collision can't cross-read; the PK
    (dirhash, name) keeps index keys inside InnoDB's 3072-byte limit
    (8 + 766*4 with utf8mb4 = exactly 3072, hence VARCHAR(766))."""
    v = int.from_bytes(hashlib.md5(directory.encode()).digest()[:8],
                       "big")
    return v - (1 << 64) if v >= (1 << 63) else v


@dataclass
class Dialect:
    placeholder: str               # "?" or "%s"
    upsert_meta: str               # full upsert statement for filemeta
    upsert_kv: str                 # full upsert statement for kv
    create_meta: str
    create_kv: str
    like_escape_clause: str = r" ESCAPE '\'"
    quote: str = "`"  # identifier quote (backtick mysql, " postgres)


MYSQL_DIALECT = Dialect(
    # schema mirrors the reference's scaffold (filer.toml [mysql],
    # mysql/mysql_sql_gen.go:24-49)
    placeholder="%s",
    create_meta="""CREATE TABLE IF NOT EXISTS {table}(
        dirhash BIGINT NOT NULL, name VARCHAR(766) NOT NULL,
        directory TEXT NOT NULL, meta LONGBLOB,
        PRIMARY KEY(dirhash, name))
        DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    create_kv="""CREATE TABLE IF NOT EXISTS kv(
        k VARCHAR(766) PRIMARY KEY, v LONGBLOB NOT NULL)
        DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    upsert_meta="""INSERT INTO {table}(dirhash,name,directory,meta)
        VALUES(%s,%s,%s,%s)
        ON DUPLICATE KEY UPDATE meta=VALUES(meta)""",
    upsert_kv="""INSERT INTO kv(k,v) VALUES(%s,%s)
        ON DUPLICATE KEY UPDATE v=VALUES(v)""",
    like_escape_clause=" ESCAPE '\\\\'",
)

POSTGRES_DIALECT = Dialect(
    placeholder="%s",
    create_meta="""CREATE TABLE IF NOT EXISTS {table}(
        dirhash BIGINT NOT NULL, name TEXT NOT NULL,
        directory TEXT NOT NULL, meta BYTEA,
        PRIMARY KEY(dirhash, name))""",
    create_kv="""CREATE TABLE IF NOT EXISTS kv(
        k TEXT PRIMARY KEY, v BYTEA NOT NULL)""",
    upsert_meta="""INSERT INTO {table}(dirhash,name,directory,meta)
        VALUES(%s,%s,%s,%s)
        ON CONFLICT(dirhash,name) DO UPDATE SET meta=EXCLUDED.meta""",
    upsert_kv="""INSERT INTO kv(k,v) VALUES(%s,%s)
        ON CONFLICT(k) DO UPDATE SET v=EXCLUDED.v""",
    quote='"',
)


class AbstractSqlStore(FilerStore):
    """FilerStore over any DB-API 2.0 connection.

    Query shapes mirror the reference's generators
    (mysql/mysql_sql_gen.go:24-49): every filemeta statement keys on
    dirhash AND carries the full directory, so index keys stay short
    and hash collisions stay harmless.

    Transport failures reconnect once via `_connect` (long-lived
    sockets get idle-closed by the server — MySQL's wait_timeout —
    and a reconnect must not surface as a filer error); server-side
    SQL errors (`server_errors` classes) are never retried, the
    connection is still synced after them."""

    # exception types that mean "the server answered with an error" —
    # set by subclasses to their wire client's error class
    server_errors: tuple = ()

    BUCKETS_DIR = "/buckets"

    def __init__(self, conn, dialect: Dialect, bucket_tables: bool = False):
        self._conn = conn
        self._d = dialect
        self._lock = threading.RLock()
        # mysql2/postgres2 layout (mysql2_store.go:60,88): entries
        # under /buckets/<bucket>/ live in a per-bucket table, so
        # deleting a bucket is one DROP TABLE instead of a scan of
        # every row. Tables are created lazily on first touch (the
        # reference creates them on the bucket-creation event;
        # CREATE IF NOT EXISTS makes both orders correct) and cached.
        self._bucket_tables = bucket_tables
        self._known_tables: set[str] = set()
        self._exec(dialect.create_meta.format(table="filemeta"))
        self._exec(dialect.create_kv)
        self._known_tables.add("filemeta")

    def _table_for(self, directory: str, create: bool = False) -> str:
        """The quoted table holding entries of `directory`. Default
        layout: always filemeta. Bucket layout: /buckets/<b>/... maps
        to table bucket_<b> (the bucket DIR ENTRY itself lives in
        /buckets, i.e. the default table). Tables are created only on
        WRITE paths (create=True) — reads on never-written buckets
        must not run DDL (unauthenticated probes would grow the
        catalog unboundedly, and a read racing a bucket drop could
        resurrect the dropped table)."""
        if self._bucket_tables and \
                directory.startswith(self.BUCKETS_DIR + "/"):
            bucket = directory[len(self.BUCKETS_DIR) + 1:].split("/")[0]
            table = self._bucket_table(bucket)
            if create and table not in self._known_tables:
                self._exec(self._d.create_meta.format(table=table))
                self._known_tables.add(table)
            return table
        return "filemeta"

    _BUCKET_NAME_OK = frozenset(
        "abcdefghijklmnopqrstuvwxyz"
        "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")

    def _bucket_table(self, bucket: str) -> str:
        # strict charset: the name lands inside a quoted SQL
        # identifier AND the drivers' printf-style parameter
        # substitution ('%' would shift every placeholder)
        if not bucket or any(c not in self._BUCKET_NAME_OK
                             for c in bucket):
            raise ValueError(f"invalid bucket name {bucket!r}")
        q = self._d.quote
        return f"{q}bucket_{bucket}{q}"

    def _read(self, table: str, sql: str, args: tuple) -> list:
        """Execute a read/point-delete against a possibly-nonexistent
        bucket table: a server error on a table THIS process never
        created reads as 'no such table' -> empty (the bucket was
        never written or was dropped); errors on known tables are
        real and re-raised."""
        try:
            return self._exec(sql, args)
        except self.server_errors:
            if table != "filemeta" and table not in self._known_tables:
                return []
            raise

    def _connect(self):
        """Build a replacement connection after a transport failure;
        subclasses with reconnect support override this."""
        raise NotImplementedError

    def _exec(self, sql: str, args: tuple = ()) -> list:
        with self._lock:
            try:
                return self._exec_locked(sql, args)
            except self.server_errors:
                raise  # SQL error on a healthy, synced connection
            except (IOError, OSError):
                try:
                    replacement = self._connect()
                except NotImplementedError:
                    raise
                try:
                    self._conn.close()
                except (IOError, OSError):
                    pass
                self._conn = replacement
                return self._exec_locked(sql, args)

    def _exec_locked(self, sql: str, args: tuple) -> list:
        cur = self._conn.cursor()
        cur.execute(sql, args)
        rows = cur.fetchall() if cur.description else []
        self._conn.commit()
        return rows

    def insert_entry(self, entry: Entry) -> None:
        d, n = entry.dir_and_name
        d = _norm(d)
        table = self._table_for(d, create=True)
        self._exec(self._d.upsert_meta.format(table=table),
                   (dir_hash(d), n, d,
                    json.dumps(entry.to_dict()).encode()))

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        d, n = _split(path)
        if not n:
            return None
        ph = self._d.placeholder
        table = self._table_for(d)
        rows = self._read(
            table,
            f"SELECT meta FROM {table} WHERE dirhash={ph} "
            f"AND name={ph} AND directory={ph}", (dir_hash(d), n, d))
        return Entry.from_dict(json.loads(rows[0][0])) if rows else None

    def delete_entry(self, path: str) -> None:
        d, n = _split(path)
        ph = self._d.placeholder
        table = self._table_for(d)
        self._read(
            table,
            f"DELETE FROM {table} WHERE dirhash={ph} AND "
            f"name={ph} AND directory={ph}", (dir_hash(d), n, d))

    def delete_folder_children(self, path: str) -> None:
        path = _norm(path)
        if self._bucket_tables and \
                path.startswith(self.BUCKETS_DIR + "/"):
            rel = path[len(self.BUCKETS_DIR) + 1:]
            if "/" not in rel:
                # the whole bucket: one DROP TABLE reclaims everything
                # (mysql2_store.go:88 OnBucketDeletion) — the O(1)
                # delete this layout exists for
                table = self._bucket_table(rel)
                self._exec(f"DROP TABLE IF EXISTS {table}")
                self._known_tables.discard(table)
                return
        if self._bucket_tables and path in ("/", self.BUCKETS_DIR):
            # the subtree spans every bucket table: drop the ones this
            # process knows about (tables created by other processes
            # need their own bucket-level deletes, same multi-writer
            # caveat as the reference's event-driven table lifecycle)
            for table in list(self._known_tables - {"filemeta"}):
                self._exec(f"DROP TABLE IF EXISTS {table}")
                self._known_tables.discard(table)
        like = _like_escape(
            path if path.endswith("/") else path + "/") + "%"
        ph = self._d.placeholder
        # whole-subtree delete (the directory LIKE arm walks nested
        # dirs; the reference deletes one level and recurses in the
        # filer — same end state, fewer round trips here)
        table = self._table_for(path)
        self._read(
            table,
            f"DELETE FROM {table} WHERE directory={ph} "
            f"OR directory LIKE {ph}{self._d.like_escape_clause}",
            (path, like))

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        ph = self._d.placeholder
        cmp = ">=" if inclusive else ">"
        table = self._table_for(dirpath)
        q = (f"SELECT meta FROM {table} WHERE "
             f"dirhash={ph} AND directory={ph}")
        args: list = [dir_hash(dirpath), dirpath]
        if start_from:
            q += f" AND name {cmp} {ph}"
            args.append(start_from)
        if prefix:
            q += f" AND name LIKE {ph}{self._d.like_escape_clause}"
            args.append(_like_escape(prefix) + "%")
        q += f" ORDER BY name LIMIT {ph}"
        args.append(limit)
        rows = self._read(table, q, tuple(args))
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def kv_put(self, key: str, value: bytes) -> None:
        self._exec(self._d.upsert_kv, (key, value))

    def kv_get(self, key: str) -> bytes | None:
        ph = self._d.placeholder
        rows = self._exec(f"SELECT v FROM kv WHERE k={ph}", (key,))
        return bytes(rows[0][0]) if rows else None

    def kv_delete(self, key: str) -> None:
        ph = self._d.placeholder
        self._exec(f"DELETE FROM kv WHERE k={ph}", (key,))

    def close(self) -> None:
        with self._lock:
            self._conn.close()


@register_store("mysql")
class MysqlStore(AbstractSqlStore):
    """weed/filer/mysql equivalent
    (/root/reference/weed/filer/mysql/mysql_store.go:14). The driver
    is the in-tree wire client (mysql_lite.py: HandshakeV10 +
    mysql_native_password + COM_QUERY text protocol), so the mysql
    dialect is a first-class store, not SDK-gated."""

    bucket_tables = False

    def __init__(self, host: str = "127.0.0.1", port: int = 3306,
                 user: str = "root", password: str = "",
                 database: str = "seaweedfs", **_):
        from .mysql_lite import MysqlConnection, MysqlError

        self._args = (host, int(port), user, password, database)
        self.server_errors = (MysqlError,)
        super().__init__(self._connect(), MYSQL_DIALECT,
                         bucket_tables=self.bucket_tables)

    def _connect(self):
        from .mysql_lite import MysqlConnection

        host, port, user, password, database = self._args
        return MysqlConnection(host, port, user=user, password=password,
                               database=database)


@register_store("mysql2")
class Mysql2Store(MysqlStore):
    """weed/filer/mysql2 equivalent
    (/root/reference/weed/filer/mysql2/mysql2_store.go:60,88): the
    same wire and schema, but entries under /buckets/<bucket>/ live in
    a table per bucket, so dropping a bucket is one DROP TABLE instead
    of a row scan."""

    bucket_tables = True


@register_store("postgres")
class PostgresStore(AbstractSqlStore):
    """weed/filer/postgres equivalent
    (/root/reference/weed/filer/postgres/postgres_store.go:14). The
    driver is the in-tree wire client (pg_lite.py: StartupMessage,
    cleartext/md5 auth, simple Query protocol, bytea hex codec)."""

    bucket_tables = False

    def __init__(self, host: str = "127.0.0.1", port: int = 5432,
                 user: str = "postgres", password: str = "",
                 database: str = "seaweedfs", **_):
        from .pg_lite import PgError

        self._args = (host, int(port), user, password, database)
        self.server_errors = (PgError,)
        super().__init__(self._connect(), POSTGRES_DIALECT,
                         bucket_tables=self.bucket_tables)

    def _connect(self):
        from .pg_lite import PgConnection

        host, port, user, password, database = self._args
        return PgConnection(host, port, user=user, password=password,
                            database=database)


@register_store("postgres2")
class Postgres2Store(PostgresStore):
    """weed/filer/postgres2 equivalent
    (/root/reference/weed/filer/postgres2/postgres2_store.go): the
    per-bucket-table layout over the postgres wire — bucket deletion
    is one DROP TABLE."""

    bucket_tables = True
