"""Per-path storage rules (`filer.conf`).

Equivalent of /root/reference/weed/filer/filer_conf.go: a set of
location-prefix rules, each carrying storage options (collection,
replication, ttl, fsync, read-only, max file-name length, disk type).
The filer consults the longest matching prefix on every write
(detectStorageOption, filer_server_handlers_write.go:219) so operators
can pin `/buckets/media/` to its own collection, force a TTL under
`/tmp/`, or mark a subtree read-only without touching clients.

The reference persists the rules as a protobuf file entry at
/etc/seaweedfs/filer.conf inside the namespace itself; here they live in
the filer store's KV space under the same name (JSON), which gives the
same properties — replicated with the metadata store, hot-editable via
the `fs.configure` shell command, no server restart.
"""
from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

CONF_KEY = "filer.conf"


@dataclass
class PathConf:
    """One rule. Empty string / zero fields mean "no opinion" and fall
    through to the filer's own defaults (filer_conf.go PathConf)."""

    location_prefix: str = "/"
    collection: str = ""
    replication: str = ""
    ttl: str = ""
    disk_type: str = ""
    fsync: bool = False
    read_only: bool = False
    max_file_name_length: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PathConf":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__
                      if k in d})


@dataclass
class FilerConf:
    rules: list[PathConf] = field(default_factory=list)

    # -- serialization --------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            {"rules": [r.to_dict() for r in self.rules]}, indent=1)

    @classmethod
    def from_json(cls, raw: str | bytes) -> "FilerConf":
        d = json.loads(raw) if raw else {}
        return cls(rules=[PathConf.from_dict(r)
                          for r in d.get("rules", [])])

    # -- rule editing (fs.configure) ------------------------------------
    def set_rule(self, rule: PathConf) -> None:
        """Insert or replace the rule for rule.location_prefix."""
        self.rules = [r for r in self.rules
                      if r.location_prefix != rule.location_prefix]
        self.rules.append(rule)
        self.rules.sort(key=lambda r: r.location_prefix)

    def delete_rule(self, location_prefix: str) -> bool:
        before = len(self.rules)
        self.rules = [r for r in self.rules
                      if r.location_prefix != location_prefix]
        return len(self.rules) != before

    # -- matching -------------------------------------------------------
    def match(self, path: str) -> PathConf:
        """Merged storage options for `path`: rules are applied from the
        shortest matching prefix to the longest, so the most specific
        rule wins per field (filer_conf.go MatchStorageRule trie walk),
        while unset fields inherit from broader rules."""
        merged = PathConf(location_prefix=path)
        for rule in sorted(self.rules,
                           key=lambda r: len(r.location_prefix)):
            p = rule.location_prefix
            if path == p or path.startswith(p if p.endswith("/")
                                            else p + "/"):
                _overlay(merged, rule)
        return merged


def _overlay(base: PathConf, over: PathConf) -> None:
    if over.collection:
        base.collection = over.collection
    if over.replication:
        base.replication = over.replication
    if over.ttl:
        base.ttl = over.ttl
    if over.disk_type:
        base.disk_type = over.disk_type
    if over.fsync:
        base.fsync = True
    if over.read_only:
        base.read_only = True
    if over.max_file_name_length:
        base.max_file_name_length = over.max_file_name_length
