"""Read-through metadata cache in front of any FilerStore.

Positive + negative entry cache and a bounded directory-listing page
cache, invalidated *exactly* through the filer's metadata event log:
`attach(meta_log)` registers a sync listener, which MetaEventLog calls
inside `append` under the filer mutation lock — the same zero-staleness
hook the native S3 front's entry cache rides (s3/native_front.py), so
read-after-write holds for BOTH mutation paths (python filer API and
the native applier channel) with no polling and no staleness window
after a mutation returns.

Why it pays: the weedkv engine serializes reads against memtable
flushes and compactions on one lock, so a grown store's LSM churn is
exactly what the read p99 measures (~114 ms at the BENCH_GATEWAY.json
geometry). A cache hit never touches the engine, and misses only pay
once per key per invalidation.

Two caches, both LRU-bounded:
- entries: path -> entry dict (positive) or miss marker (negative).
  Values are stored as dicts and rebuilt via Entry.from_dict per hit
  so callers can never mutate shared state (the filer's hardlink
  resolution writes into the entries it returns).
- pages: (dir, start_from, inclusive, limit, prefix) -> list of entry
  dicts, indexed by directory so one mutation event drops every
  cached page of that directory.

TTL'd entries are never cached: python-side expiry (Filer._expire)
emits no meta event, so a cached copy would outlive the object — the
same rule the native front applies. Expiry's store deletes still
invalidate inline (every write through this wrapper drops its own
keys) so even the event-less path can't strand a stale positive.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

from ..utils import metrics
from .entry import Entry
from .filerstore import FilerStore, _norm, _split

_MISS = object()  # negative-cache marker

DEFAULT_ENTRIES = 65536
DEFAULT_PAGES = 1024


class _LRU:
    """Minimal LRU dict; caller holds the cache lock."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.data: OrderedDict = OrderedDict()

    def get(self, key):
        v = self.data.get(key, _MISS)
        if v is not _MISS:
            self.data.move_to_end(key)
        return v

    def put(self, key, value):
        """-> the evicted key, or None."""
        self.data[key] = value
        self.data.move_to_end(key)
        if len(self.data) > self.capacity:
            k, _ = self.data.popitem(last=False)
            return k
        return None

    def drop(self, key) -> None:
        self.data.pop(key, None)


class CachingStore(FilerStore):
    """Wrap `inner` with the read-through cache. Writes pass through
    and invalidate inline; `attach(meta_log)` adds the exact
    event-log invalidation that also covers mutations this wrapper
    object never sees (none today — the inline pass-through is belt,
    the event hook is suspenders AND the refresh path that turns a
    write into a warm cache line)."""

    def __init__(self, inner: FilerStore, entries: int = DEFAULT_ENTRIES,
                 pages: int = DEFAULT_PAGES, **_):
        self.inner = inner
        self.name = f"cached-{inner.name}"
        self._lock = threading.Lock()
        self._entries = _LRU(max(1, entries))
        self._pages = _LRU(max(1, pages))
        # dir -> set of page-cache keys, so a mutation in `dir` drops
        # every cached page of that directory in O(pages-of-dir)
        self._dir_pages: dict[str, set] = {}
        # fill/invalidate race guard: a read that started BEFORE a
        # mutation must not cache its stale result AFTER the
        # mutation's invalidation ran. Every invalidation bumps the
        # affected directory's generation (and subtree invalidations
        # bump a global epoch — recursive deletes are rare, so the
        # coarse epoch almost never blocks a fill); fills snapshot
        # both before the inner read and only cache if neither moved.
        self._dir_gen: dict[str, int] = {}
        self._tree_epoch = 0

    def _bump(self, dirpath: str) -> None:
        if len(self._dir_gen) >= 262144:
            self._dir_gen.clear()
            self._tree_epoch += 1  # in-flight fills all discard
        self._dir_gen[dirpath] = self._dir_gen.get(dirpath, 0) + 1

    def _snap(self, dirpath: str) -> tuple[int, int]:
        return self._dir_gen.get(dirpath, 0), self._tree_epoch

    def attach(self, meta_log) -> None:
        meta_log.sync_listeners.append(self._on_meta_event)

    # -- cache mechanics ------------------------------------------------
    def _count(self, what: str, kind: str, n: int = 1) -> None:
        lab = {"kind": kind}
        metrics.counter_add(f"filer_store_cache_{what}_total", n,
                            labels=lab)

    def _drop_entry(self, path: str) -> None:
        self._entries.drop(path)

    def _drop_dir_pages(self, dirpath: str) -> None:
        for key in self._dir_pages.pop(dirpath, ()):
            self._pages.drop(key)

    def _invalidate_path(self, path: str) -> None:
        """One entry changed: drop it and its parent's listing pages."""
        path = _norm(path)
        d, _n = _split(path)
        with self._lock:
            self._drop_entry(path)
            self._drop_dir_pages(d)
            self._bump(d)

    def _invalidate_tree(self, path: str) -> None:
        """A subtree is gone: drop every cached key at or under it."""
        path = _norm(path)
        sub = path if path.endswith("/") else path + "/"
        with self._lock:
            for p in [p for p in self._entries.data
                      if p == path or p.startswith(sub)]:
                self._entries.drop(p)
            for d in [d for d in self._dir_pages
                      if d == path or d.startswith(sub)]:
                self._drop_dir_pages(d)
            self._tree_epoch += 1

    def _on_meta_event(self, ev: dict) -> None:
        """Sync listener (under the mutation lock): refresh or drop.
        Must stay tiny and never raise — MetaEventLog swallows
        exceptions, but a slow listener taxes every mutation."""
        new, old = ev.get("new_entry"), ev.get("old_entry")
        ent = new or old
        if ent is None:
            return
        path = _norm(ent["full_path"])
        d, _n = _split(path)
        is_dir = bool(ent.get("mode", 0) & 0o40000)
        with self._lock:
            self._drop_dir_pages(d)
            self._bump(d)
            if new is None:  # delete
                if is_dir:
                    # children died with it (delete_folder_children)
                    sub = path + "/"
                    for p in [p for p in self._entries.data
                              if p == path or p.startswith(sub)]:
                        self._entries.drop(p)
                    for dd in [dd for dd in self._dir_pages
                               if dd == path or dd.startswith(sub)]:
                        self._drop_dir_pages(dd)
                    self._tree_epoch += 1
                else:
                    self._entries.drop(path)
                return
            if new.get("ttl_sec"):
                # expiry emits no event — never cache a TTL'd entry
                self._entries.drop(path)
                return
            # create/update: the event carries the authoritative dict,
            # so the write itself warms the cache (read-after-write is
            # a hit, not a re-read)
            evicted = self._entries.put(path, new)
        if evicted is not None:
            self._count("evictions", "entry")

    # -- reads (the point) ----------------------------------------------
    def find_entry(self, path: str) -> Entry | None:
        path = _norm(path)
        d, _n = _split(path)
        with self._lock:
            v = self._entries.get(path)
            snap = self._snap(d)
        if v is not _MISS:
            if v is None:
                self._count("hits", "negative")
                return None
            self._count("hits", "entry")
            return Entry.from_dict(v)
        e = self.inner.find_entry(path)
        self._count("misses", "entry")
        payload = None if e is None or e.ttl_sec else e.to_dict()
        evicted = None
        with self._lock:
            if self._snap(d) == snap:  # no mutation raced the read
                if e is None:
                    evicted = self._entries.put(path, None)
                elif payload is not None:
                    evicted = self._entries.put(path, payload)
        if evicted is not None:
            self._count("evictions", "entry")
        return e

    def list_directory_entries(self, dirpath: str, start_from: str = "",
                               inclusive: bool = False,
                               limit: int = 1024,
                               prefix: str = "") -> list[Entry]:
        dirpath = _norm(dirpath)
        key = (dirpath, start_from, inclusive, limit, prefix)
        with self._lock:
            v = self._pages.get(key)
            snap = self._snap(dirpath)
        if v is not _MISS:
            self._count("hits", "page")
            return [Entry.from_dict(d) for d in v]
        batch = self.inner.list_directory_entries(
            dirpath, start_from, inclusive, limit, prefix)
        self._count("misses", "page")
        if any(e.ttl_sec for e in batch):
            return batch  # pages with expiring entries never cached
        # serialize OUTSIDE the lock: a 1000-entry page costs ~ms to
        # encode, and every other op would convoy behind it
        payload = [e.to_dict() for e in batch]
        evicted = None
        with self._lock:
            if self._snap(dirpath) == snap:  # no mutation raced it
                evicted = self._pages.put(key, payload)
                self._dir_pages.setdefault(dirpath, set()).add(key)
                if evicted is not None:
                    # keep the dir index honest about LRU evictions
                    self._dir_pages.get(evicted[0], set()).discard(
                        evicted)
        if evicted is not None:
            self._count("evictions", "page")
        return batch

    # -- writes: pass through, invalidate inline ------------------------
    def insert_entry(self, entry: Entry) -> None:
        self.inner.insert_entry(entry)
        self._invalidate_path(entry.full_path)

    def insert_entry_encoded(self, entry: Entry, entry_dict: dict) -> None:
        self.inner.insert_entry_encoded(entry, entry_dict)
        self._invalidate_path(entry.full_path)

    def update_entry(self, entry: Entry) -> None:
        self.inner.update_entry(entry)
        self._invalidate_path(entry.full_path)

    def delete_entry(self, path: str) -> None:
        self.inner.delete_entry(path)
        self._invalidate_path(path)

    def delete_folder_children(self, path: str) -> None:
        self.inner.delete_folder_children(path)
        self._invalidate_tree(path)

    # -- kv: uncached pass-through (hardlink records are read under
    # the filer's own locks; the win lives in entries and listings) ----
    def kv_put(self, key: str, value: bytes) -> None:
        self.inner.kv_put(key, value)

    def kv_get(self, key: str) -> bytes | None:
        return self.inner.kv_get(key)

    def kv_delete(self, key: str) -> None:
        self.inner.kv_delete(key)

    def begin_batch(self) -> None:
        self.inner.begin_batch()

    def end_batch(self) -> None:
        self.inner.end_batch()

    def close(self) -> None:
        self.inner.close()

    # -- observability --------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            sizes = {"entries": len(self._entries.data),
                     "entry_capacity": self._entries.capacity,
                     "pages": len(self._pages.data),
                     "page_capacity": self._pages.capacity}
        with metrics._lock:
            for (name, lab), v in metrics._counters.items():
                if name.startswith("filer_store_cache_"):
                    kind = dict(lab).get("kind", "")
                    short = name[len("filer_store_cache_"):-len("_total")]
                    sizes[f"{short}_{kind}"] = int(v)
        return sizes

    def debug_snapshot(self) -> dict:
        from .sharded_store import _child_snapshot

        inner_snap = getattr(self.inner, "debug_snapshot", None)
        return {"kind": "cache", "cache": self.stats(),
                "inner": inner_snap() if inner_snap
                else _child_snapshot(self.inner)}

    def publish_metrics(self) -> None:
        pm = getattr(self.inner, "publish_metrics", None)
        if pm is not None:
            pm()
        with self._lock:
            metrics.gauge_set("filer_store_cache_entries",
                              len(self._entries.data))
            metrics.gauge_set("filer_store_cache_pages",
                              len(self._pages.data))
