"""Azure Blob Storage remote client over the raw REST API.

The slot of /root/reference/weed/remote_storage/azure/azure_storage_client.go:23
with plain HTTP + SharedKey request signing instead of
azure-storage-blob-go — HMAC-SHA256 over the canonicalized headers
and resource, per the published authorization scheme.

Configure: -account=... -key=<base64> -container=...; -endpoint
overrides https://{account}.blob.core.windows.net for Azurite-style
emulators.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate, parsedate_to_datetime
from typing import Iterator

import requests

from .client import RemoteEntry, RemoteStorageClient, register_remote

API_VERSION = "2020-10-02"


def shared_key_signature(account: str, key_b64: str, method: str,
                         path: str, query: dict[str, str],
                         headers: dict[str, str]) -> str:
    """SharedKey string-to-sign + HMAC. `path` is the url path
    (/container/blob); headers must already include x-ms-date and
    x-ms-version."""
    h = {k.lower(): v for k, v in headers.items()}
    canon_headers = "".join(
        f"{k}:{h[k]}\n" for k in sorted(h) if k.startswith("x-ms-"))
    canon_resource = f"/{account}{path}"
    for k in sorted(query):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    # API >= 2015-02-21: a zero Content-Length signs as the empty
    # string (an HTTP client may add "Content-Length: 0" to bodyless
    # DELETEs; both sides must canonicalize it away)
    content_length = h.get("content-length", "")
    if content_length == "0":
        content_length = ""
    sts = "\n".join([
        method,
        h.get("content-encoding", ""),
        h.get("content-language", ""),
        content_length,
        h.get("content-md5", ""),
        h.get("content-type", ""),
        "",  # Date: always empty, x-ms-date is used instead
        h.get("if-modified-since", ""),
        h.get("if-match", ""),
        h.get("if-none-match", ""),
        h.get("if-unmodified-since", ""),
        h.get("range", ""),
    ]) + "\n" + canon_headers + canon_resource
    mac = hmac.new(base64.b64decode(key_b64), sts.encode(),
                   hashlib.sha256).digest()
    return f"SharedKey {account}:{base64.b64encode(mac).decode()}"


class AzureRemoteClient(RemoteStorageClient):
    def __init__(self, account: str = "", key: str = "",
                 container: str = "", endpoint: str = "", **_):
        if not account or not key:
            raise ValueError("azure remote storage needs -account/-key")
        if not container:
            raise ValueError("azure remote storage needs -container")
        self.account = account
        self.key = key
        self.container = container
        self.endpoint = (endpoint or
                         f"https://{account}.blob.core.windows.net"
                         ).rstrip("/")
        self._sess = requests.Session()

    # -- signed request -------------------------------------------------
    def _request(self, method: str, path: str,
                 query: dict[str, str] | None = None,
                 headers: dict[str, str] | None = None,
                 data: bytes = b"") -> requests.Response:
        query = query or {}
        headers = dict(headers or {})
        headers["x-ms-date"] = formatdate(usegmt=True)
        headers["x-ms-version"] = API_VERSION
        if data:
            headers["Content-Length"] = str(len(data))
        headers["Authorization"] = shared_key_signature(
            self.account, self.key, method, path, query, headers)
        url = self.endpoint + urllib.parse.quote(path) + (
            "?" + urllib.parse.urlencode(query) if query else "")
        return self._sess.request(method, url, headers=headers,
                                  data=data, timeout=300)

    def _blob_path(self, key: str) -> str:
        return f"/{self.container}/{key.lstrip('/')}"

    # -- verbs ----------------------------------------------------------
    def traverse(self, prefix: str = "") -> Iterator[RemoteEntry]:
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list",
                 "prefix": prefix.lstrip("/")}
            if marker:
                q["marker"] = marker
            r = self._request("GET", f"/{self.container}", q)
            r.raise_for_status()
            root = ET.fromstring(r.content)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name", "")
                props = blob.find("Properties")
                size = int(props.findtext("Content-Length", "0")) \
                    if props is not None else 0
                lm = props.findtext("Last-Modified", "") \
                    if props is not None else ""
                try:
                    mtime = parsedate_to_datetime(lm).timestamp() \
                        if lm else 0.0
                except (TypeError, ValueError):
                    mtime = 0.0
                etag = props.findtext("Etag", "") \
                    if props is not None else ""
                yield RemoteEntry(key=name, size=size, mtime=mtime,
                                  etag=etag)
            marker = root.findtext("NextMarker", "") or ""
            if not marker:
                return

    def head(self, key: str) -> RemoteEntry | None:
        r = self._request("HEAD", self._blob_path(key))
        if r.status_code == 404:
            return None
        r.raise_for_status()
        lm = r.headers.get("Last-Modified", "")
        try:
            mtime = parsedate_to_datetime(lm).timestamp() if lm else 0.0
        except (TypeError, ValueError):
            mtime = 0.0
        return RemoteEntry(
            key=key.lstrip("/"),
            size=int(r.headers.get("Content-Length", 0)),
            mtime=mtime, etag=r.headers.get("Etag", ""))

    def read_file(self, key: str, offset: int = 0,
                  size: int = -1) -> bytes:
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["x-ms-range"] = f"bytes={offset}-{end}"
        r = self._request("GET", self._blob_path(key), headers=headers)
        r.raise_for_status()
        return r.content

    def write_file(self, key: str, data: bytes) -> RemoteEntry:
        r = self._request(
            "PUT", self._blob_path(key),
            headers={"x-ms-blob-type": "BlockBlob",
                     "Content-Type": "application/octet-stream"},
            data=data)
        r.raise_for_status()
        import time as _time

        return RemoteEntry(key=key.lstrip("/"), size=len(data),
                           mtime=_time.time(),
                           etag=r.headers.get("Etag", ""))

    def delete_file(self, key: str) -> None:
        r = self._request("DELETE", self._blob_path(key))
        if r.status_code not in (202, 404):
            r.raise_for_status()

    def list_buckets(self) -> list[str]:
        r = self._request("GET", "/", {"comp": "list"})
        r.raise_for_status()
        root = ET.fromstring(r.content)
        return sorted(c.findtext("Name", "")
                      for c in root.iter("Container"))


register_remote("azure", AzureRemoteClient)
