"""Remote-mount bookkeeping: which filer dirs map to which storages.

Equivalent of the reference's remote configuration + mapping persisted
in the filer itself (/root/reference/weed/filer/remote_storage.go —
/etc/remote.conf holding pb.RemoteConf and pb.RemoteStorageMapping,
read by shell remote.* commands and filer_remote_sync). Here the
document is JSON in the filer KV store under the same logical name, so
every filer (and the shell) sees one consistent copy.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..rpc.httpclient import session

CONF_KEY = "etc/remote.conf"


@dataclass
class RemoteMount:
    dir: str            # filer directory, e.g. /buckets/photos
    storage: str        # configured storage name
    remote_path: str    # key prefix within the storage ("" = root)


@dataclass
class RemoteConf:
    storages: dict[str, dict] = field(default_factory=dict)
    mounts: dict[str, RemoteMount] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "storages": self.storages,
            "mounts": {d: {"storage": m.storage,
                           "remote_path": m.remote_path}
                       for d, m in self.mounts.items()}})

    @classmethod
    def from_json(cls, raw: str | bytes) -> "RemoteConf":
        d = json.loads(raw or "{}")
        return cls(
            storages=d.get("storages", {}),
            mounts={p: RemoteMount(dir=p, storage=m["storage"],
                                   remote_path=m.get("remote_path", ""))
                    for p, m in d.get("mounts", {}).items()})


def load_conf(filer_url: str) -> RemoteConf:
    r = session().get(f"{filer_url.rstrip('/')}/kv/{CONF_KEY}", timeout=30)
    if r.status_code == 404:
        return RemoteConf()
    r.raise_for_status()
    return RemoteConf.from_json(r.content)


def save_conf(filer_url: str, conf: RemoteConf) -> None:
    r = session().put(f"{filer_url.rstrip('/')}/kv/{CONF_KEY}",
                     data=conf.to_json().encode(), timeout=30)
    r.raise_for_status()


def find_mount(conf: RemoteConf, path: str) -> RemoteMount | None:
    """Longest-prefix mount lookup for a filer path."""
    best = None
    for d, m in conf.mounts.items():
        if path == d or path.startswith(d.rstrip("/") + "/"):
            if best is None or len(d) > len(best.dir):
                best = m
    return best


def remote_key_for(mount: RemoteMount, path: str) -> str:
    """filer path under the mount -> object key in the storage."""
    rel = path[len(mount.dir):].lstrip("/")
    prefix = mount.remote_path.strip("/")
    return f"{prefix}/{rel}" if prefix else rel
