"""filer.remote.gateway: mirror bucket lifecycle + contents outward.

Equivalent of /root/reference/weed/command/filer_remote_gateway.go +
filer_remote_gateway_buckets.go: subscribe to the filer's metadata
events under the buckets directory and

- on bucket creation, create a matching bucket in the primary remote
  storage (optionally with a random suffix to dodge global-name
  conflicts) and record the mount mapping;
- on bucket deletion, delete the remote bucket and drop the mapping;
- for every file mutation inside a mapped bucket, write the change
  back to its remote storage (same mirroring rules as
  filer.remote.sync, reusing RemoteSyncWorker.apply per bucket).

Progress is resumable: the event-stream offset persists in the filer
KV, like the reference's pb.AddOffsetFunc + remote_storage offset
tracking.
"""
from __future__ import annotations

import fnmatch
import time
import uuid

import requests

from ..rpc.httpclient import session

from ..filer.entry import Entry
from ..rpc.meta_subscriber import MetaSubscriber
from .client import make_client
from .mount import RemoteMount, load_conf, save_conf
from .sync import RemoteSyncWorker


class RemoteGateway:
    RETRIES = 4

    def __init__(self, filer_url: str, create_bucket_at: str = "",
                 bucket_suffix: bool = False, include: str = "",
                 exclude: str = "", buckets_dir: str = "/buckets"):
        self.filer = filer_url.rstrip("/") \
            if filer_url.startswith("http") else f"http://{filer_url}"
        self.buckets_dir = "/" + buckets_dir.strip("/")
        self.include = include
        self.exclude = exclude
        self.bucket_suffix = bucket_suffix
        self.conf = load_conf(self.filer)
        self._conf_time = time.monotonic()
        if not create_bucket_at and len(self.conf.storages) == 1:
            create_bucket_at = next(iter(self.conf.storages))
        self.create_bucket_at = create_bucket_at
        self.offset_key = "remote.gateway/offset"
        self._workers: dict[str, RemoteSyncWorker] = {}
        self._sub: MetaSubscriber | None = None
        self.buckets_created = 0
        self.buckets_deleted = 0
        self.failed = 0

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._sub = MetaSubscriber(self.filer, self.buckets_dir,
                                   self._handle,
                                   since_fn=self._load_offset)
        self._sub.start()

    def stop(self) -> None:
        if self._sub is not None:
            self._sub.stop()
            self._sub = None

    def _load_offset(self) -> int:
        try:
            r = session().get(f"{self.filer}/kv/{self.offset_key}",
                             timeout=5)
            if r.status_code == 200:
                return int(r.content)
        except (requests.RequestException, ValueError):
            pass
        return 0

    def _save_offset(self, ts_ns: int) -> None:
        try:
            session().put(f"{self.filer}/kv/{self.offset_key}",
                         data=str(ts_ns).encode(), timeout=5)
        except requests.RequestException:
            pass

    # -- event routing --------------------------------------------------
    def _handle(self, ev: dict) -> None:
        for attempt in range(self.RETRIES):
            try:
                self.apply(ev)
                break
            except Exception:
                if attempt == self.RETRIES - 1:
                    self.failed += 1
                    break
                time.sleep(0.5 * (attempt + 1))
        self._save_offset(ev["ts_ns"])

    def _bucket_of(self, path: str) -> str | None:
        """/buckets/<name> -> name; deeper or shallower paths -> None."""
        prefix = self.buckets_dir.rstrip("/") + "/"
        if not path.startswith(prefix):
            return None
        rest = path[len(prefix):]
        return rest if rest and "/" not in rest else None

    def _name_allowed(self, name: str) -> bool:
        if self.include and not fnmatch.fnmatch(name, self.include):
            return False
        if self.exclude and fnmatch.fnmatch(name, self.exclude):
            return False
        return True

    def apply(self, ev: dict) -> None:
        old, new = ev.get("old_entry"), ev.get("new_entry")
        path = (new or old or {}).get("full_path", "")
        bucket = self._bucket_of(path)
        if bucket is not None:
            is_dir = Entry.from_dict(new or old).is_directory
            if is_dir and new is not None and old is None:
                self._create_bucket(bucket)
                return
            if is_dir and new is None and old is not None:
                self._delete_bucket(bucket)
                return
        self._mirror_content(ev, path)

    # -- bucket lifecycle ----------------------------------------------
    def _create_bucket(self, name: str) -> None:
        if not self._name_allowed(name):
            return
        mount_dir = f"{self.buckets_dir}/{name}"
        self._reload_conf()
        if mount_dir in self.conf.mounts:
            return  # replayed event / already mapped
        if not self.create_bucket_at:
            return  # no primary storage configured: local-only bucket
        storage = self.conf.storages.get(self.create_bucket_at)
        if storage is None:
            raise ValueError(
                f"un-configured remote storage {self.create_bucket_at}")
        remote_bucket = name
        if self.bucket_suffix:
            remote_bucket = f"{name}-{uuid.uuid4().hex[:8]}"
        client = make_client(storage)
        client.write_directory(remote_bucket)
        self.conf.mounts[mount_dir] = RemoteMount(
            dir=mount_dir, storage=self.create_bucket_at,
            remote_path=remote_bucket)
        save_conf(self.filer, self.conf)
        self._conf_time = time.monotonic()
        self.buckets_created += 1

    def _delete_bucket(self, name: str) -> None:
        mount_dir = f"{self.buckets_dir}/{name}"
        self._reload_conf()
        mount = self.conf.mounts.get(mount_dir)
        if mount is None:
            return
        storage = self.conf.storages.get(mount.storage)
        if storage is not None:
            make_client(storage).remove_directory(mount.remote_path)
        del self.conf.mounts[mount_dir]
        self._workers.pop(mount_dir, None)
        save_conf(self.filer, self.conf)
        self._conf_time = time.monotonic()
        self.buckets_deleted += 1

    # -- content mirroring ----------------------------------------------
    def _reload_conf(self, max_age: float = 0.0) -> None:
        if time.monotonic() - self._conf_time >= max_age:
            self.conf = load_conf(self.filer)
            self._conf_time = time.monotonic()

    def _worker_for(self, path: str) -> RemoteSyncWorker | None:
        for d in list(self.conf.mounts):
            if path == d or path.startswith(d.rstrip("/") + "/"):
                w = self._workers.get(d)
                if w is None:
                    try:
                        w = RemoteSyncWorker(self.filer, d)
                    except ValueError:
                        return None
                    self._workers[d] = w
                return w
        return None

    def _mirror_content(self, ev: dict, path: str) -> None:
        if not path:
            return
        w = self._worker_for(path)
        if w is None:
            # mappings may have changed under us (e.g. shell
            # remote.mount from elsewhere): refresh once and retry
            self._reload_conf(max_age=2.0)
            w = self._worker_for(path)
            if w is None:
                return
        w.apply(ev)


def run_remote_gateway(filer_url: str, **kw) -> RemoteGateway:
    g = RemoteGateway(filer_url, **kw)
    g.start()
    return g
