"""Remote-storage tiering: map filer directories onto cloud buckets.

Equivalent of /root/reference/weed/remote_storage/ (the
RemoteStorageClient interface, remote_storage.go:71-87, and its
s3/gcs/azure/... implementations) plus the mount bookkeeping the shell
remote.* commands and `filer.remote.sync` use
(weed/shell/command_remote_*.go, weed/command/filer_remote_sync*.go).
"""
from .client import (LocalRemoteClient, RemoteEntry, RemoteStorageClient,
                     S3RemoteClient, make_client, register_remote)
from . import azure_client as _azure_client  # registers "azure" (REST)
from . import gcs_client as _gcs_client      # registers "gcs" (JSON API)
from .azure_client import AzureRemoteClient
from .gcs_client import GcsRemoteClient
from .mount import (RemoteConf, RemoteMount, find_mount, load_conf,
                    remote_key_for, save_conf)

__all__ = [
    "RemoteEntry", "RemoteStorageClient", "LocalRemoteClient",
    "S3RemoteClient", "GcsRemoteClient", "AzureRemoteClient",
    "make_client", "register_remote",
    "RemoteConf", "RemoteMount", "load_conf", "save_conf",
    "find_mount", "remote_key_for",
]
