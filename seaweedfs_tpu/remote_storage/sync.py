"""filer.remote.sync: push local writes under a remote mount back to
the cloud.

Equivalent of /root/reference/weed/command/filer_remote_sync.go +
filer_remote_sync_dir.go: subscribe to the filer's metadata events
under the mounted directory and mirror mutations outward — uploads for
creates/updates that carry local chunks, deletes for removals, a
delete+upload pair for renames. Events produced by our own bookkeeping
(placeholder entries from remote.meta.sync, remote-metadata refreshes)
carry no local chunks or already match the remote object, and are
skipped — that's the loop guard (the reference excludes by signature).
"""
from __future__ import annotations

import json
import time

import requests

from ..filer.entry import Entry
from ..rpc.meta_subscriber import MetaSubscriber
from .mount import find_mount, load_conf, remote_key_for
from ..rpc.httpclient import session


class RemoteSyncWorker:
    def __init__(self, filer_url: str, dir: str):
        self.filer = filer_url.rstrip("/") \
            if filer_url.startswith("http") else f"http://{filer_url}"
        self.dir = "/" + dir.strip("/")
        conf = load_conf(self.filer)
        self.mount = find_mount(conf, self.dir)
        if self.mount is None:
            raise ValueError(f"{self.dir} is not a remote mount")
        storage_conf = conf.storages[self.mount.storage]
        from .client import make_client
        self.client = make_client(storage_conf)
        self.offset_key = f"remote.sync/{self.dir.strip('/')}/offset"
        self._sub: MetaSubscriber | None = None
        self.pushed = 0
        self.deleted = 0
        self.skipped = 0
        self.failed = 0

    # offsets persist in the filer KV so restarts resume (the
    # reference's remote_storage/track_sync_offset.go)
    def _load_offset(self) -> int:
        try:
            r = session().get(f"{self.filer}/kv/{self.offset_key}",
                             timeout=5)
            if r.status_code == 200:
                return int(r.content)
        except (requests.RequestException, ValueError):
            pass
        return 0

    def _save_offset(self, ts_ns: int) -> None:
        try:
            session().put(f"{self.filer}/kv/{self.offset_key}",
                         data=str(ts_ns).encode(), timeout=5)
        except requests.RequestException:
            pass

    def start(self) -> None:
        self._sub = MetaSubscriber(self.filer, self.dir, self._handle,
                                   since_fn=self._load_offset)
        self._sub.start()

    def stop(self) -> None:
        if self._sub is not None:
            self._sub.stop()
            self._sub = None

    RETRIES = 4

    def _handle(self, ev: dict) -> None:
        """Apply with bounded retries before giving up: a transient
        endpoint failure must not silently drop a one-time write (the
        offset only advances once we stop trying)."""
        for attempt in range(self.RETRIES):
            try:
                self.apply(ev)
                break
            except Exception:
                if attempt == self.RETRIES - 1:
                    self.failed += 1  # poison event: move on so the
                    break             # stream doesn't wedge behind it
                time.sleep(0.5 * (attempt + 1))
        self._save_offset(ev["ts_ns"])

    def _key(self, path: str) -> str:
        return remote_key_for(self.mount, path)

    def _in_mount(self, path: str) -> bool:
        return path == self.dir or \
            path.startswith(self.dir.rstrip("/") + "/")

    @staticmethod
    def _recorded_key(entry: Entry) -> str:
        return json.loads(
            entry.extended.get("remote", "{}")).get("key", "")

    def apply(self, ev: dict) -> None:
        """The filer emits a rename as create(new path) THEN
        delete(old path) (filer/_move), so the rename signal on the
        create side is the entry's recorded remote key disagreeing with
        the key its path implies — the object is copied to the new key
        there, and the later delete event (whose entry still records
        the old key) removes the old object."""
        old = Entry.from_dict(ev["old_entry"]) if ev.get("old_entry") \
            else None
        new = Entry.from_dict(ev["new_entry"]) if ev.get("new_entry") \
            else None
        if new is None and old is not None:  # delete
            if not self._in_mount(old.full_path):
                return
            if old.is_directory:
                self.client.remove_directory(self._key(old.full_path))
            else:
                # the recorded key survives renames; the path-derived
                # one is the fallback for plain local files
                self.client.delete_file(
                    self._recorded_key(old) or self._key(old.full_path))
            self.deleted += 1
            return
        if new is None:
            return
        if old is not None and old.full_path != new.full_path and \
                self._in_mount(old.full_path):
            # single-event rename (defensive: our filer splits renames)
            if old.is_directory:
                self.client.remove_directory(self._key(old.full_path))
            else:
                self.client.delete_file(
                    self._recorded_key(old) or self._key(old.full_path))
        if not self._in_mount(new.full_path) or new.is_directory:
            return
        expected_key = self._key(new.full_path)
        remote_meta = json.loads(new.extended.get("remote", "{}"))
        recorded = remote_meta.get("key", "")
        if recorded and recorded != expected_key:
            # renamed remote entry: copy to the new key BEFORE the
            # old object is dropped by the upcoming delete event —
            # for an uncached placeholder the old object is the only
            # copy of the bytes
            if new.chunks:
                r = session().get(f"{self.filer}{new.full_path}",
                                 timeout=600)
                r.raise_for_status()
                data = r.content
            else:
                data = self.client.read_file(recorded)
            re_ = self.client.write_file(expected_key, data)
            self._refresh_remote_meta(new, re_)
            self.pushed += 1
            return
        if not new.chunks:
            # placeholder/uncache bookkeeping — nothing local to push
            self.skipped += 1
            return
        if remote_meta.get("etag") and remote_meta.get("etag") == new.md5:
            self.skipped += 1  # our own post-upload metadata refresh
            return
        if remote_meta and not new.md5 and \
                remote_meta.get("size") == new.file_size:
            # remote.cache materialisation: chunks were read FROM the
            # remote object — pushing them back would be a no-op write
            self.skipped += 1
            return
        r = session().get(f"{self.filer}{new.full_path}", timeout=600)
        r.raise_for_status()
        data = r.content
        re_ = self.client.write_file(expected_key, data)
        self._refresh_remote_meta(new, re_)
        self.pushed += 1

    def _refresh_remote_meta(self, entry: Entry, re_) -> None:
        """Write the entry's remote metadata back (sets etag == md5 so
        the resulting event is recognised as ours and skipped).

        The event's entry snapshot may be stale by the time we run —
        posting it back verbatim would revert a concurrent newer write
        (and delete its chunks). Re-fetch the live entry and only attach
        the remote metadata if it is still the version we pushed."""
        r = session().get(f"{self.filer}{entry.full_path}",
                         params={"meta": "1"}, timeout=60)
        if r.status_code == 404:
            return  # deleted meanwhile; the delete event will mirror it
        r.raise_for_status()
        live = r.json()
        if entry.md5 and live.get("md5") and live["md5"] != entry.md5:
            return  # newer write in flight; its own event handles it
        ent = live
        ent.setdefault("extended", {})["remote"] = json.dumps(
            {"key": re_.key, "size": re_.size, "mtime": re_.mtime,
             "etag": entry.md5 or re_.etag})
        session().post(f"{self.filer}{entry.full_path}",
                      params={"meta": "1"}, data=json.dumps(ent),
                      timeout=60).raise_for_status()


def run_remote_sync(filer_url: str, dir: str) -> RemoteSyncWorker:
    w = RemoteSyncWorker(filer_url, dir)
    w.start()
    return w
