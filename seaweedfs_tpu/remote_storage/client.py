"""Remote-storage clients: the verbs a cloud tier must support.

Equivalent of /root/reference/weed/remote_storage/remote_storage.go:71-87
(RemoteStorageClient: Traverse / ReadFile / WriteFile / DeleteFile /
WriteDirectory / RemoveDirectory) with a factory registry keyed by type
(remote_storage.go RemoteStorageClientMaker). Two implementations work
in any environment: a local directory (tests, NFS-style mounts — the
reference's localsink analogue) and any S3-compatible endpoint via the
in-tree SigV4 signer. Cloud-SDK types (gcs, azure, b2, ...) would
register here the same way but their SDKs are not in this image.
"""
from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass
from typing import Callable, Iterator


@dataclass
class RemoteEntry:
    """Metadata of one remote object (filer.proto RemoteEntry)."""

    key: str  # path within the storage, no leading slash
    size: int = 0
    mtime: float = 0.0
    etag: str = ""

    def to_extended(self) -> dict:
        return {"key": self.key, "size": self.size,
                "mtime": self.mtime, "etag": self.etag}


class RemoteStorageClient:
    def traverse(self, prefix: str = "") -> Iterator[RemoteEntry]:
        raise NotImplementedError

    def head(self, key: str) -> RemoteEntry | None:
        raise NotImplementedError

    def read_file(self, key: str, offset: int = 0,
                  size: int = -1) -> bytes:
        raise NotImplementedError

    def write_file(self, key: str, data: bytes) -> RemoteEntry:
        raise NotImplementedError

    def delete_file(self, key: str) -> None:
        raise NotImplementedError

    # object stores have no real directories; the local client does
    def write_directory(self, key: str) -> None:
        pass

    def remove_directory(self, key: str) -> None:
        pass

    def list_buckets(self) -> list[str]:
        """Top-level containers of this storage (remote.mount.buckets;
        remote_storage.go RemoteStorageClient ListBuckets)."""
        raise NotImplementedError


class LocalRemoteClient(RemoteStorageClient):
    """A plain directory as the remote (type "local")."""

    def __init__(self, root: str = "", **_):
        if not root:
            raise ValueError("local remote storage needs a root dir")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, key: str) -> str:
        p = os.path.normpath(os.path.join(self.root, key.lstrip("/")))
        if p != self.root and not p.startswith(self.root + os.sep):
            raise PermissionError(f"key escapes storage root: {key}")
        return p

    def traverse(self, prefix: str = "") -> Iterator[RemoteEntry]:
        for dirpath, _, files in sorted(os.walk(self.root)):
            for f in sorted(files):
                full = os.path.join(dirpath, f)
                key = os.path.relpath(full, self.root).replace(os.sep, "/")
                if prefix and not key.startswith(prefix.lstrip("/")):
                    continue
                st = os.stat(full)
                yield RemoteEntry(key=key, size=st.st_size,
                                  mtime=st.st_mtime)

    def head(self, key: str) -> RemoteEntry | None:
        try:
            st = os.stat(self._abs(key))
        except FileNotFoundError:
            return None
        return RemoteEntry(key=key.lstrip("/"), size=st.st_size,
                           mtime=st.st_mtime)

    def list_buckets(self) -> list[str]:
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d)))

    def read_file(self, key: str, offset: int = 0,
                  size: int = -1) -> bytes:
        with open(self._abs(key), "rb") as f:
            f.seek(offset)
            return f.read(None if size < 0 else size)

    def write_file(self, key: str, data: bytes) -> RemoteEntry:
        p = self._abs(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)
        return RemoteEntry(key=key.lstrip("/"), size=len(data),
                           mtime=time.time(),
                           etag=hashlib.md5(data).hexdigest())

    def delete_file(self, key: str) -> None:
        try:
            os.remove(self._abs(key))
        except FileNotFoundError:
            pass

    def write_directory(self, key: str) -> None:
        os.makedirs(self._abs(key), exist_ok=True)

    def remove_directory(self, key: str) -> None:
        import shutil
        shutil.rmtree(self._abs(key), ignore_errors=True)


class S3RemoteClient(RemoteStorageClient):
    """Any S3-compatible endpoint (type "s3") — including this
    framework's own gateway (remote_storage/s3/s3_storage_client.go).
    HTTP mechanics live in the shared s3.client.S3Client."""

    def __init__(self, **conf):
        from ..s3.client import S3Client
        self._c = S3Client(**conf)

    @staticmethod
    def _entry(o) -> RemoteEntry:
        return RemoteEntry(key=o.key, size=o.size, mtime=o.mtime,
                           etag=o.etag)

    def traverse(self, prefix: str = "") -> Iterator[RemoteEntry]:
        for o in self._c.list_objects(prefix):
            yield self._entry(o)

    def head(self, key: str) -> RemoteEntry | None:
        o = self._c.head_object(key)
        return self._entry(o) if o else None

    def read_file(self, key: str, offset: int = 0,
                  size: int = -1) -> bytes:
        return self._c.get_object(key, offset, size)

    def write_file(self, key: str, data: bytes) -> RemoteEntry:
        return self._entry(self._c.put_object(key, data))

    def delete_file(self, key: str) -> None:
        self._c.delete_object(key)

    def list_buckets(self) -> list[str]:
        return self._c.list_buckets()


_makers: dict[str, Callable[..., RemoteStorageClient]] = {
    "local": LocalRemoteClient,
    "s3": S3RemoteClient,
}

# present in the reference via cloud SDKs not shipped in this image;
# named so configuration errors are explicit, not "unknown type".
# (gcs and azure graduated to real in-tree REST clients; b2's
# S3-compatible endpoint works through type "s3".)
UNAVAILABLE_TYPES = ("aliyun", "tencent", "wasabi", "hdfs")


def register_remote(type_name: str,
                    maker: Callable[..., RemoteStorageClient]) -> None:
    _makers[type_name] = maker


def parse_remote_spec(spec: str) -> dict:
    """Parse a CLI/shell remote-tier spec into a client conf dict:
    full JSON (`{"type": "s3", ...}`) or the `local:<root>` shorthand
    (`-tier.remote=local:/mnt/cold`)."""
    spec = spec.strip()
    if not spec:
        raise ValueError("empty remote storage spec")
    if spec.startswith("{"):
        import json

        conf = json.loads(spec)
        if not isinstance(conf, dict) or "type" not in conf:
            raise ValueError(
                "remote storage spec JSON needs a 'type' field")
        return conf
    if ":" in spec:
        t, _, rest = spec.partition(":")
        if t == "local":
            return {"type": "local", "root": rest}
    raise ValueError(
        f"bad remote storage spec {spec!r}: use JSON with a 'type' "
        "field or the local:<root> shorthand")


def make_client(conf: dict) -> RemoteStorageClient:
    t = conf.get("type", "")
    if t in UNAVAILABLE_TYPES:
        raise KeyError(
            f"remote storage type {t!r} needs a cloud SDK not present "
            "in this build; available: " + ", ".join(sorted(_makers)))
    try:
        maker = _makers[t]
    except KeyError:
        raise KeyError(f"unknown remote storage type {t!r}; "
                       f"known: {sorted(_makers)}") from None
    return maker(**{k: v for k, v in conf.items() if k != "type"})
