"""Google Cloud Storage remote client over the raw JSON API.

The slot of /root/reference/weed/remote_storage/gcs/gcs_storage_client.go:21
with plain HTTP instead of cloud.google.com/go/storage — the same
zero-SDK approach as the filer wire stores.

Auth modes (pick one in remote.configure):
  (none)                — anonymous (public buckets, fake-gcs-server)
  -token=...            — static OAuth2 bearer token
  -token_url=...        — metadata-style endpoint returning
                          {"access_token": ..., "expires_in": ...}
                          (GCE/GKE workload identity)
  -credentials_file=... — service-account JSON key; the OAuth2 JWT
                          grant is signed in-tree (utils/rs256.py),
                          no google-auth needed

`-endpoint` overrides https://storage.googleapis.com for emulators.
"""
from __future__ import annotations

import urllib.parse
from typing import Iterator

import requests

from .client import RemoteEntry, RemoteStorageClient, register_remote

GCS_ENDPOINT = "https://storage.googleapis.com"
SCOPE = "https://www.googleapis.com/auth/devstorage.read_write"


def _rfc3339_to_unix(s: str) -> float:
    # 2024-01-02T03:04:05.678Z — stdlib-parsable after the tz fixup
    try:
        from datetime import datetime

        return datetime.fromisoformat(s.replace("Z", "+00:00")) \
            .timestamp()
    except ValueError:
        return 0.0


class GcsRemoteClient(RemoteStorageClient):
    def __init__(self, bucket: str = "", endpoint: str = "",
                 token: str = "", token_url: str = "",
                 credentials_file: str = "", project: str = "", **_):
        if not bucket:
            raise ValueError("gcs remote storage needs -bucket")
        from ..utils.gcp_auth import GcpTokenSource

        self.bucket = bucket
        self.endpoint = (endpoint or GCS_ENDPOINT).rstrip("/")
        self.project = project
        self._sess = requests.Session()
        self._tokens = GcpTokenSource(
            self._sess, token=token, token_url=token_url,
            credentials_file=credentials_file, scope=SCOPE)
        self._auth()  # fail fast on bad credentials

    def _auth(self) -> dict:
        return self._tokens.headers()

    # -- helpers --------------------------------------------------------
    def _obj_url(self, key: str, media: bool = False) -> str:
        q = urllib.parse.quote(key.lstrip("/"), safe="")
        url = (f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{q}")
        return url + "?alt=media" if media else url

    @staticmethod
    def _entry(item: dict) -> RemoteEntry:
        return RemoteEntry(
            key=item["name"], size=int(item.get("size", 0)),
            mtime=_rfc3339_to_unix(item.get("updated", "")),
            etag=item.get("md5Hash", item.get("etag", "")))

    # -- verbs ----------------------------------------------------------
    def traverse(self, prefix: str = "") -> Iterator[RemoteEntry]:
        page = ""
        while True:
            params = {"prefix": prefix.lstrip("/")}
            if page:
                params["pageToken"] = page
            r = self._sess.get(
                f"{self.endpoint}/storage/v1/b/{self.bucket}/o",
                params=params, headers=self._auth(), timeout=60)
            r.raise_for_status()
            d = r.json()
            for item in d.get("items", []):
                yield self._entry(item)
            page = d.get("nextPageToken", "")
            if not page:
                return

    def head(self, key: str) -> RemoteEntry | None:
        r = self._sess.get(self._obj_url(key), headers=self._auth(),
                           timeout=30)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return self._entry(r.json())

    def read_file(self, key: str, offset: int = 0,
                  size: int = -1) -> bytes:
        headers = self._auth()
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._sess.get(self._obj_url(key, media=True),
                           headers=headers, timeout=300)
        r.raise_for_status()
        return r.content

    def write_file(self, key: str, data: bytes) -> RemoteEntry:
        r = self._sess.post(
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o",
            params={"uploadType": "media", "name": key.lstrip("/")},
            data=data, headers={
                **self._auth(),
                "Content-Type": "application/octet-stream"},
            timeout=300)
        r.raise_for_status()
        return self._entry(r.json())

    def delete_file(self, key: str) -> None:
        r = self._sess.delete(self._obj_url(key), headers=self._auth(),
                              timeout=60)
        if r.status_code not in (204, 404):
            r.raise_for_status()

    def list_buckets(self) -> list[str]:
        params = {"project": self.project} if self.project else {}
        r = self._sess.get(f"{self.endpoint}/storage/v1/b",
                           params=params, headers=self._auth(),
                           timeout=30)
        r.raise_for_status()
        return sorted(i["name"] for i in r.json().get("items", []))


register_remote("gcs", GcsRemoteClient)
