"""ASCII armor for extended-attribute values riding HTTP headers.

The filer and the S3 gateway exchange entry extended attributes (the
x-amz-meta-* user metadata among them) as `x-seaweed-ext-*` headers so
a GET costs one round trip. Header bytes cross aiohttp (which encodes
str values as UTF-8) and fastclient (which decodes the head as
latin-1), so a non-ASCII value would round-trip corrupted unless it is
armored to pure ASCII on the wire. Percent-encoding keeps the stored
value exact: armor on emit, unarmor on parse, store the true bytes.

The reference carries the same metadata inside protobuf entries
(filer_pb Entry.Extended, /root/reference/weed/filer/filer.go) so it
never faces the issue; this is the header-wire equivalent.
"""
from __future__ import annotations

import urllib.parse


def armor(value: str) -> str:
    """-> pure-ASCII form safe for an HTTP header value (no CR/LF/%,
    no leading/trailing whitespace ambiguity, no non-ASCII)."""
    return urllib.parse.quote(str(value), safe="/")


def unarmor(value: str) -> str:
    return urllib.parse.unquote(value)
