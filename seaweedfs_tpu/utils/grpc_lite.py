"""Minimal gRPC client: HTTP/2 framing + HPACK + length-prefixed
messages, unary calls over one cleartext (h2c) connection — plus a tiny
protobuf wire encoder/decoder.

The reference speaks gRPC everywhere (its filer stores ride clientv3 /
client-go / ydb-go-sdk; its own services are gRPC). This build's RPC
substrate is HTTP+WS by design, but the store families that ONLY talk
gRPC (tikv, ydb, native etcd v3) need the real thing — so here it is
in-tree, from the RFCs (7540 framing, 7541 HPACK incl. the Appendix B
Huffman code) and the gRPC HTTP/2 transport spec, zero SDK. Validated
in tests against a real grpc-core server (tests/test_grpc_lite.py).

Scope: unary calls, h2c (no TLS — same scope as the reference's
default plaintext gRPC between cluster peers), one call at a time per
channel (the filer-store contract serializes anyway). Flow control is
honored on both directions; interleaved SETTINGS/PING/WINDOW_UPDATE/
GOAWAY frames are handled mid-call.
"""
from __future__ import annotations

import socket
import struct
import threading

# ---------------------------------------------------------------------------
# protobuf wire helpers (encoding spec: varint=0, fixed64=1, bytes=2,
# fixed32=5)
# ---------------------------------------------------------------------------


def pb_varint(v: int) -> bytes:
    out = bytearray()
    if v < 0:
        v += 1 << 64  # negative int64s encode as 10-byte varints
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def pb_tag(field: int, wire: int) -> bytes:
    return pb_varint(field << 3 | wire)


def pb_bytes(field: int, data: bytes) -> bytes:
    return pb_tag(field, 2) + pb_varint(len(data)) + data


def pb_str(field: int, s: str) -> bytes:
    return pb_bytes(field, s.encode())


def pb_uint(field: int, v: int) -> bytes:
    return b"" if v == 0 else pb_tag(field, 0) + pb_varint(v)


def pb_bool(field: int, v: bool) -> bytes:
    return pb_uint(field, 1 if v else 0)


def pb_decode(data: bytes) -> dict[int, list]:
    """Generic decode -> {field: [value, ...]} (varints as int, bytes
    as bytes; nested messages stay bytes for the caller to pb_decode)."""
    out: dict[int, list] = {}
    i, n = 0, len(data)
    while i < n:
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(data, i)
        elif wire == 1:
            v = struct.unpack_from("<Q", data, i)[0]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(data, i)
            v = data[i:i + ln]
            if len(v) != ln:
                raise ValueError("truncated protobuf")
            i += ln
        elif wire == 5:
            v = struct.unpack_from("<I", data, i)[0]
            i += 4
        else:
            raise ValueError(f"protobuf wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def pb_first(msg: dict[int, list], field: int, default=None):
    vals = msg.get(field)
    return vals[0] if vals else default


def _read_varint(data: bytes, i: int) -> tuple[int, int]:
    v = shift = 0
    while True:
        if i >= len(data):
            raise ValueError("truncated varint")
        b = data[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


# ---------------------------------------------------------------------------
# HPACK (RFC 7541)
# ---------------------------------------------------------------------------

# Appendix A static table (index 1..61)
_STATIC = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""),
    ("access-control-allow-origin", ""), ("age", ""), ("allow", ""),
    ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""),
    ("content-location", ""), ("content-range", ""),
    ("content-type", ""), ("cookie", ""), ("date", ""), ("etag", ""),
    ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""),
    ("last-modified", ""), ("link", ""), ("location", ""),
    ("max-forwards", ""), ("proxy-authenticate", ""),
    ("proxy-authorization", ""), ("range", ""), ("referer", ""),
    ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""),
    ("via", ""), ("www-authenticate", ""),
]

# Appendix B Huffman code: (code, bit length) per symbol 0..255 + EOS
_HUFFMAN = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12),
    (0x1ff9, 13), (0x15, 6), (0xf8, 8), (0x7fa, 11),
    (0x3fa, 10), (0x3fb, 10), (0xf9, 8), (0x7fb, 11),
    (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6),
    (0x1a, 6), (0x1b, 6), (0x1c, 6), (0x1d, 6),
    (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10),
    (0x1ffa, 13), (0x21, 6), (0x5d, 7), (0x5e, 7),
    (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7),
    (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7),
    (0x67, 7), (0x68, 7), (0x69, 7), (0x6a, 7),
    (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7),
    (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7),
    (0xfc, 8), (0x73, 7), (0xfd, 8), (0x1ffb, 13),
    (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5),
    (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6),
    (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5),
    (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5),
    (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15),
    (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13), (0xffffffc, 28),
    (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20), (0xfffe8, 20),
    (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22), (0x7fffd9, 23),
    (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23), (0x7fffdc, 23),
    (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24), (0x7fffdf, 23),
    (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22), (0x7fffe0, 23),
    (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23), (0x7fffe3, 23),
    (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22), (0x7fffe5, 23),
    (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23), (0xffffef, 24),
    (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20), (0x3fffdb, 22),
    (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23), (0x1fffde, 21),
    (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22), (0xfffff0, 24),
    (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23), (0x7fffec, 23),
    (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22), (0x1fffe2, 21),
    (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23), (0x7fffef, 23),
    (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22), (0x3fffe4, 22),
    (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22), (0x7ffff1, 23),
    (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20), (0x7fff1, 19),
    (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22), (0x1ffffec, 25),
    (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26), (0x7ffffde, 27),
    (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24), (0x1ffffed, 25),
    (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26), (0x7ffffe0, 27),
    (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27), (0xfffff2, 24),
    (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26), (0x3ffffe9, 26),
    (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27), (0x7ffffe5, 27),
    (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20), (0x1fffe6, 21),
    (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21), (0x7ffff3, 23),
    (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25), (0x1ffffef, 25),
    (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26), (0x7ffff4, 23),
    (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26), (0x3ffffed, 26),
    (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27), (0x7ffffea, 27),
    (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27), (0x7ffffed, 27),
    (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27), (0x3ffffee, 26),
    (0x3fffffff, 30),
]


def _build_huffman_tree():
    # binary trie: node = [left, right]; leaves = symbol int
    root: list = [None, None]
    for sym, (code, length) in enumerate(_HUFFMAN[:256]):
        node = root
        for bit in range(length - 1, -1, -1):
            b = (code >> bit) & 1
            if bit == 0:
                node[b] = sym
            else:
                if node[b] is None:
                    node[b] = [None, None]
                node = node[b]
    return root


_HUFF_ROOT = _build_huffman_tree()


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = _HUFF_ROOT
    for byte in data:
        for bit in range(7, -1, -1):
            node = node[(byte >> bit) & 1]
            if node is None:
                raise ValueError("bad huffman code")
            if isinstance(node, int):
                out.append(node)
                node = _HUFF_ROOT
    # trailing bits must be a prefix of EOS (all ones) — tolerated
    return bytes(out)


class HpackDecoder:
    """Response-side HPACK state: static + dynamic table, all literal
    forms, Huffman strings, table-size updates."""

    def __init__(self, max_size: int = 4096):
        self.dynamic: list[tuple[str, str]] = []
        self.max_size = max_size
        self.size = 0

    def _entry(self, idx: int) -> tuple[str, str]:
        if idx <= 0:
            raise ValueError("hpack index 0")
        if idx <= len(_STATIC):
            return _STATIC[idx - 1]
        didx = idx - len(_STATIC) - 1
        if didx >= len(self.dynamic):
            raise ValueError(f"hpack index {idx} out of range")
        return self.dynamic[didx]

    def _add(self, name: str, value: str) -> None:
        self.dynamic.insert(0, (name, value))
        self.size += len(name) + len(value) + 32
        while self.size > self.max_size and self.dynamic:
            n, v = self.dynamic.pop()
            self.size -= len(n) + len(v) + 32

    def decode(self, data: bytes) -> list[tuple[str, str]]:
        out: list[tuple[str, str]] = []
        i = 0
        while i < len(data):
            b = data[i]
            if b & 0x80:  # indexed
                idx, i = self._int(data, i, 7)
                out.append(self._entry(idx))
            elif b & 0x40:  # literal, incremental indexing
                idx, i = self._int(data, i, 6)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, i = self._string(data, i)
                value, i = self._string(data, i)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                new, i = self._int(data, i, 5)
                self.max_size = new
                while self.size > self.max_size and self.dynamic:
                    n, v = self.dynamic.pop()
                    self.size -= len(n) + len(v) + 32
            else:  # literal without indexing / never indexed
                idx, i = self._int(data, i, 4)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, i = self._string(data, i)
                value, i = self._string(data, i)
                out.append((name, value))
        return out

    @staticmethod
    def _int(data: bytes, i: int, prefix: int) -> tuple[int, int]:
        mask = (1 << prefix) - 1
        v = data[i] & mask
        i += 1
        if v < mask:
            return v, i
        shift = 0
        while True:
            b = data[i]
            i += 1
            v += (b & 0x7F) << shift
            if not b & 0x80:
                return v, i
            shift += 7

    def _string(self, data: bytes, i: int) -> tuple[str, int]:
        huff = bool(data[i] & 0x80)
        length, i = self._int(data, i, 7)
        raw = data[i:i + length]
        if len(raw) != length:
            raise ValueError("truncated hpack string")
        i += length
        if huff:
            raw = huffman_decode(raw)
        return raw.decode("utf-8", "replace"), i


def hpack_encode_raw(headers: list[tuple[str, str]]) -> bytes:
    """Request-side encoding: every field as 'literal without indexing,
    new name', raw strings — always legal, no encoder state."""
    out = bytearray()
    for name, value in headers:
        out.append(0x00)
        nb, vb = name.encode(), value.encode()
        out += _hpack_len(len(nb)) + nb
        out += _hpack_len(len(vb)) + vb
    return bytes(out)


def _hpack_len(n: int) -> bytes:
    if n < 127:
        return bytes([n])
    out = bytearray([127])
    n -= 127
    while n >= 128:
        out.append(n & 0x7F | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


# ---------------------------------------------------------------------------
# HTTP/2 + gRPC
# ---------------------------------------------------------------------------

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
F_DATA, F_HEADERS, F_RST, F_SETTINGS = 0, 1, 3, 4
F_PING, F_GOAWAY, F_WINDOW_UPDATE, F_CONTINUATION = 6, 7, 8, 9
FLAG_END_STREAM, FLAG_END_HEADERS, FLAG_ACK, FLAG_PADDED = 1, 4, 1, 8


class GrpcError(IOError):
    def __init__(self, code: int, message: str):
        super().__init__(f"grpc-status {code}: {message}")
        self.code = code
        self.message = message


class GrpcChannel:
    """One h2c connection; unary calls serialized by a lock. Dead
    connections re-dial on the next call."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2379,
                 timeout: float = 30.0, authority: str | None = None):
        self.host, self.port = host, int(port)
        self.timeout = timeout
        self.authority = authority or f"{host}:{port}"
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._next_stream = 1
        self._decoder = HpackDecoder()
        self._recv_buf = b""
        self._max_frame = 16384
        self._send_window = 65535       # connection-level
        self._peer_initial_window = 65535
        self._stream_window = 65535     # the single active stream's

    # -- connection -----------------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.sendall(PREFACE + self._frame(F_SETTINGS, 0, 0, b""))
        self._sock = s
        self._next_stream = 1
        self._decoder = HpackDecoder()
        self._recv_buf = b""
        self._send_window = 65535
        self._peer_initial_window = 65535
        return s

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def close(self) -> None:
        with self._lock:
            self._teardown()

    @staticmethod
    def _frame(ftype: int, flags: int, stream: int,
               payload: bytes) -> bytes:
        return struct.pack(">I", len(payload))[1:] + \
            bytes([ftype, flags]) + struct.pack(">I", stream) + payload

    def _read_frame(self, s) -> tuple[int, int, int, bytes]:
        while len(self._recv_buf) < 9:
            got = s.recv(64 << 10)
            if not got:
                raise IOError("h2 connection closed")
            self._recv_buf += got
        length = int.from_bytes(self._recv_buf[:3], "big")
        ftype, flags = self._recv_buf[3], self._recv_buf[4]
        stream = struct.unpack(">I", self._recv_buf[5:9])[0] & 0x7FFFFFFF
        while len(self._recv_buf) < 9 + length:
            got = s.recv(64 << 10)
            if not got:
                raise IOError("h2 connection closed mid-frame")
            self._recv_buf += got
        payload = self._recv_buf[9:9 + length]
        self._recv_buf = self._recv_buf[9 + length:]
        return ftype, flags, stream, payload

    def _handle_conn_frame(self, s, ftype: int, flags: int,
                           payload: bytes) -> None:
        """Frames any peer may interleave at any time."""
        if ftype == F_SETTINGS and not flags & FLAG_ACK:
            for off in range(0, len(payload) - 5, 6):
                ident, value = struct.unpack_from(">HI", payload, off)
                if ident == 5:  # MAX_FRAME_SIZE
                    self._max_frame = value
                elif ident == 4:
                    # INITIAL_WINDOW_SIZE applies RETROACTIVELY to open
                    # streams (RFC 7540 §6.9.2) — grpc-core grants its
                    # 4MB send window this way, never via per-stream
                    # WINDOW_UPDATE before the first consume
                    delta = value - self._peer_initial_window
                    self._peer_initial_window = value
                    self._stream_window += delta
            s.sendall(self._frame(F_SETTINGS, FLAG_ACK, 0, b""))
        elif ftype == F_PING and not flags & FLAG_ACK:
            s.sendall(self._frame(F_PING, FLAG_ACK, 0, payload))
        elif ftype == F_GOAWAY:
            raise IOError("h2 GOAWAY from server")
        elif ftype == F_WINDOW_UPDATE:
            self._send_window += struct.unpack(">I", payload)[0]

    # -- unary call -----------------------------------------------------
    def unary(self, path: str, request: bytes,
              metadata: list[tuple[str, str]] | None = None) -> bytes:
        """POST `path` (e.g. '/tikvpb.Tikv/RawGet') with one
        length-prefixed message; returns the response message bytes.
        Raises GrpcError on non-zero grpc-status, IOError on transport
        failure (after one reconnect attempt for idempotent retry by
        the caller)."""
        with self._lock:
            try:
                return self._unary_locked(path, request, metadata)
            except GrpcError:
                raise  # application status: the stream drained cleanly,
                # the connection is healthy — keep it
            except (OSError, IOError) as e:
                self._teardown()
                # one retry on a fresh connection (dead keep-alive)
                try:
                    return self._unary_locked(path, request, metadata)
                except GrpcError:
                    raise
                except (OSError, IOError) as e2:
                    self._teardown()
                    raise IOError(f"grpc {path}: {e2}") from e2

    def _unary_locked(self, path, request, metadata) -> bytes:
        s = self._connect()
        stream = self._next_stream
        self._next_stream += 2
        headers = [(":method", "POST"), (":scheme", "http"),
                   (":path", path), (":authority", self.authority),
                   ("content-type", "application/grpc"),
                   ("te", "trailers")]
        headers += list(metadata or [])
        s.sendall(self._frame(F_HEADERS, FLAG_END_HEADERS, stream,
                              hpack_encode_raw(headers)))
        # length-prefixed message: flag(0=uncompressed) + u32 length
        lpm = b"\x00" + struct.pack(">I", len(request)) + request
        self._stream_window = self._peer_initial_window
        pending: list[tuple[int, int, bytes]] = []
        off = 0
        while off < len(lpm):
            while min(self._send_window, self._stream_window) <= 0:
                # blocked on flow control: service frames until a
                # window opens; anything else for our stream (an early
                # error response) is buffered for _read_response
                ftype, flags, fstream, payload = self._read_frame(s)
                if fstream == 0:
                    self._handle_conn_frame(s, ftype, flags, payload)
                elif ftype == F_WINDOW_UPDATE and fstream == stream:
                    self._stream_window += \
                        struct.unpack(">I", payload)[0]
                elif ftype == F_RST and fstream == stream:
                    raise IOError(
                        f"h2 RST_STREAM "
                        f"{struct.unpack('>I', payload)[0]}")
                elif fstream == stream:
                    pending.append((ftype, flags, payload))
            take = min(len(lpm) - off, self._max_frame,
                       self._send_window, self._stream_window)
            last = off + take >= len(lpm)
            s.sendall(self._frame(F_DATA,
                                  FLAG_END_STREAM if last else 0,
                                  stream, lpm[off:off + take]))
            self._send_window -= take
            self._stream_window -= take
            off += take
        if not lpm:
            s.sendall(self._frame(F_DATA, FLAG_END_STREAM, stream, b""))
        return self._read_response(s, stream, pending)

    def _read_response(self, s, stream: int,
                       pending: list | None = None) -> bytes:
        body = bytearray()
        headers: list[tuple[str, str]] = []
        header_block = b""
        in_headers = False
        queued = list(pending or [])
        while True:
            if queued:
                ftype, flags, payload = queued.pop(0)
                fstream = stream
            else:
                ftype, flags, fstream, payload = self._read_frame(s)
            if fstream == 0:
                self._handle_conn_frame(s, ftype, flags, payload)
                continue
            if fstream != stream:
                continue  # no other streams are open; ignore strays
            if ftype == F_RST:
                raise IOError(
                    f"h2 RST_STREAM {struct.unpack('>I', payload)[0]}")
            if ftype == F_HEADERS:
                if flags & FLAG_PADDED:
                    pad = payload[0]
                    payload = payload[1:len(payload) - pad]
                if flags & 0x20:  # PRIORITY
                    payload = payload[5:]
                header_block = payload
                in_headers = not flags & FLAG_END_HEADERS
                if not in_headers:
                    headers += self._decoder.decode(header_block)
            elif ftype == F_CONTINUATION and in_headers:
                header_block += payload
                if flags & FLAG_END_HEADERS:
                    in_headers = False
                    headers += self._decoder.decode(header_block)
            elif ftype == F_DATA:
                if flags & FLAG_PADDED:
                    pad = payload[0]
                    payload = payload[1:len(payload) - pad]
                body += payload
                if payload:
                    # replenish both windows so the server never stalls
                    upd = struct.pack(">I", len(payload))
                    s.sendall(
                        self._frame(F_WINDOW_UPDATE, 0, 0, upd) +
                        self._frame(F_WINDOW_UPDATE, 0, stream, upd))
            if flags & FLAG_END_STREAM and not in_headers and \
                    ftype in (F_DATA, F_HEADERS, F_CONTINUATION):
                break
        hmap = {k: v for k, v in headers}
        status = int(hmap.get("grpc-status", "0") or 0)
        if status != 0:
            raise GrpcError(status, hmap.get("grpc-message", ""))
        if hmap.get(":status", "200") != "200":
            raise IOError(f"h2 :status {hmap.get(':status')}")
        if not body:
            return b""
        if body[0] != 0:
            raise IOError("compressed grpc response unsupported")
        (mlen,) = struct.unpack_from(">I", body, 1)
        msg = bytes(body[5:5 + mlen])
        if len(msg) != mlen:
            raise IOError("truncated grpc message")
        return msg
