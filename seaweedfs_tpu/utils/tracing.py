"""Stdlib-only distributed tracing for the request path.

W3C-``traceparent``-style context (trace-id/span-id/flags) is generated
at the gateway edge (S3, filer HTTP, WebDAV), carried across internal
hops as a ``traceparent`` header by `rpc/httpclient.py` and
`rpc/fastclient.py`, and re-parsed by every server's aiohttp middleware.
Finished spans (name, start, duration, status, peer) land in a bounded
process-global ring buffer served as JSON from ``/debug/traces`` on each
server, are summarized into ``request_trace_seconds{service,handler}``
histograms, and — when a local root span exceeds the configurable slow
threshold — emit one structured glog line carrying the full span tree.

The core is importable without aiohttp; the middleware/handler factories
import it lazily so `operation/` and the EC package can depend on this
module from sync code.
"""
from __future__ import annotations

import contextvars
import json
import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager

from . import glog, metrics

_VERSION = "00"
_HEX = set("0123456789abcdef")

# -- configuration ------------------------------------------------------

_lock = threading.Lock()
_buffer_size = 1024
_spans: deque = deque(maxlen=_buffer_size)
_slow_threshold = 1.0  # seconds; <= 0 disables the slow-request log
_sample_rate = 1.0  # head-sampling fraction for the cluster collector
_sinks: list = []  # finished-span observers (cluster span pusher)


def configure(slow_threshold: float | None = None,
              buffer_size: int | None = None,
              sample_rate: float | None = None) -> None:
    """Adjust tracing knobs (CLI: -trace.slowThreshold/-trace.bufferSize/
    -trace.sample).

    Resizing the ring keeps the most recent spans.
    """
    global _slow_threshold, _buffer_size, _spans, _sample_rate
    with _lock:
        if slow_threshold is not None:
            _slow_threshold = float(slow_threshold)
        if buffer_size is not None and int(buffer_size) != _buffer_size:
            _buffer_size = max(1, int(buffer_size))
            _spans = deque(_spans, maxlen=_buffer_size)
        if sample_rate is not None:
            _sample_rate = min(1.0, max(0.0, float(sample_rate)))


def sample_rate() -> float:
    return _sample_rate


def slow_threshold() -> float:
    """-trace.slowThreshold in seconds; <= 0 means disabled. Shared by
    the slow-request log and the span pusher's keep-if-slow pass."""
    return _slow_threshold


def sample_decision(trace_id: str, rate: float | None = None) -> bool:
    """Deterministic head-sampling verdict for one trace.

    Hashes the trace-id's low 32 bits against the rate so every process
    reaches the same keep/drop decision without coordination — a kept
    trace is kept on all hops and stitches completely on the master.
    Malformed ids are kept (losing them would hide bugs, not traffic).
    """
    r = _sample_rate if rate is None else rate
    if r >= 1.0:
        return True
    if r <= 0.0:
        return False
    try:
        bucket = int(trace_id[-8:], 16)
    except (ValueError, TypeError):
        return True
    return bucket < r * 0x100000000


# -- span sinks ---------------------------------------------------------
# Observers called with each finished span record (a plain dict); the
# cluster span pusher registers here. Called outside the ring lock and
# exceptions are swallowed: a broken sink must never fail a request.


def add_sink(fn) -> None:
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn) -> None:
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


def reset() -> None:
    with _lock:
        _spans.clear()


# -- traceparent --------------------------------------------------------


class TraceContext:
    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: str, span_id: str, flags: str = "01"):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"TraceContext({format_traceparent(self)})"


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


def _is_hex(s: str, n: int) -> bool:
    return len(s) == n and all(c in _HEX for c in s)


def format_traceparent(ctx: TraceContext) -> str:
    return f"{_VERSION}-{ctx.trace_id}-{ctx.span_id}-{ctx.flags}"


def parse_traceparent(header: str | None) -> TraceContext | None:
    """Parse ``00-<32 hex>-<16 hex>-<2 hex>``; None on any malformation
    (unknown 'ff' version, all-zero ids, wrong lengths, bad chars)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    ver, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if not _is_hex(ver, 2) or ver == "ff":
        return None
    if ver == _VERSION and len(parts) != 4:
        return None
    if not _is_hex(trace_id, 32) or trace_id == "0" * 32:
        return None
    if not _is_hex(span_id, 16) or span_id == "0" * 16:
        return None
    if not _is_hex(flags, 2):
        return None
    return TraceContext(trace_id, span_id, flags)


# -- span recording -----------------------------------------------------

_current: contextvars.ContextVar[TraceContext | None] = \
    contextvars.ContextVar("seaweedfs_tpu_trace", default=None)


def current() -> TraceContext | None:
    return _current.get()


def current_traceparent() -> str:
    """Header value for the active span ("" when not tracing)."""
    ctx = _current.get()
    return format_traceparent(ctx) if ctx is not None else ""


def inject(headers: dict) -> dict:
    """Add a traceparent header for the active span (no-op otherwise)."""
    tp = current_traceparent()
    if tp:
        headers["traceparent"] = tp
    return headers


@contextmanager
def span(name: str, *, service: str = "", kind: str = "internal",
         peer: str = "", remote: TraceContext | None = None):
    """Record one span; yields the mutable record so callers can set
    ``rec["status"]`` (e.g. the HTTP response code).

    Parentage: an explicit ``remote`` context (incoming traceparent)
    wins, else the contextvar parent, else a fresh root trace.
    """
    parent = _current.get()
    if remote is not None:
        trace_id, parent_id = remote.trace_id, remote.span_id
    elif parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = new_trace_id(), ""
    ctx = TraceContext(trace_id, new_span_id())
    token = _current.set(ctx)
    rec = {
        "trace_id": trace_id,
        "span_id": ctx.span_id,
        "parent_id": parent_id,
        "service": service,
        "name": name,
        "kind": kind,
        "peer": peer,
        "start": time.time(),
        "duration": 0.0,
        "status": "",
    }
    t0 = time.perf_counter()
    try:
        yield rec
    except BaseException:
        rec["status"] = "error"
        raise
    finally:
        rec["duration"] = time.perf_counter() - t0
        _current.reset(token)
        _finish(rec)


def _finish(rec: dict) -> None:
    with _lock:
        _spans.append(rec)
        # slow logging fires at local ROOT REQUEST spans only: child
        # spans are covered by their root's tree, and long-running
        # internal roots (EC rebuilds etc.) are expected to be slow
        slow = (_slow_threshold > 0 and not rec["parent_id"]
                and rec["kind"] == "server"
                and rec["duration"] >= _slow_threshold)
    if rec["kind"] == "server":
        metrics.histogram_observe(
            "request_trace_seconds", rec["duration"],
            {"service": rec["service"] or "unknown",
             "handler": rec["name"] or "unknown"})
    if slow:
        _log_slow(rec)
    for sink in list(_sinks):
        try:
            sink(rec)
        except Exception:
            pass


def _span_tree(trace_id: str) -> list[dict]:
    """Recorded spans of one trace nested children-under-parents."""
    with _lock:
        flat = [dict(s) for s in _spans if s["trace_id"] == trace_id]
    by_id = {s["span_id"]: s for s in flat}
    roots: list[dict] = []
    for s in flat:
        s.setdefault("children", [])
        parent = by_id.get(s["parent_id"])
        if parent is not None:
            parent.setdefault("children", []).append(s)
        else:
            roots.append(s)
    return roots


def _log_slow(rec: dict) -> None:
    tree = _span_tree(rec["trace_id"])
    glog.warning(
        "slow request trace_id=%s service=%s handler=%s "
        "duration=%.6fs threshold=%.3fs spans=%s",
        rec["trace_id"], rec["service"], rec["name"], rec["duration"],
        _slow_threshold, json.dumps(tree, sort_keys=True))


def traces_json(limit: int = 20) -> list[dict]:
    """Most-recent-first traces (grouped spans) for /debug/traces."""
    with _lock:
        snap = list(_spans)
    order: list[str] = []
    groups: dict[str, list[dict]] = {}
    for s in reversed(snap):  # newest span first
        tid = s["trace_id"]
        if tid not in groups:
            if len(order) >= max(1, limit):
                continue
            groups[tid] = []
            order.append(tid)
        groups[tid].append(dict(s))
    return [{"trace_id": tid,
             "spans": sorted(groups[tid], key=lambda s: s["start"])}
            for tid in order]


# -- aiohttp glue (lazy imports: core stays stdlib-importable) ----------

_SKIP_PATHS = {"/metrics", "/status", "/healthz", "/debug/traces",
               "/cluster/traces", "/cluster/traces/push",
               "/cluster/metrics"}


def aiohttp_middleware(service: str):
    """Per-server tracing middleware: extracts the incoming traceparent
    (or starts a root trace) and records a server span named after the
    registered handler function."""
    from aiohttp import web

    @web.middleware
    async def trace_mw(request, handler):
        if request.path in _SKIP_PATHS:
            return await handler(request)
        remote = parse_traceparent(request.headers.get("traceparent"))
        route_handler = getattr(request.match_info.route, "handler", None)
        name = getattr(route_handler, "__name__", None) or request.method
        with span(name, service=service, kind="server", remote=remote,
                  peer=request.remote or "") as rec:
            resp = await handler(request)
            rec["status"] = str(resp.status)
            return resp

    return trace_mw


async def handle_debug_traces(request):
    """GET /debug/traces?limit=N — shared route handler for all servers.
    Also carries this process's circuit-breaker view (one stop for
    "why is this hop slow/failing"): {"traces": [...], "breakers":
    [...]}; plain list requests keep working via ?format=spans."""
    from aiohttp import web

    from . import retry as _retry

    try:
        limit = int(request.query.get("limit", "20"))
    except ValueError:
        limit = 20
    traces = traces_json(limit=limit)
    if request.query.get("format") == "spans":
        return web.json_response(traces)
    return web.json_response({"traces": traces,
                              "breakers": _retry.breakers_snapshot()})
