"""Minimal Prometheus-style metrics registry.

Equivalent role to /root/reference/weed/stats/metrics.go:31-140: counters,
gauges and latency histograms exposed at /metrics in the text exposition
format. Stdlib-only.
"""
from __future__ import annotations

import threading
from collections import defaultdict

_lock = threading.Lock()
_counters: dict[tuple[str, tuple], float] = defaultdict(float)
_gauges: dict[tuple[str, tuple], float] = {}
_histograms: dict[tuple[str, tuple], list[int]] = {}
_HIST_BUCKETS = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10]


def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
    return name, tuple(sorted((labels or {}).items()))


def counter_add(name: str, value: float = 1,
                labels: dict | None = None) -> None:
    with _lock:
        _counters[_key(name, labels)] += value


def gauge_set(name: str, value: float, labels: dict | None = None) -> None:
    with _lock:
        _gauges[_key(name, labels)] = value


def histogram_observe(name: str, seconds: float,
                      labels: dict | None = None) -> None:
    key = _key(name, labels)
    with _lock:
        buckets = _histograms.get(key)
        if buckets is None:
            buckets = [0] * (len(_HIST_BUCKETS) + 1)
            _histograms[key] = buckets
        for i, ub in enumerate(_HIST_BUCKETS):
            if seconds <= ub:
                buckets[i] += 1
                break
        else:
            buckets[-1] += 1
        _counters[_key(name + "_sum", labels)] += seconds
        _counters[_key(name + "_count", labels)] += 1


def _escape_label_value(v) -> str:
    # text exposition format: \ " and newline must be escaped in values
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


def render() -> str:
    """Text exposition format: one `# TYPE` line per family, histogram
    `_sum`/`_count` kept adjacent to their `_bucket` series."""
    lines = []
    with _lock:
        hist_names = {name for name, _ in _histograms}

        def is_hist_component(name: str) -> bool:
            return ((name.endswith("_sum") and name[:-4] in hist_names)
                    or (name.endswith("_count")
                        and name[:-6] in hist_names))

        last_family = None
        for (name, labels), v in sorted(_counters.items()):
            if is_hist_component(name):
                continue  # rendered with its histogram below
            if name != last_family:
                lines.append(f"# TYPE {name} counter")
                last_family = name
            lines.append(f"{name}{_fmt_labels(labels)} {v}")
        last_family = None
        for (name, labels), v in sorted(_gauges.items()):
            if name != last_family:
                lines.append(f"# TYPE {name} gauge")
                last_family = name
            lines.append(f"{name}{_fmt_labels(labels)} {v}")
        last_family = None
        for (name, labels), buckets in sorted(_histograms.items()):
            if name != last_family:
                lines.append(f"# TYPE {name} histogram")
                last_family = name
            cum = 0
            for i, ub in enumerate(_HIST_BUCKETS):
                cum += buckets[i]
                lab = dict(labels)
                lab["le"] = str(ub)
                lines.append(
                    f"{name}_bucket{_fmt_labels(tuple(sorted(lab.items())))}"
                    f" {cum}")
            cum += buckets[-1]
            lab = dict(labels)
            lab["le"] = "+Inf"
            lines.append(
                f"{name}_bucket{_fmt_labels(tuple(sorted(lab.items())))}"
                f" {cum}")
            s = _counters.get((name + "_sum", labels), 0.0)
            c = _counters.get((name + "_count", labels), 0.0)
            lines.append(f"{name}_sum{_fmt_labels(labels)} {s}")
            lines.append(f"{name}_count{_fmt_labels(labels)} {c}")
    return "\n".join(lines) + "\n"


def reset() -> None:
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()


# -- pushgateway loop ---------------------------------------------------
# The reference's JoinCluster...Start... pusher (stats/metrics.go):
# servers can periodically PUT their rendered metrics to a Prometheus
# pushgateway instead of (or besides) being scraped.

_push_thread = None
_push_stop = None
_push_lock = threading.Lock()  # start/stop may race across threads


def start_push(gateway_url: str, job: str,
               interval_seconds: float = 15.0,
               instance: str = "") -> None:
    """Start the background pusher (idempotent while one is alive).
    Each iteration renders the LIVE registry, so counters registered
    after start_push (the collector/federation families included) ride
    along without a restart."""
    global _push_thread, _push_stop
    import threading as _th

    import requests as _rq

    url = gateway_url.rstrip("/")
    if not url.startswith("http"):
        url = "http://" + url
    url += f"/metrics/job/{job}"
    if instance:
        url += f"/instance/{instance}"

    with _push_lock:
        if _push_thread is not None and _push_thread.is_alive():
            return
        stop = _th.Event()  # captured locally: stop_push nulling the
                            # global must not crash a loop mid-iteration

        def loop():
            while not stop.wait(interval_seconds):
                try:
                    _rq.put(url, data=render().encode(),
                            headers={"Content-Type": "text/plain"},
                            timeout=10)
                except _rq.RequestException:
                    pass  # gateway outages must never hurt the server

        _push_stop = stop
        _push_thread = _th.Thread(target=loop, daemon=True)
        _push_thread.start()


def stop_push(timeout: float = 5.0) -> None:
    """Signal the pusher and join it (bounded); safe to start_push
    again — and a no-op when called before any start_push."""
    global _push_thread, _push_stop
    with _push_lock:
        thread, stop = _push_thread, _push_stop
        _push_thread = None
        _push_stop = None
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout)
