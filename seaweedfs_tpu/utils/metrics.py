"""Minimal Prometheus-style metrics registry.

Equivalent role to /root/reference/weed/stats/metrics.go:31-140: counters,
gauges and latency histograms exposed at /metrics in the text exposition
format. Stdlib-only.
"""
from __future__ import annotations

import threading
from collections import defaultdict

_lock = threading.Lock()
_counters: dict[tuple[str, tuple], float] = defaultdict(float)
_gauges: dict[tuple[str, tuple], float] = {}
_histograms: dict[tuple[str, tuple], list[int]] = {}
_HIST_BUCKETS = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10]


def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
    return name, tuple(sorted((labels or {}).items()))


def counter_add(name: str, value: float = 1,
                labels: dict | None = None) -> None:
    with _lock:
        _counters[_key(name, labels)] += value


def gauge_set(name: str, value: float, labels: dict | None = None) -> None:
    with _lock:
        _gauges[_key(name, labels)] = value


def histogram_observe(name: str, seconds: float,
                      labels: dict | None = None) -> None:
    key = _key(name, labels)
    with _lock:
        buckets = _histograms.get(key)
        if buckets is None:
            buckets = [0] * (len(_HIST_BUCKETS) + 1)
            _histograms[key] = buckets
        for i, ub in enumerate(_HIST_BUCKETS):
            if seconds <= ub:
                buckets[i] += 1
                break
        else:
            buckets[-1] += 1
        _counters[_key(name + "_sum", labels)] += seconds
        _counters[_key(name + "_count", labels)] += 1


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def render() -> str:
    lines = []
    with _lock:
        for (name, labels), v in sorted(_counters.items()):
            lines.append(f"{name}{_fmt_labels(labels)} {v}")
        for (name, labels), v in sorted(_gauges.items()):
            lines.append(f"{name}{_fmt_labels(labels)} {v}")
        for (name, labels), buckets in sorted(_histograms.items()):
            cum = 0
            for i, ub in enumerate(_HIST_BUCKETS):
                cum += buckets[i]
                lab = dict(labels)
                lab["le"] = str(ub)
                lines.append(
                    f"{name}_bucket{_fmt_labels(tuple(sorted(lab.items())))}"
                    f" {cum}")
            cum += buckets[-1]
            lab = dict(labels)
            lab["le"] = "+Inf"
            lines.append(
                f"{name}_bucket{_fmt_labels(tuple(sorted(lab.items())))}"
                f" {cum}")
    return "\n".join(lines) + "\n"


def reset() -> None:
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()


# -- pushgateway loop ---------------------------------------------------
# The reference's JoinCluster...Start... pusher (stats/metrics.go):
# servers can periodically PUT their rendered metrics to a Prometheus
# pushgateway instead of (or besides) being scraped.

_push_thread = None
_push_stop = None


def start_push(gateway_url: str, job: str,
               interval_seconds: float = 15.0,
               instance: str = "") -> None:
    global _push_thread, _push_stop
    if _push_thread is not None:
        return
    import threading as _th

    import requests as _rq

    url = gateway_url.rstrip("/")
    if not url.startswith("http"):
        url = "http://" + url
    url += f"/metrics/job/{job}"
    if instance:
        url += f"/instance/{instance}"
    _push_stop = _th.Event()

    def loop():
        while not _push_stop.wait(interval_seconds):
            try:
                _rq.put(url, data=render().encode(),
                        headers={"Content-Type": "text/plain"},
                        timeout=10)
            except _rq.RequestException:
                pass  # gateway outages must never hurt the server

    _push_thread = _th.Thread(target=loop, daemon=True)
    _push_thread.start()


def stop_push() -> None:
    global _push_thread, _push_stop
    if _push_stop is not None:
        _push_stop.set()
    _push_thread = None
    _push_stop = None
