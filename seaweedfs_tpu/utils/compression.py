"""Transparent upload compression.

Equivalent of /root/reference/weed/util/compression.go +
needle_parse_upload.go: compressible payloads (by mime/extension) are
gzipped at the volume-server write path and the needle carries
FLAG_IS_COMPRESSED; reads inflate transparently (or pass gzip through
when the client accepts it). Compression is kept only when it actually
saves space — high-entropy data is stored as-is.
"""
from __future__ import annotations

import gzip

MIN_SIZE = 128          # tiny payloads aren't worth the header
MIN_SAVINGS = 0.1       # keep gzip only if >= 10% smaller
LEVEL = 3               # the reference uses fast gzip levels

_COMPRESSIBLE_MIME_PREFIXES = ("text/",)
_COMPRESSIBLE_MIMES = {
    "application/json", "application/xml", "application/xhtml+xml",
    "application/javascript", "application/x-javascript",
    "application/rss+xml", "application/atom+xml", "image/svg+xml",
    "application/wasm", "application/x-ndjson",
}
_COMPRESSIBLE_EXTS = {
    ".txt", ".json", ".jsonl", ".ndjson", ".xml", ".html", ".htm",
    ".css", ".js", ".mjs", ".csv", ".tsv", ".md", ".svg", ".log",
    ".yaml", ".yml", ".toml", ".ini", ".conf", ".go", ".py", ".c",
    ".h", ".cc", ".java", ".rs", ".sql", ".sh", ".proto", ".wasm",
}


def is_compressible(mime: str = "", name: str = "") -> bool:
    """Mime/extension test (util/compression.go
    IsCompressableFileType)."""
    mime = (mime or "").split(";")[0].strip().lower()
    if mime.startswith(_COMPRESSIBLE_MIME_PREFIXES):
        return True
    if mime in _COMPRESSIBLE_MIMES:
        return True
    name = (name or "").lower()
    dot = name.rfind(".")
    return dot >= 0 and name[dot:] in _COMPRESSIBLE_EXTS


def maybe_gzip(data: bytes) -> tuple[bytes, bool]:
    """-> (stored bytes, compressed?). Only compresses when it pays."""
    if len(data) < MIN_SIZE:
        return data, False
    gz = gzip.compress(data, LEVEL, mtime=0)  # deterministic
    if len(gz) <= len(data) * (1 - MIN_SAVINGS):
        return gz, True
    return data, False


def is_gzipped(data: bytes) -> bool:
    return data[:2] == b"\x1f\x8b"


def ungzip(data: bytes) -> bytes:
    """Inflate stored needle bytes (single home for the codec policy)."""
    return gzip.decompress(data)
