"""JWT (HS256) write authorization, stdlib-only.

Equivalent of /root/reference/weed/security/jwt.go:30 (per-fid signed
tokens the master/filer hand to clients for volume-server writes) and
guard.go:41 (white-list + token check). Tokens are standard JWS compact
form: base64url(header).base64url(payload).base64url(hmac-sha256).
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def sign_jwt(secret: str, fid: str, expires_seconds: int = 10) -> str:
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps({
        "exp": int(time.time()) + expires_seconds,
        "fid": fid,
    }).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64(sig)}"


def verify_jwt(secret: str, token: str, fid: str | None = None) -> dict:
    """-> payload dict; raises PermissionError on any failure."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
    except ValueError:
        raise PermissionError("malformed jwt") from None
    signing_input = f"{header_b64}.{payload_b64}".encode()
    expect = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    if not hmac.compare_digest(expect, _unb64(sig_b64)):
        raise PermissionError("jwt signature mismatch")
    payload = json.loads(_unb64(payload_b64))
    if payload.get("exp", 0) < time.time():
        raise PermissionError("jwt expired")
    if fid is not None and payload.get("fid") != fid:
        # exact claim match, like volume_server_handlers.go:183 — a signed
        # token with a missing/empty fid must NOT authorize arbitrary fids
        raise PermissionError("jwt fid mismatch")
    return payload


class Guard:
    """Request guard: if a secret is configured, writes need a valid
    Authorization: Bearer token (security/guard.go:41)."""

    def __init__(self, secret: str = ""):
        self.secret = secret

    @property
    def enabled(self) -> bool:
        return bool(self.secret)

    def check(self, auth_header: str | None, fid: str | None = None) -> None:
        if not self.enabled:
            return
        if not auth_header or not auth_header.startswith("Bearer "):
            raise PermissionError("missing jwt")
        if fid is not None and "_" in fid:
            # batch-assign slots ("fid_N") share the base fid's token —
            # the reference strips the suffix before comparing the claim
            # (volume_server_handlers.go:181)
            fid = fid[:fid.rfind("_")]
        verify_jwt(self.secret, auth_header[len("Bearer "):], fid)

    def sign(self, fid: str) -> str:
        return sign_jwt(self.secret, fid) if self.enabled else ""
