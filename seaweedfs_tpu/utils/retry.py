"""Unified retry / deadline / circuit-breaker layer for internal hops.

Three cooperating pieces, shared by both rpc clients and all four
servers (the same role filer.backoff + wdclient/exclusive_locks play in
the reference, folded into one policy object):

* ``RetryPolicy`` — capped exponential backoff with **full jitter**
  (AWS architecture-blog style: ``sleep = uniform(0, min(cap, base *
  2**attempt))``), a per-attempt timeout, and an overall deadline.
  Retries are idempotency-aware: GET/HEAD and explicitly-marked
  idempotent calls retry; non-idempotent requests are replayed only
  when the far end attests it never started the work (see
  ``RETRYABLE_HEADER``).

* **Deadlines** — a budget minted once at the gateway edge (S3/filer
  request middleware) and carried downstream on every internal hop via
  the ``X-Sw-Deadline`` header (absolute unix epoch seconds).  Servers
  reject work whose deadline already passed instead of computing a
  response nobody is waiting for.  The ambient deadline lives in a
  contextvar so it flows through ``asyncio`` tasks and
  ``asyncio.to_thread`` the same way trace context does.

* ``CircuitBreaker`` — per-peer consecutive connection-failure breaker
  with a half-open probe.  Callers fail fast to the next replica (or
  503 + Retry-After when there is nowhere else to go) instead of
  re-timing-out against a dead peer on every request.

Stdlib-only on purpose — both the sync ``requests`` client and the
asyncio fastclient import this.
"""
from __future__ import annotations

import contextlib
import contextvars
import random
import threading
import time
from dataclasses import dataclass, replace
from typing import Callable, Iterator

# absolute unix-epoch seconds, decimal string, minted at the gateway
DEADLINE_HEADER = "X-Sw-Deadline"
# a 503 carrying this header attests the server rejected the request
# BEFORE doing any work (fault injection, breaker shed, deadline check)
# — safe to replay even for non-idempotent methods
RETRYABLE_HEADER = "X-Sw-Retryable"

_IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "OPTIONS"})


class DeadlineExceeded(Exception):
    """The request's overall deadline passed before the work finished."""


class BreakerOpenError(ConnectionError):
    """Fail-fast refusal: the peer's circuit breaker is open.

    Subclasses ConnectionError so existing replica-failover paths that
    catch OSError treat it as "this peer is down, try the next one".
    """

    def __init__(self, peer: str, retry_after: float = 0.0):
        super().__init__(f"circuit open for peer {peer}")
        self.peer = peer
        self.retry_after = retry_after


# ---------------------------------------------------------------------------
# Deadline propagation
# ---------------------------------------------------------------------------

_deadline: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "sw_deadline", default=None)


def current_deadline() -> float | None:
    """Absolute epoch deadline for the ambient request, or None."""
    return _deadline.get()


def remaining(default: float | None = None) -> float | None:
    """Seconds left on the ambient deadline (may be <= 0), or default."""
    dl = _deadline.get()
    if dl is None:
        return default
    return dl - time.time()


def expired() -> bool:
    dl = _deadline.get()
    return dl is not None and dl <= time.time()


def check_deadline() -> None:
    """Raise DeadlineExceeded if the ambient deadline already passed."""
    if expired():
        raise DeadlineExceeded(
            f"deadline passed {time.time() - (_deadline.get() or 0):.3f}s ago")


@contextlib.contextmanager
def deadline_scope(budget: float | None = None,
                   absolute: float | None = None) -> Iterator[float | None]:
    """Bind a deadline for the duration of the with-block.

    ``budget`` is relative seconds from now, ``absolute`` an epoch
    timestamp (e.g. parsed from ``X-Sw-Deadline``).  An inner scope can
    only tighten an outer one — a downstream hop never outlives the
    budget the edge minted.
    """
    dl = absolute if absolute is not None else (
        time.time() + budget if budget is not None else None)
    outer = _deadline.get()
    if dl is None or (outer is not None and outer < dl):
        dl = outer
    token = _deadline.set(dl)
    try:
        yield dl
    finally:
        _deadline.reset(token)


def parse_deadline(value: str | None) -> float | None:
    """Parse an X-Sw-Deadline header value; garbage parses as None."""
    if not value:
        return None
    try:
        dl = float(value)
    except ValueError:
        return None
    # sanity: refuse deadlines more than a day out (clock-skew garbage)
    if dl - time.time() > 86400:
        return None
    return dl


def inject(headers: dict) -> dict:
    """Add X-Sw-Deadline to outgoing request headers (tracing.inject
    idiom).  No-op when no ambient deadline is set."""
    dl = _deadline.get()
    if dl is not None and DEADLINE_HEADER not in headers:
        headers[DEADLINE_HEADER] = f"{dl:.6f}"
    return headers


# ---------------------------------------------------------------------------
# Retry policy
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with full jitter + deadline awareness.

    One instance describes one hop class; ``DEFAULT`` (module level,
    tunable via ``configure`` / ``-retry.*`` CLI flags) covers ordinary
    internal calls.
    """
    max_attempts: int = 3
    base_delay: float = 0.02     # seconds; first backoff ∈ [0, base)
    max_delay: float = 1.0       # backoff cap
    attempt_timeout: float = 20.0  # per-attempt budget when no deadline

    def backoff(self, attempt: int,
                rng: random.Random | None = None) -> float:
        """Full-jitter sleep before attempt ``attempt`` (1-based retry
        index: first retry ⇒ attempt=1)."""
        cap = min(self.max_delay, self.base_delay * (2 ** max(0, attempt)))
        draw = (rng or random).uniform(0, cap)
        rem = remaining()
        if rem is not None:
            draw = min(draw, max(0.0, rem))
        return draw

    def attempt_budget(self) -> float:
        """Timeout for the next attempt: per-attempt cap, clipped to
        whatever is left of the overall deadline."""
        rem = remaining()
        if rem is None:
            return self.attempt_timeout
        if rem <= 0:
            raise DeadlineExceeded("no budget left for another attempt")
        return min(self.attempt_timeout, rem)

    @staticmethod
    def idempotent(method: str, marked: bool | None = None) -> bool:
        if marked is not None:
            return marked
        return method.upper() in _IDEMPOTENT_METHODS

    def should_retry(self, attempt: int, method: str, *,
                     idempotent: bool | None = None,
                     conn_failure: bool = False,
                     status: int | None = None,
                     retryable_response: bool = False) -> bool:
        """Decide whether attempt ``attempt`` (0-based, just failed)
        may be retried.

        * ``conn_failure`` — the request never reached the peer (connect
          refused / reset with zero response bytes): always replayable.
        * ``retryable_response`` — the response carried
          ``X-Sw-Retryable`` (server attests no work was done).
        * otherwise only idempotent methods retry, and only on
          connection-ish statuses (502/503/504).
        """
        if attempt + 1 >= self.max_attempts:
            return False
        if expired():
            return False
        if conn_failure or retryable_response:
            return True
        if not self.idempotent(method, idempotent):
            return False
        return status in (502, 503, 504)

    def call(self, fn: Callable, method: str = "GET", *,
             idempotent: bool | None = None,
             classify: Callable | None = None,
             rng: random.Random | None = None):
        """Sync retry loop: ``fn(timeout)`` is invoked up to
        ``max_attempts`` times.  ``classify(exc_or_result)`` returns a
        dict of should_retry kwargs (conn_failure/status/
        retryable_response); default treats OSError as conn failure.
        """
        last_exc: BaseException | None = None
        for attempt in range(self.max_attempts):
            if attempt:
                time.sleep(self.backoff(attempt, rng))
            check_deadline()
            try:
                result = fn(self.attempt_budget())
            except DeadlineExceeded:
                raise
            except Exception as exc:  # noqa: BLE001 — classified below
                last_exc = exc
                kw = (classify(exc) if classify is not None
                      else {"conn_failure": isinstance(exc, OSError)})
                if not self.should_retry(attempt, method,
                                         idempotent=idempotent, **kw):
                    raise
                continue
            if classify is not None:
                kw = classify(result)
                if kw and self.should_retry(attempt, method,
                                            idempotent=idempotent, **kw):
                    last_exc = None
                    continue
            return result
        if last_exc is not None:
            raise last_exc
        raise DeadlineExceeded("retry budget exhausted")


# ---------------------------------------------------------------------------
# Per-peer circuit breaker
# ---------------------------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


@dataclass
class _BreakerConfig:
    failure_threshold: int = 5   # consecutive conn failures to trip
    reset_timeout: float = 5.0   # seconds open before the probe


class CircuitBreaker:
    """Connection-failure breaker for one peer (host:port).

    Only *connection-level* failures count — an HTTP error status means
    the peer is alive and must reset the streak.  Thread-safe: the sync
    requests client and the asyncio fastclient share instances.
    """

    def __init__(self, peer: str, config: _BreakerConfig):
        self.peer = peer
        self._cfg = config
        self._lock = threading.Lock()
        self._failures = 0
        self._state = CLOSED
        self._opened_at = 0.0
        self._probing = False
        self._probe_at = 0.0
        self.trips = 0  # lifetime trip count (metric)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        now = time.time()
        if (self._state == OPEN and
                now - self._opened_at >= self._cfg.reset_timeout):
            self._state = HALF_OPEN
            self._probing = False
            return
        # probe lease: an admitted probe whose caller never settled it
        # (timeout path, injected fault, crashed thread) must not hold
        # the slot forever — after reset_timeout the lease expires and
        # the next caller may probe, so a peer is never fail-fast
        # process-wide until restart just because one probe got lost
        if (self._state == HALF_OPEN and self._probing and
                now - self._probe_at >= self._cfg.reset_timeout):
            self._probing = False

    def allow(self) -> bool:
        """May a request go to this peer right now?  In half-open state
        exactly one probe is admitted; the rest fail fast until the
        probe reports back (or its lease expires)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                self._probe_at = time.time()
                return True
            return False

    def retry_after(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._cfg.reset_timeout -
                       (time.time() - self._opened_at))

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = CLOSED
            self._probing = False

    def probe_inconclusive(self) -> None:
        """Settle an admitted half-open probe whose attempt ended
        without proof either way (timed out, dropped mid-stream): the
        peer is still suspect, so go back to OPEN with a fresh timer —
        and the probe slot is released rather than leaked."""
        with self._lock:
            if self._state == HALF_OPEN and self._probing:
                self._state = OPEN
                self._opened_at = time.time()
                self._probing = False

    def release_probe(self) -> None:
        """Release an admitted probe slot without judging the peer —
        the attempt never reached it (e.g. an injected fault fired
        before any bytes moved), so the next caller may probe at once."""
        with self._lock:
            if self._state == HALF_OPEN and self._probing:
                self._probing = False

    def record_failure(self) -> None:
        """Record one connection-level failure."""
        with self._lock:
            if self._state == HALF_OPEN:
                # failed probe: straight back to open, timer restarts
                self._state = OPEN
                self._opened_at = time.time()
                self._probing = False
                return
            self._failures += 1
            if (self._state == CLOSED and
                    self._failures >= self._cfg.failure_threshold):
                self._state = OPEN
                self._opened_at = time.time()
                self.trips += 1

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open()
            return {"peer": self.peer, "state": self._state,
                    "consecutive_failures": self._failures,
                    "trips": self.trips,
                    "retry_after": round(max(0.0, self._cfg.reset_timeout -
                                             (time.time() - self._opened_at))
                                         if self._state == OPEN else 0.0, 3)}


class BreakerRegistry:
    """Process-wide peer → breaker map (all clients share one view of
    peer health, like wdclient's vidMap is shared)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}
        self.config = _BreakerConfig()

    def for_peer(self, peer: str) -> CircuitBreaker:
        peer = peer.strip().removeprefix("http://").removeprefix("https://")
        peer = peer.split("/", 1)[0]
        with self._lock:
            br = self._breakers.get(peer)
            if br is None:
                br = self._breakers[peer] = CircuitBreaker(peer, self.config)
            return br

    def snapshot(self) -> list[dict]:
        with self._lock:
            brs = list(self._breakers.values())
        return [b.snapshot() for b in sorted(brs, key=lambda b: b.peer)]

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()

    def reset_peer(self, peer: str) -> bool:
        """Forget one peer's breaker (same normalization as
        ``for_peer``). A volume server that re-registers after a
        restart is a fresh process — it must not inherit the dead
        process's OPEN breaker, or every client shuns it for a full
        reset_timeout after it came back healthy. Returns True when
        state existed and was dropped."""
        peer = peer.strip().removeprefix("http://").removeprefix("https://")
        peer = peer.split("/", 1)[0]
        with self._lock:
            return self._breakers.pop(peer, None) is not None


_registry = BreakerRegistry()


def breaker_for(peer: str) -> CircuitBreaker:
    return _registry.for_peer(peer)


def breakers_snapshot() -> list[dict]:
    return _registry.snapshot()


def reset_breakers() -> None:
    """Test hook: forget all peer state."""
    _registry.reset()


def reset_peer_breaker(peer: str) -> bool:
    """Drop one peer's breaker state (see BreakerRegistry.reset_peer)."""
    return _registry.reset_peer(peer)


# ---------------------------------------------------------------------------
# Process-wide defaults (tuned by cli.py global flags)
# ---------------------------------------------------------------------------

DEFAULT = RetryPolicy()
# budget minted at the gateway edge when the client sent no deadline;
# generous on purpose — it exists to bound runaway work (a dead peer
# chain), not to police ordinary large uploads
EDGE_BUDGET = 300.0
# hedged replica reads: fire the alternate after this many seconds
HEDGE_DELAY = 0.35


def configure(max_attempts: int | None = None,
              base_delay: float | None = None,
              max_delay: float | None = None,
              attempt_timeout: float | None = None,
              edge_budget: float | None = None,
              breaker_failures: int | None = None,
              breaker_reset: float | None = None,
              hedge_delay: float | None = None) -> None:
    """Apply -retry.* / -breaker.* / -hedge.* CLI flags."""
    global DEFAULT, EDGE_BUDGET, HEDGE_DELAY
    kw = {}
    if max_attempts is not None:
        kw["max_attempts"] = max(1, int(max_attempts))
    if base_delay is not None:
        kw["base_delay"] = float(base_delay)
    if max_delay is not None:
        kw["max_delay"] = float(max_delay)
    if attempt_timeout is not None:
        kw["attempt_timeout"] = float(attempt_timeout)
    if kw:
        DEFAULT = replace(DEFAULT, **kw)
    if edge_budget is not None:
        EDGE_BUDGET = float(edge_budget)
    if breaker_failures is not None:
        _registry.config.failure_threshold = max(1, int(breaker_failures))
    if breaker_reset is not None:
        _registry.config.reset_timeout = float(breaker_reset)
    if hedge_delay is not None:
        HEDGE_DELAY = float(hedge_delay)


def policy() -> RetryPolicy:
    return DEFAULT


# ---------------------------------------------------------------------------
# Server-side deadline middleware
# ---------------------------------------------------------------------------

def aiohttp_middleware(service: str, edge: bool = False):
    """Bind the request's deadline for the handler's context.

    Internal servers (``edge=False``) honour the X-Sw-Deadline header a
    caller sent and reject already-dead work with 504 before the
    handler runs.  Gateway-edge servers (``edge=True``: s3, filer) mint
    a fresh EDGE_BUDGET deadline when the client sent none, so every
    downstream hop inherits a bound.
    """
    from aiohttp import web

    _SKIP_PATHS = {"/metrics", "/debug/traces", "/debug/breakers",
                   "/status", "/healthz"}

    @web.middleware
    async def middleware(request, handler):
        if request.path in _SKIP_PATHS:
            return await handler(request)
        dl = parse_deadline(request.headers.get(DEADLINE_HEADER))
        if dl is not None and dl <= time.time():
            # nobody is waiting for this response any more
            return web.Response(status=504, text="deadline exceeded\n")
        if dl is None and edge:
            dl = time.time() + EDGE_BUDGET
        if dl is None:
            return await handler(request)
        token = _deadline.set(dl)
        try:
            return await handler(request)
        except DeadlineExceeded:
            return web.Response(status=504, text="deadline exceeded\n")
        finally:
            _deadline.reset(token)
    return middleware


def handle_debug_breakers_factory():
    """aiohttp handler for GET /debug/breakers (tracing's
    handle_debug_traces idiom)."""
    from aiohttp import web

    async def handle(request):
        return web.json_response({"breakers": breakers_snapshot()})
    return handle
