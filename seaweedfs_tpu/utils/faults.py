"""Deterministic fault injection for internal hops.

Chaos as a reproducible unit test instead of a SIGKILL race: a seeded
RNG decides, per matching rule, whether a hop gets an injected delay
and/or error.  Enabled via the ``-fault.spec`` global flag or the
``SEAWEEDFS_TPU_FAULT_SPEC`` env var; off (zero overhead beyond one
``enabled()`` check) by default.

Spec grammar — comma-separated rules::

    service:op:kind=value[,service:op:kind=value...]

* ``service`` — which hop the rule applies to: a server name as seen by
  its middleware (``master``/``volume``/``filer``/``s3``), a client
  component (``fastclient``/``httpclient``), or ``*``.
* ``op`` — ``read`` (GET/HEAD), ``write`` (POST/PUT/DELETE), or ``*``.
* ``kind=value`` — ``error=P`` injects a 503 with probability ``P``
  (0..1]; ``delay=30ms`` (also ``s``/``us`` suffixes, bare number =
  seconds) sleeps before the handler runs.

Example: ``volume:read:error=0.05,filer:*:delay=30ms``.

Injected errors fire **before** the handler touches any state and the
503 carries ``X-Sw-Retryable`` (see utils/retry.py), so a retried
non-idempotent request can never double-apply — that is what makes the
chaos e2e's "zero duplicate writes" assertion meaningful.
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass

_READ_METHODS = frozenset({"GET", "HEAD", "OPTIONS"})


class FaultSpecError(ValueError):
    """Malformed -fault.spec value."""


@dataclass(frozen=True)
class Rule:
    service: str   # master|volume|filer|s3|fastclient|httpclient|*
    op: str        # read|write|*
    kind: str      # error|delay
    value: float   # probability for error, seconds for delay

    def matches(self, service: str, op: str) -> bool:
        return (self.service in ("*", service) and
                self.op in ("*", op))


def _parse_duration(text: str) -> float:
    t = text.strip().lower()
    for suffix, scale in (("us", 1e-6), ("ms", 1e-3), ("s", 1.0)):
        if t.endswith(suffix):
            return float(t[:-len(suffix)]) * scale
    return float(t)


def _format_duration(seconds: float) -> str:
    if seconds >= 1.0 and float(seconds).is_integer():
        return f"{int(seconds)}s"
    ms = seconds * 1e3
    if ms >= 1.0 and float(ms).is_integer():
        return f"{int(ms)}ms"
    us = seconds * 1e6
    if float(us).is_integer():
        return f"{int(us)}us"
    return repr(seconds)


def parse_spec(text: str) -> list[Rule]:
    """Parse a -fault.spec string into rules; raises FaultSpecError on
    malformed input (a typo'd chaos spec must fail loudly at startup,
    not silently inject nothing)."""
    rules: list[Rule] = []
    for part in (p.strip() for p in text.split(",")):
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 3 or "=" not in fields[2]:
            raise FaultSpecError(
                f"bad fault rule {part!r}: want service:op:kind=value")
        service, op, kv = fields
        kind, _, raw = kv.partition("=")
        service, op, kind = service.strip(), op.strip(), kind.strip()
        if op not in ("read", "write", "*"):
            raise FaultSpecError(f"bad fault op {op!r} in {part!r}")
        if kind not in ("error", "delay"):
            raise FaultSpecError(f"bad fault kind {kind!r} in {part!r}")
        try:
            if kind == "error":
                value = float(raw)
                if not 0.0 < value <= 1.0:
                    raise ValueError
            else:
                value = _parse_duration(raw)
                if value < 0:
                    raise ValueError
        except ValueError as exc:
            raise FaultSpecError(
                f"bad fault value {raw!r} in {part!r}") from exc
        rules.append(Rule(service, op, kind, value))
    return rules


def format_spec(rules: list[Rule]) -> str:
    """Inverse of parse_spec (round-trips through parse_spec)."""
    parts = []
    for r in rules:
        raw = (_format_duration(r.value) if r.kind == "delay"
               else repr(r.value) if r.value != int(r.value)
               else repr(r.value))
        parts.append(f"{r.service}:{r.op}:{r.kind}={raw}")
    return ",".join(parts)


def op_of(method: str) -> str:
    return "read" if method.upper() in _READ_METHODS else "write"


class FaultRegistry:
    """Seeded, process-wide injection decisions + counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rules: list[Rule] = []
        self._rng = random.Random(0)
        self._seed = 0
        self._counts: dict[tuple[str, str], int] = {}

    def configure(self, spec: str | None, seed: int = 0) -> None:
        rules = parse_spec(spec) if spec else []
        with self._lock:
            self._rules = rules
            self._rng = random.Random(seed)
            self._seed = seed
            self._counts = {}

    @property
    def enabled(self) -> bool:
        return bool(self._rules)

    def decide(self, service: str, op: str) -> tuple[float, bool]:
        """(delay_seconds, inject_error) for one hop.  Deterministic
        for a fixed seed and call sequence."""
        if not self._rules:
            return 0.0, False
        delay = 0.0
        error = False
        with self._lock:
            for r in self._rules:
                if not r.matches(service, op):
                    continue
                if r.kind == "delay":
                    delay = max(delay, r.value)
                elif r.kind == "error" and self._rng.random() < r.value:
                    error = True
            if delay:
                self._counts[(service, "delay")] = \
                    self._counts.get((service, "delay"), 0) + 1
            if error:
                self._counts[(service, "error")] = \
                    self._counts.get((service, "error"), 0) + 1
        return delay, error


class FaultInjected(ConnectionError):
    """Raised by the client-side hook when a rule injects an error.

    Subclasses ConnectionError on purpose: an injected client fault
    models a connection that never carried the request, which is
    exactly the class of failure the retry layer may replay blindly.
    """


_registry = FaultRegistry()


def configure(spec: str | None = None, seed: int | None = None) -> None:
    """Apply -fault.spec / SEAWEEDFS_TPU_FAULT_SPEC.  ``seed`` defaults
    to SEAWEEDFS_TPU_FAULT_SEED or 0 for reproducible runs."""
    if spec is None:
        spec = os.environ.get("SEAWEEDFS_TPU_FAULT_SPEC") or None
    if seed is None:
        seed = int(os.environ.get("SEAWEEDFS_TPU_FAULT_SEED", "0"))
    _registry.configure(spec, seed)


def enabled() -> bool:
    return _registry.enabled


def rules() -> list[Rule]:
    """The active rule set (a copy) — lets sibling planes (the native
    volume front) mirror the configured spec at spawn."""
    with _registry._lock:
        return list(_registry._rules)


def seed() -> int:
    """The configured RNG seed (for mirroring into sibling planes)."""
    with _registry._lock:
        return _registry._seed


def native_params(service: str) -> tuple[float, float, float, float]:
    """Collapse the active rules for `service` into the four knobs the
    native front understands: (read_err, write_err, read_delay,
    write_delay). Probabilities combine as independent coin flips;
    delays stack like decide()'s max()."""
    read_keep = 1.0
    write_keep = 1.0
    read_delay = 0.0
    write_delay = 0.0
    for r in rules():
        for op in ("read", "write") if r.op == "*" else (r.op,):
            if not r.matches(service, op):
                continue
            if r.kind == "error":
                if op == "read":
                    read_keep *= 1.0 - r.value
                else:
                    write_keep *= 1.0 - r.value
            elif op == "read":
                read_delay = max(read_delay, r.value)
            else:
                write_delay = max(write_delay, r.value)
    return 1.0 - read_keep, 1.0 - write_keep, read_delay, write_delay


def counts() -> dict[str, int]:
    """{'service:kind': n} injection counters (metrics / assertions)."""
    with _registry._lock:
        return {f"{svc}:{kind}": n
                for (svc, kind), n in sorted(_registry._counts.items())}


def sync_hook(service: str, method: str) -> None:
    """Client-side hook for sync code paths: sleep the injected delay,
    raise FaultInjected for injected errors."""
    if not _registry.enabled:
        return
    delay, error = _registry.decide(service, op_of(method))
    if delay:
        time.sleep(delay)
    if error:
        raise FaultInjected(f"injected fault: {service} {method}")


async def async_hook(service: str, method: str) -> None:
    """Client-side hook for asyncio code paths."""
    if not _registry.enabled:
        return
    delay, error = _registry.decide(service, op_of(method))
    if delay:
        import asyncio

        await asyncio.sleep(delay)
    if error:
        raise FaultInjected(f"injected fault: {service} {method}")


def aiohttp_middleware(service: str):
    """Server-side injection, mounted after the tracing middleware.

    Injected errors answer 503 + X-Sw-Retryable before the handler
    runs (no state was touched ⇒ safe to replay); injected delays
    sleep in front of the handler so every downstream timing (client
    timeout, hedge, deadline) sees them.
    """
    import asyncio

    from aiohttp import web

    from . import retry as _retry

    _SKIP_PATHS = {"/metrics", "/debug/traces", "/debug/breakers",
                   "/status", "/healthz"}

    @web.middleware
    async def middleware(request, handler):
        if not _registry.enabled or request.path in _SKIP_PATHS:
            return await handler(request)
        delay, error = _registry.decide(service, op_of(request.method))
        if delay:
            await asyncio.sleep(delay)
        if error:
            return web.Response(
                status=503, text="fault injected\n",
                headers={_retry.RETRYABLE_HEADER: "1", "Retry-After": "0"})
        return await handler(request)

    return middleware
