"""Google OAuth2 token source, shared by every GCP REST client
(remote_storage/gcs_client.py, notification google_pub_sub).

Modes: static token / metadata-server token URL (GCE workload
identity) / service-account JSON key, whose RFC 7523 JWT grant is
RS256-signed in-tree (utils/rs256.py) — no google-auth SDK.
"""
from __future__ import annotations

import base64
import json
import time

TOKEN_URL = "https://oauth2.googleapis.com/token"


class GcpTokenSource:
    def __init__(self, session, token: str = "", token_url: str = "",
                 credentials_file: str = "",
                 scope: str = "https://www.googleapis.com/auth/"
                              "cloud-platform"):
        self._sess = session
        self._token_url = token_url
        self._scope = scope
        self._sa = None
        if credentials_file:
            with open(credentials_file) as f:
                self._sa = json.load(f)
        self._token = token
        self._token_exp = float("inf") if token else 0.0

    def headers(self) -> dict:
        """-> {"Authorization": ...} (empty dict = anonymous)."""
        if time.time() < self._token_exp - 60:
            return {"Authorization": f"Bearer {self._token}"} \
                if self._token else {}
        if self._token_url:
            r = self._sess.get(self._token_url,
                               headers={"Metadata-Flavor": "Google"},
                               timeout=30)
            r.raise_for_status()
            d = r.json()
            self._token = d["access_token"]
            self._token_exp = time.time() + float(
                d.get("expires_in", 3600))
        elif self._sa is not None:
            self._token, self._token_exp = self._jwt_grant()
        else:
            return {}
        return {"Authorization": f"Bearer {self._token}"}

    def _jwt_grant(self) -> tuple[str, float]:
        """OAuth2 JWT bearer grant signed with the service account's
        RSA key (what google-auth does under the hood)."""
        from . import rs256

        def b64(b: bytes) -> bytes:
            return base64.urlsafe_b64encode(b).rstrip(b"=")

        now = int(time.time())
        header = b64(json.dumps(
            {"alg": "RS256", "typ": "JWT"}).encode())
        token_uri = self._sa.get("token_uri", TOKEN_URL)
        claims = b64(json.dumps({
            "iss": self._sa["client_email"], "scope": self._scope,
            "aud": token_uri, "iat": now, "exp": now + 3600,
        }).encode())
        signing_input = header + b"." + claims
        sig = rs256.sign(self._sa["private_key"], signing_input)
        assertion = (signing_input + b"." + b64(sig)).decode()
        r = self._sess.post(token_uri, data={
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": assertion}, timeout=30)
        r.raise_for_status()
        d = r.json()
        return d["access_token"], time.time() + float(
            d.get("expires_in", 3600))
