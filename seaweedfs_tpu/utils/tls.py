"""TLS for the HTTP/WebSocket plane.

Equivalent of /root/reference/weed/security/tls.go — the reference
loads per-component cert/key/ca from security.toml and wires mutual
TLS into its gRPC channels. Here the transport is HTTP(S), so the
same configuration becomes ssl.SSLContext objects handed to the
aiohttp servers (rpc/http.py ServerThread) and, client-side, trusted
via the standard env vars (REQUESTS_CA_BUNDLE / SSL_CERT_FILE), which
requests and aiohttp both honor.

Config shape (JSON, `scaffold -config=security`):

    {"https": {"cert": "/path/server.crt", "key": "/path/server.key",
               "ca": "/path/ca.crt", "client_auth": false}}

`ca` + `client_auth: true` enables mutual TLS: only clients bearing a
certificate signed by that CA may connect.

generate_self_signed() mints a throwaway CA + server pair (tests,
quick starts) using the cryptography package when present, falling
back to the openssl binary.
"""
from __future__ import annotations

import datetime
import json
import os
import ssl
import subprocess


def load_security_config(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def server_ssl_context(cert: str, key: str, ca: str = "",
                       client_auth: bool = False) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    if ca:
        ctx.load_verify_locations(ca)
        if client_auth:
            ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(ca: str = "", cert: str = "",
                       key: str = "") -> ssl.SSLContext:
    ctx = ssl.create_default_context(
        cafile=ca or None)
    if cert:
        ctx.load_cert_chain(cert, key or None)
    return ctx


def context_from_config(conf: dict) -> ssl.SSLContext | None:
    https = conf.get("https", {})
    if not https.get("cert"):
        return None
    return server_ssl_context(https["cert"], https["key"],
                              ca=https.get("ca", ""),
                              client_auth=https.get("client_auth", False))


def generate_self_signed(out_dir: str, cn: str = "localhost",
                         sans: tuple[str, ...] = ("localhost",
                                                  "127.0.0.1")) -> dict:
    """Mint ca.crt/ca.key + server.crt/server.key (+ client pair)
    under out_dir; returns the path map."""
    os.makedirs(out_dir, exist_ok=True)
    paths = {n: os.path.join(out_dir, f)
             for n, f in (("ca_cert", "ca.crt"), ("ca_key", "ca.key"),
                          ("cert", "server.crt"), ("key", "server.key"),
                          ("client_cert", "client.crt"),
                          ("client_key", "client.key"))}
    try:
        _generate_with_cryptography(paths, cn, sans)
    except ImportError:  # pragma: no cover - image ships cryptography
        _generate_with_openssl(paths, cn, sans)
    return paths


def _generate_with_cryptography(paths: dict, cn: str,
                                sans: tuple[str, ...]) -> None:
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    def keypair():
        return ec.generate_private_key(ec.SECP256R1())

    def write_key(key, path):
        with open(path, "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()))

    def write_cert(cert, path):
        with open(path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))

    now = datetime.datetime.now(datetime.timezone.utc)
    week = now + datetime.timedelta(days=7)

    ca_key = keypair()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "seaweedfs-test-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now).not_valid_after(week)
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))
    write_key(ca_key, paths["ca_key"])
    write_cert(ca_cert, paths["ca_cert"])

    san_list = []
    for s in sans:
        try:
            san_list.append(x509.IPAddress(ipaddress.ip_address(s)))
        except ValueError:
            san_list.append(x509.DNSName(s))

    for role, cert_p, key_p in (("server", paths["cert"], paths["key"]),
                                ("client", paths["client_cert"],
                                 paths["client_key"])):
        key = keypair()
        cert = (x509.CertificateBuilder()
                .subject_name(x509.Name([x509.NameAttribute(
                    NameOID.COMMON_NAME, cn if role == "server"
                    else "seaweedfs-client")]))
                .issuer_name(ca_name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now).not_valid_after(week)
                .add_extension(x509.SubjectAlternativeName(san_list),
                               critical=False)
                .sign(ca_key, hashes.SHA256()))
        write_key(key, key_p)
        write_cert(cert, cert_p)


def _generate_with_openssl(paths: dict, cn: str,
                           sans: tuple[str, ...]) -> None:
    san = ",".join(
        (f"IP:{s}" if s.replace(".", "").isdigit() else f"DNS:{s}")
        for s in sans)
    def run(*a, **kw):
        subprocess.run(a, check=True, capture_output=True, **kw)
    run("openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
        "ec_paramgen_curve:prime256v1", "-keyout", paths["ca_key"],
        "-out", paths["ca_cert"], "-days", "7", "-nodes",
        "-subj", "/CN=seaweedfs-test-ca")
    for role, cert_p, key_p in (("server", paths["cert"], paths["key"]),
                                ("client", paths["client_cert"],
                                 paths["client_key"])):
        csr = cert_p + ".csr"
        run("openssl", "req", "-newkey", "ec", "-pkeyopt",
            "ec_paramgen_curve:prime256v1", "-keyout", key_p,
            "-out", csr, "-nodes", "-subj", f"/CN={cn}")
        run("openssl", "x509", "-req", "-in", csr, "-CA",
            paths["ca_cert"], "-CAkey", paths["ca_key"],
            "-CAcreateserial", "-out", cert_p, "-days", "7",
            "-extfile", "/dev/stdin",
            input=f"subjectAltName={san}".encode())
        os.remove(csr)
