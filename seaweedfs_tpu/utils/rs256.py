"""RS256 (RSASSA-PKCS1-v1_5 with SHA-256) signing, stdlib only.

Needed exactly once in this tree: Google service-account JWT grants
(remote_storage/gcs_client.py). A PEM private key is parsed from its
DER encoding (PKCS#8 `PrivateKeyInfo` wrapping, or a bare PKCS#1
`RSAPrivateKey`) with a ~40-line ASN.1 reader, and the signature is
the textbook `pow(em, d, n)` — RSA signing needs no randomness, so
the stdlib suffices. Verified against `openssl dgst -sha256 -sign`
in the test suite.
"""
from __future__ import annotations

import base64
import hashlib

# DigestInfo prefix for SHA-256 (RFC 8017 section 9.2 notes)
_SHA256_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def _pem_to_der(pem: str) -> bytes:
    lines = [ln.strip() for ln in pem.strip().splitlines()
             if ln.strip() and not ln.startswith("-----")]
    return base64.b64decode("".join(lines))


def _read_tlv(der: bytes, at: int) -> tuple[int, bytes, int]:
    """-> (tag, value, offset after the TLV)."""
    tag = der[at]
    length = der[at + 1]
    at += 2
    if length & 0x80:
        n = length & 0x7F
        length = int.from_bytes(der[at:at + n], "big")
        at += n
    return tag, der[at:at + length], at + length


def _parse_rsa_key(der: bytes) -> tuple[int, int]:
    """DER -> (n, d). Accepts PKCS#8 PrivateKeyInfo or PKCS#1
    RSAPrivateKey."""
    tag, body, _ = _read_tlv(der, 0)
    if tag != 0x30:
        raise ValueError("not a DER SEQUENCE")
    # collect the top-level sequence elements
    elems = []
    at = 0
    while at < len(body):
        t, v, at = _read_tlv(body, at)
        elems.append((t, v))
    if len(elems) >= 3 and elems[0][0] == 0x02 and elems[1][0] == 0x30:
        # PKCS#8: version, AlgorithmIdentifier, OCTET STRING(PKCS#1)
        return _parse_rsa_key(elems[2][1])
    # PKCS#1 RSAPrivateKey: version, n, e, d, p, q, ...
    ints = [int.from_bytes(v, "big") for t, v in elems if t == 0x02]
    if len(ints) < 4:
        raise ValueError("not an RSA private key")
    _version, n, _e, d = ints[:4]
    return n, d


def sign(private_key_pem: str, message: bytes) -> bytes:
    """RS256 signature of `message`."""
    n, d = _parse_rsa_key(_pem_to_der(private_key_pem))
    k = (n.bit_length() + 7) // 8
    digest = _SHA256_PREFIX + hashlib.sha256(message).digest()
    # EMSA-PKCS1-v1_5: 0x00 0x01 PS(0xff...) 0x00 DigestInfo
    ps_len = k - len(digest) - 3
    if ps_len < 8:
        raise ValueError("RSA key too small for SHA-256 DigestInfo")
    em = b"\x00\x01" + b"\xff" * ps_len + b"\x00" + digest
    sig = pow(int.from_bytes(em, "big"), d, n)
    return sig.to_bytes(k, "big")
