"""Per-tenant edge QoS: token-bucket admission + deadline-aware shed.

The internal hops are guarded (retries, deadlines, breakers — PR 2)
and the cluster self-heals (PR 4/7), but an overloaded S3/filer
gateway used to queue work until deadlines expired en masse: every
request was accepted, every response was a 504, and one greedy tenant
took the well-behaved ones down with it. The tail-at-scale literature
(Dean & Barroso) and f4's warm-store design both treat admission
control and load isolation as prerequisites for predictable tails —
this module is that edge layer, shared by both gateways:

* **Tenant buckets.** One reservation-style ``ratelimit.TokenBucket``
  per tenant (tenant = S3 access key at the S3 front, first path
  segment at the filer). Cardinality is BOUNDED: at most
  ``max_tenants`` distinct buckets; later arrivals share one
  ``__overflow__`` bucket, so a tenant-id spray can neither exhaust
  gateway memory nor explode the ``tenant`` metric label.
* **Async-aware acquisition.** Admission quotes a pacing delay from
  ``bucket.reserve``; the middleware ``await asyncio.sleep(wait)``s —
  never a blocking sleep on the event loop (the ROADMAP calls out the
  native fault-injection sleep pattern as exactly what NOT to reuse).
* **Deadline-aware shedding.** If the quoted queue delay exceeds the
  request's remaining ``X-Sw-Deadline`` budget the work is doomed to
  504 anyway — shed it NOW as 503 + ``Retry-After`` carrying the
  ``X-Sw-Retryable`` attestation (zero work done, safe to replay),
  and un-debit the reservation. Likewise when the delay exceeds
  ``max_delay``, the bound on acceptable queueing.
* **Weighted priority.** A tenant's ``priority`` divides the bytes
  charged per request: priority 2 pays half price for the same rate,
  i.e. classic weighted fair shares without a scheduler.

Config arrives via ``-qos.*`` CLI flags and an optional JSON spec
(``-qos.spec``), hot-reloaded on mtime change so operators can
re-rate a tenant mid-incident without a restart:

    {"default": {"rate": 2e6, "burst": 4e6, "priority": 1},
     "tenants": {"alice": {"rate": 8e6, "priority": 2}}}

Accounting lands in the standard registry (``qos_shed_total{tenant,
reason}``, ``qos_admitted_total{tenant}``,
``qos_queue_delay_seconds``), rides metrics federation into
``/cluster/metrics``, and is summarized at ``/debug/qos`` on both
gateways and under ``Qos`` in ``/cluster/status``.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time

from . import metrics
from . import sketch as _sketch
from .ratelimit import TokenBucket

OVERFLOW_TENANT = "__overflow__"
# floor charged per request (bytes): read/metadata ops carry no body
# but still cost a seek + a dispatch — shaping only writes would let
# a GET flood through unshaped
REQUEST_FLOOR = 4 << 10
# seconds between spec-file mtime checks (the hot-reload poll)
SPEC_CHECK_INTERVAL = 1.0


class Admission:
    """One admission verdict: either a pacing ``wait`` (admitted) or a
    ``shed_reason`` + ``retry_after`` hint (rejected, nothing owed)."""

    __slots__ = ("tenant", "wait", "shed_reason", "retry_after")

    def __init__(self, tenant: str, wait: float = 0.0,
                 shed_reason: str = "", retry_after: float = 0.0):
        self.tenant = tenant
        self.wait = wait
        self.shed_reason = shed_reason
        self.retry_after = retry_after

    @property
    def admitted(self) -> bool:
        return not self.shed_reason


class QosRegistry:
    """Bounded per-tenant bucket registry + admission policy."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        self.default_rate = 0.0     # bytes/sec per tenant; 0 = off
        self.default_burst: float | None = None
        self.default_priority = 1.0
        self.max_tenants = 256
        self.max_delay = 2.0        # seconds of queueing before shed
        self.request_floor = REQUEST_FLOOR
        self.spec_path = ""
        self._spec_mtime: float | None = None
        self._spec_checked = 0.0
        # per-tenant overrides from the JSON spec
        self._overrides: dict[str, dict] = {}
        self._buckets: dict[str, TokenBucket] = {}
        self._priority: dict[str, float] = {}
        self._admitted: dict[str, int] = {}
        self._shed: dict[tuple[str, str], int] = {}
        # per-tenant demand sketches (inter-arrival gap, body bytes,
        # queue delay) — recorded whether or not shaping is enabled,
        # bounded by the same max_tenants/__overflow__ rule as buckets
        self._demand: dict[str, dict] = {}

    # -- config ---------------------------------------------------------

    def configure(self, enabled: bool | None = None,
                  rate: float | None = None,
                  burst: float | None = None,
                  max_tenants: int | None = None,
                  max_delay: float | None = None,
                  request_floor: int | None = None,
                  spec: str | None = None) -> None:
        """Apply -qos.* CLI flags (None = leave unchanged)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if rate is not None:
                self.default_rate = float(rate)
            if burst is not None:
                self.default_burst = float(burst) if burst > 0 else None
            if max_tenants is not None:
                self.max_tenants = max(1, int(max_tenants))
            if max_delay is not None:
                self.max_delay = float(max_delay)
            if request_floor is not None:
                self.request_floor = max(1, int(request_floor))
            if spec is not None:
                self.spec_path = spec
                self._spec_mtime = None
                self._spec_checked = 0.0
            self._reconfigure_buckets_locked()
        if spec:
            self._maybe_reload_spec(force=True)

    def load_spec(self, spec: dict) -> None:
        """Hot-apply a JSON spec: {"default": {...}, "tenants":
        {name: {rate, burst, priority}}}. Existing buckets re-rate in
        place (waiters re-price, nothing is forgiven — see
        TokenBucket.configure)."""
        default = spec.get("default") or {}
        with self._lock:
            if "rate" in default:
                self.default_rate = float(default["rate"])
            if "burst" in default:
                self.default_burst = float(default["burst"]) or None
            if "priority" in default:
                self.default_priority = max(
                    1e-3, float(default["priority"]))
            self._overrides = {
                _clean_tenant(name): dict(cfg)
                for name, cfg in (spec.get("tenants") or {}).items()}
            self._reconfigure_buckets_locked()

    def _reconfigure_buckets_locked(self) -> None:
        for name, b in self._buckets.items():
            rate, burst, prio = self._tenant_cfg_locked(name)
            b.configure(rate, burst)
            self._priority[name] = prio

    def _tenant_cfg_locked(self, tenant: str) -> tuple[float,
                                                       float | None,
                                                       float]:
        o = self._overrides.get(tenant) or {}
        rate = float(o.get("rate", self.default_rate))
        burst = o.get("burst", self.default_burst)
        burst = float(burst) if burst else None
        prio = max(1e-3, float(o.get("priority",
                                     self.default_priority)))
        return rate, burst, prio

    def _maybe_reload_spec(self, force: bool = False) -> None:
        """mtime-gated spec reload: at most one stat() per
        SPEC_CHECK_INTERVAL, a parse only when the file changed. A
        malformed spec keeps the previous config (re-rating tenants
        mid-incident must not be all-or-nothing)."""
        path = self.spec_path
        if not path:
            return
        now = time.monotonic()
        with self._lock:
            if not force and now - self._spec_checked \
                    < SPEC_CHECK_INTERVAL:
                return
            self._spec_checked = now
            last = self._spec_mtime
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            return
        if not force and mtime == last:
            return
        try:
            with open(path, encoding="utf-8") as f:
                spec = json.load(f)
        except (OSError, ValueError):
            return
        with self._lock:
            self._spec_mtime = mtime
        self.load_spec(spec)

    # -- admission ------------------------------------------------------

    def _bucket_for(self, tenant: str) -> tuple[str, TokenBucket,
                                                float]:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                if len(self._buckets) >= self.max_tenants and \
                        tenant != OVERFLOW_TENANT:
                    # bounded cardinality: late tenants share one
                    # bucket (and one metric label value)
                    return self._bucket_for_locked(OVERFLOW_TENANT)
                return self._bucket_for_locked(tenant)
            return tenant, b, self._priority.get(
                tenant, self.default_priority)

    def _bucket_for_locked(self, tenant: str) -> tuple[str,
                                                       TokenBucket,
                                                       float]:
        b = self._buckets.get(tenant)
        if b is None:
            rate, burst, prio = self._tenant_cfg_locked(tenant)
            b = self._buckets[tenant] = TokenBucket(rate, burst)
            self._priority[tenant] = prio
            metrics.gauge_set("qos_tenants", len(self._buckets))
        return tenant, b, self._priority[tenant]

    def admit(self, tenant: str, cost: int,
              remaining: float | None) -> Admission:
        """Price one request for ``tenant``: ``cost`` bytes (floored
        at ``request_floor``, divided by the tenant's priority) against
        its bucket. Returns the pacing wait, or a shed verdict when
        the wait exceeds ``max_delay`` or the request's remaining
        deadline budget — in which case the reservation is cancelled:
        a shed request owes nothing."""
        if not self.enabled:
            return Admission(tenant)
        self._maybe_reload_spec()
        tenant, bucket, prio = self._bucket_for(_clean_tenant(tenant))
        if bucket.rate <= 0:
            return Admission(tenant)
        charged = int(max(self.request_floor, cost) / prio)
        wait = bucket.reserve(charged)
        reason = ""
        if wait > self.max_delay:
            reason = "rate"
        elif remaining is not None and wait > remaining:
            # doomed to 504 downstream: reject-early instead of
            # accepting work nobody will wait for
            reason = "deadline"
        if reason:
            bucket.cancel(charged)
            lab = {"tenant": tenant, "reason": reason}
            metrics.counter_add("qos_shed_total", labels=lab)
            with self._lock:
                self._shed[(tenant, reason)] = \
                    self._shed.get((tenant, reason), 0) + 1
            return Admission(tenant, shed_reason=reason,
                             retry_after=wait)
        metrics.counter_add("qos_admitted_total",
                            labels={"tenant": tenant})
        metrics.histogram_observe("qos_queue_delay_seconds", wait,
                                  labels={"tenant": tenant})
        with self._lock:
            self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
        return Admission(tenant, wait=wait)

    # -- tenant demand telemetry ---------------------------------------

    def record_demand(self, tenant: str, cost: int,
                      wait: float) -> None:
        """Sketch one request's demand signal for ``tenant`` (arrival
        gap, body bytes, queue delay). Runs whether or not shaping is
        enabled — the telemetry plane must see the workload before QoS
        is ever turned on — and is a no-op when telemetry is off."""
        if not _sketch.enabled():
            return
        now = time.time()
        tenant = _clean_tenant(tenant)
        with self._lock:
            d = self._demand.get(tenant)
            if d is None:
                if len(self._demand) >= self.max_tenants and \
                        tenant != OVERFLOW_TENANT:
                    # bounded label cardinality, same rule as buckets
                    tenant = OVERFLOW_TENANT
                    d = self._demand.get(tenant)
                if d is None:
                    d = self._demand[tenant] = {
                        "gap": _sketch.windowed(),
                        "bytes": _sketch.windowed(),
                        "delay": _sketch.windowed(),
                        "last_at": 0.0}
            if d["last_at"]:
                d["gap"].record(now - d["last_at"], now)
            d["last_at"] = now
            d["bytes"].record(max(0, int(cost)), now)
            d["delay"].record(max(0.0, wait), now)

    def _demand_rows_locked(self, now: float) -> list[tuple]:
        # (tenant, rate_rps, bytes_sketch, delay_sketch, gap_sketch,
        #  provisioned bytes/sec); caller holds _lock. Rate comes from
        # the mean inter-arrival gap inside the sliding window — exact
        # for steady arrivals, window-size independent.
        rows = []
        for name, d in self._demand.items():
            gap = d["gap"].merged(now)
            by = d["bytes"].merged(now)
            dl = d["delay"].merged(now)
            rate = 1.0 / gap.mean if gap.mean > 0 else 0.0
            b = self._buckets.get(name)
            prov = b.rate if b is not None else self.default_rate
            rows.append((name, rate, by, dl, gap, prov))
        return rows

    def demand_snapshot(self, now: float | None = None) -> dict:
        """Per-tenant demand digest + the provisioned rate each tenant
        is currently configured for (the QoS advisor's delta input)."""
        now = time.time() if now is None else now
        with self._lock:
            tenants = {
                name: {"rate_rps": round(rate, 3),
                       "bytes_per_sec": round(rate * by.mean, 1),
                       "bytes": by.summary(),
                       "delay": dl.summary(),
                       "gap": gap.summary(),
                       "provisioned_rate": prov}
                for name, rate, by, dl, gap, prov
                in self._demand_rows_locked(now)}
        return {"alpha": _sketch.alpha(), "window": _sketch.window(),
                "tenants": tenants}

    def export_demand_metrics(self, now: float | None = None) -> None:
        """Set ``workload_tenant_*`` gauges from the demand sketches.
        The gateways call this while rendering /metrics, so per-tenant
        demand rides the existing federation to the master's workload
        aggregator instead of needing a new wire."""
        if not _sketch.enabled():
            return
        now = time.time() if now is None else now
        with self._lock:
            rows = self._demand_rows_locked(now)
        for name, rate, by, dl, _gap, prov in rows:
            lab = {"tenant": name}
            metrics.gauge_set("workload_tenant_rate_rps", rate,
                              labels=lab)
            metrics.gauge_set("workload_tenant_bytes_per_sec",
                              rate * by.mean, labels=lab)
            metrics.gauge_set("workload_tenant_provisioned_rate",
                              prov, labels=lab)
            for q in ("0.5", "0.9", "0.99"):
                metrics.gauge_set("workload_tenant_bytes",
                                  by.quantile(float(q)),
                                  labels={"tenant": name, "q": q})
                metrics.gauge_set("workload_tenant_delay_seconds",
                                  dl.quantile(float(q)),
                                  labels={"tenant": name, "q": q})

    # -- introspection --------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            tenants = {}
            for name, b in self._buckets.items():
                st = b.state()
                st["priority"] = self._priority.get(
                    name, self.default_priority)
                st["admitted"] = self._admitted.get(name, 0)
                shed = {r: n for (t, r), n in self._shed.items()
                        if t == name}
                if shed:
                    st["shed"] = shed
                tenants[name] = st
            return {
                "enabled": self.enabled,
                "default_rate": self.default_rate,
                "default_burst": self.default_burst,
                "default_priority": self.default_priority,
                "max_tenants": self.max_tenants,
                "max_delay": self.max_delay,
                "request_floor": self.request_floor,
                "spec_path": self.spec_path,
                "tenants": tenants,
            }

    def reset(self) -> None:
        """Test hook: back to defaults, drop all buckets."""
        with self._lock:
            self.enabled = False
            self.default_rate = 0.0
            self.default_burst = None
            self.default_priority = 1.0
            self.max_tenants = 256
            self.max_delay = 2.0
            self.request_floor = REQUEST_FLOOR
            self.spec_path = ""
            self._spec_mtime = None
            self._spec_checked = 0.0
            self._overrides.clear()
            self._buckets.clear()
            self._priority.clear()
            self._admitted.clear()
            self._shed.clear()
            self._demand.clear()


def _clean_tenant(raw: str) -> str:
    """Bound the label value itself: printable, short, never empty."""
    t = "".join(c if c.isalnum() or c in "-_.+" else "_"
                for c in (raw or ""))[:64]
    return t or "anonymous"


_registry = QosRegistry()


def configure(**kw) -> None:
    _registry.configure(**kw)


def load_spec(spec: dict) -> None:
    _registry.load_spec(spec)


def admit(tenant: str, cost: int,
          remaining: float | None) -> Admission:
    return _registry.admit(tenant, cost, remaining)


def enabled() -> bool:
    return _registry.enabled


def snapshot() -> dict:
    return _registry.snapshot()


def record_demand(tenant: str, cost: int, wait: float) -> None:
    _registry.record_demand(tenant, cost, wait)


def demand_snapshot(now: float | None = None) -> dict:
    return _registry.demand_snapshot(now)


def export_demand_metrics(now: float | None = None) -> None:
    _registry.export_demand_metrics(now)


def reset() -> None:
    _registry.reset()


# -- tenant extraction ------------------------------------------------

def s3_tenant(request) -> str:
    """S3 tenant = the access key named by the request. Parsed
    cheaply, WITHOUT signature verification: attribution needs no
    authn (a spoofed key only buys its owner's — usually worse —
    rate), and admission must run before any per-request crypto."""
    auth = request.headers.get("Authorization", "")
    if auth.startswith("AWS4-HMAC-SHA256"):
        # Credential=AKID/20230101/us-east-1/s3/aws4_request
        i = auth.find("Credential=")
        if i >= 0:
            cred = auth[i + len("Credential="):].split(",", 1)[0]
            return cred.split("/", 1)[0]
    elif auth.startswith("AWS "):  # SigV2: "AWS AKID:signature"
        return auth[4:].split(":", 1)[0]
    cred = request.query.get("X-Amz-Credential", "")
    if cred:
        return cred.split("/", 1)[0]
    ak = request.query.get("AWSAccessKeyId", "")
    if ak:
        return ak
    return "anonymous"


def filer_tenant(request) -> str:
    """Filer tenant = first path segment (the top-level namespace a
    workload writes under)."""
    seg = request.path.lstrip("/").split("/", 1)[0]
    return seg or "_root"


# -- gateway middleware -----------------------------------------------

def aiohttp_middleware(service: str, tenant_of):
    """Admission middleware for the gateway edges. Sits between the
    deadline middleware (which binds the request's budget) and the
    handler: sheds with 503 + Retry-After + X-Sw-Retryable (zero work
    done — safe for clients to replay blindly), paces admitted
    requests with ``await asyncio.sleep`` (never a blocking sleep on
    the event loop)."""
    import asyncio

    from aiohttp import web

    from . import retry

    _SKIP_PATHS = {"/metrics", "/debug", "/status", "/healthz"}
    # filer control-plane prefixes: lock manager, KV config store and
    # the metadata subscription feed serve the cluster itself — QoS
    # shaping there would rate-limit identity reloads by tenant "kv".
    # All /debug/* pages ride the same exemption (they ARE the
    # instruments; shaping or sketching them would distort the read).
    _SKIP_PREFIXES = ("/dlm/", "/kv/", "/ws/", "/debug/")

    @web.middleware
    async def middleware(request, handler):
        if request.path in _SKIP_PATHS or \
                request.path.startswith(_SKIP_PREFIXES):
            return await handler(request)
        tenant = tenant_of(request)
        cost = request.content_length or 0
        if not _registry.enabled:
            # shaping off: still sketch the tenant's demand — the
            # workload plane must characterize traffic before QoS is
            # ever enabled (advisors bootstrap from exactly this)
            _registry.record_demand(tenant, cost, 0.0)
            return await handler(request)
        adm = _registry.admit(tenant, cost, retry.remaining())
        if not adm.admitted:
            return web.json_response(
                {"error": "per-tenant rate exceeded",
                 "tenant": adm.tenant, "reason": adm.shed_reason},
                status=503,
                headers={retry.RETRYABLE_HEADER: "1",
                         "Retry-After": str(max(1, int(math.ceil(
                             adm.retry_after))))})
        _registry.record_demand(adm.tenant, cost, adm.wait)
        if adm.wait > 0:
            await asyncio.sleep(adm.wait)
        return await handler(request)
    return middleware


def handle_debug_qos_factory():
    """aiohttp handler for GET /debug/qos (handle_debug_breakers
    idiom)."""
    from aiohttp import web

    async def handle(request):
        return web.json_response(snapshot())
    return handle
