"""Chunk encryption: AES-256-GCM with a random per-chunk key.

Equivalent of /root/reference/weed/util/cipher.go — GenCipherKey /
Encrypt / Decrypt. Wire format matches the reference: the random nonce
is prepended to the GCM ciphertext (which carries its auth tag), so a
stored cipher-chunk is nonce || ciphertext || tag. Each chunk gets its
OWN random 256-bit key, stored in the filer entry's chunk record
(filer_pb FileChunk.cipher_key) — the volume server only ever sees
ciphertext, and possession of the filer metadata is what grants
decryption.
"""
from __future__ import annotations

import os

KEY_SIZE = 32  # AES-256
NONCE_SIZE = 12  # GCM standard nonce


def gen_cipher_key() -> bytes:
    """Random per-chunk key (GenCipherKey, cipher.go:15)."""
    return os.urandom(KEY_SIZE)


def _aesgcm(key: bytes):
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    if len(key) != KEY_SIZE:
        raise ValueError(f"cipher key must be {KEY_SIZE} bytes")
    return AESGCM(key)


def encrypt(plaintext: bytes, key: bytes) -> bytes:
    """nonce || AES-256-GCM(plaintext) (Encrypt, cipher.go:23)."""
    nonce = os.urandom(NONCE_SIZE)
    return nonce + _aesgcm(key).encrypt(nonce, plaintext, None)


def decrypt(ciphertext: bytes, key: bytes) -> bytes:
    """Inverse of encrypt; raises ValueError on tamper/short input
    (Decrypt, cipher.go:41)."""
    if len(ciphertext) < NONCE_SIZE:
        raise ValueError("ciphertext too short")
    from cryptography.exceptions import InvalidTag

    nonce, ct = ciphertext[:NONCE_SIZE], ciphertext[NONCE_SIZE:]
    try:
        return _aesgcm(key).decrypt(nonce, ct, None)
    except InvalidTag as e:
        raise ValueError("cipher chunk failed authentication") from e
