"""HTTP byte-range parsing + multipart/byteranges assembly (RFC 7233).

The reference serves multi-range GETs as multipart/byteranges on both
the volume and filer read paths (weed/server/common.go
processRangeRequest:306-383, weed/server/volume_server_handlers_helper.go
parseRange); this module is the shared python implementation both
servers use. Semantics mirrored:

- header absent / non-"bytes" unit: no range (serve 200 full) —
  RFC 7233 §3.1 lets a server ignore units it doesn't recognize
- any syntactically bad spec: malformed (caller answers 416)
- a spec whose start is past EOF: unsatisfiable; if EVERY spec is,
  the request is unsatisfiable (416 with "Content-Range: bytes */N")
- sum of range lengths > total size: probably an attack or a dumb
  client — ignore the header, serve 200 full (common.go:312-318)
- one satisfiable range: plain 206 with Content-Range
- several: 206 multipart/byteranges, one MIME part per range
"""
from __future__ import annotations

import secrets

MALFORMED = "malformed"
UNSATISFIABLE = "unsatisfiable"
IGNORE = "ignore"


def parse_range_header(spec: str, size: int):
    """-> list[(start, length)] | MALFORMED | UNSATISFIABLE | IGNORE.

    An empty list means "no range" (absent header / foreign unit):
    serve the full body. IGNORE means the header was valid but the
    ranges sum past the object — serve the full body too.
    """
    if not spec:
        return []
    if not spec.startswith("bytes="):
        return []  # unknown unit: ignored per RFC 7233
    ranges: list[tuple[int, int]] = []
    saw_spec = False
    for part in spec[len("bytes="):].split(","):
        part = part.strip()
        if not part:
            continue
        saw_spec = True
        start_s, dash, end_s = part.partition("-")
        if not dash:
            return MALFORMED
        start_s, end_s = start_s.strip(), end_s.strip()
        try:
            if not start_s:  # suffix form "-N": the LAST N bytes
                n_last = int(end_s)
                if n_last < 0:
                    return MALFORMED
                start = max(0, size - n_last)
                length = size - start
                if length == 0:
                    continue  # "-0", or any suffix of an empty object
            else:
                start = int(start_s)
                if start < 0:
                    return MALFORMED
                end = int(end_s) if end_s else size - 1
                if end < start:
                    return MALFORMED
                if start >= size:
                    continue  # past EOF: this spec is unsatisfiable
                end = min(end, size - 1)
                length = end - start + 1
        except ValueError:
            return MALFORMED
        ranges.append((start, length))
    if saw_spec and not ranges:
        return UNSATISFIABLE
    if sum(length for _, length in ranges) > size:
        return IGNORE
    return ranges


def content_range(start: int, length: int, size: int) -> str:
    return f"bytes {start}-{start + length - 1}/{size}"


def multipart_byteranges(parts: list[tuple[int, int, bytes]],
                         mime: str, size: int) -> tuple[bytes, str]:
    """Assemble the multipart/byteranges body for `parts` of
    (start, length, data). -> (body, Content-Type header value)."""
    boundary = secrets.token_hex(16)
    out: list[bytes] = []
    for start, length, data in parts:
        head = (f"--{boundary}\r\n"
                + (f"Content-Type: {mime}\r\n" if mime else "")
                + f"Content-Range: {content_range(start, length, size)}"
                + "\r\n\r\n")
        out.append(head.encode())
        out.append(data)
        out.append(b"\r\n")
    out.append(f"--{boundary}--\r\n".encode())
    return b"".join(out), f"multipart/byteranges; boundary={boundary}"
