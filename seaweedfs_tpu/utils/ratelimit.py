"""Token-bucket byte-rate shaping for repair traffic.

The repair plane moves bulk bytes (replica re-copies, EC shard
reconstruction reads) over the same NICs and disks that serve
foreground traffic; the warehouse-cluster study (arxiv 1309.0186)
measures repair as the DOMINANT cross-rack load when it runs
unshaped. `-repair.maxBytesPerSec` caps it with one bucket per node:
every repair byte a node sends (copy_file / shard_read source side)
or receives (volume_copy / ec/copy destination side) draws from that
node's bucket, so the per-node total holds regardless of how many
concurrent transfers the bounded-concurrency workers drive.

Design notes:

* Reservation-style accounting: ``reserve(n)`` debits the bucket
  immediately and returns how long the caller must sleep before the
  bytes are genuinely available. Debiting under one lock makes grants
  strictly FIFO (no starvation: a large request queues ahead of later
  small ones rather than being overtaken forever), and lets both sync
  callers (``acquire`` sleeps) and asyncio handlers (``await
  asyncio.sleep(reserve(n))``) share one bucket without blocking an
  event loop.
* The bucket starts EMPTY and the burst allowance is small
  (``rate/8`` by default): admitted bytes over any window w are
  bounded by ``rate*w + burst``, so a 1-second window can exceed the
  cap by at most 12.5% and only right after an idle period.
* ``debt`` is the number of bytes already granted but not yet payable
  at the current fill — the queueing backlog operators see in
  /cluster/status when repair is saturating its cap.
"""
from __future__ import annotations

import threading
import time


class TokenBucket:
    """Thread-safe byte token bucket; rate <= 0 means unlimited."""

    def __init__(self, rate: float, burst: float | None = None):
        self._lock = threading.Lock()
        # waiters park on the condition so a live configure() can wake
        # them to re-price their remaining wait at the new rate
        self._cond = threading.Condition(self._lock)
        self._t = time.monotonic()
        self.configure(rate, burst)

    def configure(self, rate: float, burst: float | None = None) -> None:
        """(Re)set the rate; keeps accumulated debt so a live rate
        change never forgives bytes already granted. Sleeping waiters
        are woken to re-price what they still owe at the new rate — a
        raise un-strands them early, a cut extends their wait instead
        of letting them duck under the new cap."""
        with self._lock:
            self.rate = float(rate)
            self.burst = (float(burst) if burst is not None
                          else max(64 << 10, self.rate / 8.0))
            if not hasattr(self, "_tokens"):
                self._tokens = 0.0  # start empty: no day-one burst
            elif self._tokens > self.burst:
                self._tokens = self.burst  # a burst cut caps the fill
            self._cond.notify_all()

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def reserve(self, n: int) -> float:
        """Debit ``n`` bytes; return seconds the caller must wait
        before using them (0.0 = immediately available)."""
        if self.rate <= 0 or n <= 0:
            return 0.0
        with self._lock:
            self._refill_locked(time.monotonic())
            self._tokens -= n
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.rate

    def cancel(self, n: int) -> None:
        """Return ``n`` bytes debited by a reserve that timed out."""
        if self.rate <= 0 or n <= 0:
            return
        with self._lock:
            self._refill_locked(time.monotonic())
            self._tokens = min(self.burst, self._tokens + n)

    def _owed(self, n: int) -> float:
        """Debit ``n`` bytes; return the refill BYTES still owed before
        the grant matures (0.0 = immediately available). Unlike the
        seconds `reserve` quotes, owed bytes stay correct across a
        live `configure`: the remaining wait is owed/rate at whatever
        the rate currently is."""
        if self.rate <= 0 or n <= 0:
            return 0.0
        with self._lock:
            self._refill_locked(time.monotonic())
            self._tokens -= n
            return max(0.0, -self._tokens)

    def _pay(self, owed: float, deadline: float | None) -> bool:
        """Sleep until ``owed`` bytes have been refilled at the
        prevailing (possibly re-configured) rate. Each configure()
        wakes the wait so the residue is re-priced — a FIFO waiter is
        never stranded sleeping a stale quote."""
        with self._cond:
            while owed > 1e-9:
                rate = self.rate
                if rate <= 0:
                    return True  # now unlimited: everything is paid
                wait = owed / rate
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                t0 = time.monotonic()
                self._cond.wait(wait)
                # configure() notifies, ending the slice — but the
                # tail between the change and the wake-up ran at the
                # NEW rate, so deduct at whichever rate is lower:
                # conservative, never undercharges the live cap
                now_rate = self.rate
                paid_rate = min(rate, now_rate) if now_rate > 0 else rate
                owed -= (time.monotonic() - t0) * paid_rate
        return True

    def acquire(self, n: int, timeout: float | None = None) -> bool:
        """Blocking reserve: sleep until ``n`` bytes are available.
        With ``timeout``, refuse (and un-debit) when the queue is so
        deep the wait would exceed it."""
        if self.rate <= 0 or n <= 0:
            return True
        owed = self._owed(n)
        if timeout is not None and owed > timeout * self.rate:
            self.cancel(n)
            return False
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        if owed > 0 and not self._pay(owed, deadline):
            self.cancel(n)
            return False
        return True

    async def acquire_async(self, n: int,
                            timeout: float | None = None) -> bool:
        """Event-loop-friendly acquire: identical accounting, but the
        wait is `await asyncio.sleep(...)` — never a blocking sleep on
        the loop thread. A rate CUT mid-wait is honoured (the residue
        re-prices each slice and the waiter sleeps longer); a raise is
        picked up on the next slice boundary, so an async waiter may
        oversleep its original quote but can never violate the cap."""
        import asyncio

        if self.rate <= 0 or n <= 0:
            return True
        owed = self._owed(n)
        if timeout is not None and owed > timeout * self.rate:
            self.cancel(n)
            return False
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while owed > 1e-9:
            rate = self.rate
            if rate <= 0:
                return True
            wait = owed / rate
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    self.cancel(n)
                    return False
            t0 = time.monotonic()
            await asyncio.sleep(wait)
            # no condition to wake an async sleeper, so the slice may
            # span a configure(): deduct at the LOWER of the rates it
            # straddled — a cut is honoured in full, a raise is picked
            # up next slice (oversleep, never a cap violation)
            now_rate = self.rate
            paid_rate = min(rate, now_rate) if now_rate > 0 else rate
            owed -= (time.monotonic() - t0) * paid_rate
        return True

    @property
    def fill(self) -> float:
        """Bytes available right now (>= 0)."""
        if self.rate <= 0:
            return float("inf")
        with self._lock:
            self._refill_locked(time.monotonic())
            return max(0.0, self._tokens)

    @property
    def debt(self) -> float:
        """Bytes granted beyond the current fill (queue backlog)."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            self._refill_locked(time.monotonic())
            return max(0.0, -self._tokens)

    def state(self) -> dict:
        with self._lock:
            self._refill_locked(time.monotonic())
            return {"rate": self.rate,
                    "burst": self.burst,
                    "fill": round(max(0.0, self._tokens), 1),
                    "debt": round(max(0.0, -self._tokens), 1)}


# -- process-local bucket registry ---------------------------------------
# One named bucket per shaping domain (volume servers use "repair" for
# their node-wide repair cap). The rate arrives with each throttled
# request (the master is the single place the cap is configured), so
# the registry re-configures on change instead of erroring.

_buckets: dict[str, TokenBucket] = {}
_reg_lock = threading.Lock()


def bucket(key: str, rate: float) -> TokenBucket:
    with _reg_lock:
        b = _buckets.get(key)
        if b is None:
            b = _buckets[key] = TokenBucket(rate)
        elif b.rate != float(rate):
            b.configure(rate)
        return b


def snapshot() -> dict[str, dict]:
    with _reg_lock:
        return {key: b.state() for key, b in _buckets.items()}


def reset() -> None:
    """Test hook: drop all registered buckets."""
    with _reg_lock:
        _buckets.clear()
