"""Leveled logging with -v / -vmodule gating.

Equivalent of the reference's vendored glog fork (weed/glog/glog.go:
Info/Warning/Error/Fatal plus V-style verbosity, `-v` global level and
`-vmodule=file=level` per-file overrides). Same line format so log
tooling written for the reference parses these too:

    I0730 12:00:00.000000 12345 volume_server.py:123] message

Threads and servers share one process-wide configuration, set once
from the CLI flags (cli.py wires `-v` / `-vmodule` before dispatch).
"""
from __future__ import annotations

import inspect
import os
import sys
import threading
import time

_lock = threading.Lock()
_verbosity = 0
_vmodule: dict[str, int] = {}
_out = sys.stderr


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)

def set_vmodule(spec: str) -> None:
    """'store=2,volume_server=3' — per-module (file stem) levels."""
    _vmodule.clear()
    for part in spec.split(","):
        if not part.strip():
            continue
        mod, _, lvl = part.partition("=")
        _vmodule[mod.strip().removesuffix(".py")] = int(lvl or 0)


def set_output(stream) -> None:
    global _out
    _out = stream


def _caller(depth: int = 3) -> tuple[str, int]:
    frame = inspect.currentframe()
    for _ in range(depth):
        if frame is None or frame.f_back is None:
            break
        frame = frame.f_back
    if frame is None:
        return "?", 0
    return os.path.basename(frame.f_code.co_filename), frame.f_lineno


def V(level: int, depth: int = 2) -> bool:
    """True when messages at `level` should be emitted here (glog.V)."""
    if level <= _verbosity:
        return True
    if _vmodule:
        fname, _ = _caller(depth + 1)
        mod = fname.removesuffix(".py")
        if level <= _vmodule.get(mod, -1):
            return True
    return False


def _emit(sev: str, msg: str, depth: int = 3) -> None:
    fname, line = _caller(depth)
    now = time.time()
    stamp = time.strftime("%m%d %H:%M:%S", time.localtime(now))
    usec = int((now % 1) * 1e6)
    rec = (f"{sev}{stamp}.{usec:06d} {threading.get_native_id()} "
           f"{fname}:{line}] {msg}\n")
    with _lock:
        _out.write(rec)
        _out.flush()


def info(msg: str, *args) -> None:
    _emit("I", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    _emit("W", msg % args if args else msg)


def error(msg: str, *args) -> None:
    _emit("E", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    _emit("F", msg % args if args else msg)
    sys.exit(1)


def v(level: int, msg: str, *args) -> None:
    """glog.V(level).Infof equivalent."""
    if V(level, depth=2):
        _emit("I", msg % args if args else msg)
