"""Quantile sketches for workload-characterization telemetry.

The measured-distribution substrate under ROADMAP item 4: before any
controller threshold can be driven by data, every layer that today
only *counts* (volume heat, tenant demand, queue delay) needs a cheap
way to keep whole *distributions* and ship them to the master. This
module is that primitive, shaped like DDSketch (Masson et al., VLDB
2019) — the same trade the workload-characterization literature this
repo follows (arXiv 1709.05365) makes when summarizing access-gap and
request-size distributions:

* **Log-bucketed histogram with a relative-error guarantee.** Values
  land in geometric buckets of ratio ``gamma = (1+alpha)/(1-alpha)``;
  any quantile read back is within ``alpha`` *relative* error of the
  exact stream quantile (default 1%). Relative — not rank — error is
  the right contract for latencies/gaps/sizes spanning 6+ decades:
  p99 = 2.02 s for a true 2 s is fine, "somewhere between p98 and
  p100" is not.
* **Constant memory.** Bucket count grows with the log of the value
  range, not the stream length (~180 buckets cover 1 µs..1 day at
  alpha=0.01 — in practice far fewer are touched). A hard
  ``max_buckets`` cap collapses the smallest buckets first, so a
  pathological range degrades the *low* quantiles only.
* **Lock-cheap record path.** ``record()`` is one ``math.log``, one
  dict upsert and a few scalar updates — no internal lock. Call
  sites serialize writers themselves (the in-tree taps record under
  an already-held short lock, or from a single thread); readers take
  a consistent copy via ``to_dict()``/``merge`` on a snapshot.
* **Mergeable and serializable.** ``merge(a, b)`` is bucket-wise
  addition and is *exactly* equivalent to sketching the concatenated
  stream (same buckets, same counts — not just same error bound), so
  per-volume sketches fold into per-node, per-node into cluster-wide,
  without re-touching raw data. ``to_dict()``/``from_dict()`` is a
  compact JSON-safe encoding that round-trips losslessly and rides
  the existing heartbeat plumbing.

``WindowedSketch`` wraps N rotating sub-sketches so long-running
servers report the *recent* distribution (default 5 min window in 6
slices) instead of an all-of-time average that can never change its
mind after a workload phase shift.

Module-level ``configure()``/``enabled()`` carry the ``-telemetry.*``
CLI flags; recording taps all consult ``enabled()`` so the whole
plane can be switched off (the workload-sweep bench gates the
enabled-vs-disabled hot-path delta).
"""
from __future__ import annotations

import math
import threading

# documented relative-error bound of every quantile read back
DEFAULT_ALPHA = 0.01
# below this, a value is counted in the zero bucket (gaps/sizes of 0
# are real: back-to-back accesses, empty bodies)
MIN_TRACKABLE = 1e-9
# hard bucket cap; collapse folds the smallest buckets together so
# upper quantiles (the ones advisors read) stay exact-within-alpha
DEFAULT_MAX_BUCKETS = 512


class QuantileSketch:
    """DDSketch-style log-bucketed quantile sketch.

    Writers are NOT internally synchronized — see the module
    docstring's lock-cheap contract.
    """

    __slots__ = ("alpha", "gamma", "_log_gamma", "max_buckets",
                 "buckets", "zeros", "count", "total", "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_buckets: int = DEFAULT_MAX_BUCKETS):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.max_buckets = max(8, int(max_buckets))
        self.buckets: dict[int, int] = {}
        self.zeros = 0
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- recording ------------------------------------------------------

    def record(self, value: float, n: int = 1) -> None:
        """Count ``value`` (``n`` times). Negative values clamp to the
        zero bucket — gaps/sizes/delays are non-negative by
        construction, and a clock hiccup must not throw."""
        if n <= 0:
            return
        v = float(value)
        self.count += n
        if v > 0:
            self.total += v * n
        if v < self.min:
            self.min = max(v, 0.0)
        if v > self.max:
            self.max = v
        if v < MIN_TRACKABLE:
            self.zeros += n
            return
        idx = int(math.ceil(math.log(v) / self._log_gamma))
        b = self.buckets
        b[idx] = b.get(idx, 0) + n
        if len(b) > self.max_buckets:
            self._collapse()

    def _collapse(self) -> None:
        """Fold the two smallest buckets together until under the cap.
        Low quantiles blur; the upper quantiles advisors consume keep
        the alpha guarantee."""
        idxs = sorted(self.buckets)
        while len(idxs) > self.max_buckets:
            lo = idxs.pop(0)
            self.buckets[idxs[0]] += self.buckets.pop(lo)

    # -- queries --------------------------------------------------------

    def _bucket_value(self, idx: int) -> float:
        # midpoint estimator: relative error <= (gamma-1)/(gamma+1)
        # == alpha for any value in the bucket
        return 2.0 * self.gamma ** idx / (self.gamma + 1.0)

    def quantile(self, q: float) -> float:
        """The q-quantile (0 <= q <= 1) of the recorded stream, within
        ``alpha`` relative error of the exact stream quantile."""
        if self.count == 0:
            return 0.0
        q = min(1.0, max(0.0, q))
        rank = q * (self.count - 1)
        seen = self.zeros
        if rank < seen:
            return 0.0
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if rank < seen:
                return self._bucket_value(idx)
        return self._bucket_value(max(self.buckets)) \
            if self.buckets else 0.0

    def quantiles(self, qs) -> dict[str, float]:
        return {str(q): self.quantile(float(q)) for q in qs}

    def fraction_below(self, value: float) -> float:
        """CDF estimate: fraction of recorded values <= ``value``
        (the advisor's coverage read: how much of the stream a
        threshold already captures)."""
        if self.count == 0:
            return 0.0
        if value < MIN_TRACKABLE:
            return self.zeros / self.count
        limit = int(math.ceil(math.log(value) / self._log_gamma))
        below = self.zeros + sum(c for i, c in self.buckets.items()
                                 if i <= limit)
        return min(1.0, below / self.count)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    # -- merge / serialize ---------------------------------------------

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self. Exactly equivalent to having
        sketched the concatenated stream (bucket-wise addition)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha "
                f"({self.alpha} vs {other.alpha})")
        for idx, c in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + c
        if len(self.buckets) > self.max_buckets:
            self._collapse()
        self.zeros += other.zeros
        self.count += other.count
        self.total += other.total
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def to_dict(self) -> dict:
        """Compact JSON-safe encoding (heartbeat wire format). Bucket
        keys become strings in JSON; from_dict accepts both."""
        out: dict = {"a": self.alpha, "n": self.count}
        if self.zeros:
            out["z"] = self.zeros
        if self.buckets:
            out["b"] = {str(i): c for i, c in self.buckets.items()}
        if self.count:
            out["t"] = round(self.total, 6)
            out["lo"] = self.min
            out["hi"] = self.max
        return out

    @classmethod
    def from_dict(cls, d: dict,
                  max_buckets: int = DEFAULT_MAX_BUCKETS
                  ) -> "QuantileSketch":
        sk = cls(alpha=float(d.get("a", DEFAULT_ALPHA)),
                 max_buckets=max_buckets)
        sk.zeros = int(d.get("z", 0))
        sk.count = int(d.get("n", 0))
        sk.total = float(d.get("t", 0.0))
        sk.min = float(d.get("lo", math.inf))
        sk.max = float(d.get("hi", -math.inf))
        for i, c in (d.get("b") or {}).items():
            sk.buckets[int(i)] = int(c)
        return sk

    def summary(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """Human-facing digest for /debug payloads."""
        out = {"count": self.count, "mean": round(self.mean, 6)}
        if self.count:
            out["min"] = round(self.min, 6)
            out["max"] = round(self.max, 6)
            for q in qs:
                out[f"p{int(q * 100)}"] = round(self.quantile(q), 6)
        return out


class WindowedSketch:
    """Sliding-window wrapper: a ring of sub-sketches rotated by time,
    so ``merged()`` reflects only the trailing ``window`` seconds and
    a workload phase shift ages out instead of being averaged away.

    ``record``/``merged`` take an explicit ``now`` so tests and the
    heartbeat path stay deterministic; callers pass ``time.time()``.
    Same synchronization contract as QuantileSketch: writers
    serialize themselves.
    """

    __slots__ = ("alpha", "window", "slices", "_slice_len", "_ring")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 window: float = 300.0, slices: int = 6):
        self.alpha = float(alpha)
        self.window = max(1.0, float(window))
        self.slices = max(2, int(slices))
        self._slice_len = self.window / self.slices
        # [(slice_start_epoch, sketch)] newest last
        self._ring: list[tuple[int, QuantileSketch]] = []

    def _epoch(self, now: float) -> int:
        return int(now / self._slice_len)

    def record(self, value: float, now: float) -> None:
        ep = self._epoch(now)
        if not self._ring or self._ring[-1][0] != ep:
            self._ring.append((ep, QuantileSketch(self.alpha)))
            oldest = ep - self.slices + 1
            while self._ring and self._ring[0][0] < oldest:
                self._ring.pop(0)
        self._ring[-1][1].record(value)

    def merged(self, now: float) -> QuantileSketch:
        """The trailing-window distribution (expired slices dropped)."""
        out = QuantileSketch(self.alpha)
        oldest = self._epoch(now) - self.slices + 1
        for ep, sk in self._ring:
            if ep >= oldest:
                out.merge(sk)
        return out

    def to_dict(self, now: float) -> dict:
        return self.merged(now).to_dict()


# -- module config: the -telemetry.* flag surface -----------------------

_conf_lock = threading.Lock()
_enabled = True
_alpha = DEFAULT_ALPHA
_window = 300.0


def configure(enabled: bool | None = None, alpha: float | None = None,
              window: float | None = None) -> None:
    """Apply -telemetry.* CLI flags (None = leave unchanged)."""
    global _enabled, _alpha, _window
    with _conf_lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if alpha is not None:
            if not 0.0 < alpha < 1.0:
                raise ValueError(f"telemetry alpha must be in (0, 1), "
                                 f"got {alpha}")
            _alpha = float(alpha)
        if window is not None:
            _window = max(1.0, float(window))


def enabled() -> bool:
    return _enabled


def alpha() -> float:
    return _alpha


def window() -> float:
    return _window


def windowed() -> WindowedSketch:
    """A WindowedSketch at the configured alpha/window."""
    return WindowedSketch(alpha=_alpha, window=_window)
