"""Tiny SELECT parser for the S3 SelectObjectContent subset.

The reference wires amazon's S3 Select shape through
s3api (POST ?select&select-type=2) down to the volume Query rpc; its
supported expressions are of the form

    SELECT * FROM S3Object
    SELECT s.field1, s.nested.f2 FROM S3Object s WHERE s.x = 'v'

This parses exactly that: a projection list, an optional alias, and an
optional single WHERE comparison (=, !=, >, <, >=, <=). Anything
fancier raises ValueError — matching the reference's "unsupported sql"
errors rather than guessing.
"""
from __future__ import annotations

import re

from .json_query import OPS, Filter

_SELECT_RE = re.compile(
    r"^\s*select\s+(?P<sel>.+?)\s+from\s+s3object(\s*\[\s*(?P<ba>\w+)"
    r"\s*\]|\s+as\s+(?P<asal>\w+)|\s+(?P<al>\w+))?"
    r"(\s+where\s+(?P<where>.+?))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL)

_WHERE_RE = re.compile(
    r"^\s*(?P<field>[\w.]+)\s*(?P<op>!=|>=|<=|=|>|<)\s*"
    r"(?P<val>'[^']*'|\"[^\"]*\"|[\w.+-]+)\s*$")


def parse_select(expression: str) -> tuple[list[str], Filter]:
    """SQL text -> (selections, filter). Raises ValueError on anything
    outside the supported subset."""
    m = _SELECT_RE.match(expression)
    if not m:
        raise ValueError(f"unsupported sql: {expression!r}")
    alias = m.group("ba") or m.group("asal") or m.group("al") or ""

    def strip_alias(field: str) -> str:
        if alias and field.lower().startswith(alias.lower() + "."):
            return field[len(alias) + 1:]
        if field.lower().startswith("s3object."):
            return field[len("s3object."):]
        return field

    sel_raw = m.group("sel").strip()
    if sel_raw == "*":
        selections: list[str] = []
    else:
        selections = []
        for part in sel_raw.split(","):
            part = part.strip()
            if not re.fullmatch(r"[\w.]+", part):
                raise ValueError(
                    f"unsupported projection: {part!r}")
            selections.append(strip_alias(part))

    filt = Filter()
    where = m.group("where")
    if where:
        wm = _WHERE_RE.match(where)
        if not wm:
            raise ValueError(f"unsupported where clause: {where!r}")
        val = wm.group("val")
        if val[:1] in "'\"":
            val = val[1:-1]
        op = wm.group("op")
        if op not in OPS:
            raise ValueError(f"unsupported operand {op!r}")
        filt = Filter(field=strip_alias(wm.group("field")), op=op,
                      value=val)
    return selections, filt
