"""JSON document projection + filtering.

Equivalent of /root/reference/weed/query/json/query_json.go: documents
are JSON objects (one per line for NDJSON payloads, or a single
object/array per object body); `selections` projects top-level or
dotted-path fields; `Filter` compares one field against a constant with
the reference's operand set (=, !=, >, <, >=, <=).
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterator

OPS = ("=", "!=", ">=", "<=", ">", "<")


@dataclass
class Filter:
    field: str = ""
    op: str = "="
    value: str = ""

    def matches(self, doc: dict) -> bool:
        if not self.field:
            return True
        got = get_path(doc, self.field)
        if got is None:
            return False
        want: Any = self.value
        if isinstance(got, bool):
            want = self.value.lower() in ("true", "1")
        elif isinstance(got, (int, float)):
            # compare numerically without truncating the constant:
            # int(29.5) would make `age >= 29.5` match age=29
            try:
                want = float(self.value)
            except ValueError:
                return False
        if self.op == "=":
            return got == want
        if self.op == "!=":
            return got != want
        try:
            if self.op == ">":
                return got > want
            if self.op == "<":
                return got < want
            if self.op == ">=":
                return got >= want
            if self.op == "<=":
                return got <= want
        except TypeError:
            return False
        raise ValueError(f"bad operand {self.op!r} (want one of {OPS})")


def get_path(doc: Any, path: str) -> Any:
    """Dotted-path lookup: "a.b.c" -> doc["a"]["b"]["c"] (None when any
    hop is missing or not an object)."""
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def project(doc: dict, selections: list[str]) -> dict:
    if not selections or selections == ["*"]:
        return doc
    out = {}
    for sel in selections:
        v = get_path(doc, sel)
        if v is not None:
            out[sel] = v
    return out


def query_json_doc(doc: Any, selections: list[str],
                   filt: Filter | None = None) -> Iterator[dict]:
    """Query one parsed JSON value; a top-level array queries each
    element (query_json.go iterates arrays)."""
    filt = filt or Filter()
    docs = doc if isinstance(doc, list) else [doc]
    for d in docs:
        if isinstance(d, dict) and filt.matches(d):
            yield project(d, selections)


def query_json_bytes(data: bytes, selections: list[str],
                     filt: Filter | None = None) -> Iterator[dict]:
    """Query a raw object body: NDJSON (one doc per line) or a single
    JSON document/array."""
    text = data.decode("utf-8", "replace").strip()
    if not text:
        return
    if text.startswith(("[", "{")):
        # try the whole body as one document first: a pretty-printed
        # (multi-line) object must not fall through to line mode where
        # every line would fail to parse and be silently skipped.
        # NDJSON can't parse as a single document, so this is exact.
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            pass
        else:
            yield from query_json_doc(doc, selections, filt)
            return
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue  # reference skips unparseable lines
        yield from query_json_doc(doc, selections, filt)
