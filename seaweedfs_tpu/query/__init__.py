"""Server-side structured queries over stored objects.

Equivalent of /root/reference/weed/query/ (query_json.go) and the
VolumeServer.Query streaming rpc (volume_server.proto:107,
volume_grpc_query.go): push a projection + filter down to where the
bytes live instead of hauling whole objects to the client — the
S3-Select-shaped capability.
"""
from .json_query import Filter, query_json_bytes, query_json_doc
from .sql import parse_select

__all__ = ["Filter", "query_json_bytes", "query_json_doc",
           "parse_select"]
