"""Shared filer metadata-subscription pump.

One implementation of the reconnecting WebSocket consumer that the
replicator, the meta backup, and the mount's cache invalidation all
need (the reference's filer_pb.SubscribeMetadata client loop): a
daemon thread running its own event loop, resumable via a since-offset
callback, with clean cross-thread cancellation. Handlers run in a
worker thread so blocking IO in them can never starve the WebSocket
heartbeat.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Callable


class MetaSubscriber:
    def __init__(self, source_url: str, path_prefix: str,
                 handler: Callable[[dict], None],
                 since_fn: Callable[[], int] | None = None,
                 reconnect_delay: float = 0.5):
        """handler(event) is called for every event, in order, from a
        worker thread; since_fn() (also off-loop) supplies the resume
        offset at each (re)connect."""
        self.source = source_url.rstrip("/") \
            if source_url.startswith("http") else f"http://{source_url}"
        self.prefix = path_prefix.rstrip("/") or "/"
        self.handler = handler
        self.since_fn = since_fn or (lambda: 0)
        self.reconnect_delay = reconnect_delay
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._stop.clear()
        self._loop = None
        self._task = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        loop, task = self._loop, self._task
        if loop is not None and task is not None:
            try:
                loop.call_soon_threadsafe(task.cancel)
            except RuntimeError:
                pass  # loop already closed: thread is exiting anyway
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._task = self._loop.create_task(self._pump())
        try:
            self._loop.run_until_complete(self._task)
        except asyncio.CancelledError:
            pass
        finally:
            try:
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            except Exception:
                pass
            self._loop.close()

    async def _pump(self) -> None:
        import aiohttp

        ws_url = self.source.replace("http", "ws", 1) + \
            "/ws/meta_subscribe"
        while not self._stop.is_set():
            try:
                since = await asyncio.to_thread(self.since_fn)
                async with aiohttp.ClientSession() as sess:
                    async with sess.ws_connect(
                            ws_url,
                            params={"path_prefix": self.prefix,
                                    "since_ns": str(since)},
                            heartbeat=30) as ws:
                        async for msg in ws:
                            if self._stop.is_set():
                                return
                            if msg.type != aiohttp.WSMsgType.TEXT:
                                break
                            ev = json.loads(msg.data)
                            # handlers may do blocking HTTP: keep them
                            # off the loop so pings stay serviced
                            await asyncio.to_thread(self.handler, ev)
            except asyncio.CancelledError:
                return
            except Exception:
                pass
            await asyncio.sleep(self.reconnect_delay)
